// tony_portres: hold TCP ports with SO_REUSEPORT from a helper process.
//
// Native equivalent of the reference's reserve_reusable_port.py helper
// (spawned by ReusablePort.java:149-235): bind the requested number of
// ports with SO_REUSEPORT, print them one per line, touch the sentinel file
// to signal readiness, then hold the sockets until SIGTERM/SIGINT. A user
// process that also sets SO_REUSEPORT (TF gRPC with TF_GRPC_REUSE_PORT, a
// JAX coordinator) can bind the same port while this helper still holds it,
// closing the register-then-rebind race without ever freeing the port.
//
// usage: tony_portres <sentinel_file> [n_ports=1] [port...]
//   with explicit ports, re-reserves those exact ports instead of ephemeral.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <vector>

namespace {

volatile sig_atomic_t g_stop = 0;

void HandleStop(int) { g_stop = 1; }

int ReservePort(int want_port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
#ifdef SO_REUSEPORT
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
#endif
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(want_port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 1) < 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int BoundPort(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) < 0)
    return -1;
  return ntohs(addr.sin_port);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr,
            "usage: %s <sentinel_file> [n_ports=1] [port...]\n", argv[0]);
    return 2;
  }
  const char* sentinel = argv[1];
  std::vector<int> fds;
  if (argc > 3) {  // explicit port list
    for (int i = 3; i < argc; ++i) {
      int fd = ReservePort(atoi(argv[i]));
      if (fd < 0) {
        fprintf(stderr, "cannot reserve port %s: %s\n", argv[i],
                strerror(errno));
        return 1;
      }
      fds.push_back(fd);
    }
  } else {
    int n = argc == 3 ? atoi(argv[2]) : 1;
    for (int i = 0; i < n; ++i) {
      int fd = ReservePort(0);
      if (fd < 0) {
        fprintf(stderr, "cannot reserve ephemeral port: %s\n",
                strerror(errno));
        return 1;
      }
      fds.push_back(fd);
    }
  }
  // Install handlers and BLOCK the stop signals BEFORE the readiness
  // sentinel is visible: a supervisor that reacts to the sentinel with an
  // immediate terminate() must find the handler already in place (round-1
  // flake: default SIGTERM action killed the helper with rc -15). Blocking
  // also closes the lost-wakeup race of `while (!g_stop) pause()` — the
  // signal can only be delivered inside sigsuspend below.
  struct sigaction sa{};
  sa.sa_handler = HandleStop;
  sigemptyset(&sa.sa_mask);
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  sigset_t block, old;
  sigemptyset(&block);
  sigaddset(&block, SIGTERM);
  sigaddset(&block, SIGINT);
  sigprocmask(SIG_BLOCK, &block, &old);

  for (int fd : fds) printf("%d\n", BoundPort(fd));
  fflush(stdout);

  // readiness sentinel (reference: helper touches the file once bound)
  FILE* f = fopen(sentinel, "w");
  if (f == nullptr) {
    fprintf(stderr, "cannot touch sentinel %s: %s\n", sentinel,
            strerror(errno));
    return 1;
  }
  fclose(f);

  // Atomically unblock + wait: a SIGTERM delivered at any point since the
  // sigprocmask above is seen either before the loop (g_stop already 1) or
  // by sigsuspend itself — never lost.
  sigset_t wait_mask = old;
  sigdelset(&wait_mask, SIGTERM);
  sigdelset(&wait_mask, SIGINT);
  while (!g_stop) sigsuspend(&wait_mask);
  for (int fd : fds) close(fd);
  return 0;
}
