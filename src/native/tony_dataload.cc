// libtony_data: memory-mapped token-shard batch loader with prefetch.
//
// Native data plane for the training runtime (the reference delegated input
// pipelines to user scripts; this is the TPU-first equivalent of a
// host-side loader feeding the device: mmap'd int32 token shards, random
// crops assembled into (batch, seq+1) arrays by a background thread into a
// double buffer, so the host batch is ready before the device finishes the
// step). Exposed as a C ABI for ctypes (no pybind11 in the image);
// tony_tpu/train/native_data.py wraps it with a pure-numpy fallback.
//
// File format: raw little-endian int32 tokens, no header.

#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Loader {
  const int32_t* tokens = nullptr;
  size_t n_tokens = 0;
  size_t map_len = 0;
  long batch = 0;
  long seq = 0;          // yields rows of seq+1 tokens (inputs+shifted)
  uint64_t rng = 0;
  // double buffer: the worker only writes buf[i] while !filled[i]; the
  // consumer only reads buf[i] while filled[i] — so fills and copies never
  // touch the same buffer concurrently. Both sides walk 0,1,0,1,...
  int32_t* buf[2] = {nullptr, nullptr};
  bool filled[2] = {false, false};
  int prod = 0;          // next buffer the worker fills
  int cons = 0;          // next buffer tdl_next consumes
  bool stop = false;
  pthread_t worker{};
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  pthread_cond_t cv = PTHREAD_COND_INITIALIZER;

  size_t row_len() const { return static_cast<size_t>(seq) + 1; }
  size_t batch_elems() const { return static_cast<size_t>(batch) * row_len(); }
};

uint64_t NextRand(uint64_t* s) {  // xorshift64*
  uint64_t x = *s;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *s = x;
  return x * 0x2545F4914F6CDD1DULL;
}

void FillBatch(Loader* l, int32_t* out) {
  const size_t row = l->row_len();
  const size_t max_start = l->n_tokens - row;
  for (long b = 0; b < l->batch; ++b) {
    size_t start = static_cast<size_t>(NextRand(&l->rng) % (max_start + 1));
    memcpy(out + static_cast<size_t>(b) * row, l->tokens + start,
           row * sizeof(int32_t));
  }
}

void* WorkerMain(void* arg) {
  Loader* l = static_cast<Loader*>(arg);
  for (;;) {
    pthread_mutex_lock(&l->mu);
    while (!l->stop && l->filled[l->prod]) {
      pthread_cond_wait(&l->cv, &l->mu);
    }
    if (l->stop) {
      pthread_mutex_unlock(&l->mu);
      return nullptr;
    }
    int which = l->prod;
    pthread_mutex_unlock(&l->mu);

    FillBatch(l, l->buf[which]);  // exclusive: !filled[which]

    pthread_mutex_lock(&l->mu);
    l->filled[which] = true;
    l->prod = which ^ 1;
    pthread_cond_broadcast(&l->cv);
    pthread_mutex_unlock(&l->mu);
  }
}

}  // namespace

extern "C" {

void* tdl_open(const char* path, long batch, long seq, long seed) {
  if (batch <= 0 || seq <= 0) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st{};
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return nullptr;
  }
  auto* l = new Loader();
  l->map_len = static_cast<size_t>(st.st_size);
  l->n_tokens = l->map_len / sizeof(int32_t);
  l->batch = batch;
  l->seq = seq;
  l->rng = static_cast<uint64_t>(seed) * 0x9E3779B97F4A7C15ULL + 1;
  if (l->n_tokens < l->row_len()) {
    close(fd);
    delete l;
    return nullptr;
  }
  void* mem = mmap(nullptr, l->map_len, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    delete l;
    return nullptr;
  }
  madvise(mem, l->map_len, MADV_RANDOM);
  l->tokens = static_cast<const int32_t*>(mem);
  l->buf[0] = static_cast<int32_t*>(
      malloc(l->batch_elems() * sizeof(int32_t)));
  l->buf[1] = static_cast<int32_t*>(
      malloc(l->batch_elems() * sizeof(int32_t)));
  if (l->buf[0] == nullptr || l->buf[1] == nullptr) {
    free(l->buf[0]);
    free(l->buf[1]);
    munmap(mem, l->map_len);
    delete l;
    return nullptr;
  }
  if (pthread_create(&l->worker, nullptr, WorkerMain, l) != 0) {
    // no worker -> tdl_next would deadlock; fail open so the Python side
    // falls back to the numpy loader
    munmap(const_cast<int32_t*>(l->tokens), l->map_len);
    free(l->buf[0]);
    free(l->buf[1]);
    delete l;
    return nullptr;
  }
  return l;
}

// Copies the next (batch, seq+1) int32 batch into `out`; returns 0 ok.
// Single-consumer: call from one thread.
int tdl_next(void* handle, int32_t* out) {
  auto* l = static_cast<Loader*>(handle);
  if (l == nullptr) return -1;
  pthread_mutex_lock(&l->mu);
  int which = l->cons;
  while (!l->filled[which]) pthread_cond_wait(&l->cv, &l->mu);
  pthread_mutex_unlock(&l->mu);

  // exclusive while filled[which]: the worker never writes a filled buffer
  memcpy(out, l->buf[which], l->batch_elems() * sizeof(int32_t));

  pthread_mutex_lock(&l->mu);
  l->filled[which] = false;
  l->cons = which ^ 1;
  pthread_cond_broadcast(&l->cv);
  pthread_mutex_unlock(&l->mu);
  return 0;
}

long tdl_num_tokens(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  return l == nullptr ? -1 : static_cast<long>(l->n_tokens);
}

void tdl_close(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  if (l == nullptr) return;
  pthread_mutex_lock(&l->mu);
  l->stop = true;
  pthread_cond_broadcast(&l->cv);
  pthread_mutex_unlock(&l->mu);
  pthread_join(l->worker, nullptr);
  munmap(const_cast<int32_t*>(l->tokens), l->map_len);
  free(l->buf[0]);
  free(l->buf[1]);
  delete l;
}

}  // extern "C"
