import time, sys, jax, jax.numpy as jnp
def log(m):
    print(f"[{time.strftime('%H:%M:%S')}] {m}", flush=True)
log(f"devices {jax.devices()}")
x = jnp.ones((1024, 1024), jnp.bfloat16)
f = jax.jit(lambda a: a @ a)
t=time.monotonic(); y = f(x); log(f"dispatch1 {time.monotonic()-t:.3f}")
t=time.monotonic(); v=float(y[0,0]); log(f"sync1 {time.monotonic()-t:.3f} v={v}")
t=time.monotonic()
for i in range(10): y = f(y)
log(f"dispatch10 {time.monotonic()-t:.3f}")
t=time.monotonic(); v=float(y[0,0]); log(f"sync10 {time.monotonic()-t:.3f}")
# bigger matmul: 8192^3*2 = 1.1e12 flops/iter
x = jnp.ones((8192, 8192), jnp.bfloat16)
g = jax.jit(lambda a: a @ a)
t=time.monotonic(); y = g(x); v=float(y[0,0]); log(f"big compile+run {time.monotonic()-t:.3f}")
t=time.monotonic()
for i in range(20): y = g(y)
v=float(y[0,0])
dt=time.monotonic()-t
log(f"big 20 iters {dt:.3f}s -> {20*2*8192**3/dt/1e12:.1f} TFLOP/s")
