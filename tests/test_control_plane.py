"""Coalesced O(width) control plane (ROADMAP item 3).

Pins the width-1k rebuild's mechanics:

- the cluster-spec render is cached per generation (barrier release and
  every later poll serve ONE json.dumps, never one per caller);
- generation-keyed spec DIFFS ride heartbeat responses: a survivor of a
  peer's relaunch patches its held spec with O(changed) bytes instead of
  re-fetching the full O(width) spec — including under a mid-poll
  generation bump, attempt-fenced like register_worker_spec;
- the liveliness sweep is sharded (per-shard locks, one shard per tick)
  with detection latency pinned <= the unsharded monitor's within one
  sweep period;
- heartbeat start phases are jittered deterministically from the task
  index, the barrier poll backs off exponentially, and the RPC pool /
  shard counts size themselves from gang width;
- chaos e2e: a relaunch at width 256 propagates the new generation to
  every survivor via heartbeat-piggybacked diffs ALONE (zero full-spec
  re-fetches after the initial rendezvous), with a bit-identical final
  cluster spec on every executor.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from tony_tpu import constants as C
from tony_tpu.am.liveliness import LivelinessMonitor, auto_liveliness_shards
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.executor.task_executor import (
    TaskExecutor, apply_spec_diff, heartbeat_jitter_sec,
)
from tony_tpu.rpc.client import ClusterServiceClient
from tony_tpu.rpc.service import ClusterServiceHandler, auto_rpc_workers, serve
from tony_tpu.session.session import SPEC_DIFF_WINDOW, TonySession

chaos = pytest.mark.chaos


def _session(width: int) -> TonySession:
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", width, "test")
    s = TonySession(conf)
    s.num_expected_tasks = width
    return s


# ---------------------------------------------------------------------------
# cached render + diff protocol (session layer)
# ---------------------------------------------------------------------------

def test_spec_render_cached_per_generation():
    """Barrier release and every subsequent poll serve the SAME rendered
    string: one O(width) json.dumps per generation, not one per caller."""
    s = _session(8)
    for i in range(8):
        s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}")
    assert s.spec_stats["renders"] == 1
    first = s.cluster_spec_json()
    # repeated barrier polls + get_cluster_spec calls: zero new renders,
    # and the exact same object comes back (cache, not a re-render)
    for i in range(8):
        assert s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}") \
            is first
    assert s.cluster_spec_json() is first
    assert s.spec_stats["renders"] == 1
    # a generation bump invalidates; the re-closed barrier renders ONCE
    s.relaunch_task("worker", 3)
    assert s.cluster_spec_json() is None
    s.register_worker_spec_with_generation("worker:3", "r3:2000",
                                           expected_attempt=1)
    assert s.spec_stats["renders"] == 2
    assert json.loads(s.cluster_spec_json())["worker"][3] == "r3:2000"
    assert s.spec_stats["renders"] == 2


def test_spec_diff_single_and_multi_bump_union():
    s = _session(4)
    for i in range(4):
        s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}")
    base = json.loads(s.cluster_spec_json())
    assert s.spec_diff_since(1) == (None, False)      # up to date
    s.relaunch_task("worker", 2)
    # barrier open: no diff yet, but NOT a refetch verdict — the executor
    # keeps waiting and the diff rides a later heartbeat
    assert s.spec_diff_since(1) == (None, False)
    s.register_worker_spec_with_generation("worker:2", "r2:2000",
                                           expected_attempt=1)
    diff, refetch = s.spec_diff_since(1)
    assert not refetch
    assert diff == {"generation": 2, "changed": {"worker": {"2": "r2:2000"}}}
    # a second bump: the diff from generation 1 is the UNION of both
    s.relaunch_task("worker", 0)
    s.register_worker_spec_with_generation("worker:0", "r0:3000",
                                           expected_attempt=1)
    diff2, _ = s.spec_diff_since(1)
    assert diff2["generation"] == 3
    assert diff2["changed"] == {"worker": {"0": "r0:3000", "2": "r2:2000"}}
    # from generation 2 only the newer bump is included
    diff3, _ = s.spec_diff_since(2)
    assert diff3["changed"] == {"worker": {"0": "r0:3000"}}
    # applying the union to the generation-1 spec is bit-identical to the
    # AM's full render at generation 3
    assert json.dumps(apply_spec_diff(base, diff2["changed"])) \
        == s.cluster_spec_json()
    # a generation the window cannot cover -> full-refetch verdict
    assert s.spec_diff_since(0) == (None, True)


def test_spec_diff_membership_size_changes_and_window_fallback():
    """Elastic resize (cluster/elastic.py): a diff spanning generations
    where indices were ADDED and REMOVED must serve the correct
    membership delta — not just host:port rebinds — converge
    bit-identically with the full render, and still fall back to a
    refetch verdict once the bumps leave the retained window."""
    from tony_tpu.session.session import SPEC_DIFF_WINDOW

    s = _session(4)
    for i in range(4):
        s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}")
    base = json.loads(s.cluster_spec_json())
    g0 = s.spec_generation
    # grow 4 -> 6: the two new slots register, one bump carries them
    for _ in range(2):
        t = s.add_task_instance("worker")
        s.num_expected_tasks += 1
        s.register_worker_spec(t.task_id, f"n{t.index}:{2000 + t.index}")
    s.resize_bump_generation({"worker:4", "worker:5"}, {})
    diff, refetch = s.spec_diff_since(g0)
    assert not refetch
    assert diff["changed"] == {"worker": {"4": "n4:2004", "5": "n5:2005"}}
    assert "removed" not in diff
    grown = apply_spec_diff(base, diff["changed"], diff.get("removed"))
    assert json.dumps(grown) == s.cluster_spec_json()
    g1 = s.spec_generation
    # shrink 6 -> 3: trailing slots leave; the diff carries the removal
    removed = s.remove_task_slots("worker", 3)
    s.resize_bump_generation(set(), {"worker": {t.index for t in removed}})
    diff, refetch = s.spec_diff_since(g1)
    assert not refetch
    assert diff["changed"] == {}
    assert diff["removed"] == {"worker": [3, 4, 5]}
    shrunk = apply_spec_diff(grown, diff["changed"], diff.get("removed"))
    assert json.dumps(shrunk) == s.cluster_spec_json()
    # a straggler spanning BOTH bumps: add-then-remove nets out, the
    # genuinely-removed index survives as a removal
    both, refetch = s.spec_diff_since(g0)
    assert not refetch
    assert both["changed"] == {}
    assert sorted(both["removed"]["worker"]) == [3, 4, 5]
    assert json.dumps(apply_spec_diff(base, both["changed"],
                                      both.get("removed"))) \
        == s.cluster_spec_json()
    # outside the retained window: refetch, exactly like rebind diffs
    for _ in range(SPEC_DIFF_WINDOW + 1):
        s.resize_bump_generation(set(), {})
    assert s.spec_diff_since(g0) == (None, True)


def test_rebind_without_relaunch_rides_next_diff():
    """An executor re-registering at a NEW host:port without a relaunch
    bumps no generation, so no diff can carry the rebind on its own — it
    must fold into the NEXT bump's diff material, or survivors patching
    by diff would keep a dead address while believing they are current
    (and their spec would not be bit-identical to the AM's render)."""
    s = _session(4)
    for i in range(4):
        s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}")
    base = json.loads(s.cluster_spec_json())
    # worker:1 restarts and rebinds — same attempt, no generation bump
    s.register_worker_spec("worker:1", "rebound:9999")
    assert s.spec_generation == 1
    # now a peer relaunch bumps to generation 2: the diff from 1 must
    # include BOTH the replacement and the earlier rebind
    s.relaunch_task("worker", 2)
    s.register_worker_spec_with_generation("worker:2", "r2:2000",
                                           expected_attempt=1)
    diff, refetch = s.spec_diff_since(1)
    assert not refetch
    assert diff["changed"] == {"worker": {"1": "rebound:9999",
                                          "2": "r2:2000"}}
    assert json.dumps(apply_spec_diff(base, diff["changed"])) \
        == s.cluster_spec_json()
    # the mirror ordering — rebind AFTER the bump, BEFORE the next one: a
    # trailing survivor's diff must still carry the rebind (a full fetch
    # would have re-rendered it), not mark the survivor current with a
    # dead address
    s.register_worker_spec("worker:3", "rebound2:8888")
    diff2, refetch2 = s.spec_diff_since(1)
    assert not refetch2
    assert diff2["changed"]["worker"]["3"] == "rebound2:8888"
    assert json.dumps(apply_spec_diff(base, diff2["changed"])) \
        == s.cluster_spec_json()


def test_spec_diff_window_eviction():
    s = _session(2)
    for i in range(2):
        s.register_worker_spec(f"worker:{i}", f"h{i}:{1000 + i}")
    conf_attempts = SPEC_DIFF_WINDOW + 4
    for n in range(conf_attempts):
        t = s.relaunch_task("worker", 1)
        s.register_worker_spec_with_generation(
            "worker:1", f"r:{3000 + n}", expected_attempt=t.attempt)
    # generation 1 fell out of the bounded window -> refetch, but a
    # recent generation still diffs
    assert s.spec_diff_since(1) == (None, True)
    diff, refetch = s.spec_diff_since(s.spec_generation - 2)
    assert not refetch and diff["generation"] == s.spec_generation


def test_spec_diff_mid_poll_generation_bump_attempt_fenced(tmp_path):
    """Relaunch during an in-flight re-rendezvous: the heartbeat diff an
    executor finally receives must belong to the NEWEST generation (never
    a half-open intermediate one), and a superseded attempt's heartbeat —
    fenced exactly like its register_worker_spec — gets no diff at all."""
    from tests.test_fault_tolerance import _make_am
    am = _make_am(tmp_path, **{"tony.worker.instances": 3,
                               "tony.task.max-task-attempts": 4})
    session = am.session
    session.num_expected_tasks = 3
    for i in range(3):
        am.register_worker_spec({"task_id": f"worker:{i}",
                                 "spec": f"h{i}:{1000 + i}", "session_id": 0,
                                 "task_attempt": 0})
    # survivor worker:0 holds generation 1; worker:1 is relaunched
    session.relaunch_task("worker", 1)                      # -> generation 2
    resp = am.task_executor_heartbeat({"task_id": "worker:0",
                                       "task_attempt": 0,
                                       "spec_generation": 1})
    assert resp["spec_generation"] == 2
    assert "spec_diff" not in resp and "spec_refetch" not in resp
    assert resp["spec_ready"] is False
    # mid-poll second bump: worker:2 relaunched too     -> generation 3
    session.relaunch_task("worker", 2)
    # zombie: worker:1's dead attempt 0 pings — fenced, no diff ever
    zombie = am.task_executor_heartbeat({"task_id": "worker:1",
                                         "task_attempt": 0,
                                         "spec_generation": 1})
    assert zombie == {"spec_generation": 3}
    # replacements register (attempt-fenced: the stale attempt bounces)
    stale = am.register_worker_spec({"task_id": "worker:1",
                                     "spec": "zombie:1", "session_id": 0,
                                     "task_attempt": 0})
    assert stale["spec"] is None
    am.register_worker_spec({"task_id": "worker:1", "spec": "r1:2001",
                             "session_id": 0, "task_attempt": 1})
    am.register_worker_spec({"task_id": "worker:2", "spec": "r2:2002",
                             "session_id": 0, "task_attempt": 1})
    # the survivor's next heartbeat carries ONE diff for the newest
    # generation, covering BOTH bumps
    resp = am.task_executor_heartbeat({"task_id": "worker:0",
                                       "task_attempt": 0,
                                       "spec_generation": 1})
    assert resp["spec_ready"] is True
    diff = resp["spec_diff"]
    assert diff["generation"] == 3
    assert diff["changed"] == {"worker": {"1": "r1:2001", "2": "r2:2002"}}
    # up to date after applying: no further diff
    resp = am.task_executor_heartbeat({"task_id": "worker:0",
                                       "task_attempt": 0,
                                       "spec_generation": 3})
    assert "spec_diff" not in resp
    am.hb_monitor.stop()


# ---------------------------------------------------------------------------
# sharded liveliness sweep
# ---------------------------------------------------------------------------

def _detection_latencies(shards: int, n_tasks: int = 12,
                         hb_ms: int = 40) -> list[float]:
    detected = {}
    mon = LivelinessMonitor(hb_ms, 3, lambda tid, att:
                            detected.setdefault(tid, time.monotonic()),
                            shards=shards)
    t0 = time.monotonic()
    for i in range(n_tasks):
        mon.register(f"worker:{i}", 0)
    mon.start()
    deadline = t0 + 5.0
    while len(detected) < n_tasks and time.monotonic() < deadline:
        time.sleep(0.01)
    mon.stop()
    assert len(detected) == n_tasks
    return [ts - t0 for ts in detected.values()]


def test_sharded_sweep_detection_latency_within_one_sweep_period():
    """Sharding must not slow detection: every entry is still examined
    once per sweep period (one shard per tick), so a sharded monitor's
    worst detection latency stays within one sweep period of the
    unsharded monitor's."""
    unsharded = _detection_latencies(shards=1)
    sharded = _detection_latencies(shards=4)
    # expiry window 0.12s, sweep period 0.05s; allow scheduling slop
    sweep_period = 0.05
    assert max(sharded) <= max(unsharded) + sweep_period + 0.15, \
        (max(sharded), max(unsharded))


def test_sharded_monitor_ping_unregister_and_zombie_semantics():
    mon = LivelinessMonitor(1000, 3, lambda tid, att: None, shards=8)
    for i in range(32):
        mon.register(f"worker:{i}", attempt=i % 3)
    assert len(mon) == 32
    assert mon.ping("worker:17") is True
    assert mon.ping("worker:99") is False        # never resurrects
    assert mon.entry("worker:5")[1] == 2
    mon.unregister("worker:17")
    assert not mon.registered("worker:17") and len(mon) == 31
    mon.clear()
    assert len(mon) == 0


def test_width_aware_auto_sizing():
    assert auto_liveliness_shards(48) == 1
    assert auto_liveliness_shards(256) == 4
    assert auto_liveliness_shards(1024) == 16
    assert auto_liveliness_shards(10_000) == 16          # capped
    assert auto_rpc_workers(0) == 16
    assert auto_rpc_workers(48) == 19
    assert auto_rpc_workers(256) == 32
    assert auto_rpc_workers(1024) == 64
    assert auto_rpc_workers(10_000) == 64                # capped


def test_am_wires_width_sized_shards(tmp_path):
    from tests.test_fault_tolerance import _make_am
    am = _make_am(tmp_path, **{"tony.worker.instances": 256})
    assert am.hb_monitor.num_shards == 4
    am2 = _make_am(tmp_path, **{"tony.worker.instances": 256,
                                "tony.am.liveliness-shards": 2})
    assert am2.hb_monitor.num_shards == 2


# ---------------------------------------------------------------------------
# executor side: jitter, backoff, diff-applied respec
# ---------------------------------------------------------------------------

def test_heartbeat_jitter_deterministic_and_spread():
    phases = [heartbeat_jitter_sec(i, 1.0) for i in range(1024)]
    assert phases == [heartbeat_jitter_sec(i, 1.0) for i in range(1024)]
    assert all(0.0 <= p < 1.0 for p in phases)
    # low-discrepancy: no 100ms phase bucket hoards the gang
    buckets = [0] * 10
    for p in phases:
        buckets[int(p * 10)] += 1
    assert max(buckets) <= 2 * (1024 // 10), buckets
    assert heartbeat_jitter_sec(0, 1.0) == 0.0


def _executor(env_extra=None) -> TaskExecutor:
    env = {C.JOB_NAME: "worker", C.TASK_INDEX: "0", C.TASK_NUM: "2",
           C.AM_HOST: "127.0.0.1", C.AM_PORT: "1", C.TASK_COMMAND: "true"}
    env.update(env_extra or {})
    return TaskExecutor(env=env)


class _AliveHB:
    _silent = False

    def is_alive(self):
        return True


def test_executor_applies_diff_without_reregister(monkeypatch):
    """A survivor whose heartbeater delivered the diff re-joins the gang
    by patching its held spec — register_worker_spec is never called."""
    ex = _executor()
    ex.heartbeater = _AliveHB()
    ex._cluster_spec = {"worker": ["h0:1000", "h1:1001"]}
    ex._spec_generation = 1
    monkeypatch.setattr(
        ex, "register_and_get_cluster_spec",
        lambda: pytest.fail("survivor re-polled the rendezvous barrier"))
    ex._on_spec_diff({"generation": 2,
                      "changed": {"worker": {"1": "r1:2001"}}})
    assert ex._respec_pending or ex._latest_generation == 2
    spec = ex._await_respec_spec()
    assert spec == {"worker": ["h0:1000", "r1:2001"]}
    assert ex._spec_generation == 2 and not ex._respec_pending
    ex.client.close()
    ex.metrics_client.close()


def test_executor_refetch_verdict_falls_back(monkeypatch):
    ex = _executor()
    ex.heartbeater = _AliveHB()
    ex._cluster_spec = {"worker": ["h0:1000", "h1:1001"]}
    ex._spec_generation = 1
    ex._on_generation(2)
    ex._on_spec_refetch()
    assert ex._await_respec_spec() is None       # falls back to the barrier
    ex.client.close()
    ex.metrics_client.close()


def test_executor_without_heartbeater_skips_diff_wait():
    ex = _executor()
    assert ex._await_respec_spec() is None
    ex.client.close()
    ex.metrics_client.close()


def test_stale_diff_is_ignored():
    ex = _executor()
    ex.heartbeater = _AliveHB()
    ex._cluster_spec = {"worker": ["h0:1000"]}
    ex._spec_generation = 3
    ex._on_spec_diff({"generation": 2, "changed": {"worker": {"0": "x:1"}}})
    assert ex._pending_diff is None and not ex._respec_pending
    ex.client.close()
    ex.metrics_client.close()


# ---------------------------------------------------------------------------
# chaos e2e: width-256 relaunch propagates via diffs alone
# ---------------------------------------------------------------------------

class _HarnessHandler(ClusterServiceHandler):
    """The AM's control-plane surface over a real TonySession + sharded
    LivelinessMonitor, mirroring ApplicationMaster's register/heartbeat
    handlers (attempt fence, liveliness plant/ping, diff piggyback)."""

    def __init__(self, session: TonySession, monitor: LivelinessMonitor):
        self.session = session
        self.monitor = monitor

    def get_task_infos(self, req):
        return []

    def get_cluster_spec(self, req):
        spec = self.session.cluster_spec_json()
        if spec is not None:
            self.session.spec_stats["full_serves"] += 1
            self.session.spec_stats["full_bytes"] += len(spec)
        return {"spec": spec, "generation": self.session.spec_generation}

    def register_worker_spec(self, req):
        attempt = int(req.get("task_attempt", -1))
        spec, generation, accepted = \
            self.session.register_worker_spec_with_generation(
                req["task_id"], req["spec"], expected_attempt=attempt)
        if accepted:
            self.monitor.register(req["task_id"], max(0, attempt))
        return {"spec": spec, "generation": generation}

    def task_executor_heartbeat(self, req):
        session = self.session
        generation = session.spec_generation
        attempt = int(req.get("task_attempt", -1))
        if attempt >= 0:
            task = session.get_task_by_id(req["task_id"])
            if task is not None and attempt != task.attempt:
                return {"spec_generation": generation}
        self.monitor.ping(req["task_id"])
        resp = {"spec_generation": generation,
                "spec_ready": session.all_tasks_registered()}
        exec_gen = int(req.get("spec_generation", -1) or -1)
        if 0 < exec_gen < generation:
            diff, refetch = session.spec_diff_since(exec_gen)
            if diff is not None:
                resp["spec_diff"] = diff
            elif refetch:
                resp["spec_refetch"] = True
        return resp

    def register_execution_result(self, req):
        self.monitor.unregister(f"{req['job_name']}:{req['job_index']}")
        return {}

    def register_tensorboard_url(self, req):
        return {}

    def register_serving_endpoint(self, req):
        return {}

    def finish_application(self, req):
        return {}

    def request_profile(self, req):
        return {"error": "harness"}

    def read_task_logs(self, req):
        return {"error": "harness"}

    def get_skew(self, req):
        return {"error": "harness"}

    def get_alerts(self, req):
        return {"error": "harness"}

    def get_profile(self, req):
        return {"error": "harness"}

    def request_preemption(self, req):
        return {"error": "harness"}

    def request_rolling_update(self, req):
        return {"error": "harness"}

    def request_resize(self, req):
        return {"error": "harness"}


@chaos
def test_width256_relaunch_propagates_via_diffs_alone():
    """A relaunch at width 256 reaches every survivor through
    heartbeat-piggybacked diffs ALONE: after the initial rendezvous the
    AM serves exactly ONE more full spec (the replacement's own barrier
    release), every survivor ends at the new generation, and each
    patched spec is bit-identical to the AM's render."""
    width = 256
    session = _session(width)
    monitor = LivelinessMonitor(
        1000, 25, lambda tid, att: None,
        shards=auto_liveliness_shards(width))
    monitor.start()
    handler = _HarnessHandler(session, monitor)
    server, port = serve(cluster_handler=handler,
                         max_workers=auto_rpc_workers(width))
    n_clients = 32
    clients = [ClusterServiceClient("127.0.0.1", port)
               for _ in range(n_clients)]
    held: dict[int, tuple[int, dict]] = {}   # index -> (generation, spec)
    lock = threading.Lock()
    errors: list[str] = []

    def _register(i: int) -> None:
        c = clients[i % n_clients]
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                result = c.register_worker_spec(
                    f"worker:{i}", f"h{i}:{10_000 + i}", 0,
                    task_attempt=0, with_generation=True)
                if result is not None:
                    spec, gen = result
                    with lock:
                        held[i] = (gen, spec)
                    return
                time.sleep(0.05)
            raise TimeoutError("barrier never closed")
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"worker:{i}: {e}")

    threads = [threading.Thread(target=_register, args=(i,), daemon=True)
               for i in range(width)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, errors[:3]
    assert len(held) == width and session.all_tasks_registered()
    full_serves_after_rendezvous = session.spec_stats["full_serves"]
    assert full_serves_after_rendezvous == width

    # relaunch worker:7 (its executor dies: no more heartbeats from it)
    victim = 7
    t = session.relaunch_task("worker", victim)
    assert t.attempt == 1 and session.spec_generation == 2
    # the replacement registers (closing the barrier -> ONE full serve)
    repl = clients[0].register_worker_spec(
        f"worker:{victim}", f"r{victim}:20_007".replace("_", ""), 0,
        task_attempt=1, with_generation=True)
    assert repl is not None and repl[1] == 2

    # every survivor heartbeats with its held generation and applies the
    # piggybacked diff — the ONLY channel it learns the new spec from
    def _survive(i: int) -> None:
        c = clients[i % n_clients]
        gen, spec = held[i]
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                resp = c.task_executor_heartbeat(
                    f"worker:{i}", 0, spec_generation=gen)
                diff = resp.get("spec_diff")
                if diff:
                    assert not resp.get("spec_refetch")
                    spec = apply_spec_diff(spec, diff["changed"])
                    gen = diff["generation"]
                    with lock:
                        held[i] = (gen, spec)
                    return
                time.sleep(0.02)
            raise TimeoutError("diff never arrived")
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"worker:{i}: {e}")

    survivors = [i for i in range(width) if i != victim]
    threads = [threading.Thread(target=_survive, args=(i,), daemon=True)
               for i in survivors]
    for t2 in threads:
        t2.start()
    for t2 in threads:
        t2.join(timeout=60)
    assert not errors, errors[:3]

    final = session.cluster_spec_json()
    for i in survivors:
        gen, spec = held[i]
        assert gen == 2, f"worker:{i} stuck at generation {gen}"
        assert json.dumps(spec) == final, f"worker:{i} diverged"
    # THE acceptance number: zero full-spec re-fetches after the initial
    # rendezvous beyond the replacement's own barrier release
    assert session.spec_stats["full_serves"] == width + 1, \
        session.spec_stats
    assert session.spec_stats["diff_serves"] == len(survivors)
    # O(width**2) -> O(width): the diff fan-out cost a tiny fraction of
    # re-serving the full spec to every survivor
    full_len = len(final)
    assert session.spec_stats["diff_bytes"] < 0.1 * full_len * len(survivors)

    monitor.stop()
    server.stop(grace=0)
    for c in clients:
        c.close()
