"""Mesh / sharding / ring-attention tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu.ops.attention import reference_attention
from tony_tpu.parallel import (
    MeshPlan, logical_to_mesh_axes, make_mesh, mesh_from_env, plan_mesh,
    shard_pytree,
)
from tony_tpu.parallel.ring import ring_attention_sharded


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8, (
        "conftest must force xla_force_host_platform_device_count=8")


def test_plan_mesh_factoring():
    plan = plan_mesh(8, tp=2)
    assert plan.shape == {"dp": 1, "fsdp": 4, "tp": 2, "sp": 1, "pp": 1,
                          "ep": 1}
    assert plan.num_devices == 8
    plan = plan_mesh(8, tp=2, sp=2, dp=2)
    assert plan.shape["fsdp"] == 1
    with pytest.raises(ValueError):
        plan_mesh(8, tp=3)


def test_make_mesh_axis_names():
    mesh = make_mesh(plan_mesh(8, tp=2, sp=2))
    assert mesh.axis_names == ("dp", "fsdp", "tp", "sp", "pp", "ep")
    assert mesh.devices.size == 8


def test_mesh_from_env(monkeypatch):
    monkeypatch.setenv("TPU_MESH_SHAPE", "2,2,2")
    monkeypatch.setenv("TPU_MESH_AXES", "dp,fsdp,tp")
    mesh = mesh_from_env()
    assert mesh.axis_names == ("dp", "fsdp", "tp")
    monkeypatch.delenv("TPU_MESH_SHAPE")
    monkeypatch.delenv("TPU_MESH_AXES")
    mesh = mesh_from_env()
    assert mesh.shape["fsdp"] == 8


def test_logical_rules():
    mesh = make_mesh(plan_mesh(8, tp=2))
    assert logical_to_mesh_axes(("vocab", "embed"), mesh=mesh) == P("tp", "fsdp")
    assert logical_to_mesh_axes(("norm",), mesh=mesh) == P()
    # axes absent from the mesh fall back to replication
    small = make_mesh(MeshPlan({"dp": 8}))
    assert logical_to_mesh_axes(("vocab", "embed"), mesh=small) == P()


def test_shard_pytree_places_shards():
    mesh = make_mesh(plan_mesh(8, tp=2))
    tree = {"w": jnp.zeros((16, 32)), "b": jnp.zeros((32,))}
    logical = {"w": ("embed", "mlp"), "b": ("norm",)}
    sharded = shard_pytree(tree, logical, mesh)
    w_shard = sharded["w"].sharding
    assert isinstance(w_shard, NamedSharding)
    assert w_shard.spec == P("fsdp", "tp")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Sequence sharded over sp=4: ring result == unsharded full attention."""
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 2, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 2, 128, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    """All-to-all SP: head-sharded local attention == full attention."""
    from tony_tpu.parallel.ulysses import ulysses_attention_sharded
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 4, 256, 32   # h divisible by sp=4
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_attention_differentiable():
    from tony_tpu.parallel.ulysses import ulysses_attention_sharded
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 4, 128, 32
    ks = jax.random.split(jax.random.PRNGKey(12), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)

    def loss_u(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gu, gr in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4)


def test_ulysses_rejects_indivisible_heads():
    from tony_tpu.parallel.ulysses import ulysses_attention_sharded
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    q = jnp.zeros((1, 3, 64, 8))   # 3 heads, sp=4
    with pytest.raises(Exception):
        ulysses_attention_sharded(q, q, q, mesh)


def test_hybrid_mesh_orders_slices_outermost():
    """Fake multi-slice devices: dp (outermost) must span slices so only
    data-parallel traffic crosses DCN."""
    from tony_tpu.parallel.mesh import make_hybrid_mesh

    class FakeDev:
        def __init__(self, i, s):
            self.id = i
            self.slice_index = s

        def __repr__(self):
            return f"d{self.id}s{self.slice_index}"

    # 2 slices x 4 devices, interleaved enumeration order
    devs = [FakeDev(i, i % 2) for i in range(8)]
    plan = plan_mesh(8, tp=2, dp=2, fsdp=2)
    mesh_grid = make_hybrid_mesh(plan, devs)
    grid = mesh_grid.devices  # (dp=2, fsdp=2, tp=2, sp=1, pp=1, ep=1)
    flat_dp0 = grid[0].flatten()
    flat_dp1 = grid[1].flatten()
    assert {d.slice_index for d in flat_dp0} == {0}
    assert {d.slice_index for d in flat_dp1} == {1}


def test_hybrid_mesh_single_slice_falls_back():
    from tony_tpu.parallel.mesh import make_hybrid_mesh
    mesh = make_hybrid_mesh(plan_mesh(8, tp=2))
    assert mesh.devices.size == 8


def test_opt_state_specs_shards_masters_and_moments():
    """Optimizer state (f32 masters, Adam mu/nu) must carry the params'
    partition specs; counts/scalars replicate. Propagation alone left the
    moments replicated on the v5p AOT compile — 64 GB/chip at 8B."""
    import optax

    from tony_tpu.parallel.sharding import (
        make_partition_spec, opt_state_specs,
    )
    from tony_tpu.train.precision import with_f32_master

    params = {"embed": jnp.zeros((16, 8)),
              "layers": {"wq": jnp.zeros((4, 8, 8))}}
    axes = {"embed": ("vocab", "embed"),
            "layers": {"wq": ("layers", "embed", "heads")}}
    mesh = make_mesh(plan_mesh(8, tp=2, fsdp=2))
    with jax.set_mesh(mesh):
        pspecs = make_partition_spec(axes, mesh=mesh)
        opt = with_f32_master(optax.adamw(1e-3))
        shapes = jax.eval_shape(opt.init, params)
        ospecs = opt_state_specs(shapes, pspecs)
    # master mirrors params
    assert ospecs["master"]["embed"] == pspecs["embed"]
    assert ospecs["master"]["layers"]["wq"] == pspecs["layers"]["wq"]
    # adam moments (inside the inner chain) mirror params too
    flat = jax.tree_util.tree_leaves_with_path(ospecs["inner"])
    matched = [s for path, s in flat
               if "embed" in str(path) and s == pspecs["embed"]]
    assert len(matched) >= 2, "mu and nu must both carry the embed spec"
    # the adam count leaf specifically must replicate (not inherit some
    # param spec through a bogus suffix match)
    counts = [s for path, s in flat if "count" in str(path).lower()]
    assert counts and all(s == jax.P() for s in counts), counts


def test_trainer_opt_state_sharded_on_mesh(tmp_path, monkeypatch):
    """End-to-end: Trainer's opt state lands sharded (not replicated) on
    the mesh for a model with sharding rules."""
    from functools import partial

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_param_axes,
    )
    from tony_tpu.train.trainer import Trainer, TrainerConfig

    monkeypatch.setenv("TPU_MESH_SHAPE", "2,2")
    monkeypatch.setenv("TPU_MESH_AXES", "fsdp,tp")
    config = get_config("tiny")
    cfg = TrainerConfig(num_steps=1, master_weights=True)

    def data():
        while True:
            yield {"tokens": jnp.zeros((4, 65), jnp.int32)}

    t = Trainer(partial(llama_loss, config=config),
                partial(llama_init, config),
                data(), cfg, param_axes=llama_param_axes(config))
    t.setup()
    try:
        master_embed = t.opt_state["master"]["embed"]
        spec = master_embed.sharding.spec
        assert any(ax is not None for ax in spec), (
            f"master embed replicated: {spec}")
    finally:
        # setup() started the prefetch pipeline; without a run() (whose
        # finally owns the close) the thread would outlive this test and
        # trip test_prefetch's leak detector later in the process
        t._global_data_iter.close()


def test_ring_attention_pallas_interpret_mode(monkeypatch):
    """The ring composed with the REAL pallas kernels (interpret mode)
    inside its sp-manual region — forward + gradient parity. The CPU
    suite otherwise only exercises the ring over the blockwise branch."""
    import tony_tpu.ops.attention as att
    from tony_tpu.parallel.ring import ring_attention_sharded

    monkeypatch.setattr(att, "_FORCE", "pallas")
    monkeypatch.setattr(att, "_INTERPRET", True)
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 2, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(2), 4)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks[:3])
    g = jax.random.normal(ks[3], (b, h, s, d))
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh,
                                              causal=True) * g)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * g)

    for gr, gf in zip(jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v),
                      jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=5e-4, rtol=5e-4)


def test_ulysses_attention_pallas_interpret_mode(monkeypatch):
    """Ulysses (all-to-all SP) composed with the REAL pallas kernels in
    interpret mode — completes the interpret coverage matrix (plain,
    segmented, ring, ulysses)."""
    import tony_tpu.ops.attention as att
    from tony_tpu.parallel.ulysses import ulysses_attention_sharded

    monkeypatch.setattr(att, "_FORCE", "pallas")
    monkeypatch.setattr(att, "_INTERPRET", True)
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    b, h, s, d = 2, 4, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
