"""Pure-Python proxy relay tests — NO native toolchain required.

These cover the fallback relay path used exactly when g++/make are absent,
so they must not live under test_native.py's module-level skipif (review
finding). The native relay's equivalent behavior is tested there.
"""

import socket
import time

from conftest import recv_all as _recv_all  # shared relay-test helpers
from tony_tpu.proxy import ProxyServer, auth_preamble


def _conn(port):
    return socket.create_connection(("127.0.0.1", port), timeout=5)


def test_python_proxy_relays_without_token(echo_server):
    proxy = ProxyServer("127.0.0.1", echo_server)
    proxy.start()
    try:
        with _conn(proxy.local_port) as s:
            s.sendall(b"hello relay")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"HELLO RELAY"
    finally:
        proxy.stop()


def test_python_proxy_token_auth(echo_server):
    """VERDICT-r2 item 6 on the Python fallback relay: unauthenticated
    connections forward nothing; preamble/HTTP auth both work; one success
    unlocks the source for the grace window."""
    proxy = ProxyServer("127.0.0.1", echo_server, token="tok123")
    proxy.start()
    try:
        with _conn(proxy.local_port) as s:
            s.sendall(b"no auth\n")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
        with _conn(proxy.local_port) as s:
            req = (b"GET / HTTP/1.1\r\nHost: x\r\n"
                   b"Authorization: Bearer wrong\r\n\r\n")
            s.sendall(req)
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
        # non-ASCII garbage rejects cleanly, never crashes the handler
        # (hmac.compare_digest TypeErrors on non-ASCII str operands)
        with _conn(proxy.local_port) as s:
            s.sendall(b"TONY-PROXY-AUTH \xe9\xff\n")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
        with _conn(proxy.local_port) as s:
            s.sendall(b"GET /?tony-proxy-token=\xe9 HTTP/1.1\r\n"
                      b"Host: x\r\n\r\n")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
        # plain ?token= belongs to the proxied app (e.g. Jupyter's login
        # token), never to the proxy
        with _conn(proxy.local_port) as s:
            s.sendall(b"GET /?token=tok123 HTTP/1.1\r\nHost: x\r\n\r\n")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
        with _conn(proxy.local_port) as s:
            s.sendall(auth_preamble("tok123") + b"hello")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"HELLO"
        # grace window: same source now relays without credentials
        with _conn(proxy.local_port) as s:
            s.sendall(b"bare after unlock")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"BARE AFTER UNLOCK"
        # a preamble sent DURING the grace window is still consumed and
        # verified — the token line must never reach the upstream as
        # payload (review finding)
        with _conn(proxy.local_port) as s:
            s.sendall(auth_preamble("tok123") + b"again")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"AGAIN"
        # ...and a WRONG preamble under grace is rejected, not relayed
        with _conn(proxy.local_port) as s:
            s.sendall(b"TONY-PROXY-AUTH wrong\npayload")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
    finally:
        proxy.stop()


def test_python_proxy_http_auth_modes(echo_server):
    """Header and query-string HTTP auth, each on a fresh proxy (so the
    grace unlock from one case can't mask the next)."""
    for req in (
            b"GET / HTTP/1.1\r\nHost: x\r\n"
            b"Authorization: Bearer tok123\r\n\r\n",
            b"GET /tree?a=b&tony-proxy-token=tok123 HTTP/1.1\r\n"
            b"Host: x\r\n\r\n"):
        proxy = ProxyServer("127.0.0.1", echo_server, token="tok123")
        proxy.start()
        try:
            with _conn(proxy.local_port) as s:
                s.sendall(req)
                s.shutdown(socket.SHUT_WR)
                assert _recv_all(s) == req.upper()   # forwarded unmodified
        finally:
            proxy.stop()


def test_python_proxy_grace_not_extended_by_bare_conns(echo_server,
                                                       monkeypatch):
    """Only AUTHENTICATED connections slide the unlock window — an
    unauthenticated poller must not hold it open past expiry (review
    finding). Window is 3s with probes at ~1s/2s for CI-load slack."""
    import tony_tpu.proxy as proxy_mod

    monkeypatch.setattr(proxy_mod, "_GRACE_SEC", 3.0)
    proxy = ProxyServer("127.0.0.1", echo_server, token="tok123")
    proxy.start()
    try:
        t0 = time.monotonic()
        with _conn(proxy.local_port) as s:
            s.sendall(auth_preamble("tok123") + b"a")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"A"
        # bare connections inside the window relay but must not extend it
        for target in (1.0, 2.0):
            time.sleep(max(0.0, t0 + target - time.monotonic()))
            with _conn(proxy.local_port) as s:
                s.sendall(b"bare")
                s.shutdown(socket.SHUT_WR)
                assert _recv_all(s) == b"BARE"
        time.sleep(max(0.0, t0 + 3.6 - time.monotonic()))   # expired
        with _conn(proxy.local_port) as s:
            s.sendall(b"bare late\n")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
    finally:
        proxy.stop()


def test_python_proxy_waits_for_late_upstream():
    """The upstream may register its URL before its server binds (notebook
    bring-up gap): connections arriving in that window must be relayed once
    the server appears, not dropped on first ECONNREFUSED."""
    import threading

    # reserve a port nobody is listening on yet
    placeholder = socket.socket()
    placeholder.bind(("127.0.0.1", 0))
    port = placeholder.getsockname()[1]
    placeholder.close()

    proxy = ProxyServer("127.0.0.1", port, connect_wait_sec=8.0)
    proxy.start()

    def bind_late():
        time.sleep(1.0)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        conn.sendall(_recv_all(conn).upper())
        conn.shutdown(socket.SHUT_WR)
        conn.close()
        srv.close()

    t = threading.Thread(target=bind_late, daemon=True)
    t.start()
    try:
        with _conn(proxy.local_port) as s:
            s.sendall(b"late bind")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"LATE BIND"
    finally:
        proxy.stop()
        t.join(timeout=10)


def test_python_proxy_accepts_any_named_token(echo_server):
    """Multi-principal auth: the proxy takes a set of named tokens and any
    of them authenticates (portal scopes visibility; the proxy gates the
    byte stream)."""
    proxy = ProxyServer("127.0.0.1", echo_server,
                        token=["tok-alice", "tok-bob"])
    proxy.start()
    try:
        for tok in ("tok-alice", "tok-bob"):
            with _conn(proxy.local_port) as s:
                s.sendall(auth_preamble(tok) + b"hi")
                s.shutdown(socket.SHUT_WR)
                assert _recv_all(s) == b"HI"
        with _conn(proxy.local_port) as s:
            s.sendall(auth_preamble("tok-mallory") + b"hi")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
    finally:
        proxy.stop()
