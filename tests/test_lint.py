"""tonylint: the control-plane static-analysis pass (tools/tonylint/).

Three layers:

1. engine semantics — suppression syntax, shrink-only baseline,
   ``--changed`` against a synthetic git diff, output shapes;
2. per-rule fixtures — for every shipped rule: one offending snippet
   (fires), one clean snippet (silent), one suppressed snippet (silent,
   counted as suppressed);
3. the acceptance run — the full engine over tony_tpu/ at HEAD must be
   clean (modulo the checked-in, shrink-only baseline) and fast (<10 s
   — it IS a tier-1 test).

The legacy regex checks that tonylint subsumed keep one-line wrappers in
tests/test_logs.py / test_fleet.py / test_alerts.py, so tier-1 coverage
is unchanged.
"""

import json
import os
import subprocess
import time

import pytest

from tools.tonylint import (default_rules, findings_for, lint_repo,
                            repo_root, save_baseline)
from tools.tonylint.engine import (Project, apply_baseline, discover_files,
                                   load_baseline, run_rules)
from tools.tonylint.rules_conf import ConfigKeyRegistryRule
from tools.tonylint.rules_legacy import (AlertHotLoopRule,
                                         AlertRuleRegistryRule,
                                         GaugeRegistryRule, PrintBanRule,
                                         RendererCoverageRule)
from tools.tonylint.rules_locks import GuardedByRule, NoBlockingUnderLockRule
from tools.tonylint.rules_rpc import (AttemptFencingRule, RedactOnEgressRule,
                                      TracePropagationRule)
from tools.tonylint.rules_threads import ThreadHygieneRule

pytestmark = pytest.mark.lint

REPO = repo_root()


def _project(tmp_path, files: dict[str, str]) -> Project:
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    rels = [rel for rel in files if rel.endswith(".py")]
    return Project(str(tmp_path), rels)


def _run(tmp_path, files: dict[str, str], rules) -> list:
    report = run_rules(_project(tmp_path, files), list(rules))
    return report.findings


def _rule_ids(findings) -> list[str]:
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------

GUARDED_OFFENDER = '''
import threading

class Store:
    def __init__(self):
        self._table = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def get(self, k):
        return self._table.get(k)
'''

GUARDED_CLEAN = '''
import threading

class Store:
    def __init__(self):
        self._table = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def get(self, k):
        with self._lock:
            return self._table.get(k)

    # holds: _lock (caller contract)
    def _get_locked(self, k):
        return self._table.get(k)
'''

GUARDED_SUPPRESSED = '''
import threading

class Store:
    def __init__(self):
        self._table = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def peek(self, k):
        # tony: disable=guarded-by -- lock-free fast path, re-checked under lock
        return self._table.get(k)
'''


def test_guarded_by_fires_on_unlocked_access(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/am/s.py": GUARDED_OFFENDER},
                    [GuardedByRule()])
    assert _rule_ids(findings) == ["guarded-by"]
    assert "_table" in findings[0].message


def test_guarded_by_silent_on_locked_access_and_holds_contract(tmp_path):
    assert _run(tmp_path, {"tony_tpu/am/s.py": GUARDED_CLEAN},
                [GuardedByRule()]) == []


def test_guarded_by_suppressed(tmp_path):
    project = _project(tmp_path, {"tony_tpu/am/s.py": GUARDED_SUPPRESSED})
    report = run_rules(project, [GuardedByRule()])
    assert report.findings == []
    assert report.suppressed == 1


def test_guarded_by_checks_methods_that_redeclare(tmp_path):
    """A method that RE-assigns an annotated attribute is still checked —
    resetting guarded state without the lock is exactly the bug class the
    rule exists for (it must not exempt the whole method)."""
    src = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def reset(self):
        self._table = {}  # guarded-by: _lock
        self._count = 0
'''
    findings = _run(tmp_path, {"tony_tpu/am/s.py": src}, [GuardedByRule()])
    # both the unlocked re-declaration and the sibling write fire
    assert _rule_ids(findings) == ["guarded-by", "guarded-by"]
    assert {f.line for f in findings} == {11, 12}


def test_guarded_by_not_satisfied_by_another_objects_lock(tmp_path):
    """Holding a DIFFERENT object's same-named lock must not silence the
    rule — every class in this codebase calls its lock `_lock`, so the
    wrong-receiver case is exactly the missed-lock bug class (PR 11's
    note_full_serve) the rule exists for."""
    src = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}  # guarded-by: _lock
        self.peer = None

    def bad(self, k, v):
        with self.peer._lock:
            self._jobs[k] = v

    def good(self, k, v):
        with self._lock:
            self._jobs[k] = v
'''
    findings = _run(tmp_path, {"tony_tpu/am/s.py": src}, [GuardedByRule()])
    assert _rule_ids(findings) == ["guarded-by"]
    assert findings[0].line == 12


def test_guarded_by_subscripted_lock_table(tmp_path):
    src = '''
import threading

class Sharded:
    def __init__(self):
        # guarded-by: _locks
        self._shards = [{} for _ in range(4)]
        self._locks = [threading.Lock() for _ in range(4)]

    def good(self, idx, k):
        with self._locks[idx]:
            return self._shards[idx].get(k)

    def bad(self):
        return sum(len(s) for s in self._shards)
'''
    findings = _run(tmp_path, {"tony_tpu/am/shard.py": src},
                    [GuardedByRule()])
    assert len(findings) == 1 and findings[0].rule == "guarded-by"


# ---------------------------------------------------------------------------
# no-blocking-under-lock
# ---------------------------------------------------------------------------

BLOCKING_OFFENDER = '''
import threading
import time

class Sweeper:
    def __init__(self):
        self._lock = threading.Lock()

    def sweep(self):
        with self._lock:
            time.sleep(0.1)
'''

BLOCKING_CLEAN = '''
import threading
import time

class Sweeper:
    def __init__(self):
        self._lock = threading.Lock()

    def sweep(self):
        with self._lock:
            items = [1]
        time.sleep(0.1)
        return items
'''


def test_no_blocking_under_lock_fires_on_sleep(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/am/x.py": BLOCKING_OFFENDER},
                    [NoBlockingUnderLockRule()])
    assert _rule_ids(findings) == ["no-blocking-under-lock"]


def test_no_blocking_under_lock_silent_outside_lock(tmp_path):
    assert _run(tmp_path, {"tony_tpu/am/x.py": BLOCKING_CLEAN},
                [NoBlockingUnderLockRule()]) == []


def test_no_blocking_under_lock_suppressed_and_rpc_methods(tmp_path):
    src = '''
import threading

class AM:
    def __init__(self, backend):
        self._lock = threading.Lock()
        self.backend = backend

    def drain(self):
        with self._lock:
            # tony: disable=no-blocking-under-lock -- justified here
            self.backend.stop_container("c1")

    def drain2(self):
        with self._lock:
            self.backend.stop_container("c2")

    def local_ok(self):
        with self._lock:
            self.update_metrics({})

    def update_metrics(self, req):
        return {}
'''
    project = _project(tmp_path, {"tony_tpu/am/y.py": src})
    report = run_rules(project, [NoBlockingUnderLockRule()])
    # drain2 fires (RPC-backed container stop under lock); drain is
    # suppressed; the direct self.update_metrics local call never fires
    assert len(report.findings) == 1
    assert report.findings[0].line and report.suppressed == 1


# ---------------------------------------------------------------------------
# attempt-fencing
# ---------------------------------------------------------------------------

FENCING_OFFENDER = '''
class Handler:
    def register_execution_result(self, req):
        task = self.session.get_task_by_id(req["task_id"])
        task.completed = True
        return {}
'''

FENCING_CLEAN = '''
class Handler:
    def register_execution_result(self, req):
        task = self.session.get_task_by_id(req["task_id"])
        attempt = int(req.get("task_attempt", -1))
        if attempt >= 0 and attempt != task.attempt:
            return {}
        task.completed = True
        return {}
'''


def test_attempt_fencing_fires_on_unfenced_handler(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/am/h.py": FENCING_OFFENDER},
                    [AttemptFencingRule()])
    assert _rule_ids(findings) == ["attempt-fencing"]


def test_attempt_fencing_silent_on_fenced_handler(tmp_path):
    assert _run(tmp_path, {"tony_tpu/am/h.py": FENCING_CLEAN},
                [AttemptFencingRule()]) == []


def test_attempt_fencing_skips_abstract_and_out_of_scope(tmp_path):
    abstract = '''
import abc

class Iface(abc.ABC):
    @abc.abstractmethod
    def register_execution_result(self, req):
        """doc only"""
'''
    # abstract interface: silent; client stub dir: out of scope
    assert _run(tmp_path, {"tony_tpu/rpc/service.py": abstract,
                           "tony_tpu/rpc/client.py": FENCING_OFFENDER},
                [AttemptFencingRule()]) == []


def test_attempt_fencing_suppressed(tmp_path):
    src = FENCING_OFFENDER.replace(
        "    def register_execution_result",
        "    # tony: disable=attempt-fencing -- fenced by the caller\n"
        "    def register_execution_result")
    project = _project(tmp_path, {"tony_tpu/am/h.py": src})
    report = run_rules(project, [AttemptFencingRule()])
    assert report.findings == [] and report.suppressed == 1


# ---------------------------------------------------------------------------
# redact-on-egress
# ---------------------------------------------------------------------------

EGRESS_OFFENDER = '''
import json
import urllib.request

class PushSink:
    def deliver(self, payload):
        data = json.dumps(payload).encode()
        req = urllib.request.Request("http://hook", data=data)
        with urllib.request.urlopen(req, timeout=2):
            return True
'''

EGRESS_CLEAN = EGRESS_OFFENDER.replace(
    "data = json.dumps(payload).encode()",
    "data = json.dumps(redact_payload(payload)).encode()")


def test_redact_on_egress_fires_on_unredacted_sink(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/observability/s.py": EGRESS_OFFENDER},
                    [RedactOnEgressRule()])
    assert _rule_ids(findings) == ["redact-on-egress"]


def test_redact_on_egress_silent_when_redacted(tmp_path):
    assert _run(tmp_path, {"tony_tpu/observability/s.py": EGRESS_CLEAN},
                [RedactOnEgressRule()]) == []


def test_redact_on_egress_suppressed(tmp_path):
    src = EGRESS_OFFENDER.replace(
        "    def deliver(self, payload):",
        "    # tony: disable=redact-on-egress -- payload pre-redacted upstream\n"
        "    def deliver(self, payload):")
    project = _project(tmp_path, {"tony_tpu/observability/s.py": src})
    report = run_rules(project, [RedactOnEgressRule()])
    assert report.findings == [] and report.suppressed == 1


TRACE_EXPORT_OFFENDER = '''
class ReqCollector:
    def export(self):
        return [dict(t) for t in self._done]


def write_serving_traces_file(history_dir, traces):
    with open(history_dir + "/serving_traces.json", "w") as f:
        f.write(str(traces))
'''

TRACE_EXPORT_CLEAN = TRACE_EXPORT_OFFENDER.replace(
    "return [dict(t) for t in self._done]",
    "return redact_traces([dict(t) for t in self._done])").replace(
    "f.write(str(traces))",
    "f.write(str(redact_traces(traces)))")


def test_redact_on_egress_covers_trace_export_surfaces(tmp_path):
    """Collector export/drain snapshots and the serving-traces history
    sidecar are operator-facing egress: both must redact."""
    findings = _run(tmp_path,
                    {"tony_tpu/observability/rt.py": TRACE_EXPORT_OFFENDER},
                    [RedactOnEgressRule()])
    assert _rule_ids(findings) == ["redact-on-egress"] * 2
    msgs = " | ".join(f.message for f in findings)
    assert "request-trace payloads" in msgs and "sidecar" in msgs
    assert _run(tmp_path,
                {"tony_tpu/observability/rt.py": TRACE_EXPORT_CLEAN},
                [RedactOnEgressRule()]) == []


# ---------------------------------------------------------------------------
# trace-propagation
# ---------------------------------------------------------------------------

TRACE_PROP_OFFENDER = '''
import urllib.request


class Frontend:
    def post_handoff(self, base, payload):
        rq = urllib.request.Request(base + "/v1/migrate", data=payload,
                                    headers={"Content-Type": "a/b"})
        return urllib.request.urlopen(rq, timeout=5)
'''

TRACE_PROP_CLEAN = TRACE_PROP_OFFENDER.replace(
    'headers={"Content-Type": "a/b"}',
    'headers={"X-Tony-Trace": ctx.header_value()}')

TRACE_PROP_CLEAN_ATTR = TRACE_PROP_OFFENDER.replace(
    'headers={"Content-Type": "a/b"}',
    'headers={reqtrace.HEADER: ctx.header_value()}')


def test_trace_propagation_fires_on_dropped_header(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/serve/f.py": TRACE_PROP_OFFENDER},
                    [TracePropagationRule()])
    assert _rule_ids(findings) == ["trace-propagation"]
    assert "/v1/migrate" in findings[0].message


def test_trace_propagation_silent_when_header_forwarded(tmp_path):
    # both spellings satisfy: the literal header name or reqtrace.HEADER
    assert _run(tmp_path, {"tony_tpu/serve/f.py": TRACE_PROP_CLEAN},
                [TracePropagationRule()]) == []
    assert _run(tmp_path, {"tony_tpu/serve/f.py": TRACE_PROP_CLEAN_ATTR},
                [TracePropagationRule()]) == []


def test_trace_propagation_scoped_to_serve_and_data_plane(tmp_path):
    # outside tony_tpu/serve/: silent (webhook sinks etc. are not hops
    # of a request trace); non-data-plane URLs: silent
    assert _run(tmp_path, {"tony_tpu/am/f.py": TRACE_PROP_OFFENDER},
                [TracePropagationRule()]) == []
    other = TRACE_PROP_OFFENDER.replace("/v1/migrate", "/v1/load")
    assert _run(tmp_path, {"tony_tpu/serve/f.py": other},
                [TracePropagationRule()]) == []


def test_trace_propagation_suppressed(tmp_path):
    src = TRACE_PROP_OFFENDER.replace(
        '        rq = urllib.request.Request(',
        '        # tony: disable=trace-propagation -- loopback self-probe\n'
        '        rq = urllib.request.Request(')
    project = _project(tmp_path, {"tony_tpu/serve/f.py": src})
    report = run_rules(project, [TracePropagationRule()])
    assert report.findings == [] and report.suppressed == 1


# ---------------------------------------------------------------------------
# config-key-registry
# ---------------------------------------------------------------------------

MINI_KEYS = '''
TONY_PREFIX = "tony."
AM_MEMORY = "tony.am.memory"
UNUSED_KEY = "tony.am.unused-key"

RESERVED_SEGMENTS = frozenset({"am", "task", "queues"})


def jobtype_key(jobtype, attr):
    return f"{TONY_PREFIX}{jobtype}.{attr}"


def instances_key(jobtype):
    return jobtype_key(jobtype, "instances")


def queue_max_tpus_key(queue):
    return f"tony.queues.{queue}.max-tpus"
'''

MINI_DOCS = "| `tony.am.memory` | `'2g'` |\n"


def _conf_files(user_src: str) -> dict[str, str]:
    return {"tony_tpu/conf/keys.py": MINI_KEYS,
            "tony_tpu/am/user.py": user_src,
            "docs/configuration.md": MINI_DOCS}


def test_config_key_registry_fires_on_stray_and_reserved(tmp_path):
    user = '''
A = "tony.am.memory"          # registered: fine
B = "tony.worker.instances"   # dynamic jobtype shape: fine
C = "tony.queues.qa.max-tpus" # dynamic queue shape: fine
D = "tony.task.comand"        # reserved segment typo: FIRES
E = "tony.made.up-key"        # unknown shape: FIRES
'''
    findings = _run(tmp_path, _conf_files(user), [ConfigKeyRegistryRule()])
    msgs = " | ".join(f.message for f in findings)
    assert "tony.task.comand" in msgs and "tony.made.up-key" in msgs
    # UNUSED_KEY is defined but never referenced, and undocumented
    assert sum("UNUSED_KEY" in f.message for f in findings) == 2
    assert len(findings) == 4


def test_config_key_registry_clean(tmp_path):
    user = 'A = "tony.am.memory"\nB = UNUSED_KEY\n'
    docs = MINI_DOCS + "| `tony.am.unused-key` | x |\n"
    files = _conf_files(user)
    files["docs/configuration.md"] = docs
    assert _run(tmp_path, files, [ConfigKeyRegistryRule()]) == []


def test_config_key_registry_suppressed(tmp_path):
    user = ('# tony: disable=config-key-registry -- not a conf key\n'
            'D = "tony.not.a-key"\nB = UNUSED_KEY\nA = AM_MEMORY\n')
    files = _conf_files(user)
    files["docs/configuration.md"] = (
        MINI_DOCS + "| `tony.am.unused-key` | x |\n")
    project = _project(tmp_path, files)
    report = run_rules(project, [ConfigKeyRegistryRule()])
    assert report.findings == [] and report.suppressed == 1


# ---------------------------------------------------------------------------
# thread-hygiene
# ---------------------------------------------------------------------------

THREAD_OFFENDER = '''
import threading


def fire_and_forget(fn):
    threading.Thread(target=fn).start()


def swallow():
    try:
        fn()
    except Exception:
        pass


def bare():
    try:
        fn()
    except:
        return None
'''

THREAD_CLEAN = '''
import logging
import threading

LOG = logging.getLogger(__name__)


class Worker:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)

    def stop(self):
        self._thread.join(timeout=2)


def careful():
    try:
        fn()
    except OSError:
        pass  # narrow catch on a best-effort path: deliberate
    try:
        fn()
    except Exception:
        LOG.debug("fn failed", exc_info=True)
'''


def test_thread_hygiene_fires(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/am/t.py": THREAD_OFFENDER},
                    [ThreadHygieneRule()])
    assert _rule_ids(findings) == ["thread-hygiene"] * 3


def test_thread_hygiene_clean(tmp_path):
    assert _run(tmp_path, {"tony_tpu/am/t.py": THREAD_CLEAN},
                [ThreadHygieneRule()]) == []


def test_thread_subclass_not_fooled_by_str_join_or_daemon_comment(tmp_path):
    """The daemon/join evidence is AST shape, not text: a `", ".join(...)`
    in the module or a comment mentioning 'daemon' must not satisfy the
    subclass check, while `self.daemon = True` / a real `.join()` do."""
    offender = '''
import threading

class W(threading.Thread):
    # not a daemon on purpose? then someone must join it
    def run(self):
        print(", ".join(["a", "b"]))
'''
    findings = _run(tmp_path, {"tony_tpu/am/w.py": offender},
                    [ThreadHygieneRule()])
    assert "W(threading.Thread)" in findings[0].message
    clean_daemon = offender.replace(
        "    def run(self):",
        "    def __init__(self):\n"
        "        super().__init__(daemon=True)\n\n"
        "    def run(self):")
    assert _run(tmp_path, {"tony_tpu/am/w.py": clean_daemon},
                [ThreadHygieneRule()]) == []
    clean_joined = offender + "\n\ndef stop(w):\n    w.join(timeout=2)\n"
    assert _run(tmp_path, {"tony_tpu/am/w.py": clean_joined},
                [ThreadHygieneRule()]) == []
    # a VARIABLE-receiver string join (`sep.join(parts)`) is not reaping
    # evidence either — str.join always takes an iterable positional
    # arg, Thread.join never does
    var_join = offender + "\n\ndef render(sep, parts):\n" \
                          "    return sep.join(parts)\n"
    findings = _run(tmp_path, {"tony_tpu/am/w.py": var_join},
                    [ThreadHygieneRule()])
    assert "W(threading.Thread)" in findings[0].message


def test_thread_daemon_set_after_construction_is_clean(tmp_path):
    """`t = Thread(...); t.daemon = True; t.start()` is the stdlib's own
    documented idiom — it must not fire. Only a literal True counts:
    `t.daemon = False` is an explicit non-daemon and still fires."""
    clean = '''
import threading

def spin(fn):
    t = threading.Thread(target=fn)
    t.daemon = True
    t.start()

class Mgr:
    def start(self):
        self._worker = threading.Thread(target=self._run)
        self._worker.setDaemon(True)
        self._worker.start()
'''
    assert _run(tmp_path, {"tony_tpu/am/d.py": clean},
                [ThreadHygieneRule()]) == []
    explicit_non_daemon = clean.replace("t.daemon = True",
                                        "t.daemon = False")
    findings = _run(tmp_path, {"tony_tpu/am/d.py": explicit_non_daemon},
                    [ThreadHygieneRule()])
    assert _rule_ids(findings) == ["thread-hygiene"]


def test_thread_join_evidence_is_ast_not_text(tmp_path):
    """A comment or log string mentioning `.join(` must not exempt a
    directly-constructed non-daemon thread; a real `.join()` call on the
    assignment target does."""
    offender = '''
import threading

class Mgr:
    def start(self):
        # the caller must self._worker.join() eventually
        self._worker = threading.Thread(target=self._run)
        self._worker.start()
'''
    findings = _run(tmp_path, {"tony_tpu/am/m.py": offender},
                    [ThreadHygieneRule()])
    assert _rule_ids(findings) == ["thread-hygiene"]
    joined = offender + "\n    def stop(self):\n        self._worker.join()\n"
    assert _run(tmp_path, {"tony_tpu/am/m.py": joined},
                [ThreadHygieneRule()]) == []


def test_thread_hygiene_suppressed(tmp_path):
    src = THREAD_OFFENDER.replace(
        "    threading.Thread(target=fn).start()",
        "    # tony: disable=thread-hygiene -- reaped by the harness\n"
        "    threading.Thread(target=fn).start()").replace(
        "    except Exception:",
        "    # tony: disable=thread-hygiene -- nothing to log mid-exit\n"
        "    except Exception:").replace(
        "    except:",
        "    # tony: disable=thread-hygiene -- legacy shim\n"
        "    except:")
    project = _project(tmp_path, {"tony_tpu/am/t.py": src})
    report = run_rules(project, [ThreadHygieneRule()])
    assert report.findings == [] and report.suppressed == 3


# ---------------------------------------------------------------------------
# migrated legacy rules (fixture level; the original test files keep
# one-line wrappers running these over the real repo)
# ---------------------------------------------------------------------------

def test_print_ban_fires_and_log_ok_escapes(tmp_path):
    src = '''
def noisy():
    print("hello")


def marker():
    # log-ok: deliberate greppable bring-up line
    print("BRINGUP host ready")
'''
    findings = _run(tmp_path, {"tony_tpu/am/p.py": src}, [PrintBanRule()])
    assert len(findings) == 1 and findings[0].line == 3
    # out-of-scope dirs (train/) are not print-banned
    assert _run(tmp_path, {"tony_tpu/train/p.py": src},
                [PrintBanRule()]) == []


def test_print_ban_suppressed(tmp_path):
    src = ('def noisy():\n'
           '    # tony: disable=print-ban -- CLI surface\n'
           '    print("hello")\n')
    project = _project(tmp_path, {"tony_tpu/serve/p.py": src})
    report = run_rules(project, [PrintBanRule()])
    assert report.findings == [] and report.suppressed == 1


def test_gauge_registry_fixture(tmp_path):
    am = '''
GOOD = "tony_job_goodput_pct"
BAD = "tony_job_not_registered"
name = f"tony_job_{suffix}"
'''
    rule = GaugeRegistryRule(job_gauges={"tony_job_goodput_pct"},
                             step_time_gauges={})
    findings = _run(
        tmp_path, {"tony_tpu/am/application_master.py": am}, [rule])
    msgs = " | ".join(f.message for f in findings)
    assert "tony_job_not_registered" in msgs
    assert "f-string" in msgs
    assert len(findings) == 2
    # clean AM: silent
    rule2 = GaugeRegistryRule(job_gauges={"tony_job_goodput_pct"},
                              step_time_gauges={})
    assert _run(tmp_path, {
        "tony_tpu/am/application_master.py": 'G = "tony_job_goodput_pct"\n'},
        [rule2]) == []


def test_alert_rule_registry_fixture(tmp_path):
    am = 'RULES = ["train.goodput_floor", "train.not_a_rule"]\n'
    rule = AlertRuleRegistryRule(builtin_rules={"train.goodput_floor"})
    findings = _run(
        tmp_path, {"tony_tpu/am/application_master.py": am}, [rule])
    assert len(findings) == 1 and "train.not_a_rule" in findings[0].message


def test_alert_hot_loop_fixture(tmp_path):
    files = {
        "tony_tpu/am/application_master.py": "def _check_alerts(): pass\n",
        "tony_tpu/observability/fleet.py":
            "x = 'alert_engine.evaluate'\n",
        "tony_tpu/train/hot.py": "from x import AlertEngine\n",
    }
    findings = _run(tmp_path, files, [AlertHotLoopRule()])
    assert len(findings) == 1
    assert findings[0].path == "tony_tpu/train/hot.py"
    files["tony_tpu/train/hot.py"] = "pass\n"
    assert _run(tmp_path, files, [AlertHotLoopRule()]) == []


def test_renderer_coverage_fires_on_missing_renderer(monkeypatch):
    from tony_tpu.events import render
    missing = dict(render.RENDERERS)
    removed = next(iter(missing))
    del missing[removed]
    monkeypatch.setattr(render, "RENDERERS", missing)
    project = Project(REPO, ["tony_tpu/events/render.py"])
    report = run_rules(project, [RendererCoverageRule()])
    assert any(removed.value in f.message for f in report.findings)


# ---------------------------------------------------------------------------
# engine semantics: suppressions, baseline, --changed, output
# ---------------------------------------------------------------------------

def test_baseline_shrink_only_semantics(tmp_path):
    offender = {"tony_tpu/am/s.py": GUARDED_OFFENDER}
    findings = _run(tmp_path, offender, [GuardedByRule()])
    assert len(findings) == 1
    baseline_path = tmp_path / "baseline.json"
    save_baseline(str(baseline_path), findings, why="fixture debt")
    baseline = load_baseline(str(baseline_path))
    # exact coverage: accepted as debt, nothing new, nothing stale
    new, stale = apply_baseline(findings, baseline)
    assert new == [] and stale == []
    # a SECOND finding in the same bucket is new debt -> fails
    twice = findings + findings
    new, stale = apply_baseline(twice, baseline)
    assert len(new) == 1 and stale == []
    # the finding was fixed but the entry remains -> stale -> fails
    new, stale = apply_baseline([], baseline)
    assert new == [] and len(stale) == 1 and "shrink" in stale[0]


def test_checked_in_baseline_is_loadable_and_documented():
    baseline = load_baseline(os.path.join(REPO, "tools",
                                          "lint_baseline.json"))
    # every entry (if any) carries a one-line justification
    for key, entry in baseline.items():
        assert "::" in key
        assert entry.get("why"), f"baseline entry {key} has no justification"
        assert int(entry.get("count", 0)) >= 1


def test_changed_mode_against_synthetic_git_diff(tmp_path):
    """--changed restricts per-file rules to git-touched files;
    project-wide rules still run."""
    repo = tmp_path / "repo"
    (repo / "tony_tpu" / "am").mkdir(parents=True)
    (repo / "tony_tpu" / "am" / "a.py").write_text(GUARDED_OFFENDER)
    (repo / "tony_tpu" / "am" / "b.py").write_text(
        GUARDED_OFFENDER.replace("Store", "Other"))
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=repo, env=env, check=True,
                       capture_output=True)
    # touch ONLY b.py
    (repo / "tony_tpu" / "am" / "b.py").write_text(
        GUARDED_OFFENDER.replace("Store", "Other") + "\n# touched\n")
    report = lint_repo(str(repo), rules=[GuardedByRule()],
                       changed=True, baseline_path=os.devnull)
    assert {f.path for f in report.findings} == {"tony_tpu/am/b.py"}
    # without --changed both files fire
    report = lint_repo(str(repo), rules=[GuardedByRule()],
                       changed=False, baseline_path=os.devnull)
    assert {f.path for f in report.findings} == {"tony_tpu/am/a.py",
                                                 "tony_tpu/am/b.py"}


def test_changed_mode_with_root_below_git_toplevel(tmp_path):
    """A project root NESTED below the git toplevel (vendored checkout)
    must still match its touched files — without `git diff --relative`
    the diff emits toplevel-relative paths that never intersect the
    project relpaths, and the gate silently checks zero files."""
    (tmp_path / "vendor" / "tony_tpu" / "am").mkdir(parents=True)
    target = tmp_path / "vendor" / "tony_tpu" / "am" / "a.py"
    target.write_text(GUARDED_CLEAN)
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=tmp_path, env=env, check=True,
                       capture_output=True)
    target.write_text(GUARDED_OFFENDER)
    report = lint_repo(str(tmp_path / "vendor"), rules=[GuardedByRule()],
                       changed=True, baseline_path=os.devnull)
    assert {f.path for f in report.findings} == {"tony_tpu/am/a.py"}


def test_update_baseline_rejects_any_subset_scan(tmp_path):
    """--update-baseline with --changed, --rules, or a positional path
    subset would rewrite the WHOLE baseline from a partial scan,
    silently deleting every unscanned bucket's accepted debt — all
    three exit 2 without touching the file."""
    from tools.tonylint.__main__ import main
    (tmp_path / "tony_tpu" / "am").mkdir(parents=True)
    (tmp_path / "tony_tpu" / "am" / "s.py").write_text(GUARDED_OFFENDER)
    for extra in (["--changed"], ["--rules", "guarded-by"], ["tony_tpu/am"]):
        assert main(["--root", str(tmp_path), "--update-baseline",
                     *extra]) == 2
    assert not (tmp_path / "tools" / "lint_baseline.json").exists()


def test_update_baseline_preserves_hand_written_why(tmp_path):
    """The documented workflow adds one-line justifications by hand
    after generation; a later full --update-baseline (debt shrank
    elsewhere) must keep the surviving buckets' `why`."""
    from tools.tonylint.engine import Finding
    path = str(tmp_path / "baseline.json")
    f = Finding("guarded-by", "tony_tpu/am/s.py", 9, "msg")
    save_baseline(path, [f])
    data = json.loads(open(path).read())
    data["entries"][f.key]["why"] = "lock-free fast path, re-checked"
    with open(path, "w") as fh:
        json.dump(data, fh)
    save_baseline(path, [f])
    kept = json.loads(open(path).read())["entries"][f.key]["why"]
    assert kept == "lock-free fast path, re-checked"


def test_report_shapes_and_cli_exit_codes(tmp_path):
    (tmp_path / "tony_tpu" / "am").mkdir(parents=True)
    (tmp_path / "tony_tpu" / "am" / "s.py").write_text(GUARDED_OFFENDER)
    report = lint_repo(str(tmp_path), rules=[GuardedByRule()],
                       baseline_path=os.devnull)
    assert not report.ok
    payload = report.to_dict()
    assert payload["findings"][0]["rule"] == "guarded-by"
    assert "guarded-by" in report.render()
    # CLI contract: nonzero on findings, zero when clean
    from tools.tonylint.__main__ import main
    assert main(["--root", str(tmp_path), "--rules", "guarded-by"]) == 1
    (tmp_path / "tony_tpu" / "am" / "s.py").write_text(GUARDED_CLEAN)
    assert main(["--root", str(tmp_path), "--rules", "guarded-by"]) == 0


def test_parse_error_becomes_a_finding(tmp_path):
    findings = _run(tmp_path, {"tony_tpu/am/broken.py": "def f(:\n"},
                    [GuardedByRule()])
    assert _rule_ids(findings) == ["parse-error"]


def test_crashed_rule_becomes_a_finding_not_a_traceback(tmp_path):
    """A rule that raises (e.g. a registry rule importing a syntax-broken
    live module) must surface as a finding in the report — --json
    consumers and the pre-commit gate never see a raw traceback."""
    from tools.tonylint.engine import Rule

    class Exploding(Rule):
        id = "exploding"
        description = "always raises"

        def run(self, project):
            raise ImportError("live module is broken")

    project = _project(tmp_path, {"tony_tpu/am/ok.py": "X = 1\n"})
    report = run_rules(project, [Exploding(), GuardedByRule()])
    assert _rule_ids(report.findings) == ["exploding"]
    assert "rule crashed" in report.findings[0].message
    assert not report.ok


def test_wildcard_suppression(tmp_path):
    src = GUARDED_OFFENDER.replace(
        "        return self._table.get(k)",
        "        # tony: disable=* -- everything deliberate on this line\n"
        "        return self._table.get(k)")
    project = _project(tmp_path, {"tony_tpu/am/s.py": src})
    report = run_rules(project, [GuardedByRule()])
    assert report.findings == [] and report.suppressed == 1


def test_changed_mode_fails_loudly_when_git_fails(tmp_path):
    """--changed must never report clean because git failed — zero files
    checked is a pass exactly when it must not be."""
    from tools.tonylint.engine import GitError, changed_files
    from tools.tonylint.__main__ import main
    (tmp_path / "tony_tpu" / "am").mkdir(parents=True)
    (tmp_path / "tony_tpu" / "am" / "s.py").write_text(GUARDED_OFFENDER)
    with pytest.raises(GitError):
        changed_files(str(tmp_path))  # not a git repo
    assert main(["--root", str(tmp_path), "--changed",
                 "--rules", "guarded-by"]) == 2


# ---------------------------------------------------------------------------
# acceptance: the full pass over the repo at HEAD
# ---------------------------------------------------------------------------

def test_repo_is_clean_under_the_full_rule_set_within_budget():
    """`python -m tools.tonylint tony_tpu/` exits 0 at HEAD with the
    checked-in (shrink-only) baseline, in under 10 s — the tier-1 gate
    the ISSUE pins."""
    t0 = time.monotonic()
    report = lint_repo(REPO)
    elapsed = time.monotonic() - t0
    assert report.ok, "\n" + report.render()
    assert report.checked_files > 80
    assert {r.id for r in default_rules()} == set(report.rules)
    assert elapsed < 10.0, f"lint pass took {elapsed:.1f}s (budget 10s)"


def test_findings_for_wrapper_surface():
    """The one-line wrapper the migrated legacy tests call."""
    assert findings_for("print-ban") == []
    assert json.loads(json.dumps(lint_repo(
        REPO, rule_filter=lambda r: r.id == "print-ban").to_dict()))["ok"]


def test_findings_for_is_not_satisfied_by_a_baseline_entry(tmp_path,
                                                           monkeypatch):
    """The wrappers are the tier-1 hard assertions the pre-migration
    regex checks were: a tools/lint_baseline.json entry absorbing a
    violation must NOT make findings_for() report clean."""
    import tools.tonylint as tl
    (tmp_path / "tony_tpu" / "am").mkdir(parents=True)
    (tmp_path / "tony_tpu" / "am" / "p.py").write_text(
        'def f():\n    print("x")\n')
    (tmp_path / "tools").mkdir()
    offending = lint_repo(str(tmp_path), baseline_path=os.devnull,
                          rule_filter=lambda r: r.id == "print-ban")
    save_baseline(str(tmp_path / "tools" / "lint_baseline.json"),
                  offending.findings, why="trying to hide debt")
    # the CLI honors the baseline...
    baselined = lint_repo(str(tmp_path),
                          rule_filter=lambda r: r.id == "print-ban")
    assert baselined.ok and baselined.baselined == 1
    # ...but the wrapper surface does not
    monkeypatch.setattr(tl, "repo_root", lambda: str(tmp_path))
    tl._repo_report.cache_clear()
    try:
        assert len(tl.findings_for("print-ban")) == 1
    finally:
        tl._repo_report.cache_clear()
