"""Control-plane RPC tests: full server↔client round trip over localhost.

Covers the 7 cluster RPCs + metrics, the register-until-complete barrier
contract, and client retry against a late-starting server (reference
behavior: ApplicationRpcClient retry proxy, ApplicationRpcClient.java:47-76).
"""

import json
import threading
import time

import pytest

from tony_tpu.rpc import (
    ClusterServiceClient, MetricsServiceClient,
    ClusterServiceHandler, MetricsServiceHandler, serve,
    TaskInfo, TaskStatus,
)


class FakeClusterHandler(ClusterServiceHandler):
    """Minimal AM-session stand-in with the rendezvous barrier."""

    def __init__(self, expected=2):
        self.expected = expected
        self.registered = {}
        self.tb_url = None
        self.results = []
        self.heartbeats = []
        self.finished = False

    def get_task_infos(self, req):
        return [TaskInfo("worker", i, status=TaskStatus.RUNNING).to_dict()
                for i in range(self.expected)]

    def _spec_or_none(self):
        if len(self.registered) >= self.expected:
            return json.dumps({"worker": [self.registered[k] for k in
                                          sorted(self.registered)]})
        return None

    def get_cluster_spec(self, req):
        return {"spec": self._spec_or_none()}

    def register_worker_spec(self, req):
        self.registered[req["task_id"]] = req["spec"]
        return {"spec": self._spec_or_none()}

    def register_tensorboard_url(self, req):
        self.tb_url = req["url"]
        return {}

    def register_serving_endpoint(self, req):
        self.serving_endpoints = getattr(self, "serving_endpoints", {})
        self.serving_endpoints[req["task_id"]] = req["url"]
        return {}

    def register_execution_result(self, req):
        self.results.append(req)
        return {}

    def finish_application(self, req):
        self.finished = True
        return {}

    def task_executor_heartbeat(self, req):
        self.heartbeats.append(req["task_id"])
        return {}

    def request_profile(self, req):
        self.profile_requests = getattr(self, "profile_requests", [])
        self.profile_requests.append(req)
        return {"request_id": "fake-req", "task_id": "worker:0",
                "num_steps": int(req.get("num_steps", 0) or 5)}

    def read_task_logs(self, req):
        self.log_reads = getattr(self, "log_reads", [])
        self.log_reads.append(req)
        return {"task_id": req.get("task_id") or "worker:0",
                "stream": req.get("stream", "stderr"), "data": "",
                "offset": 0, "next_offset": 0, "eof": False,
                "source": "live"}

    def get_skew(self, req):
        return {"signals": {}, "heatmap": {"tasks": {}},
                "stragglers": [], "detections": []}

    def get_alerts(self, req):
        return {"firing": [], "log": [], "rules": []}

    def get_profile(self, req):
        return {"folded": "", "process": "fake"}

    def request_preemption(self, req):
        self.preemptions = getattr(self, "preemptions", [])
        self.preemptions.append(req)
        return {"app_id": "fake-app",
                "grace_ms": int(req.get("grace_ms", 0) or 30_000),
                "deadline_ms": int(req.get("grace_ms", 0) or 30_000)}

    def request_resize(self, req):
        self.resizes = getattr(self, "resizes", [])
        self.resizes.append(req)
        return {"app_id": "fake-app",
                "job_name": req.get("job_name", "worker"),
                "from_width": 2,
                "to_width": int(req.get("width", 0) or 0)}

    def request_rolling_update(self, req):
        self.rollouts = getattr(self, "rollouts", [])
        self.rollouts.append(req)
        return {"app_id": "fake-app",
                "generation": int(req.get("generation", 0) or 1),
                "replicas": 0}


class FakeMetricsHandler(MetricsServiceHandler):
    def __init__(self):
        self.store = {}

    def update_metrics(self, req):
        self.store[(req["task_type"], req["index"])] = req["metrics"]
        return {}


@pytest.fixture
def cluster():
    handler = FakeClusterHandler()
    metrics = FakeMetricsHandler()
    server, port = serve(cluster_handler=handler, metrics_handler=metrics)
    yield handler, metrics, port
    server.stop(grace=None)


def test_rendezvous_barrier(cluster):
    handler, _, port = cluster
    c = ClusterServiceClient("localhost", port, retries=2, retry_sleep_sec=0.1)
    # first registrant gets None back — barrier not complete
    assert c.register_worker_spec("worker:0", "host0:1111") is None
    assert c.get_cluster_spec("worker:0") is None
    # second registrant completes the gang; both now see the full spec
    spec = c.register_worker_spec("worker:1", "host1:2222")
    assert spec == {"worker": ["host0:1111", "host1:2222"]}
    assert c.get_cluster_spec("worker:0") == spec
    c.close()


def test_all_methods_round_trip(cluster):
    handler, metrics, port = cluster
    c = ClusterServiceClient("localhost", port, retries=2, retry_sleep_sec=0.1)
    infos = c.get_task_infos()
    assert [TaskInfo.from_dict(i).task_id for i in infos] == ["worker:0", "worker:1"]
    c.register_tensorboard_url("worker:0", "http://tb:6006")
    assert handler.tb_url == "http://tb:6006"
    c.register_execution_result(0, "worker", 1, session_id=0)
    assert handler.results == [{"exit_code": 0, "job_name": "worker",
                                "job_index": 1, "session_id": 0,
                                "task_attempt": -1,
                                "barrier_timeout": False,
                                "preempted": False,
                                "resized": False}]
    c.task_executor_heartbeat("worker:1")
    assert handler.heartbeats == ["worker:1"]
    resp = c.request_resize(job_name="worker", width=4,
                            requested_by="operator")
    assert resp["to_width"] == 4
    assert handler.resizes[0]["width"] == 4
    assert handler.resizes[0]["session_attempt"] == -1
    resp = c.request_preemption(grace_ms=5000, reason="drain",
                                requested_by="operator")
    assert resp["grace_ms"] == 5000
    assert handler.preemptions == [{"grace_ms": 5000, "reason": "drain",
                                    "requested_by": "operator"}]
    resp = c.request_profile(task_id="worker:0", num_steps=3)
    assert resp["request_id"] == "fake-req" and resp["num_steps"] == 3
    assert handler.profile_requests == [{"task_id": "worker:0",
                                         "num_steps": 3}]
    c.finish_application()
    assert handler.finished

    m = MetricsServiceClient("localhost", port, retries=2, retry_sleep_sec=0.1)
    m.update_metrics("worker", 0, [{"name": "hbm_gb", "value": 1.5}])
    assert metrics.store[("worker", 0)] == [{"name": "hbm_gb", "value": 1.5}]
    m.close()
    c.close()


def test_client_retries_until_server_up():
    """Executor may start before the AM socket exists (reference retry proxy)."""
    from tony_tpu.utils.common import pick_free_port
    port = pick_free_port()
    c = ClusterServiceClient("localhost", port, retries=30,
                             retry_sleep_sec=0.1, timeout_sec=1.0)
    handler = FakeClusterHandler(expected=1)
    server_holder = {}

    def start_late():
        time.sleep(0.5)
        server_holder["s"], _ = serve(cluster_handler=handler, port=port)

    t = threading.Thread(target=start_late)
    t.start()
    spec = c.register_worker_spec("worker:0", "h:1")
    assert spec == {"worker": ["h:1"]}
    t.join()
    server_holder["s"].stop(grace=None)
    c.close()


def test_client_gives_up_when_no_server():
    from tony_tpu.utils.common import pick_free_port
    c = ClusterServiceClient("localhost", pick_free_port(), retries=2,
                             retry_sleep_sec=0.05, timeout_sec=0.3)
    with pytest.raises(ConnectionError):
        c.task_executor_heartbeat("worker:0")
    c.close()


def test_heartbeat_fails_fast_against_dead_am():
    """Liveness-critical: a heartbeat against a dead AM must fail within
    seconds (one attempt, 5s deadline, no wait_for_ready), NOT sit in the
    default retry proxy — the Heartbeater's consecutive-failure counter is
    the real retry loop (TaskExecutor.java:358-368 semantics)."""
    from tony_tpu.utils.common import pick_free_port
    c = ClusterServiceClient("localhost", pick_free_port())  # default opts
    start = time.monotonic()
    with pytest.raises(ConnectionError):
        c.task_executor_heartbeat("worker:0")
    assert time.monotonic() - start < 6.0
    c.close()


def test_task_log_service_roundtrip(tmp_path):
    """The executor-hosted TaskLogService: bounded chunk reads over a
    stream file through the real gRPC stack (the AM proxy's wire)."""
    from tony_tpu.observability.logs import LogTail
    from tony_tpu.rpc.client import TaskLogServiceClient
    from tony_tpu.rpc.service import TaskLogServiceHandler

    class Handler(TaskLogServiceHandler):
        def read_log(self, req):
            chunk = LogTail(str(tmp_path / req["stream"]),
                            chunk_bytes=256).read_chunk(
                offset=int(req.get("offset", -1)),
                max_bytes=int(req.get("max_bytes", 0) or 0), final=True)
            chunk["stream"] = req["stream"]
            return chunk

    (tmp_path / "stderr").write_text("hello\nworld\n")
    server, port = serve(log_handler=Handler())
    client = TaskLogServiceClient("127.0.0.1", port)
    try:
        chunk = client.read_log("stderr", offset=0)
        assert chunk["data"] == "hello\nworld\n"
        assert chunk["eof"] is True
        assert chunk["next_offset"] == 12
        # cursor continuation returns empty-at-eof
        again = client.read_log("stderr", offset=chunk["next_offset"])
        assert again["data"] == "" and again["eof"] is True
    finally:
        client.close()
        server.stop(grace=None)
