"""Warm executor pool (cluster/warmpool.py): lease/bind fencing.

The cold-start demolition's sharpest edge is correctness, not speed: a
leased warm process must be indistinguishable from a cold spawn to the
application that binds it. These tests pin the fence — nonce-mismatched
binds are refused, stale app-A env never survives into an app-B bind, a
SIGKILLed warm child is evicted (never reused) and its replacement lease
re-binds cleanly, and a dead pool degrades to the cold path without
failing the task.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tony_tpu import constants as C
from tony_tpu.cluster.warmpool import (
    EXIT_BIND_REJECTED, WARM_READY_LINE, WarmExecutorPool,
)
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.observability.metrics import REGISTRY

pytestmark = pytest.mark.warmpool

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def _counter(name: str, **labels) -> float:
    return REGISTRY.counter(name, **labels).value


def _write_probe(tmp_path) -> str:
    """A script-entry module that reports what the bound child actually
    became: cwd, argv, and the identity env after scrub + re-apply."""
    path = tmp_path / "probe_mod.py"
    path.write_text(textwrap.dedent("""
        import json, os, sys

        def probe_main():
            out = {"cwd": os.getcwd(), "argv": sys.argv[1:],
                   "env": {k: os.environ.get(k, "")
                           for k in ("TONY_STALE_A", "TONY_TRACE_ID",
                                     "JOB_NAME")}}
            print("PROBE " + json.dumps(out), flush=True)
            return 0
    """))
    return str(path)


def _lease_probe(pool, tmp_path, env, argv=()):
    """Lease a warm child bound to the probe module; returns (probe
    dict, exit code, pid)."""
    proc = pool.lease_and_bind(
        env=env, cwd=str(tmp_path), entry="script",
        script_path=_write_probe(tmp_path), script_func="probe_main",
        argv=["probe"] + list(argv))
    assert proc is not None, "warm lease missed with a warmed pool"
    line, deadline = "", time.monotonic() + 20
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line or line.startswith("PROBE "):
            break
    assert line.startswith("PROBE "), f"no probe output, got {line!r}"
    rc = proc.wait(timeout=20)
    return json.loads(line.split(" ", 1)[1]), rc, proc.pid


@pytest.fixture
def pool():
    pools = []

    def make(size=1, ttl_ms=300_000):
        p = WarmExecutorPool(size=size, ttl_ms=ttl_ms)
        pools.append(p)
        p.start()
        assert p.wait_ready(timeout=60.0), "pool never warmed"
        return p

    yield make
    for p in pools:
        p.stop()


def test_lease_binds_fresh_identity_and_scrubs_stale(pool, tmp_path,
                                                     monkeypatch):
    """The attempt-fence env contract: stale app-A identity inherited at
    fork (TONY_* + task identity vars) is scrubbed before the app-B spec
    env lands — the bound child sees ONLY the fresh values, exactly like
    a cold spawn."""
    monkeypatch.setenv("TONY_STALE_A", "app-a-secret")
    monkeypatch.setenv("JOB_NAME", "app-a-worker")
    p = pool(size=1)
    hits0 = _counter("tony_warmpool_lease_total", outcome="hit")
    probe, rc, _ = _lease_probe(
        p, tmp_path,
        env={"TONY_TRACE_ID": "trace-b", "JOB_NAME": "worker-b"},
        argv=["x", "y"])
    assert rc == 0
    assert probe["env"]["TONY_STALE_A"] == ""       # scrubbed
    assert probe["env"]["JOB_NAME"] == "worker-b"   # re-supplied, not stale
    assert probe["env"]["TONY_TRACE_ID"] == "trace-b"
    assert probe["cwd"] == str(tmp_path)
    assert probe["argv"] == ["x", "y"]
    assert _counter("tony_warmpool_lease_total", outcome="hit") == hits0 + 1


def test_bind_refused_on_nonce_mismatch():
    """A crossed pipe can never bind a foreign spec: the child refuses
    any bind that does not echo its own fork-time nonce."""
    env = dict(os.environ)
    env[C.WARMPOOL_NONCE] = "the-real-nonce"
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cluster.warmpool"], env=env,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    try:
        assert proc.stdout.readline().strip() == WARM_READY_LINE
        proc.stdin.write(json.dumps(
            {"nonce": "forged", "entry": "executor", "env": {}}) + "\n")
        proc.stdin.close()
        assert proc.wait(timeout=20) == EXIT_BIND_REJECTED
    finally:
        proc.kill()


def test_bind_refused_on_garbage_and_clean_exit_on_eof():
    env = dict(os.environ)
    env[C.WARMPOOL_NONCE] = "n1"
    for payload, expected in (("not json at all\n", EXIT_BIND_REJECTED),
                              ("", 0)):   # EOF = pool retirement
        proc = subprocess.Popen(
            [sys.executable, "-m", "tony_tpu.cluster.warmpool"], env=env,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        try:
            assert proc.stdout.readline().strip() == WARM_READY_LINE
            if payload:
                proc.stdin.write(payload)
            proc.stdin.close()
            assert proc.wait(timeout=20) == expected
        finally:
            proc.kill()


@pytest.mark.chaos
def test_sigkilled_warm_child_evicted_replacement_fenced(pool, tmp_path):
    """Chaos acceptance: SIGKILL an idle warm child; the next lease must
    evict it (never hand it out), serve a LIVE replacement, and that
    replacement's bind must still carry the full fence (fresh identity
    env applied, rc 0)."""
    p = pool(size=2)
    victim = p._idle[0].proc
    os.kill(victim.pid, signal.SIGKILL)
    victim.wait()
    dead0 = _counter("tony_warmpool_evictions_total", reason="dead")
    probe, rc, pid = _lease_probe(
        p, tmp_path, env={"TONY_TRACE_ID": "trace-after-chaos",
                          "JOB_NAME": "worker-replacement"})
    assert rc == 0 and pid != victim.pid
    assert probe["env"]["TONY_TRACE_ID"] == "trace-after-chaos"
    assert probe["env"]["JOB_NAME"] == "worker-replacement"
    assert _counter("tony_warmpool_evictions_total",
                    reason="dead") >= dead0 + 1


def test_exhausted_pool_returns_none_then_recovers(pool, tmp_path):
    """Every candidate dead → lease returns None (the caller's cold
    fallback), and the evictions trigger respawns so the pool heals."""
    p = pool(size=1)
    os.kill(p._idle[0].proc.pid, signal.SIGKILL)
    p._idle[0].proc.wait()
    assert p.lease_and_bind(env={}, cwd=str(tmp_path)) is None
    # eviction queued a respawn: the pool becomes leasable again
    assert p.wait_ready(1, timeout=60.0)
    probe, rc, _ = _lease_probe(p, tmp_path, env={"JOB_NAME": "healed"})
    assert rc == 0 and probe["env"]["JOB_NAME"] == "healed"


def test_ttl_sweep_retires_expired_children(pool):
    p = pool(size=1, ttl_ms=1)
    time.sleep(0.05)
    ttl0 = _counter("tony_warmpool_evictions_total", reason="ttl")
    p.sweep()
    assert _counter("tony_warmpool_evictions_total",
                    reason="ttl") == ttl0 + 1


def test_backend_falls_back_to_cold_spawn_on_pool_miss(tmp_path):
    """LocalClusterBackend + a pool that can never serve (all children
    killed): launch_container must cold-spawn — the container runs and
    completes; the pool is an optimization, never a dependency."""
    from tony_tpu.cluster.backend import Container
    from tony_tpu.cluster.local import LocalClusterBackend

    p = WarmExecutorPool(size=1)
    p.start()
    assert p.wait_ready(timeout=60.0)
    os.kill(p._idle[0].proc.pid, signal.SIGKILL)
    p._idle[0].proc.wait()
    backend = LocalClusterBackend(app_id="t", warmpool=p)
    done = []
    backend._on_allocated = lambda c: None
    backend._on_completed = lambda cid, rc: done.append((cid, rc))
    try:
        cwd = str(tmp_path / "c1")
        container = Container(container_id="c1", host="localhost",
                              priority=0, memory_mb=0, vcores=0, gpus=0,
                              tpus=0)
        # not an executor command on purpose: proves the cold path ran it
        backend.launch_container(
            container, [sys.executable, "-c", "print('cold-ok')"],
            env={}, cwd=cwd)
        deadline = time.monotonic() + 30
        while not done and time.monotonic() < deadline:
            time.sleep(0.05)
        assert done == [("c1", 0)]
        with open(os.path.join(cwd, "stdout"), "rb") as f:
            assert b"cold-ok" in f.read()
    finally:
        backend.stop()


def test_from_conf_gating():
    from tony_tpu.cluster import warmpool as wp

    conf = TonyConfiguration()
    assert wp.from_conf(conf) is None          # default: disabled
    conf.set(K.WARMPOOL_ENABLED, True, "test")
    conf.set(K.WARMPOOL_SIZE, 2, "test")
    p = wp.from_conf(conf)
    try:
        assert isinstance(p, WarmExecutorPool) and p.size == 2
    finally:
        p.stop()


def test_e2e_job_leases_warm_executors(tmp_path):
    """Full chain with tony.warmpool.enabled: client → AM → backend
    leases warm executors → user scripts succeed. The AM's backend log
    proves at least one container actually rode a warm lease (the AM is
    a subprocess, so its registry is not visible here)."""
    from tony_tpu.client.tony_client import TonyClient

    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.TASK_MAX_MISSED_HEARTBEATS, 25, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 500, "test")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "test")
    conf.set(K.CONTAINER_ALLOCATION_TIMEOUT, 60_000, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "test")
    conf.set(K.WARMPOOL_ENABLED, True, "test")
    conf.set(K.WARMPOOL_SIZE, 2, "test")
    client = TonyClient(conf)
    client.init(["--executes", os.path.join(SCRIPTS, "exit_0.py"),
                 "--conf", "tony.worker.instances=2"])
    client.run()
    assert client.final_status == "SUCCEEDED"
    with open(os.path.join(client.app_dir, C.AM_STDERR), "rb") as f:
        am_log = f.read().decode("utf-8", "replace")
    assert "leased warm executor" in am_log
