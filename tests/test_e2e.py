"""Full-stack E2E suite: real client → AM → executor → user python processes.

Equivalent of the reference's crown jewel TestTonyE2E.java:89-484, which ran
real TonyClient→AM→TaskExecutor→python chains on an in-process MiniCluster
(3 NodeManagers). Here the LocalClusterBackend plays MiniCluster: every test
spawns the genuine AM and executor processes and a real user script from
tests/scripts/. Fault injection uses the same env hooks the reference
compiled into prod code (Constants.java:116-121).
"""

from __future__ import annotations

import json
import time
import os
import sys

import pytest

from tony_tpu import constants as C
from tony_tpu.client.tony_client import TonyClient
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.handler import parse_events
from tony_tpu.events.schema import EventType

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


def fast_conf(tmp_path, **overrides) -> TonyConfiguration:
    """Test-scale cadences: the reference's 1s/5s/25-missed defaults shrunk so
    the suite stays fast; expiry window = 0.2s * 25 = 5s."""
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.TASK_MAX_MISSED_HEARTBEATS, 25, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 500, "test")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "test")
    conf.set(K.CONTAINER_ALLOCATION_TIMEOUT, 60_000, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "test")
    for k, v in overrides.items():
        conf.set(k, v, "test")
    return conf


def run_job(tmp_path, argv: list[str], conf_overrides=None,
            listeners=None) -> TonyClient:
    conf = fast_conf(tmp_path, **(conf_overrides or {}))
    client = TonyClient(conf)
    for listener in listeners or []:
        client.add_listener(listener)
    client.init(argv)
    client.run()
    return client


def history_events(client: TonyClient):
    # history lives in a per-app subdir of the intermediate dir
    hist_base = os.path.join(client.app_dir, C.HISTORY_DIR_NAME)
    finals = [os.path.join(d, f)
              for d, _, files in os.walk(hist_base)
              for f in files if f.endswith(".jhist")]
    assert len(finals) == 1, finals
    return os.path.basename(finals[0]), parse_events(finals[0])


# ---------------------------------------------------------------------------
# happy paths (TestTonyE2E single/ps-worker pass cases)
# ---------------------------------------------------------------------------

def test_worker_training_should_pass(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=2"])
    assert client.final_status == "SUCCEEDED"


def test_tf_env_rendered(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("check_env.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=tensorflow"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_pytorch_env_rendered(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("check_pytorch_env.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=pytorch"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_jax_env_rendered(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("check_jax_env.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_tb_port_set_in_chief_only(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("check_tb_port.py"),
         "--conf", "tony.chief.instances=1",
         "--conf", "tony.worker.instances=2"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_worker_training_should_fail(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("exit_1.py"),
         "--conf", "tony.worker.instances=1"])
    assert client.final_status == "FAILED"


def test_succeed_despite_some_worker_failures(tmp_path):
    """Non-chief worker failure tolerated when fail-on-worker-failure is off
    (TonySession.java:276-330 'succeeded with some failed tasks')."""
    client = run_job(
        tmp_path,
        ["--conf", "tony.chief.instances=1",
         "--conf", "tony.worker.instances=2",
         "--conf", f"tony.chief.command=python {script('exit_0.py')}",
         "--conf", f"tony.worker.command=bash -c 'exit $TASK_INDEX'"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    assert "failedCnt=1" in (client.final_message or "")


def test_fail_on_worker_failure_enabled(tmp_path):
    client = run_job(
        tmp_path,
        ["--conf", "tony.chief.instances=1",
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.fail-on-worker-failure-enabled=true",
         "--conf", f"tony.chief.command=python {script('sleep_30.py')}",
         "--conf", f"tony.worker.command=bash -c 'exit $TASK_INDEX'"])
    assert client.final_status == "FAILED"


# ---------------------------------------------------------------------------
# fault injection (TestTonyE2E tiers 3)
# ---------------------------------------------------------------------------

def test_missed_heartbeats_should_fail(tmp_path, monkeypatch):
    """(reference: testPSWorkerTrainingShouldFailMissedHeartbeat,
    TestTonyE2E.java:142-158)."""
    monkeypatch.setenv(C.TEST_TASK_EXECUTOR_NUM_HB_MISS, "100")
    client = run_job(
        tmp_path,
        ["--executes", script("sleep_30.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-missed-heartbeats=5"])
    assert client.final_status == "FAILED"
    assert "missed" in (client.final_message or "")


def test_skewed_worker_should_pass(tmp_path, monkeypatch):
    """(reference: testPSSkewedWorkerTrainingShouldPass,
    TestTonyE2E.java:161-176)."""
    monkeypatch.setenv(C.TEST_TASK_EXECUTOR_SKEW, "worker#0#2000")
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=2"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_am_crash_should_fail(tmp_path, monkeypatch):
    """(reference: testAMCrashTonyShouldFail, TestTonyE2E.java:240-252)."""
    monkeypatch.setenv(C.TEST_AM_CRASH, "1")
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1"])
    assert client.final_status == "FAILED"


def test_workers_killed_should_fail(tmp_path, monkeypatch):
    """(reference: testAMStopsJobAfterWorker0Killed, TestTonyE2E.java:282-288)."""
    monkeypatch.setenv(C.TEST_WORKER_TERMINATION, "1")
    client = run_job(
        tmp_path,
        ["--executes", script("sleep_30.py"),
         "--conf", "tony.worker.instances=2"])
    assert client.final_status == "FAILED"


def test_delayed_completion_notification(tmp_path, monkeypatch):
    """Clean executor exit + delayed container-completion callback must NOT
    turn into a failure (reference: testTaskCompletionNotificationDelayed,
    TestTonyE2E.java:362-378; race rationale ApplicationMaster.java:890-918)."""
    monkeypatch.setenv(C.TEST_TASK_COMPLETION_NOTIFICATION_DELAYED, "2")
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_untracked_jobtype_crash_fails_app(tmp_path):
    """(reference: untracked-task crash detection prevents hangups,
    ApplicationMaster.java:1192-1195, TestTonyE2E.java:418-447)."""
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=1",
         "--conf", "tony.sidecar.instances=1",
         "--conf", "tony.application.untracked.jobtypes=sidecar",
         "--conf", f"tony.worker.command=python {script('sleep_30.py')}",
         "--conf", f"tony.sidecar.command=python {script('exit_1.py')}"])
    assert client.final_status == "FAILED"
    assert "untracked" in (client.final_message or "")


def test_am_retry_recovers(tmp_path):
    """Whole-session retry (ApplicationMaster.java:336-370,558-574): first
    session fails, second succeeds because ATTEMPT_NUMBER advanced."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0_if_retry.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.am.retry-count=2"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


# ---------------------------------------------------------------------------
# scheduling / DAG (reference: testTonyAMSchedulerShouldPass)
# ---------------------------------------------------------------------------

def test_dag_scheduling_order(tmp_path):
    marker_dir = str(tmp_path / "markers")
    client = run_job(
        tmp_path,
        ["--conf", "tony.prep.instances=1",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.worker.depends-on=prep",
         "--conf", f"tony.prep.command=python {script('write_marker.py')}",
         "--conf", f"tony.worker.command=python {script('write_marker.py')}",
         "--conf", f"tony.execution.env=MARKER_DIR={marker_dir}"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    markers = sorted(os.listdir(marker_dir))
    assert markers == ["prep_0", "worker_0"]


def test_cyclic_dag_fails(tmp_path):
    client = run_job(
        tmp_path,
        ["--conf", "tony.a.instances=1",
         "--conf", "tony.b.instances=1",
         "--conf", "tony.a.depends-on=b",
         "--conf", "tony.b.depends-on=a",
         "--conf", f"tony.a.command=python {script('exit_0.py')}",
         "--conf", f"tony.b.command=python {script('exit_0.py')}"])
    assert client.final_status == "FAILED"


# ---------------------------------------------------------------------------
# localization, events, listeners, single-node
# ---------------------------------------------------------------------------

def test_resource_localization_formats(tmp_path):
    """(reference: testLocalizationFormats, TestTonyE2E.java:323-340)."""
    res_dir = tmp_path / "resources"
    res_dir.mkdir()
    (res_dir / "common.txt").write_text("hello")
    archive = tmp_path / "archive_dir"
    archive.mkdir()
    (archive / "inner.txt").write_text("inner")
    client = run_job(
        tmp_path,
        ["--executes", script("check_localization.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", f"tony.worker.resources={res_dir / 'common.txt'},"
                   f"{archive}"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_history_events_written(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=2"])
    name, events = history_events(client)
    assert "SUCCEEDED" in name
    types = [e.type for e in events]
    assert types[0] == EventType.APPLICATION_INITED
    assert types.count(EventType.TASK_STARTED) == 2
    assert types.count(EventType.TASK_FINISHED) == 2
    assert types[-1] == EventType.APPLICATION_FINISHED


def test_client_listener_callbacks(tmp_path):
    """(reference: client callbacks/listeners, TestTonyE2E.java:381-415)."""
    seen = []
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1"],
        listeners=[lambda infos: seen.append(
            {i.task_id: i.status.value for i in infos})])
    assert client.final_status == "SUCCEEDED"
    assert seen, "listener never invoked"
    assert any("worker:0" in snap for snap in seen)


def test_single_node_mode(tmp_path):
    """AM runs the command itself (doPreprocessingJob/single-node,
    ApplicationMaster.java:713-765)."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.application.single-node=true"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_preprocess_model_params_reach_task_env(tmp_path):
    """A prepare-stage job's 'Model parameters: ...' stdout line lands in
    every training container's $MODEL_PARAMS (reference:
    ApplicationMaster.java:753-764, Constants.java:84)."""
    prep = tmp_path / "prep.py"
    prep.write_text("print('some log line')\n"
                    "print('Model parameters: lr=0.01 layers=4')\n"
                    "print('another line')\n")
    client = run_job(
        tmp_path,
        ["--executes", script("check_model_params.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.enable-preprocess=true",
         "--conf", f"tony.am.command={sys.executable} {prep}"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_preprocess_failure_fails_application(tmp_path):
    """A nonzero prepare-stage exit short-circuits the app (reference:
    doPreprocessingJob exit-code check, ApplicationMaster.java:746-751)."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.enable-preprocess=true",
         "--conf", f"tony.am.command={sys.executable} -c 'import sys; sys.exit(3)'"])
    assert client.final_status == "FAILED"


def test_final_conf_artifact(tmp_path):
    """The frozen conf must ship every layer merged
    (reference: testTonyFinalConf, TestTonyE2E.java:457-482)."""
    conf_file = tmp_path / "job.json"
    conf_file.write_text(json.dumps({
        "tony.worker.instances": 1,
        "tony.application.name": "from-file",
    }))
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf_file", str(conf_file),
         "--conf", "tony.application.name=from-cli"])
    final = TonyConfiguration.read(
        os.path.join(client.app_dir, C.TONY_FINAL_CONF))
    assert final.get_str(K.APPLICATION_NAME) == "from-cli"
    assert final.get_int("tony.worker.instances") == 1
    assert client.final_status == "SUCCEEDED"


# ---------------------------------------------------------------------------
def _dump_logs(client: TonyClient) -> str:
    """Collect AM + container logs for assertion messages."""
    chunks = []
    for root, _dirs, files in os.walk(client.app_dir):
        for f in files:
            if f in ("stdout", "stderr", C.AM_STDOUT, C.AM_STDERR):
                path = os.path.join(root, f)
                try:
                    with open(path, "r", errors="replace") as fh:
                        content = fh.read().strip()
                    if content:
                        chunks.append(f"==== {path} ====\n{content}")
                except OSError:
                    pass
    return "\n".join(chunks)[-8000:]


def test_notebook_path_proxies_to_single_node_app(tmp_path):
    """Notebook flow (reference: NotebookSubmitter.java:71-133 +
    ApplicationMaster.java:717-726): single-node app binds $TB_PORT, the
    URL appears in TaskInfos, and a local proxy relays to it."""
    import threading
    import urllib.request

    from tony_tpu.proxy import ProxyServer

    conf = fast_conf(tmp_path)
    conf.set(K.APPLICATION_SINGLE_NODE, True, "test")
    client = TonyClient(conf)
    client.init(["--executes", script("fake_notebook.py")])

    result = {}

    def _run():
        result["ok"] = client.run()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    url = None
    for _ in range(200):
        for info in client.get_task_infos():
            if info.url.startswith("http://"):
                url = info.url
                break
        if url:
            break
        time.sleep(0.1)
    assert url, "notebook URL never appeared in TaskInfos"
    hostport = url[len("http://"):].split("/", 1)[0]
    host, _, port = hostport.rpartition(":")
    proxy = ProxyServer(host, int(port))
    proxy.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{proxy.local_port}/", timeout=10) as resp:
            assert resp.read() == b"NOTEBOOK_OK"
    finally:
        proxy.stop()
    t.join(timeout=60)
    assert result.get("ok") is True


def test_portal_serves_real_container_logs(tmp_path):
    """Full chain for VERDICT r4 item 3: run a job through the CLI, the
    AM aggregates container stdout into history, and the portal serves
    the REAL body through /logs/:id/:dir/:stream — no synthesized URL."""
    import urllib.request

    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer

    hist_inter = str(tmp_path / "hist-int")
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=1",
         "--conf",
         "tony.worker.command=bash -c 'echo portal-sees-this-line'",
         "--conf", f"tony.history.intermediate={hist_inter}"],
        conf_overrides={"tony.history.intermediate": hist_inter})
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    server = PortalServer(
        PortalCache(hist_inter, str(tmp_path / "hist-fin")),
        port=0, host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/jobs/"
                f"{client.app_id}/logs") as resp:
            links = json.loads(resp.read().decode())
        by_task = {l["task"]: l for l in links}
        assert by_task["worker:0"]["streams"], links
        url = by_task["worker:0"]["streams"]["stdout"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{url}") as resp:
            body = resp.read().decode()
        assert "portal-sees-this-line" in body
    finally:
        server.stop()


def test_queue_quota_rejects_over_ask(tmp_path):
    """VERDICT r4 item 5 acceptance: over-quota submission fails with the
    queue named in the message; a fitting queue submits fine."""
    with pytest.raises(ValueError, match="queue 'default'.*max-tpus"):
        run_job(
            tmp_path,
            ["--executes", script("exit_0.py"),
             "--conf", "tony.worker.instances=2",
             "--conf", "tony.worker.tpus=8",
             "--conf", "tony.queues.default.max-tpus=8"])
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"), "--queue", "big",
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.worker.tpus=8",
         "--conf", "tony.queues.default.max-tpus=8",
         "--conf", "tony.queues.big.max-tpus=16"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
