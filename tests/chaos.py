"""Deterministic chaos harness for the fault-tolerance suite.

Drives the TEST_* fault-injection hooks compiled into the AM and the
TaskExecutor (the reference's pattern, Constants.java:116-121) plus the
task-relaunch injection points (TEST_TASK_KILL / TEST_TASK_HB_SILENCE)
through the LocalClusterBackend, so every recovery path is proven on the
genuine client → AM → executor → user-python chain.

Determinism contract: every randomized quantity in a chaos run derives from
`ChaosRun.seed` — injection delays come from the run's own
`random.Random(seed)`, and the seed is exported as TONY_TEST_SEED so the
rpc-client retry jitter inside the AM and every executor child process is
seeded per endpoint too (rpc/client.py). A failing chaos test therefore
replays exactly by pinning the same seed.
"""

from __future__ import annotations

import os
import random
import re
from typing import Optional

from tony_tpu import constants as C
from tony_tpu.client.tony_client import TonyClient
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.handler import parse_events
from tony_tpu.events.schema import EventType

SCRIPTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


# ---------------------------------------------------------------------------
# injections: each knows the env hook(s) it plants; the AM and executor
# subprocesses inherit them (the reference compiled the same hooks into
# prod code, Constants.java:116-121)
# ---------------------------------------------------------------------------

class Injection:
    def env(self) -> dict:
        raise NotImplementedError


class KillTask(Injection):
    """Hard-crash one attempt's container `after_ms` after its user process
    launches, WITHOUT registering a result — the container-completion
    relaunch path (executor hook TEST_TASK_KILL)."""

    def __init__(self, job: str, index: int, after_ms: int,
                 attempt: "int | str" = 0):
        self.job, self.index = job, index
        self.after_ms, self.attempt = after_ms, attempt

    def env(self) -> dict:
        return {C.TEST_TASK_KILL:
                f"{self.job}#{self.index}#{self.after_ms}#{self.attempt}"}


class SilenceHeartbeats(Injection):
    """One attempt's heartbeater goes permanently silent while its user
    process keeps running — the wedge, exercising the heartbeat-expiry
    relaunch path (executor hook TEST_TASK_HB_SILENCE)."""

    def __init__(self, job: str, index: int, attempt: "int | str" = 0):
        self.job, self.index, self.attempt = job, index, attempt

    def env(self) -> dict:
        return {C.TEST_TASK_HB_SILENCE:
                f"{self.job}#{self.index}#{self.attempt}"}


class WedgeTask(Injection):
    """One attempt's executor parks its MAIN thread forever in
    `_tony_test_wedge` right after the gang barrier — alive but making
    no progress (executor hook TEST_TASK_WEDGE). Combined with
    SilenceHeartbeats this is the canonical wedge-autopsy case: the AM's
    expiry path must pull the stack dump and name the parked frame."""

    def __init__(self, job: str, index: int, attempt: "int | str" = 0):
        self.job, self.index, self.attempt = job, index, attempt

    def env(self) -> dict:
        return {C.TEST_TASK_WEDGE:
                f"{self.job}#{self.index}#{self.attempt}"}


class MissHeartbeats(Injection):
    """Every executor skips its first `n` heartbeats
    (TEST_TASK_EXECUTOR_NUM_HB_MISS, TaskExecutor.java:334-344)."""

    def __init__(self, n: int):
        self.n = n

    def env(self) -> dict:
        return {C.TEST_TASK_EXECUTOR_NUM_HB_MISS: str(self.n)}


class DelayCompletionNotification(Injection):
    """Container-completion callbacks arrive `sec` late
    (TEST_TASK_COMPLETION_NOTIFICATION_DELAYED,
    ApplicationMaster.java:1028-1037)."""

    def __init__(self, sec: float):
        self.sec = sec

    def env(self) -> dict:
        return {C.TEST_TASK_COMPLETION_NOTIFICATION_DELAYED: str(self.sec)}


class CrashAM(Injection):
    """The AM dies right after prepare() (TEST_AM_CRASH,
    ApplicationMaster.java:337-342)."""

    def env(self) -> dict:
        return {C.TEST_AM_CRASH: "1"}


class KillAM(Injection):
    """SIGKILL the AM process `after_ms` after prepare() — no _finish, no
    status.json, executors left running. With tony.am.max-attempts > 1
    the supervisor relaunches the AM, which replays the journal and
    adopts the orphaned gang (AM hook TEST_AM_KILL). `attempt` pins the
    kill to one AM process attempt (default 0: only the first AM dies,
    the recovered attempt survives)."""

    def __init__(self, after_ms: int, attempt: int = 0):
        self.after_ms, self.attempt = after_ms, attempt

    def env(self) -> dict:
        return {C.TEST_AM_KILL: f"{self.after_ms}#{self.attempt}"}


class HangAM(Injection):
    """SIGSTOP the AM `after_ms` after prepare() and SIGCONT it
    `hang_ms` later — the wedged-not-dead control plane. Executors
    exhaust their heartbeat budget, go orphan, and must re-attach to the
    SAME address once the AM thaws (AM hook TEST_AM_HANG)."""

    def __init__(self, after_ms: int, hang_ms: int, attempt: int = 0):
        self.after_ms, self.hang_ms, self.attempt = after_ms, hang_ms, attempt

    def env(self) -> dict:
        return {C.TEST_AM_HANG:
                f"{self.after_ms}#{self.hang_ms}#{self.attempt}"}


class TerminateWorkers(Injection):
    """The AM kills every worker container once the chief registers
    (TEST_WORKER_TERMINATION, ApplicationMaster.java:1204-1215)."""

    def env(self) -> dict:
        return {C.TEST_WORKER_TERMINATION: "1"}


class Skew(Injection):
    """Delay one task between the barrier and exec
    (TEST_TASK_EXECUTOR_SKEW, TaskExecutor.java:372-392)."""

    def __init__(self, job: str, index: int, ms: int):
        self.job, self.index, self.ms = job, index, ms

    def env(self) -> dict:
        return {C.TEST_TASK_EXECUTOR_SKEW: f"{self.job}#{self.index}#{self.ms}"}


class Preempt(Injection):
    """The AM preempts ITSELF `after_ms` after prepare(), exactly as if
    an arbiter's request_preemption RPC had arrived — the drain ask
    rides the heartbeats, executors TERM their user processes, trainers
    emergency-checkpoint within `grace_ms`, and the application finishes
    PREEMPTED (AM hook TEST_TASK_PREEMPT)."""

    def __init__(self, after_ms: int, grace_ms: int = 0):
        self.after_ms, self.grace_ms = after_ms, grace_ms

    def env(self) -> dict:
        spec = str(self.after_ms)
        if self.grace_ms:
            spec += f"#{self.grace_ms}"
        return {C.TEST_TASK_PREEMPT: spec}


class StepDelay(Injection):
    """Slow EVERY train step of one task attempt by `ms` — the
    steady-state straggler (executor hook TEST_TRAINER_STEP_DELAY,
    rendered into the matching task's user-process env as
    TONY_TRAINER_STEP_DELAY_MS). attempt='*' slows every attempt;
    attempt=0 lets a relaunched replacement run healthy, which is what
    the relaunch-then-clear remediation case needs."""

    def __init__(self, job: str, index: int, ms: int,
                 attempt: "int | str" = "*"):
        self.job, self.index, self.ms, self.attempt = job, index, ms, attempt

    def env(self) -> dict:
        return {C.TEST_TRAINER_STEP_DELAY:
                f"{self.job}#{self.index}#{self.ms}#{self.attempt}"}


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

def fast_conf(tmp_path, **overrides) -> TonyConfiguration:
    """Test-scale cadences (mirrors test_e2e.fast_conf): heartbeat expiry
    window = 0.2s * max(3, max-missed)."""
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "chaos")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "chaos")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "chaos")
    conf.set(K.TASK_MAX_MISSED_HEARTBEATS, 25, "chaos")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 500, "chaos")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "chaos")
    conf.set(K.CONTAINER_ALLOCATION_TIMEOUT, 60_000, "chaos")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "chaos")
    for k, v in overrides.items():
        conf.set(k, v, "chaos")
    return conf


class ChaosRun:
    """One seeded chaos experiment: plants injection env hooks, runs a real
    job on the local backend, and exposes the evidence (final status,
    history events, AM/container logs, per-start markers)."""

    def __init__(self, tmp_path, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(f"chaos:{seed}")
        self.tmp_path = tmp_path
        self.marker_dir = str(tmp_path / "markers")
        self.client: Optional[TonyClient] = None

    def delay_ms(self, lo: int, hi: int) -> int:
        """Seed-deterministic injection delay: same seed → same delay, so a
        chaos failure replays with identical timing intent."""
        return self.rng.randint(lo, hi)

    # -- execution -----------------------------------------------------
    def run(self, argv: list, injections: "tuple | list" = (),
            conf_overrides: Optional[dict] = None,
            extra_env: Optional[dict] = None) -> TonyClient:
        # hooks + extras ride os.environ: the AM is a child process of this
        # one and executors are children of the AM, so the whole chain
        # inherits them (the reference's TEST_* hooks worked the same way)
        env = {C.TEST_SEED: str(self.seed)}
        for inj in injections:
            env.update(inj.env())
        env.update({k: str(v) for k, v in (extra_env or {}).items()})
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            conf = fast_conf(self.tmp_path, **(conf_overrides or {}))
            self.client = TonyClient(conf)
            self.client.init(list(argv)
                             + ["--conf",
                                f"tony.execution.env=MARKER_DIR={self.marker_dir}"])
            self.client.run()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return self.client

    # -- evidence ------------------------------------------------------
    @property
    def final_status(self) -> str:
        return self.client.final_status

    @property
    def final_message(self) -> str:
        return self.client.final_message or ""

    def history_events(self):
        hist_base = os.path.join(self.client.app_dir, C.HISTORY_DIR_NAME)
        finals = [os.path.join(d, f)
                  for d, _, files in os.walk(hist_base)
                  for f in files if f.endswith(".jhist")]
        assert len(finals) == 1, f"expected one .jhist, got {finals}"
        return os.path.basename(finals[0]), parse_events(finals[0])

    def app_history_dir(self) -> str:
        """The per-app history dir (holds the jhist + sidecar files)."""
        hist_base = os.path.join(self.client.app_dir, C.HISTORY_DIR_NAME)
        for d, _, files in os.walk(hist_base):
            if any(f.endswith(C.HISTORY_SUFFIX)
                   or f.endswith(C.HISTORY_INPROGRESS_SUFFIX)
                   for f in files):
                return d
        return hist_base

    def diagnostics(self) -> dict:
        """The diagnostics.json root-cause bundle a failed run flushed."""
        from tony_tpu.events.history import read_diagnostics_file
        return read_diagnostics_file(self.app_history_dir())

    def events_of_type(self, event_type: EventType) -> list:
        _, events = self.history_events()
        return [e for e in events if e.type == event_type]

    def relaunches(self) -> list:
        """TASK_RELAUNCHED payloads, in history order."""
        return [e.payload
                for e in self.events_of_type(EventType.TASK_RELAUNCHED)]

    def task_starts(self, job: str, index: int) -> list:
        """TASK_STARTED payloads for one task slot — one per container, so
        a surviving task keeps exactly one across peer relaunches."""
        return [e.payload for e in self.events_of_type(EventType.TASK_STARTED)
                if e.payload.task_type == job and e.payload.task_index == index]

    def am_log(self) -> str:
        chunks = []
        for name in (C.AM_STDERR, C.AM_STDOUT):
            path = os.path.join(self.client.app_dir, name)
            if os.path.isfile(path):
                with open(path, "r", errors="replace") as f:
                    chunks.append(f.read())
        return "\n".join(chunks)

    def session_retry_backoffs_ms(self) -> list:
        """The observed whole-session retry backoffs, parsed from the AM's
        'session failed; AM retry i/N after X ms backoff' log lines."""
        return [float(m) for m in re.findall(
            r"AM retry \d+/\d+ after (\d+) ms backoff", self.am_log())]

    def markers(self, job: str, index: int) -> list:
        """One parsed line per user-process start of `job:index` — the
        chaos scripts append {attempt, generation} on every launch, so this
        is the ground truth for 'survivor restarted its user process on the
        new generation without a new container'."""
        import json
        path = os.path.join(self.marker_dir, f"{job}_{index}")
        if not os.path.isfile(path):
            return []
        with open(path, "r") as f:
            return [json.loads(line) for line in f if line.strip()]

    def all_logs(self) -> str:
        """Every AM/container stream, for assertion messages."""
        chunks = []
        for root, _dirs, files in os.walk(self.client.app_dir):
            for f in files:
                if f in ("stdout", "stderr", C.AM_STDOUT, C.AM_STDERR):
                    path = os.path.join(root, f)
                    try:
                        with open(path, "r", errors="replace") as fh:
                            content = fh.read().strip()
                        if content:
                            chunks.append(f"==== {path} ====\n{content}")
                    except OSError:
                        pass
        return "\n".join(chunks)[-8000:]
