"""Docker runtime opt-in tests (reference model: TestUtils docker env case,
util/TestUtils.java:291)."""

from tony_tpu.cluster.docker import (
    ENV_CONTAINER_TYPE, ENV_DOCKER_IMAGE, ENV_DOCKER_MOUNTS,
    docker_env, docker_wrap_command,
)
from tony_tpu.conf import keys as K
from tony_tpu.conf.configuration import TonyConfiguration


def conf_with(**kv):
    conf = TonyConfiguration()
    for k, v in kv.items():
        conf.set(k, v, "test")
    return conf


def test_disabled_renders_nothing():
    conf = conf_with(**{K.DOCKER_IMAGE: "img:1"})
    assert docker_env(conf, "worker") is None


def test_global_image():
    conf = conf_with(**{K.DOCKER_ENABLED: True, K.DOCKER_IMAGE: "img:1",
                        K.DOCKER_MOUNTS: "/data:/data"})
    env = docker_env(conf, "worker")
    assert env[ENV_CONTAINER_TYPE] == "docker"
    assert env[ENV_DOCKER_IMAGE] == "img:1"
    assert env[ENV_DOCKER_MOUNTS] == "/data:/data"


def test_per_jobtype_image_override():
    conf = conf_with(**{K.DOCKER_ENABLED: True, K.DOCKER_IMAGE: "base:1",
                        K.jobtype_key("ps", "docker.image"): "ps-img:2"})
    assert docker_env(conf, "ps")[ENV_DOCKER_IMAGE] == "ps-img:2"
    assert docker_env(conf, "worker")[ENV_DOCKER_IMAGE] == "base:1"


def test_enabled_without_image_is_noop():
    conf = conf_with(**{K.DOCKER_ENABLED: True})
    assert docker_env(conf, "worker") is None


def test_wrap_command():
    argv = docker_wrap_command(
        "img:1", ["python", "train.py"],
        {"RANK": "0", "TONY_SECURITY_TOKEN": "s3cret"},
        mounts="/data:/mnt,/tmp", workdir="/job")
    assert argv[:4] == ["docker", "run", "--rm", "--network=host"]
    assert "-w" in argv and "/job" in argv
    assert "-v" in argv and "/data:/mnt" in argv and "/tmp:/tmp" in argv
    # pass-through form: names only — secrets must never land in argv
    # (world-readable /proc/<pid>/cmdline for the container's lifetime)
    assert "RANK" in argv and "TONY_SECURITY_TOKEN" in argv
    assert not any("s3cret" in a or "=" in a for a in argv
                   if a.startswith(("RANK", "TONY_")))
    assert argv[-3:] == ["img:1", "python", "train.py"]
