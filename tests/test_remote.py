"""RemoteClusterBackend tests: node parsing, launch-script hygiene, ssh
argv construction, and live multi-"host" scheduling over ExecTransport.

The ExecTransport cases are the multi-host analogue of the reference's
MiniCluster tier (SURVEY §4): real processes, real kill paths, separate
per-node root dirs standing in for separate hosts. SSH itself can't run
in the test image, so SSHTransport is covered at the argv/script layer
(the same split the reference used for GpuDiscoverer: parse layer tested
against fixtures, exec layer trusted to the OS)."""

from __future__ import annotations

import os
import threading
import time

import pytest

from tony_tpu.cluster.backend import EXIT_KILLED_BY_AM
from tony_tpu.cluster.remote import (
    ExecTransport, NodeSpec, RemoteClusterBackend, SSHTransport,
    build_launch_script, parse_nodes,
)


def test_parse_nodes():
    nodes = parse_nodes("tpu-vm-0:4, tpu-vm-1:2,solo", default_root="/scratch")
    assert [(n.host, n.slots, n.root) for n in nodes] == [
        ("tpu-vm-0", 4, "/scratch"), ("tpu-vm-1", 2, "/scratch"),
        ("solo", 1, "/scratch")]
    with pytest.raises(ValueError):
        NodeSpec.parse(":4")


def test_launch_script_never_leaks_secrets_to_argv():
    """Env values ride the script body (delivered over stdin), never argv —
    same rule as the docker -e KEY pass-through (round-1 ADVICE)."""
    script = build_launch_script(
        ["python", "-m", "tony_tpu.executor"],
        {"TONY_SECURITY_TOKEN": "s3cr3t", "A": "x y; rm -rf /"},
        "/nodes/n1/c1", "/nodes/n1/c1/container.pid")
    assert "export TONY_SECURITY_TOKEN=s3cr3t" in script
    assert "export A='x y; rm -rf /'" in script           # quoted, inert
    assert script.strip().endswith("exec python -m tony_tpu.executor")
    ssh = SSHTransport()
    argv = ssh.argv(NodeSpec("hostA"), "bash -s")
    assert argv[0] == "ssh" and argv[-2:] == ["hostA", "bash -s"]
    assert not any("s3cr3t" in a for a in argv)


def test_ssh_transport_requires_staging_location():
    """ssh nodes share no fs with the client: without a staging store the
    executors would silently run on an empty conf — fail at submission."""
    from tony_tpu.cluster import backend_from_conf
    from tony_tpu.conf import TonyConfiguration, keys as K

    conf = TonyConfiguration()
    conf.set(K.CLUSTER_BACKEND, "remote", "test")
    conf.set(K.CLUSTER_NODES, "hostA:2", "test")
    with pytest.raises(ValueError, match="staging.location"):
        backend_from_conf(conf, "app1")
    conf.set(K.STAGING_LOCATION, "gs://bkt/stage", "test")
    backend = backend_from_conf(conf, "app1")
    assert backend.off_host


def _collect_backend(nodes):
    backend = RemoteClusterBackend(nodes, ExecTransport(), app_id="t")
    allocated, completed = [], {}
    done = threading.Event()

    def on_alloc(c):
        allocated.append(c)

    def on_done(cid, rc):
        completed[cid] = rc
        done.set()

    backend.set_callbacks(on_alloc, on_done)
    return backend, allocated, completed, done


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_allocation_spreads_across_nodes(tmp_path):
    nodes = parse_nodes("nodeA:2,nodeB:2", default_root=str(tmp_path / "n"))
    backend, allocated, _, _ = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(4, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: len(allocated) == 4)
        hosts = sorted(c.host for c in allocated)
        assert hosts == ["nodeA", "nodeA", "nodeB", "nodeB"]
    finally:
        backend.stop()


def test_launch_runs_in_node_root_and_reports_exit(tmp_path):
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "roots"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: allocated)
        c = allocated[0]
        cwd = str(tmp_path / "am" / c.container_id)
        backend.launch_container(
            c, ["bash", "-c", "pwd; echo out-line; exit 7"], {}, cwd)
        assert done.wait(10)
        assert completed[c.container_id] == 7
        out = open(os.path.join(cwd, "stdout")).read()
        # the process ran inside the NODE's root, not the AM-side cwd...
        assert out.splitlines()[0].startswith(str(tmp_path / "roots"))
        # ...but its stdout streamed back into the AM-side container dir
        assert "out-line" in out
    finally:
        backend.stop()


def test_stop_container_kills_remote_tree(tmp_path):
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "roots"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: allocated)
        c = allocated[0]
        cwd = str(tmp_path / "am" / c.container_id)
        backend.launch_container(c, ["sleep", "600"], {}, cwd)
        pidfile = os.path.join(str(tmp_path / "roots"), c.container_id,
                               "container.pid")
        assert _wait(lambda: os.path.exists(pidfile))
        backend.stop_container(c.container_id)
        assert done.wait(10)
        assert completed[c.container_id] == EXIT_KILLED_BY_AM
    finally:
        backend.stop()


def test_slot_capacity_queues_excess_requests(tmp_path):
    """Sequential slot reuse is the UNTRACKED (gang=False) semantic —
    gang requests beyond co-residency fail fast instead (see
    test_gang_aggregate_feasibility)."""
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "n"))
    backend, allocated, completed, _ = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(2, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0, gang=False)
        assert _wait(lambda: len(allocated) == 1)
        c0 = allocated[0]
        backend.launch_container(
            c0, ["bash", "-c", "sleep 0.5"], {},
            str(tmp_path / "am" / c0.container_id))
        # second allocation only lands after the first frees the slot
        assert _wait(lambda: len(allocated) == 2, timeout=15)
        assert c0.container_id in completed or _wait(
            lambda: c0.container_id in completed)
    finally:
        backend.stop()


# ---------------------------------------------------------------------------
# placement constraints (VERDICT r4 item 2): node labels + declared
# capacity vectors, matching TonyClient.java:260 setNodeLabelExpression
# and util/Utils.java:186-204 resource quantities
# ---------------------------------------------------------------------------

def test_parse_node_attributes():
    nodes = parse_nodes(
        "tpu-a:4;label=tpu;tpus=8;memory=16g, cpu-b:2;gpus=0, plain",
        default_root="/r")
    a, b, c = nodes
    assert (a.host, a.slots, a.label, a.tpus, a.memory_mb) == \
        ("tpu-a", 4, "tpu", 8, 16384)
    assert a.gpus == -1                       # undeclared = unconstrained
    assert (b.host, b.slots, b.gpus, b.tpus) == ("cpu-b", 2, 0, -1)
    assert (c.host, c.label, c.tpus) == ("plain", "", -1)
    with pytest.raises(ValueError, match="unknown node attribute"):
        NodeSpec.parse("h:1;cores=4")
    with pytest.raises(ValueError, match="key=value"):
        NodeSpec.parse("h:1;label")


def test_labeled_request_lands_only_on_matching_node(tmp_path):
    """YARN-exclusive label semantics: labeled requests go only to nodes
    with that exact label; unlabeled requests only to the default
    partition."""
    nodes = parse_nodes("plainA:2,tpuB:2;label=tpu",
                        default_root=str(tmp_path / "n"))
    backend, allocated, _, _ = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(2, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0, node_label="tpu")
        assert _wait(lambda: len(allocated) == 2)
        assert {c.host for c in allocated} == {"tpuB"}
        backend.request_containers(2, priority=2, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: len(allocated) == 4)
        assert {c.host for c in allocated[2:]} == {"plainA"}
    finally:
        backend.stop()


def test_capacity_vector_bounds_coresidency(tmp_path):
    """A node declaring tpus=8 holds two tpus=4 containers but queues a
    third (untracked/sequential semantics) until one frees its share."""
    nodes = parse_nodes("tpuA:4;tpus=8", default_root=str(tmp_path / "n"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(3, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=4, gang=False)
        assert _wait(lambda: len(allocated) == 2)
        time.sleep(0.5)
        assert len(allocated) == 2            # third is tpu-starved
        c0 = allocated[0]
        backend.launch_container(
            c0, ["bash", "-c", "exit 0"], {},
            str(tmp_path / "am" / c0.container_id))
        assert _wait(lambda: len(allocated) == 3, timeout=15)
    finally:
        backend.stop()


def test_unsatisfiable_request_fails_fast(tmp_path):
    """An ask NO node can ever fit raises immediately with the node
    inventory in the message — not a 15-min registration-timeout spin."""
    from tony_tpu.cluster.backend import UnsatisfiableRequestError

    nodes = parse_nodes("a:2;tpus=4,b:2", default_root=str(tmp_path / "n"))
    backend, _, _, _ = _collect_backend(nodes)
    backend.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(UnsatisfiableRequestError) as ei:
            backend.request_containers(1, priority=1, memory_mb=0,
                                       vcores=1, gpus=0, tpus=0,
                                       node_label="gpu")
        assert time.monotonic() - t0 < 1.0
        assert "label='gpu'" in str(ei.value)
        assert "a:2" in str(ei.value)         # inventory listed
        # resource-quantity infeasibility: b has no declared tpu capacity
        # (unconstrained), so 16 tpus still fits SOMEWHERE -> no raise
        backend.request_containers(1, priority=2, memory_mb=0, vcores=1,
                                   gpus=0, tpus=16)
        # but a gpu ask above every declared bound with gpus declared
        # nowhere... declare one: label-free 99-gpu ask vs gpus=0 node
        nodes2 = parse_nodes("only:1;tpus=4;gpus=0;memory=1g")
        b2, _, _, _ = _collect_backend(nodes2)
        with pytest.raises(UnsatisfiableRequestError, match="tpus=8"):
            b2.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                  gpus=0, tpus=8)
        with pytest.raises(UnsatisfiableRequestError, match="memory_mb"):
            b2.request_containers(1, priority=1, memory_mb=2048, vcores=1,
                                  gpus=0, tpus=0)
    finally:
        backend.stop()


def test_gang_aggregate_feasibility(tmp_path):
    """`num` containers must be able to be CO-RESIDENT (the gang barrier
    waits for all of them): 5 asks into a 4-slot partition fail fast
    even though each single container fits."""
    from tony_tpu.cluster.backend import UnsatisfiableRequestError

    nodes = parse_nodes("tpuB:4;label=tpu", default_root=str(tmp_path))
    backend, _, _, _ = _collect_backend(nodes)
    with pytest.raises(UnsatisfiableRequestError, match="co-host at most 4"):
        backend.request_containers(5, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0, node_label="tpu")
    # resource-bounded co-residency: 8 tpus / 4 per container = 2 max
    nodes2 = parse_nodes("a:16;tpus=8")
    b2, _, _, _ = _collect_backend(nodes2)
    with pytest.raises(UnsatisfiableRequestError, match="co-host at most 2"):
        b2.request_containers(3, priority=1, memory_mb=0, vcores=1,
                              gpus=0, tpus=4)


def test_starved_head_does_not_block_other_partitions(tmp_path):
    """First-fit over the pending list: a label-starved request at the
    head (its partition full) must not stall an unlabeled request that
    plainA can place right now."""
    nodes = parse_nodes("plainA:1,tpuB:1;label=tpu",
                        default_root=str(tmp_path / "n"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0, node_label="tpu")
        assert _wait(lambda: len(allocated) == 1)
        # tpuB's single slot is now held; this labeled ask must wait...
        backend.request_containers(1, priority=2, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0, node_label="tpu")
        # ...but the unlabeled one behind it lands on plainA immediately
        backend.request_containers(1, priority=3, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: any(c.host == "plainA" for c in allocated))
        assert len([c for c in allocated if c.host == "tpuB"]) == 1
        # release tpuB -> the waiting labeled ask finally places
        c0 = allocated[0]
        backend.launch_container(
            c0, ["bash", "-c", "exit 0"], {},
            str(tmp_path / "am" / c0.container_id))
        assert _wait(lambda: len(
            [c for c in allocated if c.host == "tpuB"]) == 2, timeout=15)
    finally:
        backend.stop()


def test_joint_coresident_validation(tmp_path):
    """Cross-jobtype gang feasibility: ps=2 + worker=3 each fit a 4-slot
    pool alone, but 5 can never co-reside -> validate_coresident raises;
    a fitting combination passes."""
    from tony_tpu.cluster.backend import UnsatisfiableRequestError

    nodes = parse_nodes("a:4", default_root=str(tmp_path))
    backend, _, _, _ = _collect_backend(nodes)
    with pytest.raises(UnsatisfiableRequestError, match="jointly need"):
        backend.validate_coresident([(2, 0, 0, 0, ""), (3, 0, 0, 0, "")])
    backend.validate_coresident([(2, 0, 0, 0, ""), (2, 0, 0, 0, "")])
    # resource-dimension sum: both nodes declare tpus -> 2x(4 tpus) +
    # 1x(4 tpus) = 12 > 8 total
    nodes2 = parse_nodes("a:8;tpus=4,b:8;tpus=4")
    b2, _, _, _ = _collect_backend(nodes2)
    with pytest.raises(UnsatisfiableRequestError, match="tpus"):
        b2.validate_coresident([(2, 0, 0, 4, ""), (1, 0, 0, 4, "")])
    # an undeclared node in the partition unbounds the dimension
    nodes3 = parse_nodes("a:8;tpus=4,b:8")
    b3, _, _, _ = _collect_backend(nodes3)
    b3.validate_coresident([(2, 0, 0, 4, ""), (1, 0, 0, 4, "")])
