"""RemoteClusterBackend tests: node parsing, launch-script hygiene, ssh
argv construction, and live multi-"host" scheduling over ExecTransport.

The ExecTransport cases are the multi-host analogue of the reference's
MiniCluster tier (SURVEY §4): real processes, real kill paths, separate
per-node root dirs standing in for separate hosts. SSH itself can't run
in the test image, so SSHTransport is covered at the argv/script layer
(the same split the reference used for GpuDiscoverer: parse layer tested
against fixtures, exec layer trusted to the OS)."""

from __future__ import annotations

import os
import threading
import time

import pytest

from tony_tpu.cluster.backend import EXIT_KILLED_BY_AM
from tony_tpu.cluster.remote import (
    ExecTransport, NodeSpec, RemoteClusterBackend, SSHTransport,
    build_launch_script, parse_nodes,
)


def test_parse_nodes():
    nodes = parse_nodes("tpu-vm-0:4, tpu-vm-1:2,solo", default_root="/scratch")
    assert [(n.host, n.slots, n.root) for n in nodes] == [
        ("tpu-vm-0", 4, "/scratch"), ("tpu-vm-1", 2, "/scratch"),
        ("solo", 1, "/scratch")]
    with pytest.raises(ValueError):
        NodeSpec.parse(":4")


def test_launch_script_never_leaks_secrets_to_argv():
    """Env values ride the script body (delivered over stdin), never argv —
    same rule as the docker -e KEY pass-through (round-1 ADVICE)."""
    script = build_launch_script(
        ["python", "-m", "tony_tpu.executor"],
        {"TONY_SECURITY_TOKEN": "s3cr3t", "A": "x y; rm -rf /"},
        "/nodes/n1/c1", "/nodes/n1/c1/container.pid")
    assert "export TONY_SECURITY_TOKEN=s3cr3t" in script
    assert "export A='x y; rm -rf /'" in script           # quoted, inert
    assert script.strip().endswith("exec python -m tony_tpu.executor")
    ssh = SSHTransport()
    argv = ssh.argv(NodeSpec("hostA"), "bash -s")
    assert argv[0] == "ssh" and argv[-2:] == ["hostA", "bash -s"]
    assert not any("s3cr3t" in a for a in argv)


def test_ssh_transport_requires_staging_location():
    """ssh nodes share no fs with the client: without a staging store the
    executors would silently run on an empty conf — fail at submission."""
    from tony_tpu.cluster import backend_from_conf
    from tony_tpu.conf import TonyConfiguration, keys as K

    conf = TonyConfiguration()
    conf.set(K.CLUSTER_BACKEND, "remote", "test")
    conf.set(K.CLUSTER_NODES, "hostA:2", "test")
    with pytest.raises(ValueError, match="staging.location"):
        backend_from_conf(conf, "app1")
    conf.set(K.STAGING_LOCATION, "gs://bkt/stage", "test")
    backend = backend_from_conf(conf, "app1")
    assert backend.off_host


def _collect_backend(nodes):
    backend = RemoteClusterBackend(nodes, ExecTransport(), app_id="t")
    allocated, completed = [], {}
    done = threading.Event()

    def on_alloc(c):
        allocated.append(c)

    def on_done(cid, rc):
        completed[cid] = rc
        done.set()

    backend.set_callbacks(on_alloc, on_done)
    return backend, allocated, completed, done


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_allocation_spreads_across_nodes(tmp_path):
    nodes = parse_nodes("nodeA:2,nodeB:2", default_root=str(tmp_path / "n"))
    backend, allocated, _, _ = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(4, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: len(allocated) == 4)
        hosts = sorted(c.host for c in allocated)
        assert hosts == ["nodeA", "nodeA", "nodeB", "nodeB"]
    finally:
        backend.stop()


def test_launch_runs_in_node_root_and_reports_exit(tmp_path):
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "roots"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: allocated)
        c = allocated[0]
        cwd = str(tmp_path / "am" / c.container_id)
        backend.launch_container(
            c, ["bash", "-c", "pwd; echo out-line; exit 7"], {}, cwd)
        assert done.wait(10)
        assert completed[c.container_id] == 7
        out = open(os.path.join(cwd, "stdout")).read()
        # the process ran inside the NODE's root, not the AM-side cwd...
        assert out.splitlines()[0].startswith(str(tmp_path / "roots"))
        # ...but its stdout streamed back into the AM-side container dir
        assert "out-line" in out
    finally:
        backend.stop()


def test_stop_container_kills_remote_tree(tmp_path):
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "roots"))
    backend, allocated, completed, done = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(1, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: allocated)
        c = allocated[0]
        cwd = str(tmp_path / "am" / c.container_id)
        backend.launch_container(c, ["sleep", "600"], {}, cwd)
        pidfile = os.path.join(str(tmp_path / "roots"), c.container_id,
                               "container.pid")
        assert _wait(lambda: os.path.exists(pidfile))
        backend.stop_container(c.container_id)
        assert done.wait(10)
        assert completed[c.container_id] == EXIT_KILLED_BY_AM
    finally:
        backend.stop()


def test_slot_capacity_queues_excess_requests(tmp_path):
    nodes = parse_nodes("nodeA:1", default_root=str(tmp_path / "n"))
    backend, allocated, completed, _ = _collect_backend(nodes)
    backend.start()
    try:
        backend.request_containers(2, priority=1, memory_mb=0, vcores=1,
                                   gpus=0, tpus=0)
        assert _wait(lambda: len(allocated) == 1)
        c0 = allocated[0]
        backend.launch_container(
            c0, ["bash", "-c", "sleep 0.5"], {},
            str(tmp_path / "am" / c0.container_id))
        # second allocation only lands after the first frees the slot
        assert _wait(lambda: len(allocated) == 2, timeout=15)
        assert c0.container_id in completed or _wait(
            lambda: c0.container_id in completed)
    finally:
        backend.stop()
