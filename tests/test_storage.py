"""Staging-store tests: local dir, gs:// via a fake gsutil, URI localize.

The store is the HDFS-upload seam (TonyClient.java:519-590 role); GCS is
exercised against a PATH-shimmed `gsutil` that mirrors cp/ls onto a local
dir — the GpuDiscoverer-style canned-fixture pattern (SURVEY §4: tests
parse canned nvidia-smi output instead of real GPUs)."""

from __future__ import annotations

import os

from tony_tpu.storage import (
    GCSStore, LocalDirStore, fetch_uri, staging_store,
)
from tony_tpu.utils.localization import localize_resource, stage_resource

def test_local_store_roundtrip(tmp_path):
    store = LocalDirStore(str(tmp_path / "stage"))
    src = tmp_path / "a.txt"
    src.write_text("payload")
    uri = store.put(str(src), "a.txt")
    assert os.path.isabs(uri) and store.exists(uri)
    dest = store.fetch(uri, str(tmp_path / "out" / "a.txt"))
    assert open(dest).read() == "payload"


def test_gcs_store_roundtrip(tmp_path, fake_gcs):
    store = GCSStore("gs://bkt/apps/app1")
    src = tmp_path / "conf.json"
    src.write_text("{}")
    uri = store.put(str(src), "tony-final.json")
    assert uri == "gs://bkt/apps/app1/tony-final.json"
    assert store.exists(uri)
    assert not store.exists("gs://bkt/apps/app1/nope")
    out = fetch_uri(uri, str(tmp_path / "dl" / "conf.json"))
    assert open(out).read() == "{}"


def test_staging_store_selection(tmp_path, fake_gcs):
    app_dir = str(tmp_path / "appX")
    os.makedirs(app_dir)
    local = staging_store("", app_dir)
    assert isinstance(local, LocalDirStore)
    assert local.root == os.path.join(app_dir, "staging")
    gcs = staging_store("gs://bkt/stage", app_dir)
    assert isinstance(gcs, GCSStore)
    # per-app namespacing, like .tony/<appId> on HDFS
    assert gcs.base.endswith("/appX")
    explicit = staging_store(str(tmp_path / "shared"), app_dir)
    assert isinstance(explicit, LocalDirStore)
    # shared dirs are app-namespaced too: concurrent apps staging fixed
    # keys (tony_src.zip) into one NFS dir must not clobber each other
    assert explicit.root == str(tmp_path / "shared" / "appX")


def test_list_keys_local_and_gcs(tmp_path, fake_gcs):
    """Enumeration (checkpoint COMMIT discovery, portal history fetcher)
    on both store kinds."""
    local = LocalDirStore(str(tmp_path / "stage"))
    for key in ("a.txt", "sub/b.txt", "sub/deep/c.txt"):
        src = tmp_path / "src.txt"
        src.write_text("x")
        local.put(str(src), key)
    assert local.list_keys() == ["a.txt", "sub/b.txt", "sub/deep/c.txt"]
    assert local.list_keys("sub") == ["sub/b.txt", "sub/deep/c.txt"]
    assert local.uri("a.txt") == os.path.join(local.root, "a.txt")

    gcs = GCSStore("gs://bkt/app")
    assert gcs.list_keys() == []          # empty listing is not an error
    src = tmp_path / "s.txt"
    src.write_text("y")
    gcs.put(str(src), "x/one.txt")
    gcs.put(str(src), "x/y/two.txt")
    assert gcs.list_keys() == ["x/one.txt", "x/y/two.txt"]
    assert gcs.list_keys("x/y") == ["x/y/two.txt"]
    assert gcs.uri("x/one.txt") == "gs://bkt/app/x/one.txt"


def test_stage_and_localize_through_gcs(tmp_path, fake_gcs):
    """resource spec -> gs:// URI in conf -> container-side localize."""
    src_dir = tmp_path / "data"
    src_dir.mkdir()
    (src_dir / "f.txt").write_text("x")
    store = GCSStore("gs://bkt/app")
    staged = stage_resource(str(src_dir), store)
    assert staged.startswith("gs://bkt/app/data.zip")
    assert staged.endswith("#archive")
    workdir = tmp_path / "container"
    workdir.mkdir()
    out = localize_resource(staged, str(workdir))
    assert open(os.path.join(out, "f.txt")).read() == "x"

    plain = tmp_path / "w.txt"
    plain.write_text("w")
    staged_file = stage_resource(f"{plain}::weights.txt", store)
    assert staged_file == "gs://bkt/app/weights.txt"
    localize_resource(staged_file, str(workdir))
    assert open(workdir / "weights.txt").read() == "w"
