"""Staging-store tests: local dir, gs:// via a fake gsutil, URI localize.

The store is the HDFS-upload seam (TonyClient.java:519-590 role); GCS is
exercised against a PATH-shimmed `gsutil` that mirrors cp/ls onto a local
dir — the GpuDiscoverer-style canned-fixture pattern (SURVEY §4: tests
parse canned nvidia-smi output instead of real GPUs)."""

from __future__ import annotations

import os
import stat

import pytest

from tony_tpu.storage import (
    GCSStore, LocalDirStore, fetch_uri, staging_store,
)
from tony_tpu.utils.localization import localize_resource, stage_resource

FAKE_GSUTIL = """#!/bin/bash
# fake gsutil: maps gs://<bucket>/<key> onto $FAKE_GCS_ROOT/<bucket>/<key>
set -e
cmd=$1; shift
map() { echo "$FAKE_GCS_ROOT/${1#gs://}"; }
case "$cmd" in
  cp)
    src=$1; dst=$2
    [[ $src == gs://* ]] && src=$(map "$src")
    if [[ $dst == gs://* ]]; then dst=$(map "$dst"); mkdir -p "$(dirname "$dst")"; fi
    cp "$src" "$dst"
    ;;
  ls)
    p=$(map "$1"); [[ -e $p ]] || { echo "CommandException: no URLs matched" >&2; exit 1; }
    ;;
  *) echo "unsupported: $cmd" >&2; exit 2 ;;
esac
"""


@pytest.fixture
def fake_gcs(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    gsutil = bindir / "gsutil"
    gsutil.write_text(FAKE_GSUTIL)
    gsutil.chmod(gsutil.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCS_ROOT", str(tmp_path / "gcs"))
    return tmp_path / "gcs"


def test_local_store_roundtrip(tmp_path):
    store = LocalDirStore(str(tmp_path / "stage"))
    src = tmp_path / "a.txt"
    src.write_text("payload")
    uri = store.put(str(src), "a.txt")
    assert os.path.isabs(uri) and store.exists(uri)
    dest = store.fetch(uri, str(tmp_path / "out" / "a.txt"))
    assert open(dest).read() == "payload"


def test_gcs_store_roundtrip(tmp_path, fake_gcs):
    store = GCSStore("gs://bkt/apps/app1")
    src = tmp_path / "conf.json"
    src.write_text("{}")
    uri = store.put(str(src), "tony-final.json")
    assert uri == "gs://bkt/apps/app1/tony-final.json"
    assert store.exists(uri)
    assert not store.exists("gs://bkt/apps/app1/nope")
    out = fetch_uri(uri, str(tmp_path / "dl" / "conf.json"))
    assert open(out).read() == "{}"


def test_staging_store_selection(tmp_path, fake_gcs):
    app_dir = str(tmp_path / "appX")
    os.makedirs(app_dir)
    local = staging_store("", app_dir)
    assert isinstance(local, LocalDirStore)
    assert local.root == os.path.join(app_dir, "staging")
    gcs = staging_store("gs://bkt/stage", app_dir)
    assert isinstance(gcs, GCSStore)
    # per-app namespacing, like .tony/<appId> on HDFS
    assert gcs.base.endswith("/appX")
    explicit = staging_store(str(tmp_path / "shared"), app_dir)
    assert isinstance(explicit, LocalDirStore)
    # shared dirs are app-namespaced too: concurrent apps staging fixed
    # keys (tony_src.zip) into one NFS dir must not clobber each other
    assert explicit.root == str(tmp_path / "shared" / "appX")


def test_stage_and_localize_through_gcs(tmp_path, fake_gcs):
    """resource spec -> gs:// URI in conf -> container-side localize."""
    src_dir = tmp_path / "data"
    src_dir.mkdir()
    (src_dir / "f.txt").write_text("x")
    store = GCSStore("gs://bkt/app")
    staged = stage_resource(str(src_dir), store)
    assert staged.startswith("gs://bkt/app/data.zip")
    assert staged.endswith("#archive")
    workdir = tmp_path / "container"
    workdir.mkdir()
    out = localize_resource(staged, str(workdir))
    assert open(os.path.join(out, "f.txt")).read() == "x"

    plain = tmp_path / "w.txt"
    plain.write_text("w")
    staged_file = stage_resource(f"{plain}::weights.txt", store)
    assert staged_file == "gs://bkt/app/weights.txt"
    localize_resource(staged_file, str(workdir))
    assert open(workdir / "weights.txt").read() == "w"
