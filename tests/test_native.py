"""Native helper tests: build, proxy relay, port reservation.

Reference models: the tony-proxy relay behavior (ProxyServer.java:21-91) and
TestPortAllocation.java's real-socket SO_REUSEPORT checks (:19-80); skip
cleanly when no toolchain is present, as the reference skipped SO_REUSEPORT
tests off-Linux.
"""

import os
import shutil
import socket
import socketserver
import threading

import pytest

from tony_tpu.utils.native import (
    launch_native_proxy, launch_port_reservation, native_binary,
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no native toolchain")


def test_native_binaries_build():
    assert native_binary("tony_proxy") is not None
    assert native_binary("tony_portres") is not None


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(data.upper())


@pytest.fixture()
def echo_server():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def test_native_proxy_relays_both_directions(echo_server):
    launched = launch_native_proxy("127.0.0.1", echo_server)
    assert launched is not None
    proc, port = launched
    try:
        payload = b"hello tpu proxy " * 1000   # multi-buffer payload
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(payload)
            s.shutdown(socket.SHUT_WR)
            received = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                received += chunk
        assert received == payload.upper()
    finally:
        proc.kill()
        proc.wait()


def test_native_proxy_concurrent_connections(echo_server):
    launched = launch_native_proxy("127.0.0.1", echo_server)
    assert launched is not None
    proc, port = launched
    try:
        socks = [socket.create_connection(("127.0.0.1", port), timeout=5)
                 for _ in range(8)]
        for i, s in enumerate(socks):
            s.sendall(f"conn{i}".encode())
        for i, s in enumerate(socks):
            assert s.recv(100) == f"CONN{i}".upper().encode()
        for s in socks:
            s.close()
    finally:
        proc.kill()
        proc.wait()


def test_port_reservation_holds_and_reuseport_binds(tmp_path):
    sentinel = str(tmp_path / "ready")
    launched = launch_port_reservation(sentinel, n_ports=2)
    assert launched is not None
    proc, ports = launched
    try:
        assert len(ports) == 2 and os.path.exists(sentinel)
        # a plain bind must fail while the helper holds the port...
        plain = socket.socket()
        plain.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        with pytest.raises(OSError):
            plain.bind(("", ports[0]))
        plain.close()
        # ...but an SO_REUSEPORT bind (the TF/JAX server pattern) succeeds
        reuser = socket.socket()
        reuser.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        reuser.bind(("", ports[0]))
        reuser.close()
    finally:
        proc.terminate()
        assert proc.wait(timeout=5) == 0
