"""Native helper tests: build, proxy relay, port reservation.

Reference models: the tony-proxy relay behavior (ProxyServer.java:21-91) and
TestPortAllocation.java's real-socket SO_REUSEPORT checks (:19-80); skip
cleanly when no toolchain is present, as the reference skipped SO_REUSEPORT
tests off-Linux.
"""

import os
import shutil
import socket

import pytest

from conftest import recv_all as _recv_all  # shared relay-test helpers
from tony_tpu.utils.native import (
    launch_native_proxy, launch_port_reservation, native_binary,
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no native toolchain")


def test_native_binaries_build():
    assert native_binary("tony_proxy") is not None
    assert native_binary("tony_portres") is not None


def test_native_proxy_relays_both_directions(echo_server):
    launched = launch_native_proxy("127.0.0.1", echo_server)
    assert launched is not None
    proc, port = launched
    try:
        payload = b"hello tpu proxy " * 1000   # multi-buffer payload
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(payload)
            s.shutdown(socket.SHUT_WR)
            received = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                received += chunk
        assert received == payload.upper()
    finally:
        proc.kill()
        proc.wait()


def test_native_proxy_concurrent_connections(echo_server):
    launched = launch_native_proxy("127.0.0.1", echo_server)
    assert launched is not None
    proc, port = launched
    try:
        socks = [socket.create_connection(("127.0.0.1", port), timeout=5)
                 for _ in range(8)]
        for i, s in enumerate(socks):
            s.sendall(f"conn{i}".encode())
        for i, s in enumerate(socks):
            assert s.recv(100) == f"CONN{i}".upper().encode()
        for s in socks:
            s.close()
    finally:
        proc.kill()
        proc.wait()


def test_native_proxy_token_auth(echo_server):
    """VERDICT-r2 item 6: with TONY_PROXY_TOKEN set, the native relay
    forwards nothing until the connection authenticates (preamble or
    HTTP), closes unauthenticated connections, and — after one success —
    unlocks the source address for a grace window (browser parallel
    connections carry no credentials)."""
    launched = launch_native_proxy("127.0.0.1", echo_server, token="tok123")
    assert launched is not None
    proc, port = launched
    try:
        # every reject case FIRST (one success unlocks this source ip)
        for payload in (
                b"sneaky payload\n",                                # no auth
                b"TONY-PROXY-AUTH wrong\npayload",                  # bad tok
                b"GET /?tony-proxy-token=no HTTP/1.1\r\nHost: x\r\n\r\n",
                # plain ?token= belongs to the proxied app, never to us
                b"GET /?token=tok123 HTTP/1.1\r\nHost: x\r\n\r\n",
                b"GET / HTTP/1.1\r\nAuthorization: Bearer no\r\n\r\n"):
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(payload)
                s.shutdown(socket.SHUT_WR)
                assert _recv_all(s) == b"", payload
        # good preamble: stripped, rest relayed both ways
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"TONY-PROXY-AUTH tok123\nhello")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"HELLO"
        # source now unlocked: a bare connection relays (grace window)
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"bare after unlock")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"BARE AFTER UNLOCK"
        # a preamble during the grace window is still consumed/verified —
        # the token line must never reach the upstream as payload
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"TONY-PROXY-AUTH tok123\nagain")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b"AGAIN"
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"TONY-PROXY-AUTH wrong\npayload")
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == b""
    finally:
        proc.kill()
        proc.wait()


def test_native_proxy_http_auth_modes(echo_server):
    """Header and query-string HTTP auth, each on a fresh proxy (so the
    grace unlock from one case can't mask the next)."""
    for req in (
            b"GET / HTTP/1.1\r\nHost: x\r\n"
            b"Authorization: Bearer tok123\r\n\r\n",
            b"GET /tree?a=b&tony-proxy-token=tok123 HTTP/1.1\r\n"
            b"Host: x\r\n\r\n"):
        launched = launch_native_proxy("127.0.0.1", echo_server,
                                       token="tok123")
        assert launched is not None
        proc, port = launched
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(req)
                s.shutdown(socket.SHUT_WR)
                assert _recv_all(s) == req.upper()   # forwarded unmodified
        finally:
            proc.kill()
            proc.wait()


def test_native_proxy_auth_payload_larger_than_first_read(echo_server):
    """A valid preamble followed by a large coalesced payload must not be
    rejected by the pre-auth buffer cap (review finding)."""
    launched = launch_native_proxy("127.0.0.1", echo_server, token="tok123")
    assert launched is not None
    proc, port = launched
    try:
        payload = b"x" * (20 * 1024)
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"TONY-PROXY-AUTH tok123\n" + payload)
            s.shutdown(socket.SHUT_WR)
            assert _recv_all(s) == payload.upper()
    finally:
        proc.kill()
        proc.wait()


def test_port_reservation_holds_and_reuseport_binds(tmp_path):
    sentinel = str(tmp_path / "ready")
    launched = launch_port_reservation(sentinel, n_ports=2)
    assert launched is not None
    proc, ports = launched
    try:
        assert len(ports) == 2 and os.path.exists(sentinel)
        # a plain bind must fail while the helper holds the port...
        plain = socket.socket()
        plain.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        with pytest.raises(OSError):
            plain.bind(("", ports[0]))
        plain.close()
        # ...but an SO_REUSEPORT bind (the TF/JAX server pattern) succeeds
        reuser = socket.socket()
        reuser.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        reuser.bind(("", ports[0]))
        reuser.close()
    finally:
        proc.terminate()
        assert proc.wait(timeout=5) == 0
