"""DAG scheduler tests (reference model: TestTaskScheduler.java:32+)."""

from tony_tpu.conf import TonyConfiguration
from tony_tpu.session import (
    TonySession, TaskScheduler, ResourceRequestor, FinalStatus,
    JobContainerRequest,
)
from tony_tpu.session.scheduler import is_dag


class RecordingRequestor(ResourceRequestor):
    def __init__(self):
        self.requested = []

    def request_containers(self, request):
        self.requested.append(request.job_name)


def make_session(**jobs_and_deps):
    conf = TonyConfiguration()
    for job, (n, deps) in jobs_and_deps.items():
        conf.set(f"tony.{job}.instances", n)
        if deps:
            conf.set(f"tony.{job}.depends-on", deps)
    return TonySession(conf)


def test_is_dag_detects_cycle():
    a = JobContainerRequest("a", 1, depends_on=["b"])
    b = JobContainerRequest("b", 1, depends_on=["a"])
    assert not is_dag([a, b])
    assert is_dag([JobContainerRequest("a", 1, depends_on=[]),
                   JobContainerRequest("b", 1, depends_on=["a"])])
    assert not is_dag([JobContainerRequest("x", 1, depends_on=["x"])])


def test_cycle_fails_session():
    s = make_session(a=(1, "b"), b=(1, "a"))
    req = RecordingRequestor()
    sched = TaskScheduler(s, req)
    sched.schedule_tasks()
    assert not sched.dependency_check_passed
    assert s.final_status == FinalStatus.FAILED
    assert req.requested == []


def test_independent_jobs_all_scheduled_immediately():
    s = make_session(worker=(2, ""), ps=(1, ""))
    req = RecordingRequestor()
    TaskScheduler(s, req).schedule_tasks()
    assert sorted(req.requested) == ["ps", "worker"]
    assert s.num_expected_tasks == 3


def test_dependency_release_chain():
    """prep(2) -> train(1) -> eval(1): released one level at a time as
    instances complete (TaskScheduler.registerDependencyCompleted)."""
    s = make_session(prep=(2, ""), train=(1, "prep"), evaluate=(1, "train"))
    req = RecordingRequestor()
    sched = TaskScheduler(s, req)
    sched.schedule_tasks()
    assert req.requested == ["prep"]
    assert s.num_expected_tasks == 2

    sched.register_dependency_completed("prep")
    assert "train" not in req.requested          # 1 of 2 preps done
    sched.register_dependency_completed("prep")
    assert req.requested == ["prep", "train"]    # both done -> train released
    assert s.num_expected_tasks == 3

    sched.register_dependency_completed("train")
    assert req.requested == ["prep", "train", "evaluate"]
    assert s.num_expected_tasks == 4


def test_diamond_dependency():
    s = make_session(src=(1, ""), left=(1, "src"), right=(1, "src"),
                     sink=(1, "left,right"))
    req = RecordingRequestor()
    sched = TaskScheduler(s, req)
    sched.schedule_tasks()
    assert req.requested == ["src"]
    sched.register_dependency_completed("src")
    assert sorted(req.requested[1:]) == ["left", "right"]
    sched.register_dependency_completed("left")
    assert "sink" not in req.requested
    sched.register_dependency_completed("right")
    assert req.requested[-1] == "sink"
