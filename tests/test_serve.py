"""Online-serving subsystem tests (serve/engine.py + serve/frontend.py +
the `serving` jobtype e2e).

The load-bearing contract: continuous-batching greedy decode is
BIT-IDENTICAL to the offline `generate()` oracle for the same prompts,
under staggered arrival order and slot recycling, with zero decode-step
recompiles after warmup. Everything else (backpressure, streaming,
endpoint registration, shutdown hygiene) is the serving lifecycle around
that core. All CPU-backend, tier-1 fast.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu.models.generate import generate
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.serve.engine import (
    BudgetExceededError, ContinuousBatchingEngine, QueueFullError,
    admit_step_cache_size, decode_step_cache_size,
)
from tony_tpu.serve.frontend import ServeFrontend

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def model():
    cfg = get_config("tiny")
    return llama_init(cfg, jax.random.PRNGKey(0)), cfg


def _prompts(cfg, lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
            for n in lengths]


def _oracle(params, cfg, prompt, n, **kw):
    """Offline single-request greedy generate — the parity oracle."""
    out = generate(params, cfg, jnp.asarray([prompt], jnp.int32), n, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _drain(engine, handles, max_steps=200):
    for _ in range(max_steps):
        if all(h.done.is_set() for h in handles):
            return
        engine.step()
    raise AssertionError("engine did not finish the workload")


# ---------------------------------------------------------------------------
# the core contract
# ---------------------------------------------------------------------------

def test_staggered_arrivals_bit_identical_to_offline_oracle(model):
    """Requests arriving mid-flight, recycled slots, mixed prompt lengths:
    every request's greedy tokens equal offline generate() on that prompt
    alone — and the persistent decode step never recompiles."""
    params, cfg = model
    prompts = _prompts(cfg, (8, 5, 8, 11, 5, 3))
    engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                      token_budget=32, queue_depth=16)
    # warmup: one request through, so compile counts are steady-state
    warm = engine.submit(prompts[0], 2)
    _drain(engine, [warm])
    decode_compiles = decode_step_cache_size()

    handles = [engine.submit(prompts[0], 6), engine.submit(prompts[1], 6)]
    engine.step()
    engine.step()
    # staggered: these arrive while slots are mid-decode
    handles.append(engine.submit(prompts[2], 4))
    handles.append(engine.submit(prompts[3], 6))
    engine.step()
    handles.append(engine.submit(prompts[4], 3))
    handles.append(engine.submit(prompts[5], 5))
    _drain(engine, handles)

    for h, p in zip(handles, prompts):
        want = _oracle(params, cfg, p, h.max_new_tokens)
        assert h.tokens == want, f"request {h.request_id} diverged"
        assert h.finish_reason == "length"
    # zero recompiles after warmup: ONE persistent decode step regardless
    # of arrival pattern; admissions compile once per distinct prompt len
    assert decode_step_cache_size() == decode_compiles


def test_admission_compiles_once_per_prompt_length(model):
    params, cfg = model
    engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                      token_budget=32, queue_depth=16)
    h = engine.submit(_prompts(cfg, (7,))[0], 2)
    _drain(engine, [h])
    admit_compiles = admit_step_cache_size()
    # same length again (twice) -> no new admission compile
    hs = [engine.submit(p, 2) for p in _prompts(cfg, (7, 7), seed=3)]
    _drain(engine, hs)
    assert admit_step_cache_size() == admit_compiles


def test_slot_recycling_under_eos_latch(model):
    """A row finishing on eos frees its slot immediately; the next queued
    request runs in the recycled slot and still matches its oracle."""
    params, cfg = model
    prompts = _prompts(cfg, (6, 9, 4), seed=1)
    # pick an eos that fires mid-stream for prompt 0 (from the oracle)
    full = _oracle(params, cfg, prompts[0], 8)
    eos = full[2]
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=32, queue_depth=8,
                                      eos_id=eos)
    handles = [engine.submit(prompts[0], 8), engine.submit(prompts[1], 4),
               engine.submit(prompts[2], 4)]
    _drain(engine, handles)

    first = handles[0]
    assert first.finish_reason == "eos"
    assert first.tokens[-1] == eos
    assert first.tokens == full[:len(first.tokens)]
    # the recycled slot served the queued requests; oracle with the SAME
    # eos latch (offline pads with eos after the latch — engine stops)
    for h, p in zip(handles[1:], prompts[1:]):
        want = _oracle(params, cfg, p, h.max_new_tokens, eos_id=eos)
        assert h.tokens == want[:len(h.tokens)]
        if h.finish_reason == "eos":
            assert h.tokens[-1] == eos
        else:
            assert len(h.tokens) == h.max_new_tokens
    assert engine.active_slots() == 0


def test_quant_cache_composes_with_engine(model):
    """int8 KV slots: engine greedy == offline generate(quant_cache=True)
    — both paths quantize identical rows via the shared write path."""
    params, cfg = model
    prompts = _prompts(cfg, (8, 6), seed=2)
    engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                      token_budget=32, queue_depth=8,
                                      quant_cache=True)
    handles = [engine.submit(p, 5) for p in prompts]
    _drain(engine, handles)
    for h, p in zip(handles, prompts):
        assert h.tokens == _oracle(params, cfg, p, 5, quant_cache=True)


def test_submit_validation(model):
    params, cfg = model
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=16, queue_depth=2)
    with pytest.raises(BudgetExceededError):
        engine.submit(list(range(10)), 10)      # 20 > budget 16
    with pytest.raises(BudgetExceededError):
        engine.submit([], 4)
    engine.submit([1, 2, 3], 4)
    engine.submit([1, 2, 3], 4)
    with pytest.raises(QueueFullError):
        engine.submit([1, 2, 3], 4)             # queue_depth=2


def test_per_request_latency_breakdown(model):
    """Every finished request carries queue_wait/prefill/decode stamps;
    the snapshot exposes p50/p95/p99 per phase and metrics() ships the
    tails over the AM channel (PR5 pillar 3). A second wave submitted
    while slots are busy must observe a strictly positive queue wait."""
    params, cfg = model
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=32, queue_depth=8)
    prompts = _prompts(cfg, (4, 4), seed=7)
    finished = []
    engine.on_request_finished = finished.append
    h1 = engine.submit(prompts[0], 4)
    h2 = engine.submit(prompts[1], 4)   # queued behind h1's only slot
    _drain(engine, [h1, h2])
    for h in (h1, h2):
        assert h.queue_wait_s is not None and h.queue_wait_s >= 0
        assert h.prefill_s is not None and h.prefill_s > 0
        assert h.decode_s is not None and h.decode_s >= 0
    # h2 waited for h1's slot: its queue phase is real time, not epsilon
    assert h2.queue_wait_s > h1.queue_wait_s
    assert [h.request_id for h in finished] == [h1.request_id,
                                                h2.request_id]
    snap = engine.snapshot()
    for phase in ("queue_wait_s", "prefill_s", "decode_ms_per_token"):
        for tag in ("p50", "p95", "p99"):
            assert snap[f"{phase}_{tag}"] is not None, (phase, tag)
    assert snap["queue_wait_s_p99"] >= snap["queue_wait_s_p50"]
    names = {m["name"] for m in engine.metrics()}
    assert {"SERVING_QUEUE_WAIT_P95_S", "SERVING_PREFILL_P95_S",
            "SERVING_DECODE_P95_MS"} <= names
    engine.stop()


def test_queued_token_budget_sheds_before_request_count(model):
    """The queued-WORK bound: a few near-budget requests shed load even
    while the request-count bound still has room."""
    params, cfg = model
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=16, queue_depth=4)
    assert engine.queue_token_budget == 32       # queue_depth * budget / 2
    engine.submit(list(range(12)), 4)            # 16 tokens
    engine.submit(list(range(12)), 4)            # 32 tokens pending
    with pytest.raises(QueueFullError, match="token budget"):
        engine.submit(list(range(12)), 4)        # count 2 < 4, tokens full


# ---------------------------------------------------------------------------
# frontend
# ---------------------------------------------------------------------------

def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_frontend_blocking_streaming_and_metrics(model):
    params, cfg = model
    prompts = _prompts(cfg, (6,), seed=4)
    engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                      token_budget=32, queue_depth=8)
    engine.start()
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1")
    frontend.start()
    try:
        want = _oracle(params, cfg, prompts[0], 5)
        # blocking
        resp = json.loads(_post(frontend.port,
                                {"prompt": prompts[0],
                                 "max_new_tokens": 5}).read())
        assert resp["tokens"] == want
        assert resp["finish_reason"] == "length"
        # streaming: chunked JSON lines ending in a done record
        with _post(frontend.port, {"prompt": prompts[0],
                                   "max_new_tokens": 5,
                                   "stream": True}) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert [rec["token"] for rec in lines[:-1]] == want
        assert lines[-1]["done"] and lines[-1]["n_tokens"] == 5
        # metrics snapshot reflects the traffic
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{frontend.port}/v1/metrics",
            timeout=10).read())
        assert snap["tokens_emitted"] >= 10
        assert snap["ttft_p50_s"] is not None
        # healthz
        ok = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{frontend.port}/healthz",
            timeout=10).read())
        assert ok == {"ok": True}
    finally:
        frontend.stop()
        engine.stop()


def test_frontend_backpressure_fills_429_then_drains_and_accepts(model):
    """Bounded queue fills -> 429 with Retry-After; drains -> accepts."""
    params, cfg = model
    prompt = _prompts(cfg, (4,), seed=5)[0]
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=16, queue_depth=2)
    # engine NOT stepping: fill the queue deterministically
    held = [engine.submit(prompt, 3), engine.submit(prompt, 3)]
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1")
    frontend.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(frontend.port, {"prompt": prompt, "max_new_tokens": 3})
        assert e.value.code == 429
        assert e.value.headers.get("Retry-After")
        # the shed request is a first-class SLI now: the admission
        # counters feed the reject-rate burn-rate alert rule, and the
        # scrape carries them (serve_bench's scraped-metrics contract)
        snap = engine.snapshot()
        assert snap["requests_rejected"] == 1
        assert snap["requests_submitted"] == 2     # the two held ones
        names = {m["name"]: m["value"] for m in engine.metrics()}
        assert names["SERVING_REJECTED_TOTAL"] == 1.0
        assert names["SERVING_SUBMITTED_TOTAL"] == 2.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/v1/metrics"
                f"?format=prometheus", timeout=10) as resp:
            exposition = resp.read().decode()
        assert "tony_serving_requests_rejected" in exposition
        # a never-fits request is a 400, not a retryable 429 — and not
        # a reject-rate SLI event either (retrying can never help)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(frontend.port, {"prompt": prompt, "max_new_tokens": 99})
        assert e.value.code == 400
        assert engine.snapshot()["requests_rejected"] == 1
        # drain, then the same request is accepted and served
        engine.start()
        _drain_started(held)
        resp = json.loads(_post(frontend.port,
                                {"prompt": prompt,
                                 "max_new_tokens": 3}).read())
        assert resp["tokens"] == _oracle(params, cfg, prompt, 3)
    finally:
        frontend.stop()
        engine.stop()


def _drain_started(handles, timeout=60.0):
    deadline = time.monotonic() + timeout
    for h in handles:
        if not h.done.wait(timeout=max(0.0, deadline - time.monotonic())):
            raise AssertionError("started engine did not drain the queue")


def test_cancel_frees_slot_and_drops_pending(model):
    """A cancelled in-flight request frees its slot at the next step; a
    cancelled pending request is dropped without ever paying a prefill —
    the remaining request still matches its oracle."""
    params, cfg = model
    prompts = _prompts(cfg, (6, 5, 7), seed=7)
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=32, queue_depth=8)
    inflight = engine.submit(prompts[0], 20)
    queued_cancel = engine.submit(prompts[1], 4)
    survivor = engine.submit(prompts[2], 4)
    engine.step()                      # admits inflight, decodes once
    assert engine.active_slots() == 1
    inflight.cancel()
    queued_cancel.cancel()
    _drain(engine, [inflight, queued_cancel, survivor])
    assert inflight.finish_reason == "cancelled"
    assert len(inflight.tokens) < 20   # stopped well short of max_new
    assert queued_cancel.finish_reason == "cancelled"
    assert queued_cancel.tokens == []  # never admitted
    assert survivor.tokens == _oracle(params, cfg, prompts[2], 4)


def test_engine_stop_fails_outstanding_requests(model):
    params, cfg = model
    prompt = _prompts(cfg, (4,), seed=6)[0]
    engine = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                      token_budget=16, queue_depth=4)
    pending = [engine.submit(prompt, 3) for _ in range(3)]
    engine.stop()
    for h in pending:
        assert h.done.is_set() and h.finish_reason == "shutdown"
    with pytest.raises(RuntimeError):
        engine.submit(prompt, 3)


def test_runtimes_render_serving_port():
    """A serving task's env carries the port IT registered at the barrier
    — the cluster-spec entry and the bound HTTP port must be one and the
    same endpoint."""
    from tony_tpu.conf import TonyConfiguration
    from tony_tpu.executor.runtimes import render_framework_env

    spec = {"serving": ["h1:5001", "h2:5002"], "worker": ["h3:6001"]}
    env = render_framework_env("jax", spec, "serving", 1,
                               TonyConfiguration())
    assert env["SERVING_PORT"] == "5002"
    # non-serving tasks never get the var
    env = render_framework_env("jax", spec, "worker", 0,
                               TonyConfiguration())
    assert "SERVING_PORT" not in env


# ---------------------------------------------------------------------------
# the serving jobtype, end to end on the local backend
# ---------------------------------------------------------------------------

def _port_closed(host, port, attempts=50):
    for _ in range(attempts):
        try:
            with socket.create_connection((host, port), timeout=0.5):
                time.sleep(0.1)
        except OSError:
            return True
    return False


def test_serving_jobtype_e2e_endpoint_proxy_and_clean_shutdown(tmp_path):
    """`cli submit`-equivalent path with the serving jobtype: the AM
    launches `python -m tony_tpu.serve`, the endpoint lands in task infos
    + history, /v1/generate answers THROUGH tony_tpu.proxy, and shutdown
    leaves no orphan process or held port."""
    from tony_tpu import constants as C
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.conf import TonyConfiguration, keys as K
    from tony_tpu.events.handler import parse_events
    from tony_tpu.events.schema import EventType
    from tony_tpu.proxy import ProxyServer
    from tony_tpu.rpc.client import ClusterServiceClient

    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 300, "test")
    conf.set(K.SERVING_SLOTS, 2, "test")
    conf.set(K.SERVING_TOKEN_BUDGET, 64, "test")
    conf.set(K.SERVING_QUEUE_DEPTH, 8, "test")
    client = TonyClient(conf)
    client.init(["--conf", "tony.serving.instances=1"])
    client.submit()
    monitor = threading.Thread(target=client.monitor, daemon=True)
    monitor.start()
    endpoint = None
    try:
        # wait for the AM RPC, then for the registered endpoint
        import os
        hostport_path = os.path.join(client.app_dir, C.AM_HOSTPORT_FILE)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not os.path.exists(
                hostport_path):
            time.sleep(0.1)
        assert os.path.exists(hostport_path), "AM never came up"
        with open(hostport_path) as f:
            host, _, port = f.read().strip().rpartition(":")
        rpc = ClusterServiceClient(host, int(port), retries=2,
                                   retry_sleep_sec=0.2, timeout_sec=5.0)
        while time.monotonic() < deadline and endpoint is None:
            try:
                infos = rpc.get_task_infos()
            except Exception:  # noqa: BLE001 — AM mid-boot
                infos = []
            for info in infos:
                if info.get("name") == "serving-endpoint":
                    endpoint = info["url"]
            if endpoint is None:
                time.sleep(0.2)
        assert endpoint, "serving endpoint never registered"
        srv_host = endpoint.split("//", 1)[1].rsplit(":", 1)[0]
        srv_port = int(endpoint.rsplit(":", 1)[1])

        # front the endpoint with the authenticated-capable TCP proxy
        proxy = ProxyServer(srv_host, srv_port, local_port=0)
        proxy.start()
        try:
            body = json.dumps({"prompt": [1, 2, 3, 4],
                               "max_new_tokens": 4}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{proxy.local_port}/v1/generate",
                data=body,
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(req,
                                                     timeout=120).read())
            assert len(resp["tokens"]) == 4
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{proxy.local_port}/healthz",
                timeout=30).read())
            assert health == {"ok": True}
        finally:
            proxy.stop()

        # give the serving metrics reporter (300 ms cadence) a couple of
        # pushes so the history carries SERVING_* gauges
        time.sleep(1.0)

        # shutdown: the client tells the AM to finish; the serving
        # container gets TERM->KILL and the executor reaps the server
        rpc.finish_application()
        rpc.close()
    finally:
        monitor.join(timeout=120)
        client.cleanup()
    assert not monitor.is_alive(), "client monitor never returned"
    # serving runs until told to stop: a client-initiated stop is KILLED
    assert client.final_status == "KILLED"
    # no orphan: the endpoint's port must be released
    assert _port_closed(srv_host, srv_port), \
        "serving port still open after shutdown — orphan server"
    # the endpoint registration is a history event (new schema entry)
    hist_base = os.path.join(client.app_dir, C.HISTORY_DIR_NAME)
    finals = [os.path.join(d, f) for d, _, files in os.walk(hist_base)
              for f in files if f.endswith(".jhist")]
    assert len(finals) == 1, finals
    events = parse_events(finals[0])
    served = [e for e in events
              if e.type == EventType.SERVING_ENDPOINT_REGISTERED]
    assert served and served[0].payload.url == endpoint
    assert served[0].payload.task_type == "serving"
    # serving metrics flowed through the trainer's metrics RPC path into
    # the AM store and out into history (what the portal job page shows)
    metric_names = {m.get("name")
                    for e in events if hasattr(e.payload, "metrics")
                    for m in e.payload.metrics}
    assert "SERVING_TOKENS_PER_SEC" in metric_names, metric_names
