"""Serving-fleet tests: least-loaded router, connection draining,
dead-endpoint eviction, rolling updates, and the arbiter-backed
autoscaler (serve/router.py + serve/autoscaler.py + the AM wiring).

The load-bearing contracts, each pinned here:

- **draining chaos e2e**: a replica preempted mid-stream finishes its
  in-flight streamed request (zero client-visible errors) while the
  router fails new traffic over to the survivors;
- **SIGKILL eviction**: a replica dying without a drain (host loss) is
  marked DOWN within the probe-derived latency bound and re-admits
  itself when it comes back;
- **autoscaler through the arbiter**: a sustained SLI breach files the
  replica ask THROUGH the admission arbiter and the AUTOSCALE_DECISION
  event carries the arbiter's verdict (event-pinned acceptance).

Real engines/frontends where streams matter; stub HTTP replicas where
only the routing table is under test. All CPU-backend, tier-1 fast.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import jax

from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.serve.router import (
    DOWN, DRAINING, UP, FleetRouter, endpoints_from_task_infos,
)

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def model():
    cfg = get_config("tiny")
    return llama_init(cfg, jax.random.PRNGKey(0)), cfg


def _prompts(cfg, lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
            for n in lengths]


def _post(port, payload, path="/v1/generate", timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _get(port, path, timeout=10):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout).read())


# ---------------------------------------------------------------------------
# stub replica: a real HTTP server with a scriptable load snapshot
# ---------------------------------------------------------------------------

class _StubReplica:
    """Answers /v1/load from a mutable dict and /v1/generate with a
    canned body naming itself — enough surface to test the routing
    table without paying for a model."""

    def __init__(self, name: str, port: int = 0, **load):
        self.name = name
        self.load = {"queue_depth": 0, "slots_free": 4, "active_slots": 0,
                     "n_slots": 4, "draining": False,
                     "weights_generation": 0, **load}
        self.requests = 0
        self.status_code = 200
        stub = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if code == 429:
                    self.send_header("Retry-After", "1")
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") == "/v1/load":
                    return self._json(dict(stub.load))
                self._json({"error": "nope"}, 404)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                if self.path.rstrip("/") == "/v1/drain":
                    stub.load["draining"] = True
                    return self._json(dict(stub.load))
                stub.requests += 1
                if stub.status_code != 200:
                    return self._json({"error": "shed"}, stub.status_code)
                self._json({"served_by": stub.name})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), _H)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def kill(self):
        """SIGKILL equivalent: the socket goes away with no drain."""
        self.httpd.shutdown()
        self.httpd.server_close()


def _router(eps, **kw):
    kw.setdefault("probe_ttl_ms", 30)
    kw.setdefault("probe_timeout_ms", 500)
    rtr = FleetRouter(eps, port=0, host="127.0.0.1", **kw)
    rtr.start()
    return rtr


# ---------------------------------------------------------------------------
# routing table semantics (stub replicas)
# ---------------------------------------------------------------------------

def test_least_loaded_routing_prefers_shallow_queue_then_free_slots():
    a = _StubReplica("a", queue_depth=5, slots_free=0)
    b = _StubReplica("b", queue_depth=0, slots_free=1)
    c = _StubReplica("c", queue_depth=0, slots_free=4)
    rtr = _router([a.url, b.url, c.url])
    try:
        got = json.loads(_post(rtr.port, {"prompt": [1]}).read())
        assert got["served_by"] == "c"          # empty queue, most slots
        c.load.update(queue_depth=9)
        time.sleep(0.3)     # several prober sweeps, even under load
        got = json.loads(_post(rtr.port, {"prompt": [1]}).read())
        assert got["served_by"] == "b"
    finally:
        rtr.stop()
        for s in (a, b, c):
            s.kill()


def test_429_spillover_retries_next_least_loaded_and_fleet_wide_429():
    a = _StubReplica("a", slots_free=4)
    b = _StubReplica("b", slots_free=2)
    a.status_code = 429                         # the preferred pick sheds
    rtr = _router([a.url, b.url], spillover_retries=2)
    try:
        got = json.loads(_post(rtr.port, {"prompt": [1]}).read())
        assert got["served_by"] == "b"          # spilled, not failed
        assert rtr.stats["spillovers_429"] == 1
        b.status_code = 429                     # whole fleet sheds
        time.sleep(0.3)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(rtr.port, {"prompt": [1]})
        assert e.value.code == 429              # the fleet-wide answer
        assert e.value.headers.get("Retry-After")
    finally:
        rtr.stop()
        a.kill()
        b.kill()


def test_draining_replica_excluded_from_new_sends():
    a = _StubReplica("a", slots_free=4)
    b = _StubReplica("b", slots_free=1)
    rtr = _router([a.url, b.url])
    try:
        assert json.loads(
            _post(rtr.port, {"prompt": [1]}).read())["served_by"] == "a"
        a.load["draining"] = True
        time.sleep(0.3)
        for _ in range(3):
            got = json.loads(_post(rtr.port, {"prompt": [1]}).read())
            assert got["served_by"] == "b"
        states = {e["url"]: e["state"] for e in rtr.endpoints()}
        assert states[a.url] == DRAINING and states[b.url] == UP
    finally:
        rtr.stop()
        a.kill()
        b.kill()


def test_sigkilled_replica_evicted_within_latency_bound_and_readmits():
    """Dead-endpoint eviction latency: after a SIGKILL-style death the
    router marks the replica DOWN within dead_after_failures probes of
    the TTL cadence — pinned at <2s with a 30ms TTL — and traffic keeps
    flowing through the survivor with zero client-visible errors. A
    replacement on the same port re-admits itself on one good probe."""
    a = _StubReplica("a", slots_free=4)
    b = _StubReplica("b", slots_free=2)
    rtr = _router([a.url, b.url], dead_after_failures=2,
                  probe_timeout_ms=200)
    try:
        assert json.loads(
            _post(rtr.port, {"prompt": [1]}).read())["served_by"] == "a"
        port = a.port
        a.kill()
        t0 = time.monotonic()
        # traffic through the dead window: every request must succeed
        # (connect failure -> failover to b), never a 5xx to the client
        evicted_at = None
        while time.monotonic() - t0 < 5.0:
            got = json.loads(_post(rtr.port, {"prompt": [1]}).read())
            assert got["served_by"] == "b"
            states = {e["url"]: e["state"] for e in rtr.endpoints()}
            if states[a.url] == DOWN:
                evicted_at = time.monotonic() - t0
                break
            time.sleep(0.02)
        assert evicted_at is not None, "dead replica never marked DOWN"
        assert evicted_at < 2.0, \
            f"eviction took {evicted_at:.2f}s (bound: 2s)"
        # resurrection on the same port: the background prober keeps
        # sweeping DOWN endpoints, so one good probe re-admits the
        # replica — no traffic required (requests here just observe)
        a2 = _StubReplica("a2", port=port, slots_free=9)
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                _post(rtr.port, {"prompt": [1]}).read()
                states = {e["url"]: e["state"] for e in rtr.endpoints()}
                if states[a2.url] == UP:
                    break
                time.sleep(0.05)
            assert states[a2.url] == UP, "revived replica never re-admitted"
        finally:
            a2.kill()
    finally:
        rtr.stop()
        b.kill()


def test_endpoint_set_diff_merge_keeps_probe_state_and_drops_removed():
    a = _StubReplica("a")
    b = _StubReplica("b")
    rtr = _router([a.url])
    try:
        assert rtr.probe(a.url) is not None
        infos = [
            {"name": "serving-endpoint", "task_id": "serving:0",
             "url": a.url, "generation": 2, "draining": False},
            {"name": "serving-endpoint", "task_id": "serving:1",
             "url": b.url, "generation": 2, "draining": True},
            {"name": "tensorboard", "url": "http://tb:1"},   # not serving
        ]
        rtr.set_endpoints(endpoints_from_task_infos(infos))
        eps = {e["url"]: e for e in rtr.endpoints()}
        assert set(eps) == {a.url, b.url}
        assert eps[a.url]["generation"] == 2
        assert eps[a.url]["load"] is not None       # probe state survived
        assert eps[b.url]["state"] == DRAINING      # AM drain hint honored
        rtr.set_endpoints([{"url": a.url, "task_id": "serving:0",
                            "generation": 2}])
        assert [e["url"] for e in rtr.endpoints()] == [a.url]
    finally:
        rtr.stop()
        a.kill()
        b.kill()


def test_am_rolling_update_cycles_one_replica_at_a_time(tmp_path):
    """The AM's rolling-update state machine: request_rolling_update
    bumps the weights epoch and arms the rollout; each monitor pass
    drains ONE replica's endpoint, force-relaunches it, and only
    advances once the replacement re-registers healthy at the new
    generation — finishing with ROLLING_UPDATE_COMPLETED ok=True."""
    am, events = _fleet_am(tmp_path)
    for i, t in enumerate(am.session.job_tasks["serving"]):
        t.container_id = f"c{i}"
        am.register_serving_endpoint(
            {"task_id": t.task_id, "url": f"http://h:{9000 + i}"})

    resp = am.request_rolling_update({"requested_by": "test"})
    assert resp == {"app_id": "app_fleet_1", "generation": 1,
                    "replicas": 2}
    from tony_tpu.events.schema import EventType
    assert [e.type for e in events
            if e.type == EventType.ROLLING_UPDATE_STARTED]
    # idempotent while in flight
    assert am.request_rolling_update({})["duplicate"] is True

    # pass 1: serving:0 drains, relaunches (its dead attempt's endpoint
    # leaves the set with its container), rollout waits on it
    am._check_rolling_update()
    assert "serving:0" not in am._serving_endpoints
    assert am.scheduler.replacements == ["serving"]
    am._check_rolling_update()      # still waiting — no replacement yet
    assert am._serving_endpoints["serving:1"]["draining"] is False
    # replacement re-registers (no explicit generation -> AM epoch 1)
    am.register_serving_endpoint(
        {"task_id": "serving:0", "url": "http://h:9100"})
    assert am._serving_endpoints["serving:0"]["generation"] == 1

    # pass 2 notices the healthy gen-1 replica, cycles serving:1
    am._check_rolling_update()
    assert "serving:1" not in am._serving_endpoints
    am.register_serving_endpoint(
        {"task_id": "serving:1", "url": "http://h:9101"})
    am._check_rolling_update()      # serving:1 healthy -> rollout done
    done = [e for e in events
            if e.type == EventType.ROLLING_UPDATE_COMPLETED]
    assert len(done) == 1
    assert done[0].payload.ok is True
    assert done[0].payload.replicas_updated == 2
    assert done[0].payload.generation == 1


# ---------------------------------------------------------------------------
# draining chaos e2e: preemption mid-stream with REAL engines
# ---------------------------------------------------------------------------

def test_preempted_replica_finishes_inflight_stream_zero_errors(model):
    """The acceptance chaos e2e: two live replicas behind the router, a
    streamed request in flight on one, then that replica is preempted
    (drain). The open stream runs to completion token by token — zero
    client-visible errors — while new traffic fails over to the
    survivor; the drained engine reports empty once the stream ends."""
    from tony_tpu.serve.engine import ContinuousBatchingEngine
    from tony_tpu.serve.frontend import ServeFrontend

    params, cfg = model
    prompts = _prompts(cfg, (6, 5, 7), seed=11)
    engines, fronts = [], []
    for _ in range(2):
        e = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                     token_budget=48, queue_depth=8)
        e.start()
        f = ServeFrontend(e, port=0, host="127.0.0.1")
        f.start()
        engines.append(e)
        fronts.append(f)
    rtr = _router([f"http://127.0.0.1:{f.port}" for f in fronts],
                  spillover_retries=1)
    try:
        # warmup (compile) outside the measured chaos
        json.loads(_post(rtr.port,
                         {"prompt": prompts[2], "max_new_tokens": 2},
                         timeout=120).read())

        tokens, errors = [], []
        started = threading.Event()

        def stream():
            try:
                with _post(rtr.port, {"prompt": prompts[0],
                                      "max_new_tokens": 24,
                                      "stream": True},
                           timeout=120) as r:
                    for line in r:
                        rec = json.loads(line)
                        if "token" in rec:
                            tokens.append(rec["token"])
                            started.set()
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(repr(e))
                started.set()

        th = threading.Thread(target=stream, daemon=True)
        th.start()
        assert started.wait(timeout=120), "stream never produced a token"

        # preempt the replica holding the stream: drain it mid-flight
        victim = next(i for i, e in enumerate(engines)
                      if e.load()["active_slots"] > 0)
        survivor = 1 - victim
        drained = json.loads(_post(fronts[victim].port, {},
                                   path="/v1/drain").read())
        assert drained["draining"]

        # new traffic fails over to the survivor (the prober notices the
        # drain within a sweep) and NEVER errors; the drained replica
        # takes no new sends
        time.sleep(0.3)
        before = engines[victim].load()
        for p in (prompts[1], prompts[2]):
            got = json.loads(_post(rtr.port,
                                   {"prompt": p, "max_new_tokens": 3},
                                   timeout=120).read())
            assert len(got["tokens"]) == 3
        assert engines[survivor].stats.requests_submitted >= 2
        assert engines[victim].stats.requests_submitted \
            == before["active_slots"] + engines[victim].stats.requests_finished

        # the preempted stream runs to completion: all 24 tokens, no error
        th.join(timeout=120)
        assert not th.is_alive(), "in-flight stream wedged after drain"
        assert errors == [], f"client saw errors across the drain: {errors}"
        assert len(tokens) == 24
        assert engines[victim].wait_drained(30.0), \
            "drained engine still holds work after its stream finished"
        # direct submits to the draining replica answer 503 + the header
        # (the machine-readable drain contract the router keys off)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(fronts[victim].port,
                  {"prompt": prompts[1], "max_new_tokens": 2})
        assert e.value.code == 503
        assert e.value.headers.get("X-Tony-Draining") == "1"
    finally:
        rtr.stop()
        for f in fronts:
            f.stop()
        for e in engines:
            e.stop()


def test_engine_load_snapshot_shape_and_drain_flag(model):
    """Satellite pin: /v1/load is the router's probe — queue depth, free
    slots, draining, weights generation — and never requires the
    metrics render."""
    from tony_tpu.serve.engine import ContinuousBatchingEngine
    from tony_tpu.serve.frontend import ServeFrontend

    params, cfg = model
    engine = ContinuousBatchingEngine(params, cfg, n_slots=3,
                                      token_budget=16, queue_depth=8,
                                      weights_generation=7)
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1")
    frontend.start()
    try:
        load = _get(frontend.port, "/v1/load")
        assert load == {"ok": True, "queue_depth": 0, "slots_free": 3,
                        "active_slots": 0, "n_slots": 3,
                        "draining": False, "weights_generation": 7,
                        "role": "both", "token_budget": 16}
        # a queued (not stepping) request shows up in the snapshot
        engine.submit(_prompts(cfg, (4,), seed=3)[0], 2)
        load = _get(frontend.port, "/v1/load")
        assert load["queue_depth"] == 1
        engine.begin_drain()
        assert _get(frontend.port, "/v1/load")["draining"] is True
    finally:
        frontend.stop()
        engine.stop()


# ---------------------------------------------------------------------------
# autoscaler: hysteresis/cooldown + the arbiter-backed ask (event-pinned)
# ---------------------------------------------------------------------------

def test_autoscaler_hysteresis_cooldown_and_windowed_reject_rate():
    from tony_tpu.serve.autoscaler import AutoscalerConfig, ReplicaAutoscaler

    cfg = AutoscalerConfig(min_replicas=1, max_replicas=4,
                           queue_depth_up=8, reject_rate_up_pct=1.0,
                           occupancy_down_pct=30, hysteresis_passes=2,
                           cooldown_ms=10_000)
    sc = ReplicaAutoscaler(cfg)
    hot = {"ttft_p95_s": 0.0, "queue_depth": 40.0, "occupancy_pct": 100.0,
           "submitted_total": 100.0, "rejected_total": 0.0}
    # pass 1 breaches but hysteresis holds; pass 2 fires
    assert sc.evaluate(hot, 2, now_ms=0)["action"] == "hold"
    v = sc.evaluate(hot, 2, now_ms=1000)
    assert v["action"] == "up" and v["target"] == 3
    sc.note_scaled(1000)
    # cooldown suppresses the action, not the streak accounting
    assert sc.evaluate(hot, 3, now_ms=2000)["reason"] == "cooldown"
    assert sc.evaluate(hot, 3, now_ms=3000)["action"] == "hold"
    v = sc.evaluate(hot, 3, now_ms=12_000)      # cooldown over -> fires
    assert v["action"] == "up" and v["target"] == 4
    sc.note_scaled(12_000)
    # windowed reject rate: cumulative counters' inter-pass delta
    sc2 = ReplicaAutoscaler(AutoscalerConfig(hysteresis_passes=1,
                                             cooldown_ms=0,
                                             queue_depth_up=0))
    calm = {"queue_depth": 0.0, "occupancy_pct": 90.0,
            "submitted_total": 1000.0, "rejected_total": 10.0}
    assert sc2.evaluate(calm, 2, 0)["action"] == "hold"  # first pass: no delta
    burst = dict(calm, submitted_total=1080.0, rejected_total=30.0)
    v = sc2.evaluate(burst, 2, 1000)            # 20/(80+20) = 20% > 1%
    assert v["action"] == "up" and "reject rate" in v["reason"]
    # scale-down only below occupancy floor with an empty queue
    sc3 = ReplicaAutoscaler(AutoscalerConfig(hysteresis_passes=1,
                                             cooldown_ms=0))
    idle = {"queue_depth": 0.0, "occupancy_pct": 5.0,
            "submitted_total": 0.0, "rejected_total": 0.0}
    sc3.evaluate(idle, 3, 0)
    v = sc3.evaluate(idle, 3, 1000)
    assert v["action"] == "down" and v["target"] == 2
    assert sc3.evaluate(idle, 1, 2000)["action"] == "hold"  # min_replicas


def _fleet_am(tmp_path, **extra_conf):
    """An in-process AM with a 2-replica serving jobtype, stub backend/
    scheduler, and an event recorder — the harness for the autoscaler
    and rolling-update state machines."""
    from tony_tpu.am.application_master import ApplicationMaster
    from tony_tpu.conf import TonyConfiguration
    from tony_tpu.session.session import TonySession

    class _StubBackend:
        def start(self):
            ...

        def stop_container(self, cid):
            ...

        def release_container(self, cid):
            ...

        def request_containers(self, *a, **k):
            ...

    class _StubScheduler:
        def __init__(self):
            self.scale_ups = []
            self.replacements = []

        def schedule_scale_up(self, job_name):
            self.scale_ups.append(job_name)

        def schedule_replacement(self, job_name):
            self.replacements.append(job_name)

    conf = TonyConfiguration()
    for k, v in {"tony.serving.instances": 2, **extra_conf}.items():
        conf.set(k, v, "test")
    am = ApplicationMaster(conf, "app_fleet_1", str(tmp_path),
                           backend=_StubBackend())
    am.session = TonySession(conf, session_id=0)
    am.scheduler = _StubScheduler()
    events = []
    am.event_handler.emit = events.append
    return am, events


def test_scaled_down_replica_does_not_trip_relaunch_barrier(tmp_path):
    """A serving replica's clean exit (autoscaler scale-down) is
    routine fleet lifecycle: it must NOT count toward the
    completed-peer relaunch barrier, or one scale-down would disable
    crash relaunches for the whole application. A completed GANG peer
    still blocks — serving is the only barrier-exempt jobtype."""
    am, _ = _fleet_am(tmp_path, **{"tony.worker.instances": 2,
                                   "tony.task.max-task-attempts": 3})
    am.session.on_task_completed("serving", 1, 0)   # scale-down exit
    worker = am.session.get_task("worker", 0)
    worker.container_id = "cw"
    assert am._maybe_relaunch_task(worker, "crash") is True, \
        "a completed serving replica must not block gang relaunches"
    # the REAL barrier is untouched: a completed worker peer blocks
    am2, _ = _fleet_am(tmp_path / "b", **{"tony.worker.instances": 2,
                                          "tony.task.max-task-attempts": 3})
    am2.session.on_task_completed("worker", 1, 0)
    w0 = am2.session.get_task("worker", 0)
    w0.container_id = "cw0"
    assert am2._maybe_relaunch_task(w0, "crash") is False


def test_scale_up_ask_preempts_lower_priority_trainer_via_arbiter():
    """The PR-10 integration contract: a serving scale-up's chip ask is
    judged against the live fleet book — on a full cluster it names a
    lower-priority trainer as the checkpoint-then-evict victim rather
    than queueing the fleet into starvation."""
    from tony_tpu.conf import TonyConfiguration
    from tony_tpu.observability.fleet import job_summary
    from tony_tpu.serve.autoscaler import replica_ask_verdict

    conf = TonyConfiguration()
    conf.set("tony.arbiter.total-tpus", 8, "test")
    conf.set("tony.arbiter.preemption-enabled", True, "test")
    fleet = [job_summary("trainer_lowpri", "b", "default", "RUNNING",
                         allocated_chips=8, priority=-1,
                         started_ms=1000)]
    d = replica_ask_verdict(conf, "serve_app", chips=4,
                            fleet_summaries=fleet, priority=5)
    assert d.action == "preempt"
    assert [v.app_id for v in d.victims] == ["trainer_lowpri"]
    # chips == 0 (CPU/dev fleet): trivially admits, arbiter or not
    assert replica_ask_verdict(conf, "serve_app", chips=0,
                               fleet_summaries=fleet).action == "admit"


def test_am_autoscaler_files_arbiter_backed_ask_and_grows_the_gang(
        tmp_path):
    """Event-pinned acceptance: sustained SLI breach -> the AM's monitor
    pass emits AUTOSCALE_DECISION carrying the arbiter's verdict, adds a
    serving task slot, and requests exactly one container through the
    scheduler; the cooldown stops a second ask on the very next pass."""
    from tony_tpu.events.schema import EventType

    am, events = _fleet_am(
        tmp_path,
        **{"tony.autoscaler.enabled": True,
           "tony.autoscaler.hysteresis-passes": 1,
           "tony.autoscaler.max-replicas": 4,
           "tony.autoscaler.queue-depth-up": 8})
    assert am.autoscaler is not None, \
        "serving jobtype + enabled flag must arm the autoscaler"
    am.metrics_store.update_metrics({
        "task_type": "serving", "index": 0, "metrics": [
            {"name": "SERVING_QUEUE_DEPTH", "value": 40.0},
            {"name": "SERVING_SLOT_OCCUPANCY_PCT", "value": 100.0},
            {"name": "SERVING_TTFT_P95_S", "value": 0.4},
            {"name": "SERVING_SUBMITTED_TOTAL", "value": 50.0},
            {"name": "SERVING_REJECTED_TOTAL", "value": 0.0}]})

    before = len(am.session.job_tasks["serving"])
    am._check_autoscaler()
    decisions = [e for e in events
                 if e.type == EventType.AUTOSCALE_DECISION]
    assert len(decisions) == 1, "the ask must be event-pinned"
    p = decisions[0].payload
    assert p.direction == "up" and p.to_replicas == before + 1
    assert p.arbiter_action == "admit"      # 0-chip dev ask: fits whole
    assert p.queue_depth == 40.0            # the SLI evidence rides along
    assert len(am.session.job_tasks["serving"]) == before + 1
    assert am.scheduler.scale_ups == ["serving"]
    # cooldown: the immediately-following pass must NOT ask again
    am._check_autoscaler()
    assert len([e for e in events
                if e.type == EventType.AUTOSCALE_DECISION]) == 1
