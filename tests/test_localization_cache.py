"""Content-addressed localization cache + persistent compile-cache
wiring (the other two legs of the cold-start demolition).

Pins the cache's correctness invariants — identical bytes land once
machine-wide, materialization is a hardlink, a killed fetch never leaves
a torn blob or a lying marker — plus the atomic store fetch idiom and
the `tony.executor.jax-cache-dir` → $TONY_JAX_CACHE_DIR env render the
trainer/serving engine consume.
"""

from __future__ import annotations

import glob
import os
import threading

import pytest

from tony_tpu import constants as C
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.utils.localization import (
    LocalizationCache, localize_resource,
)

pytestmark = pytest.mark.warmpool


@pytest.fixture
def cache(tmp_path):
    return LocalizationCache(str(tmp_path / "cache"))


def _write(tmp_path, name: str, data: bytes) -> str:
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def test_identical_bytes_stored_once(cache, tmp_path):
    a = _write(tmp_path, "a.bin", b"same-bytes")
    b = _write(tmp_path, "b.bin", b"same-bytes")
    other = _write(tmp_path, "c.bin", b"different")
    blob_a = cache.get_or_add_file(a)       # miss
    blob_b = cache.get_or_add_file(b)       # hit: same digest
    blob_c = cache.get_or_add_file(other)   # miss
    assert blob_a == blob_b != blob_c
    assert len(os.listdir(cache.by_digest)) == 2
    assert (cache.hits, cache.misses) == (1, 2)


def test_materialize_is_hardlink_and_overwrites_stale(cache, tmp_path):
    src = _write(tmp_path, "res.bin", b"payload")
    blob = cache.get_or_add_file(src)
    dest_dir = str(tmp_path / "container")
    os.makedirs(dest_dir)
    stale = os.path.join(dest_dir, "res.bin")
    with open(stale, "wb") as f:
        f.write(b"stale-from-a-previous-attempt")
    out = cache.materialize(blob, dest_dir, "res.bin")
    assert out == stale
    assert os.stat(out).st_ino == os.stat(blob).st_ino   # hardlink
    with open(out, "rb") as f:
        assert f.read() == b"payload"
    # no tmp debris from the atomic link+rename
    assert not glob.glob(os.path.join(dest_dir, "*.link-tmp-*"))


def test_concurrent_materialize_same_dest_is_safe(cache, tmp_path):
    """The width-k regression this fixes: k executors run as THREADS of
    one pool process, all materializing the same resource to the same
    path. Every thread must succeed (no tmp-name collision, no
    delete-under-a-neighbor) and the final file must be whole."""
    src = _write(tmp_path, "res.bin", b"x" * 65536)
    blob = cache.get_or_add_file(src)
    dest_dir = str(tmp_path / "shared_container")
    errors = []

    def _one():
        try:
            cache.materialize(blob, dest_dir, "res.bin")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=_one) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    with open(os.path.join(dest_dir, "res.bin"), "rb") as f:
        assert f.read() == b"x" * 65536


def test_stat_memo_hashes_each_source_once(cache, tmp_path, monkeypatch):
    """Digest memoization by (dev, ino, size, mtime_ns): hashing the
    source costs more than the copy the cache saves, so a width-k gang
    re-localizing one resource must sha256 it exactly once machine-wide
    — and an edited source (new mtime) must be re-hashed, never served
    stale."""
    from tony_tpu.utils import localization as loc

    real = loc._sha256_file
    hashed = []
    monkeypatch.setattr(loc, "_sha256_file",
                        lambda p: (hashed.append(p), real(p))[1])
    src = _write(tmp_path, "big.bin", b"r" * 4096)
    blob1 = cache.get_or_add_file(src)
    for _ in range(8):                       # the rest of the gang
        assert cache.get_or_add_file(src) == blob1
    assert len(hashed) == 1
    assert cache.hits == 8

    # a rewritten source is a different stat identity: re-hash, new blob
    os.utime(src, ns=(1, 1))   # force a distinct mtime_ns
    with open(src, "wb") as f:
        f.write(b"s" * 4096)
    blob2 = cache.get_or_add_file(src)
    assert blob2 != blob1 and len(hashed) == 2


def test_uri_fetched_once_machine_wide(cache):
    calls = []

    def fetcher(uri, dest):
        calls.append(uri)
        with open(dest, "wb") as f:
            f.write(b"remote-bytes")

    blob1 = cache.get_or_fetch_uri("gs://bucket/res", fetcher)
    blob2 = cache.get_or_fetch_uri("gs://bucket/res", fetcher)
    assert blob1 == blob2 and calls == ["gs://bucket/res"]
    with open(blob1, "rb") as f:
        assert f.read() == b"remote-bytes"


def test_failed_fetch_leaves_no_marker_no_blob(cache):
    def broken(uri, dest):
        with open(dest, "wb") as f:
            f.write(b"half-")
        raise OSError("connection reset")

    with pytest.raises(OSError):
        cache.get_or_fetch_uri("gs://bucket/flaky", broken)
    # nothing cached, nothing torn: the next attempt refetches
    assert os.listdir(cache.by_uri) == []
    assert os.listdir(cache.by_digest) == []
    assert not glob.glob(os.path.join(cache.root, ".fetch-tmp-*"))

    def working(uri, dest):
        with open(dest, "wb") as f:
            f.write(b"whole")

    blob = cache.get_or_fetch_uri("gs://bucket/flaky", working)
    with open(blob, "rb") as f:
        assert f.read() == b"whole"


def test_localize_resource_through_cache_dedups_copies(cache, tmp_path):
    src = _write(tmp_path, "data.txt", b"training-data")
    d1, d2 = str(tmp_path / "c1"), str(tmp_path / "c2")
    out1 = localize_resource(src, d1, cache=cache)
    out2 = localize_resource(src, d2, cache=cache)
    # both containers see the file; bytes exist once (3 links: blob + 2)
    assert os.stat(out1).st_ino == os.stat(out2).st_ino
    assert os.stat(out1).st_nlink == 3
    assert cache.hits >= 1


def test_from_conf_gating(tmp_path):
    conf = TonyConfiguration()
    assert LocalizationCache.from_conf(conf) is None   # default off
    conf.set(K.LOCALIZATION_CACHE_ENABLED, True, "test")
    conf.set(K.LOCALIZATION_CACHE_DIR, str(tmp_path / "locs"), "test")
    cache = LocalizationCache.from_conf(conf)
    assert cache is not None
    assert cache.root == str(tmp_path / "locs")


def test_local_store_fetch_is_atomic(tmp_path):
    from tony_tpu.storage import LocalDirStore

    store = LocalDirStore(str(tmp_path / "store"))
    uri = store.put(_write(tmp_path, "src.bin", b"stored-bytes"), "src.bin")
    dest = str(tmp_path / "out" / "src.bin")
    got = store.fetch(uri, dest)
    assert got == dest
    with open(dest, "rb") as f:
        assert f.read() == b"stored-bytes"
    # the download-to-tmp + rename idiom leaves no debris
    assert not glob.glob(f"{dest}.fetch-tmp-*")
    assert not glob.glob(os.path.join(str(tmp_path / "store"),
                                      "*.put-tmp-*"))


# ---------------------------------------------------------------------------
# persistent XLA compile cache wiring
# ---------------------------------------------------------------------------

class _FakeJaxConfig:
    def __init__(self):
        self.calls = {}

    def update(self, key, value):
        self.calls[key] = value


class _FakeJax:
    def __init__(self):
        self.config = _FakeJaxConfig()


def test_compile_cache_env_rendered_into_user_env():
    """tony.executor.jax-cache-dir lands in EVERY framework's user env
    as $TONY_JAX_CACHE_DIR — the trainer/serving engine pick it up."""
    from tony_tpu.executor.runtimes import render_framework_env

    spec = {"worker": ["h0:1000", "h1:1001"]}
    conf = TonyConfiguration()
    env = render_framework_env("jax", spec, "worker", 0, conf)
    assert C.JAX_CACHE_DIR not in env                  # knob unset
    conf.set(K.EXECUTOR_JAX_CACHE_DIR, "/var/cache/tony-jax", "test")
    env = render_framework_env("jax", spec, "worker", 0, conf)
    assert env[C.JAX_CACHE_DIR] == "/var/cache/tony-jax"
    # framework-independent: tensorflow tasks get it too
    env = render_framework_env("tensorflow", spec, "worker", 1, conf)
    assert env[C.JAX_CACHE_DIR] == "/var/cache/tony-jax"


def test_maybe_enable_compile_cache_honors_env(tmp_path, monkeypatch):
    from tony_tpu.utils.compilecache import maybe_enable_compile_cache

    cache_dir = str(tmp_path / "jax_cache")
    monkeypatch.setenv(C.JAX_CACHE_DIR, cache_dir)
    jax = _FakeJax()
    assert maybe_enable_compile_cache(jax_module=jax) == cache_dir
    assert jax.config.calls["jax_compilation_cache_dir"] == cache_dir
    assert os.path.isdir(cache_dir)

    # unset → disabled, jax untouched
    monkeypatch.delenv(C.JAX_CACHE_DIR)
    jax2 = _FakeJax()
    assert maybe_enable_compile_cache(jax_module=jax2) is None
    assert jax2.config.calls == {}


def test_maybe_enable_compile_cache_never_raises(tmp_path, monkeypatch):
    """The cache is an optimization, never a dependency: a jax that
    refuses the config keys degrades to a warning, not a crash."""
    from tony_tpu.utils.compilecache import maybe_enable_compile_cache

    class _Refusing:
        class config:  # noqa: N801 — mimics jax.config
            @staticmethod
            def update(key, value):
                raise ValueError("unknown config")

    monkeypatch.setenv(C.JAX_CACHE_DIR, str(tmp_path / "d"))
    assert maybe_enable_compile_cache(jax_module=_Refusing()) is None
