"""Utils tests (reference model: util/TestUtils.java)."""

import os
import time

from tony_tpu.utils import common, fs
from tony_tpu.utils.shell import execute_shell


def test_poll_till_non_null():
    state = {"n": 0}

    def fn():
        state["n"] += 1
        return "ready" if state["n"] >= 3 else None

    assert common.poll_till_non_null(fn, 0.01, 5) == "ready"
    assert common.poll_till_non_null(lambda: None, 0.01, 0.05) is None


def test_parse_env_list():
    assert common.parse_env_list(["A=1", "B=x=y", "C="]) == \
        {"A": "1", "B": "x=y", "C": ""}


def test_zip_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("hello")
    (src / "sub" / "b.txt").write_text("world")
    z = fs.zip_dir(str(src), str(tmp_path / "out.zip"))
    dest = fs.unzip(z, str(tmp_path / "dest"))
    assert open(os.path.join(dest, "a.txt")).read() == "hello"
    assert open(os.path.join(dest, "sub", "b.txt")).read() == "world"


def test_execute_shell_exit_codes(tmp_path):
    assert execute_shell("exit 0") == 0
    assert execute_shell("exit 3") == 3
    out = tmp_path / "o.txt"
    with open(out, "w") as f:
        assert execute_shell("echo -n $MY_VAR", extra_env={"MY_VAR": "v1"},
                             stdout=f) == 0
    assert out.read_text() == "v1"


def test_execute_shell_timeout():
    start = time.monotonic()
    rc = execute_shell("sleep 30", timeout_sec=0.5)
    assert rc == 124
    assert time.monotonic() - start < 5
