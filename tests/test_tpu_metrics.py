"""TPU duty-cycle sampling tests (VERDICT r2 item 3).

A fake libtpu metrics gRPC server (same service/method path and wire
shape as the TPU-VM daemon tpu-info queries) proves the whole chain:
wire codec -> LibtpuMetricsClient -> default_tpu_sampler's duty_cycle
key -> TaskMonitor MAX/AVG_TPU_UTILIZATION -> the AM MetricsStore's
heartbeating-but-idle wedge diagnosis.
"""

from __future__ import annotations

import struct
from concurrent import futures

import grpc
import pytest

from tony_tpu.executor.tpu_metrics import (
    DUTY_CYCLE_PCT, HBM_USAGE_BYTES, METHOD, SERVICE, TPU_METRICS_ADDR_ENV,
    LibtpuMetricsClient, encode_string_field, parse_message,
    parse_metric_response,
)


# --- tiny proto writers for the fake server --------------------------------

def _varint(v: int) -> bytes:
    out = b""
    while True:
        bits = v & 0x7F
        v >>= 7
        if v:
            out += bytes((bits | 0x80,))
        else:
            return out + bytes((bits,))


def _len_field(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _varint_field(field: int, v: int) -> bytes:
    return _varint(field << 3) + _varint(v)


def _double_field(field: int, v: float) -> bytes:
    return _varint((field << 3) | 1) + struct.pack("<d", v)


def fake_metric_response(name: str, per_device: dict[int, float],
                         as_int: bool = False) -> bytes:
    """MetricResponse{ TPUMetric{ name=1, repeated Metric=2 } } with
    Metric{ Attribute{value{key_attr}}=1, Gauge=2 }."""
    metrics = b""
    for dev, value in per_device.items():
        attr = _len_field(2, _varint_field(1, dev))      # AttrValue.key_attr
        gauge = (_varint_field(2, int(value)) if as_int
                 else _double_field(1, value))
        metrics += _len_field(2, _len_field(1, attr) + _len_field(2, gauge))
    tpu_metric = _len_field(1, name.encode()) + metrics
    return _len_field(1, tpu_metric)


class _FakeLibtpu:
    """In-process stand-in for the TPU-VM metrics daemon."""

    def __init__(self, metrics: dict[str, dict[int, float]],
                 int_metrics: set[str] = frozenset()):
        self.metrics = metrics
        self.int_metrics = set(int_metrics)
        self.requests: list[str] = []

        def handler(request: bytes, context) -> bytes:
            req = parse_message(request)
            name = req[1][0].decode()
            self.requests.append(name)
            if name not in self.metrics:
                context.abort(grpc.StatusCode.NOT_FOUND, name)
            return fake_metric_response(name, self.metrics[name],
                                        as_int=name in self.int_metrics)

        method = grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=lambda b: b,
            response_serializer=lambda b: b)
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self.server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(SERVICE,
                                                 {METHOD: method}),))
        self.port = self.server.add_insecure_port("127.0.0.1:0")
        self.server.start()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.server.stop(grace=None)


@pytest.fixture()
def fake_libtpu():
    srv = _FakeLibtpu(
        metrics={DUTY_CYCLE_PCT: {0: 87.5, 1: 12.5},
                 HBM_USAGE_BYTES: {0: 9e9, 1: 8e9}},
        int_metrics={HBM_USAGE_BYTES})
    yield srv
    srv.stop()


def test_wire_codec_roundtrip():
    data = fake_metric_response(DUTY_CYCLE_PCT, {0: 55.0, 3: 65.0})
    assert parse_metric_response(data) == {0: 55.0, 3: 65.0}
    # int-gauge arm (HBM) decodes too
    data = fake_metric_response(HBM_USAGE_BYTES, {0: 8_000_000_000},
                                as_int=True)
    assert parse_metric_response(data) == {0: 8_000_000_000.0}
    # request encoding is field-1 string
    req = parse_message(encode_string_field(1, DUTY_CYCLE_PCT))
    assert req[1][0].decode() == DUTY_CYCLE_PCT


def test_client_reads_duty_cycle_and_hbm(fake_libtpu):
    client = LibtpuMetricsClient(addr=fake_libtpu.addr)
    assert client.duty_cycle_pct() == pytest.approx(50.0)  # mean of chips
    assert client.hbm_usage_bytes() == pytest.approx(17e9)
    assert fake_libtpu.requests == [DUTY_CYCLE_PCT, HBM_USAGE_BYTES]


def test_client_unreachable_returns_none_fast():
    client = LibtpuMetricsClient(addr="127.0.0.1:1", timeout_sec=2.0)
    assert client.duty_cycle_pct() is None
    assert client.get_metric(DUTY_CYCLE_PCT) == {}


def test_default_sampler_emits_duty_cycle(fake_libtpu, monkeypatch):
    import tony_tpu.executor.task_monitor as tm

    monkeypatch.setenv(TPU_METRICS_ADDR_ENV, fake_libtpu.addr)
    monkeypatch.setattr(tm, "_libtpu_client", None)   # drop cached client
    sample = tm.default_tpu_sampler()
    assert sample["duty_cycle"] == pytest.approx(50.0)
    assert sample["hbm_bytes"] == pytest.approx(17e9)


def test_task_monitor_reports_utilization_from_libtpu(fake_libtpu,
                                                     monkeypatch):
    """The live path: TaskMonitor's default sampler hits the (fake) libtpu
    service and MAX/AVG_TPU_UTILIZATION go live in the snapshot."""
    import tony_tpu.executor.task_monitor as tm

    monkeypatch.setenv(TPU_METRICS_ADDR_ENV, fake_libtpu.addr)
    monkeypatch.setattr(tm, "_libtpu_client", None)

    class _NullClient:
        def update_metrics(self, *a, **k):
            pass

    monitor = tm.TaskMonitor(_NullClient(), "worker", 0, lambda: None,
                             interval_sec=999.0,
                             tpu_sampler=tm.default_tpu_sampler)
    monitor._sample_and_push()
    fake_libtpu.metrics[DUTY_CYCLE_PCT] = {0: 25.0, 1: 25.0}
    monitor._sample_and_push()
    by_name = {m["name"]: m["value"] for m in monitor.snapshot()}
    assert by_name["MAX_TPU_UTILIZATION"] == pytest.approx(50.0)
    assert by_name["AVG_TPU_UTILIZATION"] == pytest.approx(37.5)
    assert by_name["MAX_TPU_HBM_BYTES"] == pytest.approx(17e9)
    # the LAST sample rides along — the AM's wedge detector keys on it,
    # since the monotonic MAX would mask a ran-healthy-then-wedged task
    assert by_name["TPU_UTILIZATION"] == pytest.approx(25.0)


def test_am_flags_heartbeating_but_idle_task():
    """The diagnosable condition: duty cycle ~0 across N consecutive
    metric updates flags the task; recovery clears it."""
    from tony_tpu.am.application_master import MetricsStore

    store = MetricsStore(low_util_intervals=3)

    def push(duty, max_duty=None):
        store.update_metrics({
            "task_type": "worker", "index": 0,
            "metrics": [
                {"name": "TPU_UTILIZATION", "value": duty},
                {"name": "MAX_TPU_UTILIZATION",
                 "value": max_duty if max_duty is not None else duty},
            ]})

    push(0.0)
    push(0.2)
    assert store.low_utilization_tasks() == []      # not yet N intervals
    push(0.0)
    assert store.low_utilization_tasks() == ["worker:0"]
    push(42.0)                                      # woke up
    assert store.low_utilization_tasks() == []
    # ran-healthy-then-wedged: lifetime MAX stays high but the LAST
    # sample drops to ~0 — the detector must still fire (review finding)
    for _ in range(3):
        push(0.0, max_duty=62.0)
    assert store.low_utilization_tasks() == ["worker:0"]
    # tasks with NO utilization source are never flagged (worker:0 stays
    # flagged from the wedge above; ps:0 must not join it)
    store.update_metrics({"task_type": "ps", "index": 0, "metrics": [
        {"name": "MAX_MEMORY_BYTES", "value": 1.0}]})
    assert store.low_utilization_tasks() == ["worker:0"]
    # task completion clears the wedge state (a finished task must not
    # read as wedged; a relaunch with the same id starts clean)
    store.clear_utilization_state("worker", 0)
    assert store.low_utilization_tasks() == []


def test_am_flags_task_whose_metrics_daemon_went_silent():
    """The hardest wedge: the runtime hangs so hard the libtpu daemon
    stops answering — TPU_UTILIZATION disappears from the pushes. A task
    that reported duty before and stopped counts as idle."""
    from tony_tpu.am.application_master import MetricsStore

    store = MetricsStore(low_util_intervals=2)

    def push(metrics):
        store.update_metrics({"task_type": "worker", "index": 0,
                              "metrics": metrics})

    push([{"name": "TPU_UTILIZATION", "value": 60.0}])   # healthy
    for _ in range(2):                                    # daemon silent
        push([{"name": "MAX_MEMORY_BYTES", "value": 1.0}])
    assert store.low_utilization_tasks() == ["worker:0"]


def test_moe_dispatch_mode_validated():
    import pytest as _pytest

    from tony_tpu.models.moe import get_moe_config

    with _pytest.raises(ValueError, match="dispatch_mode"):
        get_moe_config("moe_tiny", dispatch_mode="Dense")
