"""Native token-shard loader tests: correctness vs the shard contents,
native/numpy agreement on distribution shape, prefetch liveness."""

import shutil

import numpy as np
import pytest

from tony_tpu.train.native_data import (
    _load_lib, token_batches, write_token_file,
)

NEEDS_TOOLCHAIN = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no native toolchain")


def make_shard(tmp_path, n=10_000):
    # tokens[i] = i so every batch row must be a contiguous slice
    tokens = np.arange(n, dtype=np.int32)
    path = str(tmp_path / "shard.bin")
    write_token_file(path, tokens)
    return path


def _check_rows_are_contiguous_slices(batch, n):
    toks = batch["tokens"]
    for row in toks:
        start = row[0]
        assert start + len(row) <= n
        np.testing.assert_array_equal(row, np.arange(start,
                                                     start + len(row)))


def test_numpy_fallback_batches(tmp_path):
    path = make_shard(tmp_path)
    it = token_batches(path, batch=4, seq=16, prefer_native=False)
    seen_starts = set()
    for _ in range(10):
        batch = next(it)
        assert batch["tokens"].shape == (4, 17)
        _check_rows_are_contiguous_slices(batch, 10_000)
        seen_starts.update(batch["tokens"][:, 0].tolist())
    assert len(seen_starts) > 10  # actually random crops


@NEEDS_TOOLCHAIN
def test_native_loader_batches(tmp_path):
    assert _load_lib() is not None, "libtony_data.so failed to build/load"
    path = make_shard(tmp_path)
    it = token_batches(path, batch=4, seq=16, prefer_native=True)
    seen_starts = set()
    for _ in range(50):   # enough to exercise the double buffer many times
        batch = next(it)
        assert batch["tokens"].shape == (4, 17)
        _check_rows_are_contiguous_slices(batch, 10_000)
        seen_starts.update(batch["tokens"][:, 0].tolist())
    assert len(seen_starts) > 20


@NEEDS_TOOLCHAIN
def test_native_loader_deterministic_per_seed(tmp_path):
    path = make_shard(tmp_path)
    a = next(token_batches(path, batch=8, seq=8, seed=7))
    b = next(token_batches(path, batch=8, seq=8, seed=7))
    c = next(token_batches(path, batch=8, seq=8, seed=8))
    np.testing.assert_array_equal(a["tokens"], b["tokens"])
    assert not np.array_equal(a["tokens"], c["tokens"])


def test_loader_rejects_too_short_shard(tmp_path):
    path = str(tmp_path / "tiny.bin")
    write_token_file(path, np.arange(4, dtype=np.int32))
    with pytest.raises((ValueError, OSError)):
        next(token_batches(path, batch=1, seq=16, prefer_native=False))
