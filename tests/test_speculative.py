"""Speculative decoding (models/speculative.py).

The load-bearing property is LOSSLESSNESS: greedy speculative output
must be byte-identical to vanilla greedy `generate` for ANY draft —
a perfect draft only makes it faster, a garbage draft only slower.
That makes vanilla greedy the exact oracle for every test here."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.generate import decode_step, generate, prefill
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.models.speculative import speculative_generate, window_logits

CFG = get_config("tiny")                       # 2 layers, vocab 256
DRAFT_CFG = get_config("tiny", n_layers=1)     # same vocab, smaller


def _params(key, config=CFG):
    return llama_init(config, jax.random.PRNGKey(key))


def _prompt(key, b=2, p=8):
    return jax.random.randint(jax.random.PRNGKey(key), (b, p), 0,
                              CFG.vocab_size, jnp.int32)


@pytest.mark.parametrize("quant_cache", [False, True])
def test_window_logits_matches_decode_step(quant_cache):
    """W=1 window against a uniform-length cache must reproduce
    decode_step (same math through a different masking path) — on both
    cache layouts, since each has its own write/dequant branch."""
    params = _params(0)
    tokens = _prompt(3, b=2, p=10)
    logits, cache = prefill(params, tokens, CFG, cache_len=16,
                            quant_cache=quant_cache)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    ref, _ = decode_step(params, CFG, cache, tok, jnp.int32(10))
    lens = jnp.full((2,), 10, jnp.int32)
    win, _ = window_logits(params, CFG, cache, tok[:, None], lens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(win[:, 0]),
                               rtol=0, atol=1e-4)


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_lossless_vs_vanilla_greedy(gamma):
    params, draft = _params(0), _params(7, DRAFT_CFG)
    prompt = _prompt(1)
    want = generate(params, CFG, prompt, max_new_tokens=12)
    got = speculative_generate(params, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=12, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_perfect_draft_still_lossless():
    """Draft == target: every proposal is accepted (the fast path) and
    the stream is still exactly vanilla greedy."""
    params = _params(0)
    prompt = _prompt(2)
    want = generate(params, CFG, prompt, max_new_tokens=10)
    got = speculative_generate(params, params, CFG, CFG, prompt,
                               max_new_tokens=10, gamma=3)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_adversarial_draft_still_lossless():
    """A draft initialized from a different seed (near-random proposals
    at tiny scale) exercises the accepted==0 correction path."""
    params, draft = _params(0), _params(99, CFG)
    prompt = _prompt(4, b=3, p=6)
    want = generate(params, CFG, prompt, max_new_tokens=9)
    got = speculative_generate(params, draft, CFG, CFG, prompt,
                               max_new_tokens=9, gamma=4)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_gamma_exceeds_budget_and_long_run():
    """Boundary coverage: gamma larger than the whole budget (every
    round over-drafts), and a longer run whose rows finish in different
    rounds — both must still match the oracle exactly."""
    params, draft = _params(0), _params(7, DRAFT_CFG)
    prompt = _prompt(8, b=3, p=5)
    want = generate(params, CFG, prompt, max_new_tokens=2)
    got = speculative_generate(params, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=2, gamma=6)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    want = generate(params, CFG, prompt, max_new_tokens=33)
    got = speculative_generate(params, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=33, gamma=5)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_composes_with_int8_weights():
    """Speculative decode over an int8 weight-only TARGET must equal
    that target's own greedy decode (lossless relative to whatever
    model actually runs — quantized or not)."""
    from tony_tpu.models.quant import quantize_params

    params, draft = _params(0), _params(7, DRAFT_CFG)
    qparams = quantize_params(params)
    prompt = _prompt(6)
    want = generate(qparams, CFG, prompt, max_new_tokens=10)
    got = speculative_generate(qparams, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=10, gamma=3)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_composes_with_int8_kv_cache():
    """quant_cache=True speculative must be byte-identical to
    quant_cache=True vanilla greedy: both paths quantize the SAME K/V
    rows at the same positions, so the lossless identity is exact even
    though the cache itself is lossy."""
    params, draft = _params(0), _params(7, DRAFT_CFG)
    prompt = _prompt(9)
    want = generate(params, CFG, prompt, max_new_tokens=10,
                    quant_cache=True)
    got = speculative_generate(params, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=10, gamma=3,
                               quant_cache=True)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_eos_latch_matches_vanilla():
    """eos_id latching: pick an eos that PROVABLY fires mid-stream (a
    token from the vanilla output's interior), then speculative must
    reproduce vanilla's forced-eos tail exactly."""
    params, draft = _params(0), _params(7, DRAFT_CFG)
    prompt = _prompt(11)
    base = np.asarray(generate(params, CFG, prompt, max_new_tokens=12))
    eos = int(base[0][4])   # fires at position 4 of row 0 at the latest
    want = generate(params, CFG, prompt, max_new_tokens=12, eos_id=eos)
    got = speculative_generate(params, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=12, gamma=3, eos_id=eos)
    w = np.asarray(want)
    assert (w[0] == eos).any()   # the latch actually engaged
    np.testing.assert_array_equal(w, np.asarray(got))


def test_composes_with_full_int8_stack():
    """int8 weights AND int8 KV cache together (what the demo's
    --quant int8 --quant-cache --draft-config enables) must equal the
    same-stack vanilla greedy."""
    from tony_tpu.models.quant import quantize_params

    params, draft = _params(0), _params(7, DRAFT_CFG)
    qparams = quantize_params(params)
    prompt = _prompt(10)
    want = generate(qparams, CFG, prompt, max_new_tokens=10,
                    quant_cache=True)
    got = speculative_generate(qparams, draft, CFG, DRAFT_CFG, prompt,
                               max_new_tokens=10, gamma=3,
                               quant_cache=True)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_vocab_mismatch_rejected():
    params = _params(0)
    bad_cfg = get_config("tiny", vocab_size=128)
    bad = llama_init(bad_cfg, jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(params, bad, CFG, bad_cfg, _prompt(5),
                             max_new_tokens=4)
