"""Model + training tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tony_tpu.models.llama import (
    get_config, llama_forward, llama_init, llama_loss, llama_param_axes,
)
from tony_tpu.models.mnist import mnist_accuracy, mnist_init, mnist_loss
from tony_tpu.models.linear import linreg_init, linreg_loss
from tony_tpu.parallel import make_mesh, plan_mesh, shard_pytree
from tony_tpu.train.checkpoint import (
    latest_step, restore_checkpoint, save_checkpoint,
)
from tony_tpu.train.data import (
    synthetic_linreg, synthetic_mnist, synthetic_tokens,
)
from tony_tpu.train.step import make_train_step
from tony_tpu.train.trainer import Trainer, TrainerConfig


def test_llama_forward_shapes_and_param_count():
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    counted = sum(x.size for x in jax.tree.leaves(params))
    assert counted == cfg.num_params()
    # axes tree matches params tree structure
    axes = llama_param_axes(cfg)
    jax.tree.map(lambda p, a: None, params, axes,
                 is_leaf=lambda x: isinstance(x, tuple))


def test_llama_causality():
    """Future tokens must not affect past logits."""
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, -1].set(99)  # change only the last token
    l1 = llama_forward(params, t1, cfg)
    l2 = llama_forward(params, t2, cfg)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not np.allclose(l1[0, -1], l2[0, -1])


def test_llama_trains_on_mesh():
    """Loss must descend under a dp+fsdp+tp mesh with sharded params."""
    cfg = get_config("tiny")
    mesh = make_mesh(plan_mesh(8, tp=2))
    params = llama_init(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, llama_param_axes(cfg), mesh)
    opt = optax.adam(1e-2)
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt)
    data = synthetic_tokens(8, 32, cfg.vocab_size)
    with jax.set_mesh(mesh):
        opt_state = jax.device_put(opt.init(params))
        losses = []
        for _ in range(30):
            batch = {k: jax.device_put(v) for k, v in next(data).items()}
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[::10]


def test_llama_trains_with_sequence_parallelism():
    """sp=2 ring-attention path: loss finite and decreasing."""
    cfg = get_config("tiny")
    mesh = make_mesh(plan_mesh(8, sp=2, tp=2, dp=2, fsdp=1))
    params = llama_init(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, llama_param_axes(cfg), mesh)
    opt = optax.adam(1e-2)
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt)
    data = synthetic_tokens(4, 32, cfg.vocab_size)
    with jax.set_mesh(mesh):
        opt_state = jax.device_put(opt.init(params))
        losses = []
        for _ in range(10):
            batch = {k: jax.device_put(v) for k, v in next(data).items()}
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sp_matches_no_sp_forward():
    """The ring-attention path must compute the same function."""
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.arange(64, dtype=jnp.int32).reshape(2, 32) % cfg.vocab_size
    plain = llama_forward(params, tokens, cfg)
    mesh = make_mesh(plan_mesh(8, sp=4, dp=2, fsdp=1))
    with jax.set_mesh(mesh):
        sp = jax.jit(lambda p, t: llama_forward(p, t, cfg))(params, tokens)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(sp),
                               atol=2e-4, rtol=2e-4)


def test_mnist_learns():
    params = mnist_init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    step = make_train_step(mnist_loss, opt)
    opt_state = opt.init(params)
    data = synthetic_mnist(64)
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, next(data))
    acc = float(mnist_accuracy(params, next(data)))
    assert acc > 0.9, acc


def test_linreg_learns():
    params = linreg_init(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    step = make_train_step(linreg_loss, opt)
    opt_state = opt.init(params)
    data = synthetic_linreg(64)
    for _ in range(100):
        params, opt_state, loss = step(params, opt_state, next(data))
    assert float(loss) < 0.01


def test_checkpoint_roundtrip(tmp_path):
    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": jnp.asarray(7)}
    save_checkpoint(str(tmp_path), 7, state)
    save_checkpoint(str(tmp_path), 3, state)
    assert latest_step(str(tmp_path)) == 7
    restored = restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(restored["params"]["w"],
                                  np.arange(6.0).reshape(2, 3))
    assert int(restored["step"]) == 7


def test_trainer_resume(tmp_path):
    """Trainer must resume from the latest checkpoint (AM-retry survival)."""
    cfg = TrainerConfig(num_steps=5, log_every=1, checkpoint_every=5,
                        checkpoint_dir=str(tmp_path), learning_rate=1e-2,
                        warmup_steps=1)
    data = synthetic_mnist(32)
    t1 = Trainer(mnist_loss, mnist_init, data, cfg)
    t1.run()
    assert latest_step(str(tmp_path)) == 5
    cfg2 = TrainerConfig(num_steps=10, log_every=1, checkpoint_every=5,
                         checkpoint_dir=str(tmp_path), learning_rate=1e-2,
                         warmup_steps=1)
    t2 = Trainer(mnist_loss, mnist_init, data, cfg2)
    t2.setup()
    assert t2.step == 5  # resumed, not restarted
    t2.run()
    assert latest_step(str(tmp_path)) == 10


def test_llama_ulysses_sp_mode_trains():
    """Full llama step with ulysses SP on a seq-sharded mesh."""
    from functools import partial
    import optax
    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_param_axes,
    )
    from tony_tpu.parallel import make_mesh, plan_mesh, shard_pytree
    from tony_tpu.train.step import make_train_step

    mesh = make_mesh(plan_mesh(8, sp=2, tp=2))
    config = get_config("tiny", sp_mode="ulysses")
    params = shard_pytree(llama_init(config, jax.random.PRNGKey(0)),
                          llama_param_axes(config), mesh)
    opt = optax.adam(1e-3)
    step = make_train_step(partial(llama_loss, config=config), opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0,
                                config.vocab_size, jnp.int32)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        _, _, loss = step(params, opt_state, {"tokens": tokens})
    assert np.isfinite(float(loss))


def test_grad_accum_matches_full_batch():
    """grad_accum=2 on the same global batch must produce the SAME update
    as a single full-batch step. SGD, not adam: the update is then linear
    in the mean gradient, so this pins the accumulation math itself
    (adam's first step is ~sign(g), which amplifies f32 accumulation-order
    noise wherever g is near zero)."""
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    loss_fn = lambda p, b: llama_loss(p, b, cfg)  # noqa: E731

    step_full = make_train_step(loss_fn, opt)
    step_accum = make_train_step(loss_fn, opt, grad_accum=2)
    import copy
    p1, o1, l1 = step_full(copy.deepcopy(params), opt.init(params), batch)
    p2, o2, l2 = step_accum(copy.deepcopy(params), opt.init(params), batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_grad_accum_on_mesh():
    """grad_accum under a dp+fsdp+tp mesh: loss decreases, shapes hold."""
    cfg = get_config("tiny")
    mesh = make_mesh(plan_mesh(8, tp=2))
    params = shard_pytree(llama_init(cfg, jax.random.PRNGKey(0)),
                          llama_param_axes(cfg), mesh)
    opt = optax.adam(1e-2)
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt,
                           grad_accum=2)
    data = synthetic_tokens(8, 32, cfg.vocab_size)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        losses = []
        for _ in range(10):
            batch = {k: jax.device_put(v) for k, v in next(data).items()}
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_grad_accum_rejects_indivisible_batch():
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(1e-2)
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt,
                           grad_accum=3, jit=False)
    tokens = jnp.zeros((4, 33), jnp.int32)
    import pytest
    with pytest.raises(ValueError, match="not divisible"):
        step(params, opt.init(params), {"tokens": tokens})


def test_trainer_eval_loop():
    """eval_every runs the held-out loss on cadence; eval loss tracks the
    train loss down on the same synthetic distribution."""
    cfg = TrainerConfig(num_steps=6, log_every=2, eval_every=3,
                        eval_batches=2, learning_rate=1e-2, warmup_steps=1)
    t = Trainer(mnist_loss, mnist_init, synthetic_mnist(32), cfg,
                eval_data_iter=synthetic_mnist(32, seed=9))
    t.run()
    evals = [m for m in t.metrics_history if "eval_loss" in m]
    assert [m["step"] for m in evals] == [3, 6]
    assert t.last_eval_loss is not None
    assert np.isfinite(t.last_eval_loss)


def test_resnet_learns():
    """Conv family (models/resnet.py): loss descends on synthetic mnist."""
    from tony_tpu.models.resnet import (
        get_resnet_config, resnet_accuracy, resnet_init, resnet_loss,
    )

    cfg = get_resnet_config("resnet_tiny")
    params = resnet_init(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(3e-3)
    step = make_train_step(lambda p, b: resnet_loss(p, b, cfg), opt)
    opt_state = jax.jit(opt.init)(params)
    data = synthetic_mnist(32)
    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, next(data))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    acc = float(resnet_accuracy(params, next(data), cfg))
    assert acc > 0.5, acc


def test_resnet50_proxy_shapes():
    """The 50-layer-equivalent preset compiles and produces class logits."""
    from tony_tpu.models.resnet import (
        get_resnet_config, resnet_forward, resnet_init,
    )

    cfg = get_resnet_config("resnet50_proxy", num_classes=12,
                            stages=((1, 8, 1), (1, 16, 2)), stem_channels=8,
                            groups=4)
    params = resnet_init(cfg, jax.random.PRNGKey(0))
    imgs = jnp.zeros((2, 32, 32, 3), jnp.float32)
    logits = resnet_forward(params, imgs, cfg)
    assert logits.shape == (2, 12)
    assert logits.dtype == jnp.float32


def test_f32_master_rescues_bf16_underflow():
    """With lr small enough that bf16 updates underflow the ULP, plain
    bf16 adam stalls EXACTLY (params unchanged) while the f32-master
    wrapper keeps making progress — the defining property of master
    weights."""
    from tony_tpu.train.precision import with_f32_master

    w0_host = np.full((64,), 1.0, np.float32)  # ULP(1.0) = 2^-8 in bf16

    def fresh():
        return {"w": jnp.full((64,), 1.0, jnp.bfloat16)}

    def loss_fn(params, batch):
        return jnp.sum((params["w"].astype(jnp.float32) - 2.0) ** 2)

    # sgd step = lr * grad = 1e-5 * 2 ≈ 2e-5 << 2^-8: underflows in bf16
    plain = optax.sgd(1e-5)
    step_plain = make_train_step(loss_fn, plain)
    p1, s1 = fresh(), plain.init(fresh())
    for _ in range(50):
        p1, s1, _ = step_plain(p1, s1, None)
    np.testing.assert_array_equal(np.asarray(p1["w"], np.float32),
                                  w0_host)  # stalled exactly

    master = with_f32_master(optax.sgd(1e-5))
    step_m = make_train_step(loss_fn, master)
    p2, s2 = fresh(), master.init(fresh())
    for _ in range(300):
        p2, s2, _ = step_m(p2, s2, None)
    # loss pulls w from 1.0 toward 2.0: the master accumulated
    # ~300*2e-5 = 6e-3 of progress, and 6e-3 > ULP(1.0)=2^-8 so the
    # visible bf16 params moved too
    assert float(np.asarray(s2["master"]["w"], np.float32)[0]) > 1.004
    assert float(np.asarray(p2["w"], np.float32)[0]) > 1.0


def test_f32_master_trains_llama_bf16_on_mesh():
    """Full sharded step with master weights on the bf16 tiny config."""
    cfg = get_config("tiny", dtype=jnp.bfloat16)
    from tony_tpu.train.precision import with_f32_master

    mesh = make_mesh(plan_mesh(8, tp=2))
    params = shard_pytree(llama_init(cfg, jax.random.PRNGKey(0)),
                          llama_param_axes(cfg), mesh)
    opt = with_f32_master(optax.adam(1e-2))
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt)
    data = synthetic_tokens(8, 32, cfg.vocab_size)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        losses = []
        for _ in range(10):
            batch = {k: jax.device_put(v) for k, v in next(data).items()}
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # params stayed bf16; master is f32
    assert params["embed"].dtype == jnp.bfloat16
    assert opt_state["master"]["embed"].dtype == jnp.float32


def test_master_weights_with_grad_accum_keeps_f32_grads():
    """grad_accum + master weights together: the f32-accumulated mean
    gradient must reach the master un-quantized (params stay bf16, loss
    finite, master f32) — the combination the trainer wires."""
    from tony_tpu.train.precision import with_f32_master

    cfg = get_config("tiny", dtype=jnp.bfloat16)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    opt = with_f32_master(optax.adam(1e-2))
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt,
                           grad_accum=2, emit_accum_dtype=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size, jnp.int32)
    opt_state = jax.jit(opt.init)(params)
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": tokens})
    assert np.isfinite(float(loss))
    assert params["embed"].dtype == jnp.bfloat16
    assert opt_state["master"]["embed"].dtype == jnp.float32


def test_vit_learns():
    """ViT family (models/vit.py): attention-on-images loss descends on a
    separable synthetic task."""
    from tony_tpu.models.vit import get_config, vit_init, vit_loss

    cfg = get_config("vit_tiny", image_size=16, patch_size=4,
                     in_channels=1, n_layers=2)
    params = vit_init(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(3e-3)
    step = make_train_step(lambda p, b: vit_loss(p, b, cfg), opt)
    opt_state = jax.jit(opt.init)(params)
    rng = np.random.default_rng(0)
    labels = rng.integers(0, cfg.num_classes, 64).astype(np.int32)
    # class-dependent mean intensity: linearly separable from patches
    images = (rng.normal(0, 0.1, (64, 16, 16, 1))
              + labels[:, None, None, None] / 10.0).astype(np.float32)
    batch = {"images": jnp.asarray(images), "labels": jnp.asarray(labels)}
    losses = []
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_vit_s16_proxy_shapes():
    from tony_tpu.models.vit import get_config, vit_forward, vit_init

    cfg = get_config("vit_s16_proxy", image_size=32, n_layers=2,
                     num_classes=7)
    params = vit_init(cfg, jax.random.PRNGKey(0))
    logits = vit_forward(params, jnp.zeros((2, 32, 32, 3)), cfg)
    assert logits.shape == (2, 7) and logits.dtype == jnp.float32


def test_vit_trains_sharded_on_mesh():
    """Sharded ViT train step on the fsdp x tp mesh: non-causal flash
    dispatch under a multi-axis mesh, params sharded by vit_param_axes."""
    from tony_tpu.models.vit import (
        get_config, vit_init, vit_loss, vit_param_axes,
    )
    from tony_tpu.parallel import make_mesh, plan_mesh
    from tony_tpu.parallel.sharding import shard_pytree

    cfg = get_config("vit_tiny", image_size=16, patch_size=4,
                     in_channels=1)
    mesh = make_mesh(plan_mesh(8, tp=2))
    params = vit_init(cfg, jax.random.PRNGKey(0))
    want = float(vit_loss(params, {
        "images": jnp.ones((8, 16, 16, 1)),
        "labels": jnp.zeros((8,), jnp.int32)}, cfg))
    params = shard_pytree(params, vit_param_axes(cfg), mesh)
    opt = optax.adam(1e-3)
    step = make_train_step(lambda p, b: vit_loss(p, b, cfg), opt)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        batch = {"images": jnp.ones((8, 16, 16, 1)),
                 "labels": jnp.zeros((8,), jnp.int32)}
        params, opt_state, loss = step(params, opt_state, batch)
    np.testing.assert_allclose(float(loss), want, rtol=1e-4)


def test_llama3_70b_preset_geometry():
    """The 70B preset carries the Llama-3-70B geometry and ~70B params
    (the >16B pp regime docs/SCALING.md compiles against v5p-128)."""
    from tony_tpu.models.llama import get_config

    cfg = get_config("llama3_70b")
    assert (cfg.dim, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads,
            cfg.ffn_dim) == (8192, 80, 64, 8, 28_672)
    assert 6.9e10 < cfg.num_params() < 7.2e10, cfg.num_params()


def test_trainer_double_setup_mesh_loss():
    """setup() twice (session retry path) must not stack a duplicate
    mesh= kwarg onto a loss_takes_mesh loss (r4 advisor)."""
    def meshy_loss(params, batch, mesh=None):
        assert mesh is not None
        return mnist_loss(params, batch)

    cfg = TrainerConfig(num_steps=2, log_every=1, warmup_steps=1)
    t = Trainer(meshy_loss, mnist_init, synthetic_mnist(32), cfg,
                loss_takes_mesh=True)
    t.setup()
    t.setup()          # retry: rebinds against the ORIGINAL loss_fn
    t.run()
    assert t.last_loss is not None
