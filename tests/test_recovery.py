"""AM-crash survivability: journal replay, supervised restart, adoption.

The tentpole's three legs, each pinned at its own layer:

- **journal** (am/journal.py): attempt-stamped WAL units — roundtrip,
  torn tail, attempt fencing, snapshot+incremental, session rollover,
  discard;
- **supervised restart** (am/supervisor.py): the relaunch-until-verdict
  loop against a scripted fake AM process;
- **orphan mode + adoption** (executor/task_executor.py): budget-
  exhausted heartbeater enters orphan mode instead of os._exit, the
  re-attach swaps RPC clients, and the grace expiry self-fences through
  the TERM→emergency-checkpoint→KILL ladder;

then proven whole on the real client → supervisor → AM → executor →
user-python chain: SIGKILL the AM mid-training at width 64 and the
restarted attempt adopts every live executor with ZERO user-process
relaunches and a loss trajectory bit-identical to an undisturbed twin.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import threading
import time

import pytest

from tony_tpu import constants as C
from tony_tpu.am import journal as J
from tony_tpu.am import supervisor as sup
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.handler import EventHandler, parse_events
from tony_tpu.events.history import JobMetadata
from tony_tpu.events.render import render_event
from tony_tpu.events.schema import (
    AmRecoveryCompleted, AmRecoveryStarted, Event, EventType, TaskStarted,
)
from tony_tpu.executor import task_executor as te
from tony_tpu.observability import fleet

from tests.chaos import ChaosRun, HangAM, KillAM, script

recovery = pytest.mark.recovery
chaos = pytest.mark.chaos
pytestmark = recovery


# ---------------------------------------------------------------------------
# journal units: the WAL a fresh AM attempt replays
# ---------------------------------------------------------------------------

def test_journal_roundtrip_restores_tasks_endpoints_clocks(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path))
    j.append(J.REC_SESSION, session_id=1, expected=2,
             instances={"worker": 2})
    j.append(J.REC_CONTAINER, task_id="worker:0", attempt=0,
             container_id="c1", host="h1")
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="h1:10", generation=1)
    j.append(J.REC_REGISTER, task_id="worker:1", attempt=0,
             host_port="h2:11", generation=1)
    j.append(J.REC_ENDPOINT, task_id="worker:0", url="http://h1:9",
             generation=1)
    j.append(J.REC_CLOCK, am_downtime_s=1.5, relaunch_downtime_s=0.5)
    j.append(J.REC_COMPLETED, task_id="worker:1", attempt=0, exit_code=0,
             status="SUCCEEDED")
    j.close()

    st = J.replay(str(tmp_path))
    assert st.session_id == 1 and st.num_expected == 2
    assert st.instances == {"worker": 2}
    assert st.replayed_records == 7
    assert st.tasks["worker:0"]["host_port"] == "h1:10"
    assert st.tasks["worker:0"]["container_id"] == "c1"
    assert st.tasks["worker:1"]["completed"] is True
    # the adoption barrier's membership: registered ∧ not terminal
    assert set(st.live_tasks()) == {"worker:0"}
    assert st.endpoints["worker:0"]["url"] == "http://h1:9"
    assert st.clocks["am_downtime_s"] == 1.5
    assert st.clocks["relaunch_downtime_s"] == 0.5
    assert st.last_ts_ms > 0
    # dict roundtrip (the snapshot's serialization)
    st2 = J.RecoveredState.from_dict(st.to_dict())
    assert st2.to_dict() == st.to_dict()


def test_journal_torn_tail_keeps_prefix(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path))
    j.append(J.REC_SESSION, session_id=1, expected=1)
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="h:1", generation=1)
    j.close()
    # a SIGKILL mid-append leaves a torn record: the scan must keep the
    # durable prefix and drop only the tail
    with open(J.journal_path(str(tmp_path)), "a", encoding="utf-8") as f:
        f.write('{"type": "register", "task_id": "worker:1", "ho')
    st = J.replay(str(tmp_path))
    assert st.replayed_records == 2
    assert set(st.tasks) == {"worker:0"}


def test_journal_attempt_fencing_drops_stale_records(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path))
    j.append(J.REC_SESSION, session_id=1, expected=1)
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="old:1", generation=1)
    j.append(J.REC_RELAUNCH, task_id="worker:0", attempt=1, generation=2)
    # a stale attempt-0 record written by a zombie must not resurrect
    # the voided registration
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="zombie:1", generation=1)
    j.close()
    st = J.replay(str(tmp_path))
    t = st.tasks["worker:0"]
    assert t["attempt"] == 1
    assert t["host_port"] == ""          # relaunch voided it; fence held
    assert st.spec_generation == 2
    assert st.live_tasks() == {}


def test_journal_snapshot_plus_incremental(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path), snapshot_every=3)
    j.append(J.REC_SESSION, session_id=1, expected=2,
             instances={"worker": 2})
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="h1:10", generation=1)
    j.append(J.REC_REGISTER, task_id="worker:1", attempt=0,
             host_port="h2:11", generation=1)
    # the third append crossed snapshot_every: state compacted, WAL reset
    assert os.path.exists(J.snapshot_path(str(tmp_path)))
    assert os.path.getsize(J.journal_path(str(tmp_path))) == 0
    # incremental records after the snapshot layer on top of it
    j.append(J.REC_RELAUNCH, task_id="worker:1", attempt=1, generation=2)
    j.close()
    st = J.replay(str(tmp_path))
    assert st.tasks["worker:0"]["host_port"] == "h1:10"
    assert st.tasks["worker:1"]["attempt"] == 1
    assert st.spec_generation == 2
    assert set(st.live_tasks()) == {"worker:0"}


def test_journal_session_rollover_clears_tasks_keeps_clocks(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path))
    j.append(J.REC_SESSION, session_id=1, expected=1)
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="h:1", generation=1)
    j.append(J.REC_CLOCK, am_downtime_s=2.0)
    j.append(J.REC_SESSION, session_id=2, expected=1)
    j.close()
    st = J.replay(str(tmp_path))
    assert st.session_id == 2
    assert st.tasks == {}                 # the retry voided registrations
    assert st.clocks["am_downtime_s"] == 2.0   # downtime carries across


def test_journal_discard_removes_both_files(tmp_path):
    j = J.ControlPlaneJournal(str(tmp_path), snapshot_every=1)
    j.append(J.REC_SESSION, session_id=1, expected=1)
    j.append(J.REC_REGISTER, task_id="worker:0", attempt=0,
             host_port="h:1", generation=1)
    assert J.has_journal(str(tmp_path))
    j.discard()
    assert not J.has_journal(str(tmp_path))
    assert not os.path.exists(J.journal_path(str(tmp_path)))
    assert not os.path.exists(J.snapshot_path(str(tmp_path)))


def test_recovery_events_render():
    line = render_event(EventType.AM_RECOVERY_STARTED,
                        {"application_id": "app_1", "am_attempt": 1,
                         "live_tasks": 64, "replayed_records": 130})
    assert "recover" in line.lower() and "64" in line
    line = render_event(EventType.AM_RECOVERY_COMPLETED,
                        {"application_id": "app_1", "am_attempt": 1,
                         "adopted": 63, "lost": 1, "replayed_records": 130,
                         "duration_ms": 1200, "downtime_ms": 4000})
    assert "63" in line and "1" in line


# ---------------------------------------------------------------------------
# supervisor units: relaunch until a verdict, never past max-attempts
# ---------------------------------------------------------------------------

class _FakeAmPopen:
    """Scripted stand-in for the `python -m tony_tpu.am` child."""

    launches: list = []        # (attempt_env, rc) per launch
    script: list = []          # rc queue
    write_status_at: set = ()  # launch ordinals that leave status.json
    status_path = ""

    def __init__(self, argv, env=None, **kw):
        ordinal = len(_FakeAmPopen.launches)
        self._rc = _FakeAmPopen.script[ordinal]
        _FakeAmPopen.launches.append((env.get(C.AM_ATTEMPT), self._rc))
        if ordinal in _FakeAmPopen.write_status_at:
            with open(_FakeAmPopen.status_path, "w") as f:
                f.write("{}")

    def wait(self):
        return self._rc

    def poll(self):
        return self._rc

    def send_signal(self, sig):
        pass


def _sup_conf(max_attempts: int) -> TonyConfiguration:
    conf = TonyConfiguration()
    conf.set(K.AM_MAX_ATTEMPTS, max_attempts, "test")
    conf.set(K.AM_RETRY_BACKOFF_BASE_MS, 1, "test")
    conf.set(K.AM_RETRY_BACKOFF_MAX_MS, 2, "test")
    return conf


def _supervise_scripted(tmp_path, monkeypatch, script_rcs, max_attempts,
                        write_status_at=()):
    _FakeAmPopen.launches = []
    _FakeAmPopen.script = list(script_rcs)
    _FakeAmPopen.write_status_at = set(write_status_at)
    _FakeAmPopen.status_path = os.path.join(str(tmp_path), C.AM_STATUS_FILE)
    monkeypatch.setattr(sup.subprocess, "Popen", _FakeAmPopen)
    return sup.supervise("app_sup", str(tmp_path),
                         conf=_sup_conf(max_attempts))


def test_supervisor_relaunches_crashed_am_with_attempt_env(tmp_path,
                                                           monkeypatch):
    rc = _supervise_scripted(tmp_path, monkeypatch, [1, 137, 0],
                             max_attempts=3)
    assert rc == 0
    # every relaunch carried the next TONY_AM_ATTEMPT — the env the AM
    # keys journal replay on
    assert [a for a, _ in _FakeAmPopen.launches] == ["0", "1", "2"]


def test_supervisor_stops_at_max_attempts(tmp_path, monkeypatch):
    rc = _supervise_scripted(tmp_path, monkeypatch, [1, 1], max_attempts=2)
    assert rc == 1
    assert len(_FakeAmPopen.launches) == 2


def test_supervisor_respects_status_json_verdict(tmp_path, monkeypatch):
    """A non-zero AM exit AFTER status.json exists is an application
    outcome (e.g. FAILED), not an AM crash — no relaunch."""
    rc = _supervise_scripted(tmp_path, monkeypatch, [3], max_attempts=5,
                             write_status_at={0})
    assert rc == 3
    assert len(_FakeAmPopen.launches) == 1


# ---------------------------------------------------------------------------
# heartbeater orphan-hook units
# ---------------------------------------------------------------------------

class _HbClientStub:
    def __init__(self, fail: bool):
        self.fail = fail
        self.pings = 0
        self.calls: list = []

    def task_executor_heartbeat(self, *a, **kw):
        if self.fail:
            raise ConnectionError("AM is gone")
        self.pings += 1
        return {}

    def call(self, method, req=None, **kw):
        self.calls.append((method, req, kw))
        if self.fail:
            raise ConnectionError("AM is gone")
        return {}


def test_heartbeater_orphan_hook_resets_budget_and_resumes():
    dead, live = _HbClientStub(fail=True), _HbClientStub(fail=False)
    hb = te.Heartbeater(dead, "worker:0", interval_sec=0.01,
                        failure_budget=2)
    hooks = []

    def on_orphaned():
        hooks.append(1)
        hb.swap_client(live)     # "a recovered AM adopted us"
        return True

    hb._on_orphaned = on_orphaned
    hb.start()
    deadline = time.monotonic() + 10
    while live.pings < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    hb.stop()
    # (Heartbeater shadows Thread._stop with an Event, so join() is
    # unusable — stop() + the polled condition above is the sync point)
    # one orphan episode, then heartbeats resumed on the swapped client
    assert hooks == [1]
    assert live.pings >= 3


def test_heartbeater_exits_when_orphan_hook_gives_up(monkeypatch):
    exits, fatals = [], []
    monkeypatch.setattr(te.os, "_exit", lambda code: exits.append(code))
    hb = te.Heartbeater(_HbClientStub(fail=True), "worker:0",
                        interval_sec=0.01, failure_budget=2,
                        on_fatal=lambda: fatals.append(1),
                        on_orphaned=lambda: False)
    hb.start()
    deadline = time.monotonic() + 10
    while not exits and time.monotonic() < deadline:
        time.sleep(0.02)
    hb.stop()
    assert exits and exits[0] == C.EXIT_HEARTBEAT_FAILURE
    # the hook already self-fenced the user process; on_fatal still runs
    # as the last-resort kill on this path
    assert fatals


def test_heartbeater_without_hook_keeps_reference_self_destruct(monkeypatch):
    exits = []
    monkeypatch.setattr(te.os, "_exit", lambda code: exits.append(code))
    hb = te.Heartbeater(_HbClientStub(fail=True), "worker:0",
                        interval_sec=0.01, failure_budget=1)
    hb.start()
    deadline = time.monotonic() + 10
    while not exits and time.monotonic() < deadline:
        time.sleep(0.02)
    hb.stop()
    assert exits and exits[0] == C.EXIT_HEARTBEAT_FAILURE


# ---------------------------------------------------------------------------
# executor orphan-mode units
# ---------------------------------------------------------------------------

def _executor(tmp_path) -> te.TaskExecutor:
    env = {C.JOB_NAME: "worker", C.TASK_INDEX: "0",
           C.AM_HOST: "127.0.0.1", C.AM_PORT: "1",
           C.TONY_APP_DIR: str(tmp_path)}
    return te.TaskExecutor(env=env, client=_HbClientStub(fail=True),
                           metrics_client=object())


def test_orphan_grace_expiry_self_fences_with_checkpoint_ladder(tmp_path):
    """No AM ever publishes an address: the orphan must fence itself
    through _terminate_user_proc (TERM→checkpoint→KILL), report the
    heartbeat-failure verdict best-effort, and return False so the
    heartbeater exits the process."""
    ex = _executor(tmp_path)
    ex._orphan_grace_sec = 0.4
    calls = []
    ex._terminate_user_proc = lambda: calls.append("term")
    t0 = time.monotonic()
    assert ex._on_hb_orphaned() is False
    assert time.monotonic() - t0 >= 0.4
    assert calls == ["term"]
    # the terminal verdict was attempted fail-fast (one attempt, short
    # deadline — a dead AM must not hold the fence open for minutes)
    method, req, kw = ex.client.calls[-1]
    assert method == "register_execution_result"
    assert req["exit_code"] == C.EXIT_HEARTBEAT_FAILURE
    assert kw.get("retries") == 1 and kw.get("wait_for_ready") is False


def test_orphan_ignores_malformed_hostport_file(tmp_path):
    ex = _executor(tmp_path)
    ex._orphan_grace_sec = 0.3
    ex._terminate_user_proc = lambda: None
    # a torn amhostport (no port yet) must not be dialed
    with open(os.path.join(str(tmp_path), C.AM_HOSTPORT_FILE), "w") as f:
        f.write("hostonly-no-colon")
    assert ex._on_hb_orphaned() is False
    assert ex._orphan_reattach("host:notaport") is False


def test_orphan_reattach_swaps_clients_attempt_fenced(tmp_path,
                                                      monkeypatch):
    """A successful re-registration swaps both the executor's and the
    heartbeater's channel to the recovered AM and closes the dead one."""
    registered, made = [], []

    class _FakeChannel:
        def __init__(self, host, port, auth_token=None, task_auth_id=None):
            self.addr = (host, port)
            self.closed = False
            made.append(self)

        def call(self, method, req, **kw):
            registered.append((method, req))
            return {"spec": None}     # recovering AM: barrier open

        def close(self):
            self.closed = True

    monkeypatch.setattr(te, "ClusterServiceClient", _FakeChannel)
    ex = _executor(tmp_path)
    old_client = ex.client

    class _Closeable:
        closed = False

        def close(self):
            self.closed = True

    ex.client = _Closeable()
    ex.heartbeater = te.Heartbeater(ex.client, "worker:0",
                                    interval_sec=60)
    assert ex._orphan_reattach("127.0.0.1:5123") is True
    assert made and made[0].addr == ("127.0.0.1", 5123)
    assert ex.client is made[0]
    assert ex.heartbeater._client is made[0]
    # the re-registration is attempt-stamped (the recovering AM fences on it)
    method, req = registered[0]
    assert method == "register_worker_spec"
    assert req["task_id"] == "worker:0" and req["task_attempt"] == 0
    del old_client


# ---------------------------------------------------------------------------
# history + fleet across an AM restart
# ---------------------------------------------------------------------------

def test_event_handler_resume_yields_single_jhist(tmp_path):
    """Attempt 0 crashes mid-history; the recovered attempt adopts the
    .inprogress file and the application still ends with EXACTLY ONE
    .jhist carrying both attempts' events."""
    md0 = JobMetadata(application_id="app_r", started=1000, user="alice")
    h0 = EventHandler(str(tmp_path), md0)
    h0.start()
    h0.emit(Event(EventType.TASK_STARTED,
                  TaskStarted("worker", 0, "h1", "c1"), timestamp=1001))
    inprog = glob.glob(os.path.join(str(tmp_path),
                                    f"*{C.HISTORY_INPROGRESS_SUFFIX}"))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if inprog and os.path.getsize(inprog[0]) > 0:
            break
        time.sleep(0.02)
        inprog = glob.glob(os.path.join(str(tmp_path),
                                        f"*{C.HISTORY_INPROGRESS_SUFFIX}"))
    # h0 is now abandoned (SIGKILL) — no stop(), file left in progress

    md1 = JobMetadata(application_id="app_r", started=9999, user="")
    h1 = EventHandler(str(tmp_path), md1, resume=True)
    h1.start()
    h1.emit(Event(EventType.AM_RECOVERY_STARTED,
                  AmRecoveryStarted("app_r", am_attempt=1, live_tasks=1),
                  timestamp=2000))
    final = h1.stop("SUCCEEDED")

    finals = glob.glob(os.path.join(str(tmp_path), f"*.{C.HISTORY_SUFFIX}")) \
        or glob.glob(os.path.join(str(tmp_path), "*.jhist"))
    assert len(finals) == 1
    assert not glob.glob(os.path.join(str(tmp_path),
                                      f"*{C.HISTORY_INPROGRESS_SUFFIX}"))
    types = [e.type for e in parse_events(final)]
    assert EventType.TASK_STARTED in types
    assert EventType.AM_RECOVERY_STARTED in types
    # the adopted metadata kept attempt 0's start stamp in the file name
    assert "1000" in os.path.basename(final)


class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, s: float) -> None:
        self.t += s


def test_fleet_lost_job_refolds_to_running_on_recovered_heartbeat():
    """Satellite: an AM outage demotes the job to LOST in the fleet
    registry; the RECOVERING attempt's first jobstate republish (fresh
    heartbeat stamp) must fold it straight back — LOST is a presumption,
    not a terminal verdict."""
    clock = _FakeClock(1000.0)
    reg = fleet.FleetRegistry(stale_after_ms=2000, clock=clock)
    reg.observe(fleet.job_summary(
        "app_a", "alice", "default", "RUNNING", gang_width=64,
        requested_chips=64, started_ms=990_000,
        heartbeat_ms=int(clock() * 1000)))
    clock.tick(5.0)          # the crash: heartbeats stop
    reg.refresh(force=True)
    assert reg.jobs()[0]["state"] == fleet.LOST_STATE
    # recovered attempt re-binds and republishes immediately (flap guard)
    reg.observe(fleet.job_summary(
        "app_a", "alice", "default", "RUNNING", gang_width=64,
        requested_chips=64, started_ms=990_000,
        heartbeat_ms=int(clock() * 1000)))
    reg.refresh(force=True)
    assert reg.jobs()[0]["state"] == "RUNNING"


# ---------------------------------------------------------------------------
# chaos e2e helpers
# ---------------------------------------------------------------------------

def _pids_matching(token: str, scope: str, exclude: str = "") -> list:
    """PIDs whose /proc cmdline contains the exact argv `token` plus the
    `scope` substring (the run's tmp dir — keeps parallel test runs on a
    shared box out of each other's blast radius)."""
    out = []
    for p in os.listdir("/proc"):
        if not p.isdigit():
            continue
        try:
            with open(f"/proc/{p}/cmdline", "rb") as f:
                args = f.read().decode("utf-8", "replace").split("\0")
        except OSError:
            continue
        if token in args and any(scope in a for a in args) \
                and (not exclude or exclude not in args):
            out.append(int(p))
    return out


def _procs_with_cwd_under(root: str) -> list:
    out = []
    for p in os.listdir("/proc"):
        if not p.isdigit():
            continue
        try:
            cwd = os.readlink(f"/proc/{p}/cwd")
        except OSError:
            continue
        if cwd.startswith(root):
            out.append(int(p))
    return out


def _wait_until(pred, timeout_sec: float, what: str) -> None:
    deadline = time.monotonic() + timeout_sec
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out after {timeout_sec}s waiting for {what}")


# ---------------------------------------------------------------------------
# chaos e2e: orphan grace expiry WITHOUT a supervisor (max-attempts=1)
# ---------------------------------------------------------------------------

@chaos
def test_orphan_grace_self_fence_without_supervisor(tmp_path):
    """SIGKILL the AM with tony.am.max-attempts=1: nobody restarts it.
    Executors must go orphan (user processes untouched), wait out the
    full tony.am.orphan-grace-ms, then self-fence through the TERM →
    emergency-checkpoint → KILL ladder — the trainers' SIGTERM traps
    prove the checkpoint window was honored, and no orphan process may
    outlive the grace."""
    run = ChaosRun(tmp_path, seed=21)
    run.run(
        ["--executes", script("recovery_gang_worker.py"),
         "--conf", "tony.worker.instances=2"],
        injections=[KillAM(after_ms=2500)],
        conf_overrides={
            K.TASK_HB_FAILURE_BUDGET: 2,
            K.AM_ORPHAN_GRACE_MS: 2500,
        },
        extra_env={"RECOVERY_STEPS": "600", "RECOVERY_STEP_SLEEP": "0.05"})
    # no supervisor, no status.json: the client reports the AM crash
    assert run.final_status == "FAILED", run.all_logs()
    assert "exited unexpectedly" in run.final_message

    # both trainers were TERMed inside the ladder and wrote their
    # emergency-checkpoint markers before exiting
    _wait_until(
        lambda: all(os.path.isfile(os.path.join(run.marker_dir,
                                                f"ckpt_worker_{i}"))
                    for i in range(2)),
        45, "orphan self-fence checkpoint markers")
    for i in range(2):
        with open(os.path.join(run.marker_dir, f"ckpt_worker_{i}")) as f:
            assert json.loads(f.read())["emergency"] is True
    # every orphan fenced itself: nothing is left running under this app
    _wait_until(lambda: not _procs_with_cwd_under(str(tmp_path)),
                30, "orphaned executor/user processes to exit")


# ---------------------------------------------------------------------------
# chaos e2e: wedged-not-dead AM (SIGSTOP/SIGCONT) — re-attach, same address
# ---------------------------------------------------------------------------

@chaos
def test_hung_am_thaws_and_orphans_reattach_same_address(tmp_path):
    """SIGSTOP the AM mid-training: executors exhaust the heartbeat
    budget, orphan, and keep re-dialing the UNCHANGED amhostport until
    the thawed AM answers. No relaunch, no second user-process start,
    the job still succeeds."""
    run = ChaosRun(tmp_path, seed=22)
    run.run(
        ["--executes", script("recovery_gang_worker.py"),
         "--conf", "tony.worker.instances=2"],
        injections=[HangAM(after_ms=2000, hang_ms=3000)],
        conf_overrides={
            K.TASK_HB_FAILURE_BUDGET: 2,
            K.AM_ORPHAN_GRACE_MS: 60_000,
            # AM-side expiry window 0.2s * 60 = 12s: the silent stretch
            # (hang + orphan re-dial backoff) must not expire anyone
            K.TASK_MAX_MISSED_HEARTBEATS: 60,
        },
        extra_env={"RECOVERY_STEPS": "200", "RECOVERY_STEP_SLEEP": "0.05"})
    assert run.final_status == "SUCCEEDED", run.all_logs()
    assert run.relaunches() == [], run.all_logs()
    for i in range(2):
        assert run.markers("worker", i) == [{"attempt": 0, "generation": 1}]
    run.history_events()      # exactly one .jhist


# ---------------------------------------------------------------------------
# chaos e2e: the headline — SIGKILL the AM at width 64 mid-training
# ---------------------------------------------------------------------------

def _recovery_argv(width: int, extra: "list | None" = None) -> list:
    return (["--executes", script("recovery_gang_worker.py"),
             "--conf", f"tony.worker.instances={width}"] + (extra or []))


_W64_CONF = {
    # one core hosts ~130 processes: 1s heartbeats keep the AM's inbox
    # (and the box) sane; the expiry window scales with it
    K.TASK_HEARTBEAT_INTERVAL_MS: 1000,
    K.TASK_MAX_MISSED_HEARTBEATS: 25,
    K.TASK_REGISTRATION_TIMEOUT_SEC: 300,
    K.CONTAINER_ALLOCATION_TIMEOUT: 300_000,
}


@chaos
def test_am_kill_at_width64_adopts_gang_zero_relaunches_bit_identical(
        tmp_path):
    """The tentpole, end to end at width 64 on the real process chain:

    1. all 64 trainers launch and park at their mid-training hold;
    2. the AM is SIGKILLed (found via /proc, exact argv match — never
       the supervisor);
    3. the supervisor relaunches it; the new attempt replays the
       journal, enters RECOVERING, republishes amhostport;
    4. every orphaned executor re-attaches; AM_RECOVERY_COMPLETED
       reports adopted=64, lost=0;
    5. the hold releases, training finishes, the job SUCCEEDS with
       ZERO user-process relaunches and a loss trajectory bit-identical
       to an undisturbed twin run.
    """
    width = 64
    disturbed_dir = tmp_path / "disturbed"
    twin_dir = tmp_path / "twin"
    disturbed_dir.mkdir()
    twin_dir.mkdir()
    release = str(tmp_path / "release")

    run = ChaosRun(disturbed_dir, seed=23)
    watcher_err: list = []

    def _watcher():
        try:
            # (1) every trainer is past the barrier and parked at its hold
            _wait_until(
                lambda: all(os.path.isfile(
                    os.path.join(run.marker_dir, f"worker_{i}"))
                    for i in range(width)),
                240, "all width-64 start markers")
            # (2) SIGKILL the AM — exact argv token, supervisor excluded
            pids = _pids_matching("tony_tpu.am", str(disturbed_dir),
                                  exclude="tony_tpu.am.supervisor")
            assert len(pids) == 1, f"expected one AM, found {pids}"
            os.kill(pids[0], signal.SIGKILL)
            # (3+4) the recovered attempt finishes adopting the gang
            def _recovered():
                for p in glob.glob(os.path.join(
                        str(disturbed_dir), "**",
                        f"*{C.HISTORY_INPROGRESS_SUFFIX}"),
                        recursive=True):
                    try:
                        for e in parse_events(p):
                            if e.type == EventType.AM_RECOVERY_COMPLETED:
                                return True
                    except Exception:  # noqa: BLE001 — torn mid-write line
                        pass
                return False
            _wait_until(_recovered, 180, "AM_RECOVERY_COMPLETED in history")
        except BaseException as exc:  # noqa: BLE001
            watcher_err.append(exc)
        finally:
            # (5) always release the gang, pass or fail — no wedged run
            with open(release, "w") as f:
                f.write("go")

    watcher = threading.Thread(target=_watcher, daemon=True)
    watcher.start()
    run.run(
        _recovery_argv(width,
                       ["--conf", "tony.am.max-attempts=3",
                        "--conf", "tony.am.retry-backoff-base-ms=250",
                        "--conf", "tony.am.retry-backoff-max-ms=500"]),
        conf_overrides=dict(_W64_CONF, **{
            K.TASK_HB_FAILURE_BUDGET: 2,
            K.AM_ORPHAN_GRACE_MS: 120_000,
        }),
        extra_env={"RECOVERY_STEPS": "8", "RECOVERY_STEP_SLEEP": "0.05",
                   "CHAOS_RECOVERY_HOLD": release})
    watcher.join(timeout=30)
    assert not watcher_err, watcher_err

    assert run.final_status == "SUCCEEDED", run.all_logs()

    # zero user-process relaunches: every slot started EXACTLY once, on
    # attempt 0 against the restored generation
    for i in range(width):
        assert run.markers("worker", i) == \
            [{"attempt": 0, "generation": 1}], f"worker:{i} relaunched"
    assert run.relaunches() == [], run.all_logs()

    # the recovery ledger: one restart, the whole gang adopted
    started = run.events_of_type(EventType.AM_RECOVERY_STARTED)
    completed = run.events_of_type(EventType.AM_RECOVERY_COMPLETED)
    assert len(started) == 1 and len(completed) == 1
    assert started[0].payload.am_attempt == 1
    assert started[0].payload.replayed_records > 0
    assert completed[0].payload.adopted == width
    assert completed[0].payload.lost == 0
    assert completed[0].payload.replayed_records > 0
    assert completed[0].payload.downtime_ms > 0

    # exactly one .jhist despite two AM attempts (resumed history)
    run.history_events()

    # goodput ledger charges the outage to the new am_downtime phase
    with open(os.path.join(run.app_history_dir(), C.GOODPUT_FILE)) as f:
        goodput = json.load(f)
    assert goodput["job"]["am_downtime_s"] > 0

    # the undisturbed twin: same trainer, same steps, no kill, no hold
    twin = ChaosRun(twin_dir, seed=23)
    twin.run(_recovery_argv(width), conf_overrides=dict(_W64_CONF),
             extra_env={"RECOVERY_STEPS": "8", "RECOVERY_STEP_SLEEP": "0.05"})
    assert twin.final_status == "SUCCEEDED", twin.all_logs()

    # bit-identical loss trajectories, every rank
    for i in range(width):
        with open(os.path.join(run.marker_dir, f"loss_worker_{i}"),
                  "rb") as f:
            disturbed_loss = f.read()
        with open(os.path.join(twin.marker_dir, f"loss_worker_{i}"),
                  "rb") as f:
            twin_loss = f.read()
        assert disturbed_loss == twin_loss, \
            f"worker:{i} loss diverged across the AM outage"
    assert twin.relaunches() == []
