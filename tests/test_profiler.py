"""Always-on control-plane profiler + stall watchdog
(observability/profiler.py) and the wedge-autopsy path built on it.

Four layers:

1. units — FoldTable boundedness and collapsed-fold format, beacon
   staleness vs the idle exemption, cross-process stack-dump redaction,
   dominant-frame selection, the profiler's overhead self-measurement
   and past-budget throttle, watchdog detect/clear latch semantics;
2. wiring — ``install_process_profiler`` honors ``tony.profiler.enabled``
   and ``enable_crash_dumps`` reports its success;
3. lint fixtures — the ``watchdog-beacon`` and ``process-entry-profiler``
   rules fire / stay silent / suppress like every other shipped rule;
4. chaos e2e — a wedged executor (TEST_TASK_WEDGE + silenced
   heartbeats) is autopsied end to end: diagnostics.json's ``stacks``
   section names the parked frame and the history carries a latched
   PROCESS_STALL_DETECTED / _CLEARED pair.
"""

import inspect
import random
import signal
import threading
import time

import pytest

from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.schema import EventType
from tony_tpu.observability.logs import redact
from tony_tpu.observability.profiler import (
    DEFAULT_HZ, OTHER_KEY, OVERHEAD_BUDGET_PCT, STALL_CLEARED,
    STALL_DETECTED, Beacon, FoldTable, SamplingProfiler, StallWatchdog,
    _reset_beacons, beacons, collect_thread_stacks, dominant_frame,
    enable_crash_dumps, fold_frames, install_process_profiler,
    register_beacon,
)

from tests.chaos import ChaosRun, SilenceHeartbeats, WedgeTask, script

pytestmark = pytest.mark.profiler


@pytest.fixture(autouse=True)
def _fresh_beacon_registry():
    """The beacon registry is process-global; isolate every test."""
    _reset_beacons()
    yield
    _reset_beacons()


# ---------------------------------------------------------------------------
# FoldTable: bounded collapsed-stack histogram
# ---------------------------------------------------------------------------

def test_fold_table_bounds_distinct_stacks_and_discloses_drops():
    table = FoldTable(max_stacks=2)
    table.add("t;a.f")
    table.add("t;b.g")
    for _ in range(3):
        table.add("t;c.h")        # over the cap: folds into (other)
    table.add("t;a.f")            # existing key still accumulates at cap
    snap = table.snapshot()
    assert snap == {"t;a.f": 2, "t;b.g": 1, OTHER_KEY: 3}
    assert table.dropped == 3


def test_fold_table_folded_is_hottest_first_flamegraph_lines():
    table = FoldTable()
    table.add("t;cold.f", 1)
    table.add("t;hot.g", 5)
    assert table.folded() == "t;hot.g 5\nt;cold.f 1\n"
    assert FoldTable().folded() == ""


def test_fold_frames_labels_are_module_dot_function_leafward():
    labels = fold_frames(inspect.currentframe())
    assert labels[-1] == "test_profiler." \
        "test_fold_frames_labels_are_module_dot_function_leafward"
    assert all("." in lab for lab in labels)


# ---------------------------------------------------------------------------
# Beacon: staleness with the idle exemption
# ---------------------------------------------------------------------------

def test_beacon_staleness_and_idle_exemption():
    b = Beacon("loop", cadence_sec=1.0)
    far = time.monotonic() + 100.0
    # never beaten -> IDLE -> exempt no matter how old
    assert not b.is_stale(4.0, now=far)
    b.beat()
    assert not b.is_stale(4.0, now=time.monotonic())   # fresh
    assert b.is_stale(4.0, now=far)                    # ACTIVE + old = wedge
    assert b.age_sec(now=far) > 99.0
    b.idle()                                           # blocking on work
    assert not b.is_stale(4.0, now=far)


def test_register_beacon_replaces_by_name():
    first = register_beacon("loop", 1.0)
    second = register_beacon("loop", 2.0)
    assert beacons() == [second] and first is not second


# ---------------------------------------------------------------------------
# stack snapshots: redaction + dominant-frame attribution
# ---------------------------------------------------------------------------

def test_collect_thread_stacks_shape_and_leaf_first_frames():
    threads = collect_thread_stacks(redactor=None)
    me = [t for t in threads if t["ident"] == threading.get_ident()]
    assert len(me) == 1
    # leaf-first: the capture itself is the leaf, this function is next
    assert "profiler.py" in me[0]["frames"][0]
    assert ":collect_thread_stacks" in me[0]["frames"][0]
    assert ":test_collect_thread_stacks_shape_and_leaf_first_frames" \
        in me[0]["frames"][1]
    assert isinstance(me[0]["daemon"], bool)


def test_collect_thread_stacks_redacts_on_the_way_out():
    # default: the shared log redactor (dumps cross process boundaries)
    sig = inspect.signature(collect_thread_stacks)
    assert sig.parameters["redactor"].default is redact
    threads = collect_thread_stacks(redactor=lambda s: "X")
    assert threads and all(t["name"] == "X" for t in threads)
    assert all(f == "X" for t in threads for f in t["frames"])


def test_dominant_frame_prefers_ident_then_main_then_non_self():
    threads = [
        {"name": "tony-profiler", "ident": 1, "frames": ["p.py:1:prof"]},
        {"name": "MainThread", "ident": 2,
         "frames": ["m.py:9:leaf", "m.py:1:root"]},
        {"name": "worker", "ident": 3, "frames": ["w.py:5:spin"]},
    ]
    assert dominant_frame(threads, ident=3) == "w.py:5:spin"
    assert dominant_frame(threads) == "m.py:9:leaf"
    no_main = [t for t in threads if t["name"] != "MainThread"]
    assert dominant_frame(no_main) == "w.py:5:spin"   # skips profiler's own
    assert dominant_frame([]) == ""


# ---------------------------------------------------------------------------
# SamplingProfiler: attribution, self-overhead, past-budget throttle
# ---------------------------------------------------------------------------

def _park(evt):
    evt.wait()


def test_sampler_attributes_stacks_per_thread_and_excludes_itself():
    evt = threading.Event()
    t = threading.Thread(target=_park, name="park-thread", args=(evt,),
                         daemon=True)
    t.start()
    try:
        prof = SamplingProfiler("unit", rng=random.Random(0))
        prof.sample_once()          # called inline; the thread never runs
        folded = prof.folded_text()
        assert "park-thread;" in folded
        assert "test_profiler._park" in folded
        # the sampling thread itself is cost, not workload
        assert "tony-profiler;" not in folded
    finally:
        evt.set()
        t.join(timeout=5)


def test_sampler_measures_its_own_overhead():
    prof = SamplingProfiler("unit", rng=random.Random(0))
    assert prof.overhead_pct() == 0.0
    for _ in range(4):
        prof.sample_once()
    snap = prof.snapshot()
    assert snap["samples"] == 4
    assert snap["overhead_pct"] > 0.0            # walking frames costs
    assert snap["overhead_budget_pct"] == OVERHEAD_BUDGET_PCT == 1.0
    assert snap["hz"] == DEFAULT_HZ
    assert snap["throttle"] == 1.0               # nowhere near budget


def test_sampler_throttles_itself_past_budget_instead_of_blowing_it():
    # an impossible budget: every sample is over it, so the profiler must
    # back its own cadence off (doubling, capped) rather than keep paying
    prof = SamplingProfiler("unit", overhead_budget_pct=0.0,
                            rng=random.Random(0))
    base_interval = 1.0 / prof.hz
    for _ in range(20):
        prof.sample_once()
    snap = prof.snapshot()
    assert 1.0 < snap["throttle"] <= 32.0
    # the throttle stretches the sampling interval (jitter is +/-25%)
    assert prof._interval() > base_interval * snap["throttle"] * 0.75 * 0.99


# ---------------------------------------------------------------------------
# StallWatchdog: latched detect/clear pairs, idle loops exempt
# ---------------------------------------------------------------------------

def test_watchdog_latches_one_detect_then_one_clear():
    events = []
    beacon = register_beacon("loop", 0.05)
    beacon.beat()
    wd = StallWatchdog("unit-proc", stall_factor=2.0,
                       event_sink=lambda n, p: events.append((n, p)))
    far = time.monotonic() + 10.0
    wd.check_once(now=far)
    wd.check_once(now=far + 1.0)      # latched: no detect storm
    assert [n for n, _ in events] == [STALL_DETECTED]
    name, payload = events[0]
    assert payload["process"] == "unit-proc"
    assert payload["beacon"] == "loop"
    assert payload["stalled_ms"] > payload["cadence_ms"]
    # the beat came from this thread, so attribution lands on our leaf
    assert payload["blocking_frame"]
    assert "loop" in wd.stalled()
    beacon.beat()                     # progress resumes
    wd.check_once(now=time.monotonic())
    assert [n for n, _ in events] == [STALL_DETECTED, STALL_CLEARED]
    assert events[1][1]["beacon"] == "loop"
    assert wd.stalled() == {}


def test_watchdog_ignores_idle_beacons():
    events = []
    beacon = register_beacon("queue-loop", 0.05)
    beacon.idle()                     # blocked on work arrival, not wedged
    wd = StallWatchdog("unit-proc",
                       event_sink=lambda n, p: events.append((n, p)))
    wd.check_once(now=time.monotonic() + 1000.0)
    assert events == []


def test_watchdog_sink_failure_never_escapes():
    beacon = register_beacon("loop", 0.05)
    beacon.beat()
    wd = StallWatchdog("unit-proc",
                       event_sink=lambda n, p: 1 / 0)
    wd.check_once(now=time.monotonic() + 10.0)    # must not raise
    assert "loop" in wd.stalled()


# ---------------------------------------------------------------------------
# wiring: one-call install + crash dumps
# ---------------------------------------------------------------------------

def test_install_process_profiler_respects_enabled_flag():
    conf = TonyConfiguration()
    conf.set(K.PROFILER_ENABLED, False, "test")
    assert install_process_profiler("unit", conf=conf) == (None, None)


def test_install_process_profiler_returns_running_pair():
    conf = TonyConfiguration()
    conf.set(K.PROFILER_HZ, 5, "test")
    prof, wd = install_process_profiler("unit", conf=conf)
    try:
        assert isinstance(prof, SamplingProfiler) and prof.is_alive()
        assert isinstance(wd, StallWatchdog) and wd.is_alive()
        assert prof.hz == 5.0
    finally:
        prof.stop()
        wd.stop()


def test_enable_crash_dumps_registers_signal():
    assert enable_crash_dumps(signal.SIGUSR2) is True


# ---------------------------------------------------------------------------
# lint fixtures: the two profiler-coverage rules
# ---------------------------------------------------------------------------

BEACON_OFFENDER = '''
import threading

class Pusher(threading.Thread):
    def run(self):
        while not self._stop.wait(1.0):
            self._push_once()
'''

BEACON_CLEAN = '''
import threading
from tony_tpu.observability.profiler import register_beacon

class Pusher(threading.Thread):
    def run(self):
        beacon = register_beacon("pusher", 1.0)
        while not self._stop.wait(1.0):
            beacon.beat()
            self._push_once()
        beacon.idle()
'''

BEACON_SUPPRESSED = '''
import threading

class Pusher(threading.Thread):
    # tony: disable=watchdog-beacon -- the observer cannot watch itself
    def run(self):
        while not self._stop.wait(1.0):
            self._push_once()
'''

BEACON_TARGET_OFFENDER = '''
import threading

class Mover:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while not self._stop.is_set():
            self.move_once()
'''


@pytest.mark.lint
def test_watchdog_beacon_rule_fixtures(tmp_path):
    from tests.test_lint import _run
    from tools.tonylint.rules_profiler import WatchdogBeaconRule
    findings = _run(tmp_path, {"tony_tpu/am/x.py": BEACON_OFFENDER},
                    [WatchdogBeaconRule()])
    assert [f.rule for f in findings] == ["watchdog-beacon"]
    assert "run()" in findings[0].message
    findings = _run(tmp_path, {"tony_tpu/am/x.py": BEACON_TARGET_OFFENDER},
                    [WatchdogBeaconRule()])
    assert [f.rule for f in findings] == ["watchdog-beacon"]
    assert _run(tmp_path, {"tony_tpu/am/x.py": BEACON_CLEAN},
                [WatchdogBeaconRule()]) == []
    assert _run(tmp_path, {"tony_tpu/am/x.py": BEACON_SUPPRESSED},
                [WatchdogBeaconRule()]) == []


@pytest.mark.lint
def test_process_entry_profiler_rule_fixtures(tmp_path):
    from tests.test_lint import _run
    from tools.tonylint.rules_profiler import ENTRY_FILES, \
        ProcessEntryProfilerRule
    wired = ("from tony_tpu.observability.profiler import "
             "install_process_profiler\n"
             "install_process_profiler('am')\n")
    dark = "def main():\n    return 0\n"
    # one wired entry: only the others are findings
    findings = _run(tmp_path, {"tony_tpu/am/__main__.py": wired},
                    [ProcessEntryProfilerRule()])
    assert len(findings) == len(ENTRY_FILES) - 1
    assert "tony_tpu/am/__main__.py" not in [f.path for f in findings]
    # present but dark: flagged by name
    findings = _run(tmp_path, {"tony_tpu/am/__main__.py": dark},
                    [ProcessEntryProfilerRule()])
    am = [f for f in findings if f.path == "tony_tpu/am/__main__.py"]
    assert len(am) == 1 and "install_process_profiler" in am[0].message


# ---------------------------------------------------------------------------
# chaos e2e: the wedge autopsy, end to end
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_wedged_executor_autopsy_names_the_blocking_frame(tmp_path):
    """A worker parks forever post-barrier with its heartbeater silenced
    — alive but wedged. The AM's expiry path must pull the executor's
    stack dump over the token-authed log service, put the parked frame
    into diagnostics.json's `stacks` section, and latch exactly one
    PROCESS_STALL_DETECTED / _CLEARED pair in history."""
    run = ChaosRun(tmp_path, seed=21)
    run.run(
        ["--executes", script("sleep_30.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-missed-heartbeats=5",
         "--conf", "tony.task.max-task-attempts=1"],
        injections=[WedgeTask("worker", 0, attempt=0),
                    SilenceHeartbeats("worker", 0, attempt=0)])
    assert run.final_status == "FAILED", run.all_logs()

    # the autopsy: diagnostics.json carries the wedged executor's stacks
    diag = run.diagnostics()
    stacks = diag.get("stacks") or {}
    assert "worker:0" in stacks, (diag, run.all_logs())
    rec = stacks["worker:0"]
    assert rec["reason"].startswith("missed"), rec
    # not "it missed heartbeats" but WHERE it is stuck, by name
    assert "_tony_test_wedge" in rec["blocking_frame"], rec
    assert any("_tony_test_wedge" in f
               for t in rec["threads"] for f in t["frames"]), rec

    # latched pair in history: one detect naming the frame, one clear
    det = [e for e in run.events_of_type(EventType.PROCESS_STALL_DETECTED)
           if e.payload.task_id == "worker:0"]
    assert len(det) == 1, run.all_logs()
    assert det[0].payload.process == "executor:worker:0"
    assert "_tony_test_wedge" in det[0].payload.blocking_frame
    clr = [e for e in run.events_of_type(EventType.PROCESS_STALL_CLEARED)
           if e.payload.task_id == "worker:0"]
    assert len(clr) == 1, run.all_logs()
    assert clr[0].payload.reason == "teardown"
