"""Rule-driven alerting engine (tony_tpu/observability/alerts.py).

Covers: the lifecycle state machine (pending → firing → resolved, dedup,
for-duration, flap suppression), the burn-rate math (counter windows,
gauge exceed-fractions, fast+slow multi-window evaluation — unit-pinned),
rule-spec parsing, the attempt-aware step-regression baseline (the
SloWatchdog false-positive fix), sinks, the fleet-scope rules + portal
surfaces, `cli alerts`, two tier-1 static checks (registered-rule table
coverage; no alert work on the hot loop), and the chaos e2e acceptance:
an injected steady-state step delay + goodput drop drives
pending → firing (event, webhook + file sink, /api, portal timeline)
and → resolved once the fault clears.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import urllib.request

import pytest

from tony_tpu.events.schema import EventType
from tony_tpu.observability import alerts as A

pytestmark = pytest.mark.alerts

SCRIPTS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


class _Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, sec: float) -> None:
        self.t += sec


def _engine(rules, clock, **kw):
    kw.setdefault("default_for_ms", 0)
    kw.setdefault("flap_suppress_ms", 0)
    return A.AlertEngine(rules, clock=clock, **kw)


def _ctx(clock, **kw):
    return A.AlertContext(now_ms=int(clock.t * 1000), **kw)


# ---------------------------------------------------------------------------
# lifecycle state machine
# ---------------------------------------------------------------------------

def test_lifecycle_pending_firing_resolved_with_for_duration():
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.hot", "M", ">", 5, for_ms=1000)],
                  clock)

    def tick(value):
        return eng.evaluate(_ctx(clock, gauges={"worker:0": {"M": value}}))

    assert tick(10) == []                      # condition true -> pending
    assert eng.firing() == []
    clock.advance(0.5)
    assert tick(10) == []                      # still inside for-duration
    clock.advance(0.6)
    fired = tick(10)
    assert [t["status"] for t in fired] == ["firing"]
    assert fired[0]["rule_id"] == "t.hot"
    assert fired[0]["key"] == "worker:0"
    assert fired[0]["for_ms"] >= 1000
    assert len(eng.firing()) == 1
    clock.advance(0.1)
    assert tick(10) == []                      # steady firing: no re-event
    clock.advance(0.1)
    resolved = tick(1)
    assert [t["status"] for t in resolved] == ["resolved"]
    assert resolved[0]["active_ms"] > 0
    assert eng.firing() == []
    # the whole story is in the bounded log
    assert [t["status"] for t in eng.log()] == ["firing", "resolved"]


def test_condition_evaporating_before_for_duration_never_alerts():
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.blip", "M", ">", 5, for_ms=1000)],
                  clock)
    eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 9}}))
    clock.advance(0.5)
    eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 1}}))   # blip cleared
    clock.advance(1.0)
    eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 1}}))
    assert eng.log() == [] and eng.firing() == []


def test_dedup_one_state_per_rule_and_key():
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.hot", "M", ">", 5, for_ms=0)],
                  clock)
    gauges = {"w:0": {"M": 9}, "w:1": {"M": 9}, "w:2": {"M": 1}}
    fired = eng.evaluate(_ctx(clock, gauges=gauges))
    assert sorted(t["key"] for t in fired) == ["w:0", "w:1"]
    # repeated evaluation: same firing instances, zero new transitions
    for _ in range(3):
        clock.advance(0.1)
        assert eng.evaluate(_ctx(clock, gauges=gauges)) == []
    assert len(eng.firing()) == 2
    assert eng.firing_counts() == {("t.hot", "warning"): 2}


def test_flap_suppression_latches_but_mutes():
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.flap", "M", ">", 5, for_ms=0)],
                  clock, flap_suppress_ms=60_000)

    def tick(value):
        return eng.evaluate(_ctx(clock, gauges={"w:0": {"M": value}}))

    assert tick(9)[0]["suppressed"] is False
    clock.advance(1)
    assert tick(1)[0]["status"] == "resolved"
    clock.advance(1)
    refire = tick(9)         # re-fire 1s after resolve: a flap
    assert refire[0]["status"] == "firing"
    assert refire[0]["suppressed"] is True
    # the state still latched (visible in firing()), just not notified
    assert len(eng.firing()) == 1
    assert eng.firing()[0]["flaps"] == 1


def test_flap_that_persists_late_notifies():
    """A re-fire inside the suppression window is muted — but if the
    'flap' then stays bad past the window it is a sustained incident:
    one late firing notification goes out, and the eventual resolve
    notifies normally instead of inheriting the suppression."""
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.sus", "M", ">", 5, for_ms=0)],
                  clock, flap_suppress_ms=60_000)

    def tick(value):
        return eng.evaluate(_ctx(clock, gauges={"w:0": {"M": value}}))

    tick(9)
    clock.advance(1)
    tick(1)                                   # resolved
    clock.advance(1)
    assert tick(9)[0]["suppressed"] is True   # flap: muted
    clock.advance(30)
    assert tick(9) == []                      # still inside the window
    clock.advance(31)
    late = tick(9)                            # outlived the window
    assert [t["status"] for t in late] == ["firing"]
    assert late[0]["suppressed"] is False
    assert late[0]["late_notify"] is True
    clock.advance(1)
    resolved = tick(1)
    assert resolved[0]["status"] == "resolved"
    assert resolved[0]["suppressed"] is False


def test_log_is_bounded():
    clock = _Clock()
    eng = _engine([A.threshold_rule("t.hot", "M", ">", 5, for_ms=0)],
                  clock, log_max=8)
    for i in range(20):
        clock.advance(1)
        eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 9}}))
        clock.advance(1)
        eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 1}}))
    assert len(eng.log()) == 8


def test_broken_rule_never_kills_the_pass():
    clock = _Clock()

    def boom(ctx):
        raise RuntimeError("bad rule")

    eng = _engine([A.AlertRule("t.boom", boom),
                   A.threshold_rule("t.ok", "M", ">", 5, for_ms=0)],
                  clock)
    fired = eng.evaluate(_ctx(clock, gauges={"w:0": {"M": 9}}))
    assert [t["rule_id"] for t in fired] == ["t.ok"]


# ---------------------------------------------------------------------------
# burn-rate math (unit-pinned)
# ---------------------------------------------------------------------------

def test_counter_window_delta_pinned():
    pts = [[0, 0.0], [30_000, 10.0], [60_000, 30.0]]
    # full window: baseline is the sample at the window start
    assert A.counter_window_delta(pts, 60_000, 60_000) == 30.0
    # half window: baseline = value at/before t=30s -> 10
    assert A.counter_window_delta(pts, 60_000, 30_000) == 20.0
    # window opening between samples: latest sample at/before start wins
    assert A.counter_window_delta(pts, 60_000, 20_000) == 20.0
    # series younger than the window: earliest sample is the baseline
    assert A.counter_window_delta(pts[1:], 60_000, 600_000) == 20.0
    # counter reset clamps to 0, never negative
    assert A.counter_window_delta([[0, 50.0], [60_000, 5.0]],
                                  60_000, 60_000) == 0.0
    assert A.counter_window_delta([], 60_000, 60_000) == 0.0


def test_gauge_exceed_fraction_pinned():
    pts = [[t * 10_000, 1.0 if t % 2 else 10.0] for t in range(6)]
    # samples at 0..50s alternate 10,1,10,1,10,1 over threshold 5
    assert A.gauge_exceed_fraction(pts, 50_000, 60_000, 5.0) == 0.5
    # trailing 20s window holds ts=30s,40s,50s -> values 1,10,1
    assert A.gauge_exceed_fraction(pts, 50_000, 20_000, 5.0) \
        == pytest.approx(1 / 3)
    assert A.gauge_exceed_fraction([], 50_000, 20_000, 5.0) == 0.0


def test_burn_rate_pinned():
    # 30% bad over a 1% budget burns 30x; zero budget never divides
    assert A.burn_rate(0.3, 0.01) == pytest.approx(30.0)
    assert A.burn_rate(0.0, 0.01) == 0.0
    assert A.burn_rate(0.5, 0.0) == 0.0


def test_ratio_burn_rule_fast_and_slow_windows_must_agree():
    # cumulative counters sampled each 10s over 120s. First 60s: clean
    # (0 rejects); last 60s: heavy rejects -> slow window dilutes.
    bad, ok = [], []
    total_bad = total_ok = 0
    for t in range(13):
        ts = t * 10_000
        if t > 6:
            total_bad += 30
            total_ok += 70
        else:
            total_ok += 100
        bad.append([ts, float(total_bad)])
        ok.append([ts, float(total_ok)])
    series = {"SERVING_REJECTED_TOTAL": {"serving:0": bad},
              "SERVING_SUBMITTED_TOTAL": {"serving:0": ok}}
    rule = A.ratio_burn_rule(
        "serve.reject_rate_burn", "SERVING_REJECTED_TOTAL",
        "SERVING_SUBMITTED_TOTAL", budget_fraction=0.01,
        fast_ms=60_000, slow_ms=120_000, factor=14.0)
    ctx = A.AlertContext(now_ms=120_000, history_fn=series.get)
    obs = rule.evaluate(ctx)
    # fast window: 180/600 = 30% -> 30x; slow: 180/1200 = 15% -> 15x;
    # both >= 14 -> fires, with the evidence in the annotations
    assert len(obs) == 1
    assert obs[0]["key"] == "serving:0"
    assert obs[0]["annotations"]["burn_fast"] == pytest.approx(30.0)
    assert obs[0]["annotations"]["burn_slow"] == pytest.approx(15.0)
    # a factor between the two windows' burns must NOT fire (slow-window
    # filter: a fast blip alone never pages)
    strict = A.ratio_burn_rule(
        "serve.reject_rate_burn", "SERVING_REJECTED_TOTAL",
        "SERVING_SUBMITTED_TOTAL", budget_fraction=0.01,
        fast_ms=60_000, slow_ms=120_000, factor=20.0)
    assert strict.evaluate(ctx) == []


def test_gauge_burn_rule_ttft_ceiling():
    # TTFT p95 above the 0.5s ceiling for the whole back half of the
    # run: the fast window (t=60..120s: 7 samples, 6 bad) burns
    # (6/7)/0.01 ≈ 85.7x budget, the slow (13 samples, 6 bad) ≈ 46x —
    # both over the factor, so the rule fires with pinned evidence
    pts = [[t * 10_000, 0.1 if t <= 6 else 0.9] for t in range(13)]
    series = {"SERVING_TTFT_P95_S": {"serving:0": pts}}
    rule = A.gauge_burn_rule("serve.ttft_p95_burn", "SERVING_TTFT_P95_S",
                             0.5, fast_ms=60_000, slow_ms=120_000,
                             factor=14.0)
    obs = rule.evaluate(A.AlertContext(now_ms=120_000,
                                       history_fn=series.get))
    assert len(obs) == 1
    assert obs[0]["annotations"]["burn_fast"] == pytest.approx(
        round(600.0 / 7.0, 3))
    assert obs[0]["annotations"]["burn_slow"] == pytest.approx(
        round(600.0 / 13.0, 3))


# ---------------------------------------------------------------------------
# rule specs + conf builders
# ---------------------------------------------------------------------------

def test_parse_duration_and_rule_spec():
    assert A.parse_duration_ms("500ms") == 500
    assert A.parse_duration_ms("30s") == 30_000
    assert A.parse_duration_ms("5m") == 300_000
    rule = A.parse_rule_spec(
        "hbm.high:TPU_MEMORY_USAGE_PCT>95:for=30s:severity=critical")
    assert rule.rule_id == "hbm.high"
    assert rule.severity == "critical" and rule.for_ms == 30_000
    obs = rule.evaluate(A.AlertContext(
        now_ms=0, gauges={"worker:1": {"TPU_MEMORY_USAGE_PCT": 97.0}}))
    assert obs[0]["key"] == "worker:1"
    for bad in ("nonsense", "id:METRIC~5", "id:M>5:for=xx",
                "id:M>5:severity=shouty", "id:M>5:scope=galaxy"):
        with pytest.raises(ValueError):
            A.parse_rule_spec(bad)


def test_am_gates_legacy_slo_checks_when_engine_subsumes_them(tmp_path):
    """One condition, one notifier: with only legacy tony.slo.* keys
    set, the engine inherits the thresholds AND the AM zeroes the
    legacy watchdog's matching checks — a regression must not produce
    SLO_VIOLATION and ALERT_FIRING in parallel every tick."""
    from tony_tpu.am.application_master import ApplicationMaster
    from tony_tpu.cluster.backend import ClusterBackend
    from tony_tpu.conf import TonyConfiguration, keys as K

    class _NullBackend(ClusterBackend):
        off_host = False

        def set_callbacks(self, *a, **k): ...
        def start(self): ...
        def stop(self): ...
        def request_containers(self, *a, **k): ...
        def release_container(self, *a, **k): ...
        def launch_container(self, *a, **k): ...
        def stop_container(self, *a, **k): ...
        def validate_coresident(self, *a, **k): ...

    conf = TonyConfiguration()
    conf.set(K.SLO_STEP_TIME_REGRESSION_PCT, 40, "t")
    conf.set(K.SLO_GOODPUT_FLOOR_PCT, 60, "t")
    am = ApplicationMaster.__new__(ApplicationMaster)
    try:
        ApplicationMaster.__init__(am, conf, "app_gate_test",
                                   str(tmp_path), backend=_NullBackend())
    except TypeError:
        pytest.skip("backend stub drifted from ClusterBackend ABC")
    assert am.alert_engine is not None
    rules = {r.rule_id for r in am.alert_engine.rules}
    assert {"train.step_time_regression",
            "train.goodput_floor"} <= rules
    assert am.slo.step_regression_pct == 0
    assert am.slo.goodput_floor_pct == 0


def test_engine_from_conf_builds_rules_and_slo_fallback():
    from tony_tpu.conf import TonyConfiguration, keys as K
    conf = TonyConfiguration()
    conf.set(K.SLO_STEP_TIME_REGRESSION_PCT, 50, "t")   # legacy key
    conf.set(K.ALERTS_GOODPUT_FLOOR_PCT, 70, "t")
    conf.set(K.ALERTS_TTFT_P95_SLO_MS, 500, "t")
    conf.set(K.ALERTS_REJECT_RATE_BUDGET_PCT, 1.0, "t")
    conf.set(K.ALERTS_RULES, "hbm.high:TPU_MEMORY_USAGE_PCT>95", "t")
    eng = A.engine_from_conf(conf)
    assert sorted(r.rule_id for r in eng.rules) == [
        "hbm.high", "serve.reject_rate_burn", "serve.ttft_p95_burn",
        "train.goodput_floor", "train.step_time_regression"]
    # disabled entirely
    off = TonyConfiguration()
    off.set(K.ALERTS_ENABLED, False, "t")
    off.set(K.ALERTS_GOODPUT_FLOOR_PCT, 70, "t")
    assert A.engine_from_conf(off) is None
    # no live thresholds -> no engine, no per-tick work
    assert A.engine_from_conf(TonyConfiguration()) is None


# ---------------------------------------------------------------------------
# attempt-aware step-regression baseline (the SloWatchdog fix)
# ---------------------------------------------------------------------------

def test_step_regression_baseline_resets_on_attempt_bump():
    from tony_tpu.observability.perf import SloWatchdog
    dog = SloWatchdog(step_regression_pct=50)
    steady = [[i, 100.0] for i in range(8)]
    assert dog.current_step_regressions({"worker:0": steady}) == []
    # a real regression within attempt 0 is detected
    regressed = steady + [[8, 400.0]]
    out = dog.current_step_regressions({"worker:0": regressed})
    assert out and out[0]["task_id"] == "worker:0"
    # relaunch: attempt 1's recompile steps land in the SAME series.
    # Pre-fix these tripped the latch against attempt 0's baseline;
    # now the bump resets the baseline window to the new attempt.
    recompile = regressed + [[i, 400.0] for i in range(9, 14)]
    assert dog.current_step_regressions(
        {"worker:0": recompile}, attempts={"worker:0": 1}) == []
    # ...and the new attempt's own baseline IS the slow recompile pace,
    # so a further regression within attempt 1 still fires
    worse = recompile + [[14, 400.0], [15, 1200.0]]
    out = dog.current_step_regressions(
        {"worker:0": worse}, attempts={"worker:0": 1})
    assert out and out[0]["value"] == 1200.0
    assert "attempt 1" in out[0]["message"]


def test_step_regression_baseline_survives_series_decimation():
    """The baseline mark is a timestamp, not an index: the TimeSeries
    behind the trajectories halves itself in place when full, so an
    index recorded at the attempt bump would drift (or point past the
    end forever). Detection must keep working on a series that
    decimated after the bump."""
    from tony_tpu.observability.perf import SloWatchdog
    dog = SloWatchdog(step_regression_pct=50)
    attempt0 = [[i, 100.0] for i in range(8)]
    assert dog.current_step_regressions({"w:0": attempt0}) == []
    # attempt bump observed with one new-attempt point at the tail
    bump = attempt0 + [[8, 400.0]]
    assert dog.current_step_regressions({"w:0": bump},
                                        attempts={"w:0": 1}) == []
    # the series then DECIMATES (every other point) while attempt 1
    # keeps appending: the boundary timestamp still cuts correctly
    decimated = attempt0[::2] + [[8, 400.0], [9, 400.0], [10, 400.0]]
    assert dog.current_step_regressions({"w:0": decimated},
                                        attempts={"w:0": 1}) == []
    # ...and a genuine regression within attempt 1 still fires on the
    # decimated series
    worse = decimated + [[11, 400.0], [12, 1800.0]]
    out = dog.current_step_regressions({"w:0": worse},
                                       attempts={"w:0": 1})
    assert out and out[0]["value"] == 1800.0


def test_legacy_check_rearms_latch_on_attempt_bump():
    from tony_tpu.observability.perf import SloWatchdog
    dog = SloWatchdog(step_regression_pct=50)
    series = {"worker:0": [[i, 100.0] for i in range(7)] + [[8, 400.0]]}
    assert len(dog.check(series)) == 1
    assert dog.check(series) == []            # latched
    # the relaunch resets both baseline and latch: no violation reported
    # for the replacement's identical-looking slow tail
    series2 = {"worker:0": series["worker:0"]
               + [[i, 400.0] for i in range(9, 15)]}
    assert dog.check(series2, attempts={"worker:0": 1}) == []
    assert dog.active() == []


def test_step_regression_rule_wraps_watchdog():
    rule = A.step_regression_rule(50.0)
    series = {"TRAIN_STEP_TIME_MS": {
        "worker:3": [[i, 100.0] for i in range(7)] + [[8, 300.0]]}}
    obs = rule.evaluate(A.AlertContext(now_ms=0,
                                       history_fn=series.get))
    assert obs[0]["key"] == "worker:3"
    assert rule.rule_id == "train.step_time_regression"


# ---------------------------------------------------------------------------
# job + fleet rules
# ---------------------------------------------------------------------------

def test_goodput_and_mfu_floor_rules():
    good = A.goodput_floor_rule(60.0)
    assert good.evaluate(A.AlertContext(
        now_ms=0, job={"goodput_pct": 45.0}))[0]["key"] == "job"
    assert good.evaluate(A.AlertContext(
        now_ms=0, job={"goodput_pct": 75.0})) == []
    # absence of data is never a violation
    assert good.evaluate(A.AlertContext(now_ms=0)) == []
    mfu = A.mfu_floor_rule(30.0)
    assert mfu.evaluate(A.AlertContext(
        now_ms=0, job={"mfu_pct": 12.0}))[0]["value"] == 12.0


def _job(app, state="RUNNING", queue="prod", requested=8, allocated=8,
         **extra):
    from tony_tpu.observability import fleet
    summary = fleet.job_summary(app, "u", queue, state, gang_width=2,
                                requested_chips=requested,
                                allocated_chips=allocated,
                                started_ms=1000)
    summary.update(extra)
    return summary


def test_fleet_rules_quota_lost_and_idle_chips():
    ctx = A.AlertContext(now_ms=0, fleet={
        "queues": {"prod": 32, "dev": 100},
        "jobs": [
            _job("app_a", allocated=31),            # prod at 97%
            _job("app_b", state="LOST"),
            _job("app_c", queue="dev", requested=16, allocated=0),
        ]})
    quota = A.queue_quota_rule(95.0).evaluate(ctx)
    assert [o["key"] for o in quota] == ["queue:prod"]
    assert quota[0]["value"] == pytest.approx(96.88, abs=0.01)
    lost = A.job_lost_rule().evaluate(ctx)
    assert [o["key"] for o in lost] == ["job:app_b"]
    idle = A.idle_chips_rule().evaluate(ctx)
    assert [o["key"] for o in idle] == ["job:app_c"]
    # a saturated queue excuses the wait: no idle-chips observation
    ctx2 = A.AlertContext(now_ms=0, fleet={
        "queues": {"prod": 31},
        "jobs": [_job("app_a", allocated=31),
                 _job("app_d", requested=16, allocated=0)]})
    assert A.idle_chips_rule().evaluate(ctx2) == []


def test_fleet_view_alerts_and_families(tmp_path):
    from tony_tpu.observability.fleet import FleetView
    from tony_tpu.observability.prometheus import get_sample, parse, render
    eng = _engine([A.queue_quota_rule(95.0), A.job_lost_rule()],
                  _Clock())
    view = FleetView(str(tmp_path), queues={"prod": 32},
                     settle_accounting=False, alert_engine=eng)
    view.registry.observe(_job("app_a", allocated=31,
                               alerts_firing=2))
    view.refresh(force=True)
    firing = eng.firing()
    assert [a["rule_id"] for a in firing] == [
        "fleet.queue_quota_saturated"]
    payload = view.api_alerts()
    assert payload["firing"][0]["key"] == "queue:prod"
    # jobs reporting their own firing alerts surface too
    assert payload["jobs"][0]["app_id"] == "app_a"
    assert payload["jobs"][0]["alerts_firing"] == 2
    parsed = parse(render(view.families()))
    assert get_sample(parsed, "tony_alert_firing",
                      rule="fleet.queue_quota_saturated",
                      severity="warning") == 1.0
    # the per-job gauge republished through the fleet exposition
    assert get_sample(parsed, "tony_job_alerts_firing",
                      app_id="app_a", queue="prod", user="u") == 2.0


# ---------------------------------------------------------------------------
# bundle + timeline + portal + CLI surfaces
# ---------------------------------------------------------------------------

def _alerts_bundle():
    return {
        "firing": [{"rule_id": "train.goodput_floor", "key": "job",
                    "severity": "warning", "scope": "job",
                    "since_ms": 5000, "value": 42.0, "threshold": 60.0,
                    "message": "job goodput 42.0% below the 60% floor",
                    "flaps": 0}],
        "log": [
            {"ts_ms": 5000, "rule_id": "train.goodput_floor",
             "key": "job", "status": "firing", "severity": "warning",
             "scope": "job", "value": 42.0, "threshold": 60.0,
             "message": "job goodput 42.0% below the 60% floor",
             "suppressed": False, "for_ms": 1000},
            {"ts_ms": 9000, "rule_id": "train.goodput_floor",
             "key": "job", "status": "resolved", "severity": "warning",
             "scope": "job", "value": 65.0, "threshold": 60.0,
             "message": "", "suppressed": False, "active_ms": 4000},
        ],
        "rules": ["train.goodput_floor"],
        "generated_ms": 9000,
    }


def test_alerts_file_roundtrip(tmp_path):
    from tony_tpu.events.history import read_alerts_file, write_alerts_file
    write_alerts_file(str(tmp_path), _alerts_bundle())
    assert read_alerts_file(str(tmp_path)) == _alerts_bundle()
    assert read_alerts_file(str(tmp_path / "missing")) == {}


def test_alert_event_roundtrip_and_render():
    from tony_tpu.events.render import render_event
    from tony_tpu.events.schema import AlertFiring, AlertResolved, Event
    ev = Event(EventType.ALERT_FIRING,
               AlertFiring(rule_id="serve.ttft_p95_burn", key="serving:0",
                           severity="page", scope="task", value=28.0,
                           threshold=14.0, message="burning", for_ms=900))
    back = Event.from_dict(ev.to_dict())
    assert back.payload.rule_id == "serve.ttft_p95_burn"
    text = render_event("ALERT_FIRING", ev.to_dict()["payload"])
    assert "serve.ttft_p95_burn" in text and "page" in text
    ev2 = Event(EventType.ALERT_RESOLVED,
                AlertResolved(rule_id="serve.ttft_p95_burn",
                              key="serving:0", active_ms=1234))
    assert "1234" in render_event("ALERT_RESOLVED",
                                  ev2.to_dict()["payload"])


def test_incident_timeline_orders_and_correlates():
    events = [
        {"type": "TASK_RELAUNCHED", "timestamp": 4000,
         "payload": {"task_type": "worker", "task_index": 1,
                     "attempt": 1, "generation": 2, "reason": "crash"}},
        {"type": "STRAGGLER_DETECTED", "timestamp": 7000,
         "payload": {"task_type": "worker", "task_index": 2,
                     "signal": "step_time_ms", "phase": "steady_state",
                     "span_ids": ["abc123"]}},
        # the same firing the alert log carries: must dedup
        {"type": "ALERT_FIRING", "timestamp": 5000,
         "payload": {"rule_id": "train.goodput_floor", "key": "job",
                     "severity": "warning"}},
        {"type": "TASK_FINISHED", "timestamp": 8000,
         "payload": {"task_type": "worker", "task_index": 0,
                     "status": "SUCCEEDED"}},
    ]
    diagnostics = {"first_failure": {"task_id": "worker:1", "attempt": 0,
                                     "ts_ms": 3500, "reason": "exit 1",
                                     "signature": "device_oom"},
                   "first_failure_spans": [{"span_id": "def456"}]}
    timeline = A.build_incident_timeline(
        events=events, alerts_bundle=_alerts_bundle(),
        diagnostics=diagnostics)
    ts = [r["ts_ms"] for r in timeline]
    assert ts == sorted(ts)
    kinds = [r["kind"] for r in timeline]
    assert kinds.count("diagnosis") == 1
    # alert log entry at 5000 deduped against the ALERT_FIRING event
    firing_rows = [r for r in timeline
                   if "train.goodput_floor" in r["summary"]
                   and "FIRING" in r["summary"]]
    assert len(firing_rows) == 1
    # healthy TASK_FINISHED stays out; span links survive
    assert not any("SUCCEEDED" in r["summary"] for r in timeline)
    spans = [r.get("span_ids") for r in timeline if r.get("span_ids")]
    assert ["abc123"] in spans and ["def456"] in spans


def _history_app(tmp_path, app, bundle=None, status="SUCCEEDED"):
    from tony_tpu.events.handler import EventHandler
    from tony_tpu.events.history import JobMetadata, write_alerts_file
    inter = tmp_path / "inter"
    md = JobMetadata(application_id=app, started=1000)
    handler = EventHandler(str(inter / app), md)
    handler.start()
    handler.stop(status)
    if bundle is not None:
        write_alerts_file(str(inter / app), bundle)
    return inter


def test_portal_serves_alerts_api_timeline_and_panel(tmp_path):
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    app = "application_alerts_1"
    inter = _history_app(tmp_path, app, bundle=_alerts_bundle())
    cache = PortalCache(str(inter), str(tmp_path / "fin"))
    server = PortalServer(cache, port=0, host="127.0.0.1")
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/api/jobs/{app}/alerts",
                                    timeout=10) as resp:
            bundle = json.loads(resp.read())
        assert bundle["source"] == "history"
        assert bundle["firing"][0]["rule_id"] == "train.goodput_floor"
        with urllib.request.urlopen(f"{base}/api/jobs/{app}/timeline",
                                    timeout=10) as resp:
            timeline = json.loads(resp.read())
        assert any("train.goodput_floor" in r["summary"]
                   for r in timeline)
        with urllib.request.urlopen(f"{base}/jobs/{app}",
                                    timeout=10) as resp:
            page = resp.read().decode()
        assert "Firing alerts" in page
        assert "Incident timeline" in page
        assert "train.goodput_floor" in page
    finally:
        server.stop()


def test_portal_fleet_alerts_api_and_index_panel(tmp_path):
    from tony_tpu.observability.fleet import FleetView
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    eng = _engine([A.job_lost_rule()], _Clock())
    view = FleetView(str(tmp_path / "store"), queues={"prod": 32},
                     settle_accounting=False, alert_engine=eng)
    view.registry.observe(_job("app_lost", state="LOST",
                               alerts_firing=0))
    view.registry.observe(_job("app_hot", alerts_firing=3))
    cache = PortalCache(str(tmp_path / "inter"), str(tmp_path / "fin"))
    server = PortalServer(cache, port=0, host="127.0.0.1", fleet=view)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/api/fleet/alerts",
                                    timeout=10) as resp:
            payload = json.loads(resp.read())
        assert [a["rule_id"] for a in payload["firing"]] == [
            "fleet.job_lost"]
        apps = {j["app_id"]: j for j in payload["jobs"]}
        assert apps["app_hot"]["alerts_firing"] == 3
        assert "app_lost" in apps
        with urllib.request.urlopen(base, timeout=10) as resp:
            page = resp.read().decode()
        assert "firing alerts" in page
        assert "fleet.job_lost" in page
        with urllib.request.urlopen(f"{base}/metrics",
                                    timeout=10) as resp:
            exposition = resp.read().decode()
        assert "tony_alert_firing" in exposition
    finally:
        server.stop()


def test_cli_alerts_renders_bundle_offline(tmp_path, capsys):
    from tony_tpu.cli.__main__ import alerts as cli_alerts
    app = "application_alerts_cli"
    inter = _history_app(tmp_path, app, bundle=_alerts_bundle())
    assert cli_alerts([str(inter / app)]) == 0
    out = capsys.readouterr().out
    assert "1 firing alert(s):" in out
    assert "train.goodput_floor" in out
    assert "incident timeline" in out
    assert cli_alerts([str(inter / app), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["rules"] == [
        "train.goodput_floor"]


def test_cli_alerts_missing_bundle(tmp_path, capsys):
    from tony_tpu.cli.__main__ import alerts as cli_alerts
    assert cli_alerts([str(tmp_path)]) == 1
    assert "no alert bundle" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# static checks (tier-1 CI hygiene)
# ---------------------------------------------------------------------------

def test_every_rule_id_literal_is_registered():
    """No silently-dead rules. The literal⊆BUILTIN_RULES sweep is now a
    tonylint rule (tools/tonylint/rules_legacy.py `alert-rule-registry`);
    the buildable-table half stays here (it constructs an engine)."""
    from tools.tonylint import findings_for
    assert findings_for("alert-rule-registry") == []
    # the table itself stays honest: every entry is buildable from
    # a conf that enables everything
    from tony_tpu.conf import TonyConfiguration, keys as K
    conf = TonyConfiguration()
    for key, value in ((K.ALERTS_STEP_REGRESSION_PCT, 50),
                       (K.ALERTS_GOODPUT_FLOOR_PCT, 60),
                       (K.ALERTS_MFU_FLOOR_PCT, 30),
                       (K.ALERTS_TTFT_P95_SLO_MS, 500),
                       (K.ALERTS_QUEUE_DEPTH_SLO, 32),
                       (K.ALERTS_REJECT_RATE_BUDGET_PCT, 1.0)):
        conf.set(key, value, "t")
    built = {r.rule_id for r in A.engine_from_conf(conf).rules}
    built |= {r.rule_id for r in A.fleet_engine_from_conf(conf).rules}
    assert built == set(A.BUILTIN_RULES)


def test_alert_engine_never_touches_the_hot_loop():
    """The acceptance bound: the engine runs only on the AM monitor
    cadence and the portal fleet-scan cadence. Now a tonylint rule
    (`alert-hot-loop`, incl. the two sanctioned-call-site positive
    controls)."""
    from tools.tonylint import findings_for
    assert findings_for("alert-hot-loop") == []


# ---------------------------------------------------------------------------
# chaos e2e
# ---------------------------------------------------------------------------

class _WebhookServer:
    def __init__(self):
        self.received: list[dict] = []
        outer = self

        class _Hook(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                outer.received.append(
                    json.loads(self.rfile.read(length).decode()))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), _Hook)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_port}/hook"

    def stop(self):
        self.httpd.shutdown()


def _alert_overrides(sink_file, webhook_url, **extra):
    over = {
        # threshold = 5x the per-attempt baseline: wide enough that CI
        # scheduling jitter on clean 30ms steps never crosses it, while
        # the injected +300ms stall clears it by >2x
        "tony.alerts.step-regression-pct": 400,
        "tony.alerts.for-ms": 200,
        "tony.alerts.flap-suppress-ms": 0,
        "tony.alerts.file": sink_file,
        "tony.alerts.webhook-url": webhook_url,
        "tony.alerts.webhook-timeout-ms": 1000,
    }
    over.update(extra)
    return over


@pytest.mark.chaos
def test_alert_fires_and_resolves_e2e(tmp_path):
    """Acceptance: an injected steady-state step delay + goodput drop
    (every gang member stalls +300ms/step for steps 40-56, carved into
    input_stall) drives the step-regression AND goodput-floor rules
    pending → firing — ALERT_FIRING history events, webhook + file-sink
    delivery, alerts.json, /api/jobs/:id/alerts, the portal incident
    timeline — and the step rule → resolved after the fault clears."""
    from tests.chaos import ChaosRun
    webhook = _WebhookServer()
    sink_file = str(tmp_path / "alert-sink.jsonl")
    run = ChaosRun(tmp_path, seed=31)
    try:
        run.run(
            ["--executes", script("alert_gang_worker.py"),
             "--conf", "tony.worker.instances=3"],
            conf_overrides=_alert_overrides(
                sink_file, webhook.url,
                **{"tony.alerts.goodput-floor-pct": 55}),
            extra_env={"ALERT_STEP_MS": 30, "ALERT_PUSH_STEPS": 4,
                       "ALERT_RUN_SECONDS": 4.0,
                       "ALERT_MIN_STEPS": 84,
                       "ALERT_FAULT": "40:56:300"})
    finally:
        webhook.stop()
    assert run.final_status == "SUCCEEDED", run.all_logs()

    firing = run.events_of_type(EventType.ALERT_FIRING)
    resolved = run.events_of_type(EventType.ALERT_RESOLVED)
    fired_rules = {e.payload.rule_id for e in firing}
    assert "train.step_time_regression" in fired_rules, run.all_logs()
    assert "train.goodput_floor" in fired_rules, run.all_logs()
    step_fired = [e for e in firing
                  if e.payload.rule_id == "train.step_time_regression"]
    assert step_fired[0].payload.key.startswith("worker:")
    assert step_fired[0].payload.for_ms >= 200
    # the fault cleared: the step-regression alert resolved before the
    # run ended (the goodput floor is cumulative — whether it climbs
    # back above the floor inside the run depends on wall-clock load,
    # so only its FIRING is pinned)
    resolved_rules = {e.payload.rule_id for e in resolved}
    assert "train.step_time_regression" in resolved_rules, run.all_logs()

    # delivery: webhook received the firing transition(s), file sink
    # appended them, and both carry the evidence
    assert webhook.received, run.all_logs()
    assert any(p.get("status") == "firing" for p in webhook.received)
    with open(sink_file, "r", encoding="utf-8") as f:
        sunk = [json.loads(line) for line in f if line.strip()]
    assert any(p["status"] == "resolved" for p in sunk)

    # alerts.json landed in history with the full transition log
    from tony_tpu.events.history import read_alerts_file
    bundle = read_alerts_file(run.app_history_dir())
    statuses = [t["status"] for t in bundle.get("log", [])]
    assert "firing" in statuses and "resolved" in statuses
    # no step-regression alert stays latched after the fault cleared
    assert not any(a["rule_id"] == "train.step_time_regression"
                   for a in bundle.get("firing", []))

    # surfaces: /api/jobs/:id/alerts + the portal incident timeline
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    hist_root = os.path.dirname(run.app_history_dir())
    cache = PortalCache(hist_root, str(tmp_path / "fin"))
    server = PortalServer(cache, port=0, host="127.0.0.1")
    server.start()
    try:
        app_id = os.path.basename(run.app_history_dir())
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(
                f"{base}/api/jobs/{app_id}/alerts", timeout=10) as resp:
            api_bundle = json.loads(resp.read())
        assert [t["status"] for t in api_bundle["log"]] == statuses
        with urllib.request.urlopen(f"{base}/jobs/{app_id}",
                                    timeout=10) as resp:
            page = resp.read().decode()
        assert "Incident timeline" in page
        assert "train.step_time_regression" in page
    finally:
        server.stop()

    # ...and the CLI renders the same bundle offline
    from tony_tpu.cli.__main__ import alerts as cli_alerts
    assert cli_alerts([run.app_history_dir()]) == 0


@pytest.mark.chaos
def test_step_regression_no_false_positive_after_relaunch_e2e(tmp_path):
    """The SloWatchdog fix, pinned under chaos: a task killed mid-run
    relaunches and its replacement runs slow recompile steps. The
    attempt-aware baseline makes those steps the NEW baseline — no
    step-regression alert fires for the relaunched slot, and the job
    converges to SUCCEEDED."""
    from tests.chaos import ChaosRun, KillTask
    run = ChaosRun(tmp_path, seed=32)
    run.run(
        ["--executes", script("alert_gang_worker.py"),
         "--conf", "tony.worker.instances=3",
         "--conf", "tony.task.max-task-attempts=2"],
        injections=[KillTask("worker", 1, after_ms=1200, attempt=0)],
        conf_overrides={
            # 4x threshold: the +250ms recompile steps over a ~30ms
            # attempt-0 baseline WOULD fire without the attempt-aware
            # reset — the counterfactual this test exists to rule out
            "tony.alerts.step-regression-pct": 300,
            "tony.alerts.for-ms": 200,
        },
        extra_env={"ALERT_STEP_MS": 30, "ALERT_PUSH_STEPS": 4,
                   "ALERT_RUN_SECONDS": 4.0,
                   "ALERT_MIN_STEPS": 48,
                   "ALERT_RECOMPILE_STEPS": 8,
                   "ALERT_RECOMPILE_MS": 250})
    assert run.final_status == "SUCCEEDED", run.all_logs()
    relaunches = run.relaunches()
    assert len(relaunches) == 1 and relaunches[0].task_index == 1, \
        run.all_logs()
    # the engine WAS alive with the rule registered (the no-alert
    # assertion below must not pass vacuously)
    from tony_tpu.events.history import read_alerts_file
    bundle = read_alerts_file(run.app_history_dir())
    assert "train.step_time_regression" in bundle.get("rules", []), bundle
    # the replacement's slow recompile tail must NOT read as a
    # regression against the dead attempt's steady state
    step_alerts = [
        e for e in run.events_of_type(EventType.ALERT_FIRING)
        if e.payload.rule_id == "train.step_time_regression"]
    assert step_alerts == [], (
        [f"{e.payload.key}: {e.payload.message}" for e in step_alerts],
        run.all_logs())
