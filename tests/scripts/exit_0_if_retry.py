"""Fixture: fail on the first AM session, succeed on retry — exercises the
session retry loop (reference: AM retry E2E scenarios)."""
import os
import sys
sys.exit(1 if os.environ.get("ATTEMPT_NUMBER", "0") == "0" else 0)
