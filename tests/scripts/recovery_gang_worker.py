"""Fixture: AM-crash survivability gang member (tests/test_recovery.py).

Every start appends a {attempt, generation} line to
$MARKER_DIR/<job>_<idx> (the chaos harness's relaunch ground truth) and
then emits a fully deterministic per-step loss trajectory to
$MARKER_DIR/loss_<job>_<idx> — loss is a pure function of (step, task
index), so two runs of the same gang produce bit-identical loss files
no matter how long an AM outage stalled the middle of one of them.

Knobs (env):
- RECOVERY_STEPS       total steps (default 8)
- RECOVERY_STEP_SLEEP  seconds slept per step (default 0.05)
- CHAOS_RECOVERY_HOLD  path: at the halfway step, poll until this file
  exists (bounded) — the disturbed run's way of parking the gang
  mid-training while the AM is killed, recovered, and the adoption
  barrier drains. Unset (the undisturbed twin) → no hold, same output.

SIGTERM (the executor's TERM→emergency-checkpoint→KILL ladder) writes
$MARKER_DIR/ckpt_<job>_<idx> — the "emergency checkpoint" evidence the
orphan-grace self-fence test asserts — then exits.
"""

import json
import os
import signal
import sys
import time

job = os.environ["JOB_NAME"]
index = int(os.environ["TASK_INDEX"])
attempt = int(os.environ.get("TASK_ATTEMPT", "0"))
generation = int(os.environ.get("SPEC_GENERATION", "0"))
marker_dir = os.environ["MARKER_DIR"]
steps = int(os.environ.get("RECOVERY_STEPS", "8"))
step_sleep = float(os.environ.get("RECOVERY_STEP_SLEEP", "0.05"))
hold_file = os.environ.get("CHAOS_RECOVERY_HOLD", "")

os.makedirs(marker_dir, exist_ok=True)
with open(os.path.join(marker_dir, f"{job}_{index}"), "a") as f:
    f.write(json.dumps({"attempt": attempt, "generation": generation}) + "\n")


def _on_term(signum, frame):
    with open(os.path.join(marker_dir, f"ckpt_{job}_{index}"), "w") as fh:
        fh.write(json.dumps({"attempt": attempt, "emergency": True}) + "\n")
    sys.exit(0)


signal.signal(signal.SIGTERM, _on_term)

loss_path = os.path.join(marker_dir, f"loss_{job}_{index}")
with open(loss_path, "a") as f:
    for step in range(steps):
        if hold_file and step == steps // 2:
            deadline = time.monotonic() + 180
            while not os.path.exists(hold_file) \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
        # pure function of (step, index): bit-identical across runs
        loss = round(1.0 / (step + 1) + index * 1e-3, 9)
        f.write(f"{step} {loss:.9f}\n")
        f.flush()
        time.sleep(step_sleep)

raise SystemExit(0)
