"""Fixture: long-running task the AM must manage (reference: scripts/sleep_30.py)."""
import time
time.sleep(30)
