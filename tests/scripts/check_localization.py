"""Fixture: staged resources must be materialized in the task cwd
(reference: scripts/check_archive_file_localization.py)."""
import os
import sys

assert os.path.isfile("common.txt"), os.listdir(".")
assert os.path.isdir("archive_dir"), os.listdir(".")
assert os.path.isfile(os.path.join("archive_dir", "inner.txt"))
sys.exit(0)
