"""Fixture: sharded training that crashes mid-run on attempt 0 and must
RESUME from the sharded checkpoint on the AM's retry (the reference
delegated checkpointing to frameworks but had to survive restarts via
ATTEMPT_NUMBER — ApplicationMaster.java:369,581-582; here the Trainer +
sharded checkpoint close the loop)."""
import json
import os
import sys

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

from tony_tpu.models.mnist import mnist_init, mnist_loss  # noqa: E402
from tony_tpu.train.data import synthetic_mnist  # noqa: E402
from tony_tpu.train.trainer import Trainer, TrainerConfig  # noqa: E402

ckpt_dir = os.environ["CKPT_DIR"]           # may be gs:// (store protocol)
report_dir = os.environ.get("REPORT_DIR", ckpt_dir)
attempt = int(os.environ.get("ATTEMPT_NUMBER", "0"))
crash_at = int(os.environ.get("CRASH_AT_STEP", "3"))
total = int(os.environ.get("TOTAL_STEPS", "6"))

trainer = Trainer(
    loss_fn=mnist_loss, init_fn=mnist_init,
    data_iter=synthetic_mnist(32),
    config=TrainerConfig(num_steps=crash_at if attempt == 0 else total,
                         log_every=1, checkpoint_every=1,
                         checkpoint_dir=ckpt_dir, learning_rate=1e-2,
                         warmup_steps=1))
trainer.setup()
resumed_from = trainer.step
trainer.run()
if attempt == 0:
    # simulate preemption AFTER checkpoints exist
    print(f"attempt 0 dying at step {trainer.step}", flush=True)
    os._exit(1)
os.makedirs(report_dir, exist_ok=True)
with open(os.path.join(report_dir, "resume_report.json"), "w") as f:
    json.dump({"attempt": attempt, "resumed_from": resumed_from,
               "finished_at": trainer.step}, f)
print(f"attempt {attempt} resumed from {resumed_from} "
      f"finished at {trainer.step}", flush=True)
sys.exit(0)
