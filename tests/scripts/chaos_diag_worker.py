"""Diagnostics-chaos gang member (tests/test_logs.py).

Every instance prints a PLANTED credential and an OOM-shaped error line
to stderr at startup — the redaction + signature-classification bait.
The victim ($CHAOS_DIAG_VICTIM, "job:index") then fails once every gang
member has started (deterministic ordering via the marker files):

- CHAOS_DIAG_MODE=sigkill: the victim kills itself with SIGKILL — the
  executor reports exit -9 with its own classified diagnostics (the
  register_execution_result path, signal attribution pinned);
- otherwise the victim just sleeps and an external injection
  (TEST_TASK_KILL) hard-crashes its container without a registered
  result (the AM-side container-completion diagnostics path).

Survivors sleep until the AM stops them (KILLED_BY_AM — never a failure
record).
"""

import json
import os
import signal
import sys
import time

job = os.environ["JOB_NAME"]
index = int(os.environ["TASK_INDEX"])
task_num = int(os.environ.get("TASK_NUM", "1"))
attempt = int(os.environ.get("TASK_ATTEMPT", "0"))
marker_dir = os.environ["MARKER_DIR"]

# bait: a credential-shaped value that must NEVER appear in any shipped
# tail or diagnostics bundle, plus a classifiable failure line
PLANTED = os.environ.get("CHAOS_PLANTED_TOKEN", "deadbeef" * 8)
print(f"booting with TONY_SECURITY_TOKEN={PLANTED}", file=sys.stderr)
print("RESOURCE_EXHAUSTED: out of memory while allocating 16.00G on "
      "device", file=sys.stderr, flush=True)

os.makedirs(marker_dir, exist_ok=True)
with open(os.path.join(marker_dir, f"{job}_{index}"), "a") as f:
    f.write(json.dumps({"attempt": attempt}) + "\n")


def peers_started() -> bool:
    return all(os.path.isfile(os.path.join(marker_dir, f"{job}_{i}"))
               for i in range(task_num))


if os.environ.get("CHAOS_DIAG_VICTIM") == f"{job}:{index}" and attempt == 0:
    deadline = time.monotonic() + 30
    while not peers_started() and time.monotonic() < deadline:
        time.sleep(0.05)
    if os.environ.get("CHAOS_DIAG_MODE") == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    time.sleep(60)   # TEST_TASK_KILL takes it down mid-run
    raise SystemExit(1)

time.sleep(60)
raise SystemExit(1)
