"""Chaos-harness gang member for the alerting suite (tests/test_alerts.py).

Simulates a synchronous training loop without a model, pushing the
measured TRAIN_STEP_TIME_MS plus the goodput ledger's phase gauges over
the public metrics RPC — exactly the signals the AM's alert engine reads
on its monitor cadence. Fault seams are STEP-COUNT based, not
wall-clock based: the sandboxed CI environment distorts sleeps by
integer factors, so "slow between steps A and B" is deterministic where
"slow between seconds X and Y" is not.

- **transient steady-state fault** (`ALERT_FAULT` = "start_step:
  end_step:extra_ms"): steps in [start, end) are slowed by `extra_ms`,
  with the extra carved into the ledger's `input_stall` phase — so BOTH
  the step-time-regression rule and the goodput-floor rule see a fault
  that later clears (pending → firing → resolved). Attempt 0 only; a
  relaunched attempt runs clean.
- **recompile tail** (`ALERT_RECOMPILE_STEPS` / `ALERT_RECOMPILE_MS`):
  a relaunched attempt (TASK_ATTEMPT > 0) runs its first N steps slow —
  the seam the attempt-aware step-regression baseline is pinned
  against: those slow steps must become the NEW baseline, not trip the
  latch against attempt 0's steady state.

Tasks run until the wall deadline (ALERT_RUN_SECONDS) AND at least
ALERT_MIN_STEPS steps — guaranteeing baseline, fault, and recovery
pushes all exist no matter how the clock stretches. The first report is
primed before the step clock starts so the one-time jax import inside
the reporter never pollutes a step-time sample.
"""

import os
import time

from tony_tpu import constants as C
from tony_tpu.observability.perf import GoodputLedger
from tony_tpu.train.metrics import TpuMetricsReporter

step_s = int(os.environ.get("ALERT_STEP_MS", "30")) / 1000.0
push_steps = int(os.environ.get("ALERT_PUSH_STEPS", "4"))
run_s = float(os.environ.get("ALERT_RUN_SECONDS", "4"))
min_steps = int(os.environ.get("ALERT_MIN_STEPS", "45"))
attempt = int(os.environ.get(C.TASK_ATTEMPT, "0") or 0)
generation = int(os.environ.get(C.SPEC_GENERATION, "0") or 0)

fault_start = fault_end = 0
fault_extra_s = 0.0
fault = os.environ.get("ALERT_FAULT", "")
if fault and attempt == 0:
    start_step, end_step, extra_ms = fault.split(":")
    fault_start, fault_end = int(start_step), int(end_step)
    fault_extra_s = float(extra_ms) / 1000.0

recompile_steps = int(os.environ.get("ALERT_RECOMPILE_STEPS", "0") or 0) \
    if attempt > 0 else 0
recompile_extra_s = int(os.environ.get("ALERT_RECOMPILE_MS", "220")) \
    / 1000.0

if generation > 1:
    # a relaunch already happened; the re-rendezvoused gang just needs a
    # short healthy epoch so the application converges
    run_s = min(run_s, 2.0)
    min_steps = min(min_steps, 25)

ledger = GoodputLedger.from_env(os.environ)
reporter = TpuMetricsReporter()
ledger.transition("compile")
# priming push: pays the reporter's one-time jax import (seconds under
# CI load) inside the compile phase, BEFORE the step clock starts
reporter.report(extra=ledger.metrics())
ledger.transition("train_step")

deadline = time.monotonic() + run_s
last_push = time.monotonic()
steps_since_push = 0
stall_since_push = 0.0
step_no = 0
while time.monotonic() < deadline or step_no < min_steps:
    extra = 0.0
    faulted = fault_extra_s and fault_start <= step_no < fault_end
    if faulted:
        extra += fault_extra_s
    if step_no < recompile_steps:
        extra += recompile_extra_s
    time.sleep(step_s + extra)
    step_no += 1
    steps_since_push += 1
    if faulted:
        # the transient fault is a stall, not compute: carve it out of
        # train_step so the goodput ledger (and the goodput-floor rule)
        # see the drop
        stall_since_push += fault_extra_s
    if steps_since_push >= push_steps:
        now = time.monotonic()
        if stall_since_push > 0:
            ledger.carve("input_stall", stall_since_push)
            stall_since_push = 0.0
        step_ms = 1000.0 * (now - last_push) / steps_since_push
        reporter.report(extra=ledger.metrics()
                        + [{"name": "TRAIN_STEP_TIME_MS",
                            "value": round(step_ms, 3)}])
        last_push, steps_since_push = now, 0

ledger.transition("idle")
reporter.report(extra=ledger.metrics())
reporter.close(timeout=5)
raise SystemExit(0)
