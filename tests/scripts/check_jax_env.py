"""Fixture: assert the JAX/TPU runtime env was rendered (new capability —
no reference equivalent; consumed by jax.distributed.initialize)."""
import os
import sys

addr = os.environ["JAX_COORDINATOR_ADDRESS"]
host, _, port = addr.rpartition(":")
assert host and int(port) > 0, addr
pid = int(os.environ["JAX_PROCESS_ID"])
n = int(os.environ["JAX_NUM_PROCESSES"])
assert 0 <= pid < n, (pid, n)
assert int(os.environ["TPU_NUM_SLICES"]) >= 1
sys.exit(0)
