"""Fixture: goodput ledger exercised through the real channels — a
GoodputLedger seeded from the executor-rendered TONY_GOODPUT_SEED env
(so the executor's localization/rendezvous phases are in the books),
driven through the trainer's phase transitions with real sleeps, and
pushed to the AM over the public metrics RPC via TpuMetricsReporter.
The e2e test then asserts history's goodput.json: phases sum to
wall-clock within 1%, input_stall was carved out of train_step, and the
job-level goodput_pct is derived from these numbers."""
import os
import sys
import time

from tony_tpu import constants as C
from tony_tpu.observability.perf import GoodputLedger
from tony_tpu.train.metrics import TpuMetricsReporter

ledger = GoodputLedger.from_env(os.environ)
seed = os.environ.get(C.TONY_GOODPUT_SEED, "")
if not seed:
    print("no TONY_GOODPUT_SEED in the rendered env", file=sys.stderr)
    sys.exit(1)

reporter = TpuMetricsReporter()

time.sleep(0.05)                     # init
ledger.transition("compile")
time.sleep(0.10)
ledger.transition("train_step")
time.sleep(0.20)
ledger.carve("input_stall", 0.05)    # the prefetch counter's seconds
reporter.report(extra=ledger.metrics()
                + [{"name": "TRAIN_MFU_PCT", "value": 41.5},
                   {"name": "TRAIN_TOKENS_PER_SEC_PER_CHIP",
                    "value": 12345.0}])
ledger.transition("checkpoint_save")
time.sleep(0.05)
ledger.transition("train_step")
time.sleep(0.05)
ledger.transition("idle")
reporter.report(extra=ledger.metrics())
time.sleep(0.3)                      # let the async push land
reporter.close(timeout=10)

snap = ledger.snapshot()
drift = abs(sum(snap["phases"].values()) - snap["wall_s"])
sys.exit(0 if drift < 0.01 * snap["wall_s"] else 1)
