"""Fixture: fail immediately (reference: scripts/exit_1.py)."""
import sys
sys.exit(1)
