"""Fixture: trainer-side observability, exercised exactly through the
channels the real Trainer uses — a SpanRecorder seeded from the env the
executor rendered (parent = its user_process span), spans shipped via
TpuMetricsReporter's non-blocking queue, and two gauge samples pushed
through the public metrics RPC so the AM's timeseries holds >= 2 points.
Sleeps long enough for the test to scrape the AM's /metrics mid-run."""
import os
import time

from tony_tpu import constants as C
from tony_tpu.observability.trace import SpanRecorder
from tony_tpu.rpc.client import MetricsServiceClient
from tony_tpu.train.metrics import TpuMetricsReporter

rec = SpanRecorder.from_env(os.environ)
assert rec.enabled, "no trace context in the rendered env"
assert os.environ.get(C.TONY_PARENT_SPAN), "no parent span in the env"

span = rec.start("trainer_setup")
time.sleep(0.05)
rec.end(span)

reporter = TpuMetricsReporter()
reporter.report_spans(rec.drain())

client = MetricsServiceClient(os.environ[C.AM_HOST],
                              int(os.environ[C.METRICS_RPC_PORT]))
task_type = os.environ[C.JOB_NAME]
index = int(os.environ[C.TASK_INDEX])
client.update_metrics(task_type, index,
                      [{"name": "E2E_TEST_GAUGE", "value": 1.0}], attempt=0)
time.sleep(0.1)
client.update_metrics(task_type, index,
                      [{"name": "E2E_TEST_GAUGE", "value": 2.0}], attempt=0)

# window for the test harness to scrape the live AM /metrics endpoint
time.sleep(2.0)
reporter.close(timeout=10)
client.close()
