"""Fixture: the training task must see the parameters the preprocess
stage printed (reference: ApplicationMaster.java:753-764 scrape into
Constants.TASK_PARAM_KEY)."""
import os
import sys

assert os.environ.get("MODEL_PARAMS") == "lr=0.01 layers=4", \
    f"MODEL_PARAMS={os.environ.get('MODEL_PARAMS')!r}"
sys.exit(0)
