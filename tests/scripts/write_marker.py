"""Fixture: write a per-task marker file so tests can assert execution order/env."""
import os
import sys

marker_dir = os.environ["MARKER_DIR"]
os.makedirs(marker_dir, exist_ok=True)
name = f"{os.environ['JOB_NAME']}_{os.environ['TASK_INDEX']}"
with open(os.path.join(marker_dir, name), "w") as f:
    f.write(str(os.times()[4]))
sys.exit(0)
