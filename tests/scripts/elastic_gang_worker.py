"""Elastic-resize gang member (tests/test_elastic.py membership e2e).

Every start appends a marker line {attempt, generation, spec_width} to
$MARKER_DIR/<job>_<idx> — spec_width is the gang width the rendered
CLUSTER_SPEC carried, so the test can prove each user-process
generation ran against the resized membership. Behavior: install a
SIGTERM handler that exits 0 promptly (the quiesce drain's graceful
path — a real Trainer would emergency-checkpoint here), then loop until
$MARKER_DIR/done exists (the test's finish signal) and exit 0.
"""

import json
import os
import signal
import sys
import time

job = os.environ["JOB_NAME"]
index = int(os.environ["TASK_INDEX"])
attempt = int(os.environ.get("TASK_ATTEMPT", "0"))
generation = int(os.environ.get("SPEC_GENERATION", "0"))
marker_dir = os.environ["MARKER_DIR"]
spec = json.loads(os.environ.get("CLUSTER_SPEC", "{}") or "{}")
spec_width = len(spec.get(job, []))

os.makedirs(marker_dir, exist_ok=True)
with open(os.path.join(marker_dir, f"{job}_{index}"), "a") as f:
    f.write(json.dumps({"attempt": attempt, "generation": generation,
                        "spec_width": spec_width}) + "\n")

signal.signal(signal.SIGTERM, lambda s, fr: sys.exit(0))

deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    if os.path.isfile(os.path.join(marker_dir, "done")):
        raise SystemExit(0)
    time.sleep(0.05)
raise SystemExit(1)
