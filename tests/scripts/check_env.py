"""Fixture: assert the TF runtime env was rendered
(reference: scripts/check_env_and_venv.py)."""
import json
import os
import sys

spec = json.loads(os.environ["CLUSTER_SPEC"])
tf_config = json.loads(os.environ["TF_CONFIG"])
assert "worker" in spec and len(spec["worker"]) >= 1, spec
assert tf_config["task"]["type"] == os.environ["JOB_NAME"]
assert tf_config["task"]["index"] == int(os.environ["TASK_INDEX"])
assert tf_config["cluster"] == spec
for entry in spec["worker"]:
    host, _, port = entry.rpartition(":")
    assert host and int(port) > 0, entry
sys.exit(0)
