"""Chaos-harness gang member (tests/chaos.py + tests/test_fault_tolerance.py).

Every start appends a {attempt, generation} line to $MARKER_DIR/<job>_<idx>,
so the test can prove which attempt of which task ran against which
cluster-spec generation. Behavior:

- generation > 1: a relaunch already happened and this process was launched
  against the post-relaunch spec — exit 0 (the job converges).
- generation 1 and this task is $CHAOS_EXIT_ONE (format "job:index") on its
  first attempt: wait until every gang member has started (their generation-1
  markers exist — the deterministic ordering guarantee), then exit 1. The
  executor reports the failure, exercising the register_execution_result
  relaunch path.
- generation 1 otherwise: sleep — the process is either hard-killed by an
  injection (TEST_TASK_KILL / heartbeat expiry) or stopped by its executor
  for re-rendezvous once a peer is relaunched.
"""

import json
import os
import time

job = os.environ["JOB_NAME"]
index = int(os.environ["TASK_INDEX"])
task_num = int(os.environ.get("TASK_NUM", "1"))
attempt = int(os.environ.get("TASK_ATTEMPT", "0"))
generation = int(os.environ.get("SPEC_GENERATION", "0"))
marker_dir = os.environ["MARKER_DIR"]

os.makedirs(marker_dir, exist_ok=True)
with open(os.path.join(marker_dir, f"{job}_{index}"), "a") as f:
    f.write(json.dumps({"attempt": attempt, "generation": generation}) + "\n")


def peers_started() -> bool:
    for i in range(task_num):
        path = os.path.join(marker_dir, f"{job}_{i}")
        if not os.path.isfile(path):
            return False
    return True


if generation > 1:
    raise SystemExit(0)

if (os.environ.get("CHAOS_EXIT_ONE") == f"{job}:{index}" and attempt == 0):
    deadline = time.monotonic() + 30
    while not peers_started() and time.monotonic() < deadline:
        time.sleep(0.05)
    raise SystemExit(1)

time.sleep(60)
raise SystemExit(1)
