"""Serves one HTTP response on $TB_PORT, stands in for a notebook server."""
import os
from http.server import BaseHTTPRequestHandler, HTTPServer


class H(BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"NOTEBOOK_OK"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


srv = HTTPServer(("127.0.0.1", int(os.environ["TB_PORT"])), H)
srv.timeout = 10
# serve one request then exit 0 so the app finishes promptly
srv.handle_request()
