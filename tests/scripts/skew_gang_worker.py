"""Chaos-harness gang member for the straggler suite (tests/test_skew.py).

Simulates a synchronous training loop without jax: every "step" sleeps
SKEW_STEP_MS (plus the TONY_TRAINER_STEP_DELAY_MS the executor renders
for a TEST_TRAINER_STEP_DELAY-matched task — the same seam the real
Trainer honors), and on a ~SKEW_PUSH_MS cadence pushes the measured
TRAIN_STEP_TIME_MS plus the goodput ledger's phase gauges over the
public metrics RPC — exactly the signals the AM's skew tracker folds
into its windowed sketches.

All tasks run until the shared wall deadline (SKEW_RUN_SECONDS from
launch) so a slowed task does fewer, slower steps instead of running
longer than its peers; a post-relaunch generation (> 1) runs a short
healthy tail so the remediation case converges to SUCCEEDED.
"""

import os
import time

from tony_tpu import constants as C
from tony_tpu.observability.perf import GoodputLedger
from tony_tpu.train.metrics import TpuMetricsReporter

step_s = int(os.environ.get("SKEW_STEP_MS", "30")) / 1000.0
push_s = int(os.environ.get("SKEW_PUSH_MS", "150")) / 1000.0
run_s = float(os.environ.get("SKEW_RUN_SECONDS", "4"))
generation = int(os.environ.get("SPEC_GENERATION", "0"))
delay_s = float(os.environ.get(C.TRAINER_STEP_DELAY_MS, "0") or 0) / 1000.0

if generation > 1:
    # a relaunch already happened; the re-rendezvoused gang just needs a
    # short healthy epoch so the application converges
    run_s = min(run_s, 1.5)

ledger = GoodputLedger.from_env(os.environ)
reporter = TpuMetricsReporter()
ledger.transition("compile")
time.sleep(0.02)
ledger.transition("train_step")

deadline = time.monotonic() + run_s
last_push = time.monotonic()
steps_since_push = 0
while time.monotonic() < deadline:
    time.sleep(step_s + delay_s)
    steps_since_push += 1
    now = time.monotonic()
    if now - last_push >= push_s and steps_since_push:
        step_ms = 1000.0 * (now - last_push) / steps_since_push
        reporter.report(extra=ledger.metrics()
                        + [{"name": "TRAIN_STEP_TIME_MS",
                            "value": round(step_ms, 3)}])
        last_push, steps_since_push = now, 0

ledger.transition("idle")
reporter.report(extra=ledger.metrics())
reporter.close(timeout=5)
raise SystemExit(0)
