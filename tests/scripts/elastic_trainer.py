"""Fixture: elastic-resize target (tests/test_elastic.py chaos e2e).

A real sharded Trainer (mnist MLP; only w0 is fsdp-sharded — 784 splits
evenly at widths 1..8, so the SAME script runs pre- and post-resize)
that checkpoints every step and runs long enough for a mid-run quiesce
to land. Each user-process generation writes its OWN report
(`<name>_s<resumed_from>-<stopped_at>.json`) carrying the segment's
per-step losses and the mesh width it trained at, so the e2e can stitch
the full trajectory back together and compare it bit-for-bit against
the checkpoint-stop-restart (evict-and-resume) twin at the same width
schedule. On SIGTERM (the resize quiesce) the Trainer's emergency path
commits one synchronous checkpoint and exits EXIT_PREEMPTED; the
executor's armed respec relaunches this script against the new mesh."""
import json
import os
import sys

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

import optax  # noqa: E402

from tony_tpu.models.mnist import mnist_init, mnist_loss  # noqa: E402
from tony_tpu.train.data import synthetic_mnist  # noqa: E402
from tony_tpu.train.trainer import Trainer, TrainerConfig  # noqa: E402

ckpt_dir = os.environ["CKPT_DIR"]
report_dir = os.environ.get("REPORT_DIR", ckpt_dir)
report_name = os.environ.get("REPORT_NAME", "report")
total = int(os.environ.get("TOTAL_STEPS", "500"))
# the evict-and-resume twin stops EARLY at a resize boundary but must
# run the identical optimizer: the LR schedule's horizon comes from
# TOTAL_STEPS, the stopping point from STOP_AT_STEP
stop = int(os.environ.get("STOP_AT_STEP") or 0) or total

# only w0 (784 x 300) shards along the mesh: 784 divides evenly at every
# width this e2e resizes through, and the resharding restore still has
# real multi-shard work to do
param_axes = {"w0": ("embed", None), "w1": (None, None),
              "w2": (None, None), "b0": (None,), "b1": (None,),
              "b2": (None,)}

schedule = optax.warmup_cosine_decay_schedule(0.0, 1e-2, 1, max(total, 2))
trainer = Trainer(
    loss_fn=mnist_loss, init_fn=mnist_init,
    data_iter=synthetic_mnist(32),
    config=TrainerConfig(num_steps=stop, log_every=1,
                         checkpoint_every=1, checkpoint_dir=ckpt_dir,
                         optimizer=optax.adamw(schedule,
                                               weight_decay=0.01),
                         prefetch_depth=0),
    param_axes=param_axes)
trainer.setup()
resumed_from = trainer.step
mesh_width = int(trainer.mesh.devices.size)

rc = 0
try:
    trainer.run()
except SystemExit as e:                      # the quiesce/preempt exit
    rc = int(e.code or 0)

os.makedirs(report_dir, exist_ok=True)
name = f"{report_name}_s{resumed_from:04d}-{trainer.step:04d}.json"
with open(os.path.join(report_dir, name), "w") as f:
    json.dump({"resumed_from": resumed_from,
               "stopped_at": trainer.step,
               "mesh_width": mesh_width,
               "preempted": trainer.preempted,
               "losses": [[m["step"], m["loss"]]
                          for m in trainer.metrics_history
                          if "loss" in m]}, f)
print(f"elastic trainer segment {resumed_from}->{trainer.step} at mesh "
      f"width {mesh_width} (preempted={trainer.preempted}, rc={rc})",
      flush=True)
sys.exit(rc)
