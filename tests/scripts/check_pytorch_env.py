"""Fixture: assert the PyTorch runtime env was rendered
(reference: scripts/exit_0_check_pytorchenv.py)."""
import os
import sys

assert os.environ["INIT_METHOD"].startswith("tcp://"), os.environ["INIT_METHOD"]
rank = int(os.environ["RANK"])
world = int(os.environ["WORLD"])
assert 0 <= rank < world, (rank, world)
assert os.environ["MASTER_ADDR"]
assert int(os.environ["MASTER_PORT"]) > 0
sys.exit(0)
