"""Fixture: checkpoint-then-evict preemption target (tests/test_preemption.py).

A real sharded Trainer (mnist MLP, params fsdp-sharded over the mesh the
executor rendered) that checkpoints every step and runs long enough for a
mid-run drain to land. On SIGTERM the Trainer's emergency path commits one
synchronous checkpoint and exits EXIT_PREEMPTED; this wrapper records the
evidence (stopped step, preempted flag, per-step loss trajectory) in a
report file either way, so the e2e can assert no-data-loss and
bit-consistent resume."""
import json
import os
import sys

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

from tony_tpu.models.mnist import mnist_init, mnist_loss  # noqa: E402
from tony_tpu.train.data import synthetic_mnist  # noqa: E402
from tony_tpu.train.trainer import Trainer, TrainerConfig  # noqa: E402

ckpt_dir = os.environ["CKPT_DIR"]           # may be gs:// (store protocol)
report_dir = os.environ.get("REPORT_DIR", ckpt_dir)
report_name = os.environ.get("REPORT_NAME", "report")
total = int(os.environ.get("TOTAL_STEPS", "500"))

# params sharded over the mesh's fsdp axis ("embed" logical dim), so a
# width-2 run writes 2 shards per leaf and a width-1 resume exercises the
# resharding restore (2 saved regions pasted into 1 target shard)
param_axes = {f"w{i}": ("embed", None) for i in range(3)}
param_axes.update({f"b{i}": (None,) for i in range(3)})

trainer = Trainer(
    loss_fn=mnist_loss, init_fn=mnist_init,
    data_iter=synthetic_mnist(32),
    config=TrainerConfig(num_steps=total, log_every=1,
                         checkpoint_every=1, checkpoint_dir=ckpt_dir,
                         learning_rate=1e-2, warmup_steps=1,
                         prefetch_depth=0),
    param_axes=param_axes)
trainer.setup()
resumed_from = trainer.step

rc = 0
try:
    trainer.run()
except SystemExit as e:                      # the preempted exit path
    rc = int(e.code or 0)

os.makedirs(report_dir, exist_ok=True)
with open(os.path.join(report_dir, f"{report_name}.json"), "w") as f:
    json.dump({"resumed_from": resumed_from,
               "stopped_at": trainer.step,
               "preempted": trainer.preempted,
               "losses": [[m["step"], m["loss"]]
                          for m in trainer.metrics_history
                          if "loss" in m]}, f)
print(f"trainer stopped at step {trainer.step} "
      f"(preempted={trainer.preempted}, rc={rc})", flush=True)
sys.exit(rc)
