"""Fixture task for the fleet e2e: stay alive long enough for the AM's
jobstate publisher to land a RUNNING entry in the shared staging store,
pushing a goodput ledger + MFU gauge so the fleet summary carries real
job-level numbers. Sleep length via FLEET_TASK_SLEEP_SEC."""
import os
import time

from tony_tpu.observability.perf import GoodputLedger
from tony_tpu.train.metrics import TpuMetricsReporter

sleep_sec = float(os.environ.get("FLEET_TASK_SLEEP_SEC", "2"))
ledger = GoodputLedger.from_env(os.environ)
reporter = TpuMetricsReporter()

ledger.transition("train_step")
deadline = time.monotonic() + sleep_sec
while time.monotonic() < deadline:
    reporter.report(extra=ledger.metrics()
                    + [{"name": "TRAIN_MFU_PCT", "value": 33.3}])
    time.sleep(0.2)
reporter.report(extra=ledger.metrics())
reporter.close(timeout=10)
