"""Fixture: succeed immediately (reference: src/test/resources/scripts/exit_0.py)."""
import sys
sys.exit(0)
