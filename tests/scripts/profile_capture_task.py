"""Fixture: the trainer-side half of the request_profile workflow,
driven through the REAL channels — the executor writes the heartbeat-
piggybacked request into profile_request.json in this cwd, this process
polls it with the real ProfileCapture state machine, "captures" via
stub trace fns (the artifact contract, without dragging jax into the
fixture), and publishes the completion over the public metrics RPC.
The e2e test asserts the AM copied the artifact into history and
emitted exactly one PROFILE_CAPTURED event for the double-requested id.
"""
import os
import sys
import time

from tony_tpu.observability.perf import ProfileCapture
from tony_tpu.train.metrics import TpuMetricsReporter

reporter = TpuMetricsReporter()
state = {"captured": False}


def publish(pd):
    reporter.report_profile_done(pd)
    state["captured"] = True


def start_trace(out_dir):
    # the stub "trace": what jax.profiler.start_trace would begin writing
    with open(os.path.join(out_dir, "trace.xplane.pb"), "wb") as f:
        f.write(b"fake-xplane-trace")


pc = ProfileCapture(cwd=os.getcwd(), publish=publish,
                    start_fn=start_trace, stop_fn=lambda: None)

deadline = time.monotonic() + 40
while not state["captured"] and time.monotonic() < deadline:
    pc.poll()                 # the trainer polls at log boundaries
    if pc.active:
        pc.on_step()          # one "train step" per tick
    time.sleep(0.05)

time.sleep(1.0)               # let the async profile_done push land
reporter.close(timeout=10)
sys.exit(0 if state["captured"] else 1)
