"""Live-tail test workload (tests/test_logs.py): prints numbered lines
to stderr (with a planted credential that must never survive redaction),
then idles briefly so the follow client can observe the stream live, and
exits 0."""

import os
import sys
import time

planted = os.environ.get("CHAOS_PLANTED_TOKEN", "cafebabe" * 8)
print(f"api_key={planted}", file=sys.stderr, flush=True)
for i in range(50):
    print(f"logline {i}", file=sys.stderr, flush=True)
    time.sleep(0.02)
print("stream done", file=sys.stderr, flush=True)
time.sleep(3.0)
raise SystemExit(0)
