"""Fixture: TB_PORT must be set on the chief only
(reference: scripts/check_tb_port_set_in_chief_only.py)."""
import os
import sys

is_chief = os.environ.get("IS_CHIEF", "false") == "true"
has_tb = "TB_PORT" in os.environ
sys.exit(0 if is_chief == has_tb else 1)
