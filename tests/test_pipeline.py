"""Pipeline parallelism tests on the virtual CPU mesh: forward parity vs
sequential execution, gradient parity, and bubble-schedule correctness."""

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.parallel import make_mesh, plan_mesh
from tony_tpu.parallel.pipeline import (
    make_pipelined_fn, split_microbatches, stack_stage_params,
)

N_STAGES = 4
DIM = 16


def stage_fn(params, x):
    """One pipeline stage: tanh MLP."""
    return jnp.tanh(x @ params["w"] + params["b"])


def make_params(key):
    per_stage = []
    for i in range(N_STAGES):
        k = jax.random.fold_in(key, i)
        per_stage.append({
            "w": jax.random.normal(k, (DIM, DIM)) / DIM ** 0.5,
            "b": jnp.zeros((DIM,)),
        })
    return per_stage


def sequential(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


def test_pipeline_matches_sequential():
    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    per_stage = make_params(jax.random.PRNGKey(0))
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, DIM))

    f = make_pipelined_fn(stage_fn, mesh, n_micro=8)
    got = f(stacked, x)
    want = sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_single_microbatch():
    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    per_stage = make_params(jax.random.PRNGKey(2))
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, DIM))
    got = make_pipelined_fn(stage_fn, mesh, n_micro=1)(stacked, x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(sequential(per_stage, x)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match():
    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    per_stage = make_params(jax.random.PRNGKey(4))
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, DIM))
    target = jax.random.normal(jax.random.PRNGKey(6), (8, DIM))

    f = make_pipelined_fn(stage_fn, mesh, n_micro=4)

    def loss_pipe(stacked):
        return jnp.mean((f(stacked, x) - target) ** 2)

    def loss_seq(stacked):
        per = [jax.tree.map(lambda p: p[i], stacked)
               for i in range(N_STAGES)]
        return jnp.mean((sequential(per, x) - target) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for kp, gp in g_pipe.items():
        np.testing.assert_allclose(np.asarray(gp), np.asarray(g_seq[kp]),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"grad mismatch for {kp}")


def test_split_microbatches_validates():
    import pytest
    with pytest.raises(ValueError):
        split_microbatches(jnp.zeros((10, 3)), 4)
    mb = split_microbatches(jnp.zeros((12, 3)), 4)
    assert mb.shape == (4, 3, 3)


def test_llama_pipelined_matches_sequential():
    """Pipelined llama forward == plain forward on a pp=4 mesh."""
    from tony_tpu.models.llama import (
        get_config, llama_forward, llama_forward_pipelined, llama_init,
    )

    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    config = get_config("tiny", n_layers=4)
    params = llama_init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                config.vocab_size, jnp.int32)
    got = llama_forward_pipelined(params, tokens, config, mesh, n_micro=4)
    want = llama_forward(params, tokens, config)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_llama_pipelined_trains():
    from functools import partial
    import optax
    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss_pipelined,
    )
    from tony_tpu.train.step import make_train_step

    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    config = get_config("tiny", n_layers=4)
    params = llama_init(config, jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    step = make_train_step(
        partial(llama_loss_pipelined, config=config, mesh=mesh, n_micro=4),
        opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                config.vocab_size, jnp.int32)
    opt_state = jax.jit(opt.init)(params)
    first = None
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": tokens})
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))


def test_llama_pipelined_grads_match_sequential():
    """VERDICT r2 item 2 acceptance: gradient parity of the pipelined
    llama (1F1B custom backward, remat + flash attention inside stages)
    against the plain sequential forward's AD grads."""
    from functools import partial

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_loss_pipelined,
    )

    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    config = get_config("tiny", n_layers=4)
    params = llama_init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                config.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want = jax.grad(partial(llama_loss, config=config))(params, batch)
    got = jax.grad(partial(llama_loss_pipelined, config=config, mesh=mesh,
                           n_micro=4))(params, batch)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want)
    flat_g = jax.tree.leaves(got)
    for (path, w), g in zip(flat_w, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=2e-4, rtol=2e-3,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}")


def test_llama_pipelined_composes_pp_with_sp():
    """pp x sp composition: the pipeline widens its manual region to
    {pp, sp} and runs ring/ulysses attention DIRECTLY inside the stage
    (shard_map cannot nest inside a manual region — the earlier nested
    form produced silently wrong layer grads). Gradient parity against
    the meshless sequential model for BOTH sp flavors."""
    from functools import partial

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_loss_pipelined,
    )

    base = get_config("tiny", n_layers=4)
    params = llama_init(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                base.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want = jax.jit(jax.grad(partial(llama_loss, config=base)))(params,
                                                               batch)
    mesh = make_mesh(plan_mesh(8, pp=2, sp=2, fsdp=2))
    for sp_mode in ("ring", "ulysses"):
        config = get_config("tiny", n_layers=4, sp_mode=sp_mode)
        with jax.set_mesh(mesh):
            got = jax.jit(jax.grad(partial(
                llama_loss_pipelined, config=config, mesh=mesh,
                n_micro=2)))(params, batch)
        for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=2e-4, rtol=2e-3,
                                       err_msg=f"sp_mode={sp_mode}")


def test_llama_pipelined_composes_pp_with_fsdp_tp():
    """Stage weights shard on pp AND fsdp/tp simultaneously: the staged
    logical axes resolve to multi-axis PartitionSpecs, and the pipelined
    train step runs SHARDED under an ambient pp x fsdp x tp mesh."""
    from functools import partial

    import optax
    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss_pipelined,
        llama_pipeline_param_axes,
    )
    from tony_tpu.parallel.sharding import logical_to_mesh_axes
    from tony_tpu.train.step import make_train_step
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(plan_mesh(8, pp=2, fsdp=2, tp=2))
    config = get_config("tiny", n_layers=4)
    staged_axes = llama_pipeline_param_axes(config)
    # wq: (stage, layers, embed, heads) -> pp + fsdp + tp in ONE spec
    assert logical_to_mesh_axes(staged_axes["wq"], mesh=mesh) == \
        P("pp", None, "fsdp", "tp")
    assert logical_to_mesh_axes(staged_axes["w_down"], mesh=mesh) == \
        P("pp", None, "tp", "fsdp")

    params = llama_init(config, jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    step = make_train_step(
        partial(llama_loss_pipelined, config=config, mesh=mesh, n_micro=2),
        opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                config.vocab_size, jnp.int32)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": tokens})
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# interleaved virtual-stage schedule (VERDICT r3 item 4)
# ---------------------------------------------------------------------------

def test_interleaved_schedule_wave_invariants():
    """The σ-wave schedule is a valid lockstep pipeline: every (chunk,
    microbatch) slot runs exactly once, each virtual stage s consumes its
    predecessor s-1's output exactly one ppermute tick after it was
    produced on the ppermute-source device, and one phase spans exactly
    n_micro*v + n - 1 ticks (bubble (n-1)/v of the unchunked (n-1))."""
    from tony_tpu.parallel.pipeline import (
        _sched_bwd, _sched_fwd, interleaved_ticks,
    )

    for (M, n, v) in [(8, 4, 2), (4, 2, 2), (8, 2, 4), (4, 4, 1)]:
        T = interleaved_ticks(M, n, v)
        assert T == M * v + n - 1
        for sched, direction in ((_sched_fwd, +1), (_sched_bwd, -1)):
            seen = {}
            for t in range(T):
                for d in range(n):
                    valid, j, m = (int(x) for x in sched(t, d, M, n, v))
                    if not valid:
                        continue
                    assert (j, m, d) not in seen
                    seen[(j, m, d)] = t
            # each (j, m) slot runs exactly once on every device (the
            # lockstep schedule shifts it per device): M*v*n valid slots
            assert len(seen) == M * v * n
            # wave dependency: virtual stage s = j*n+d (fwd) consumes
            # s-1's output produced one tick earlier on the ppermute
            # source; mirrored for bwd
            for (j, m, d), t in seen.items():
                if direction == +1:
                    s = j * n + d
                    if s == 0:
                        continue
                    pj, pd = (s - 1) // n, (s - 1) % n
                else:
                    s = j * n + (n - 1 - d)   # distance from the exit
                    if j == v - 1 and d == n - 1:
                        continue   # entry slot reads the dy stream
                    # cotangent producer: virtual stage succ = j*n+d+1
                    succ = j * n + d + 1
                    pj, pd = succ // n, succ % n
                    if pj >= v:
                        continue
                assert seen.get((pj, m, pd)) == t - 1, (
                    (j, m, d, t, direction))


def test_llama_pipelined_interleaved_grads_match_sequential():
    """Gradient parity of the INTERLEAVED (v=2) pipelined llama against
    plain sequential AD — same acceptance as the v=1 schedule."""
    from functools import partial

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_loss_pipelined,
    )

    mesh = make_mesh(plan_mesh(8, pp=4, fsdp=2, dp=1))
    config = get_config("tiny", n_layers=8)
    params = llama_init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                config.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want = jax.grad(partial(llama_loss, config=config))(params, batch)
    got = jax.grad(partial(llama_loss_pipelined, config=config,
                           mesh=mesh, n_micro=4, n_virtual=2))(
                               params, batch)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want)
    flat_g = jax.tree.leaves(got)
    for (path, w), g in zip(flat_w, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=2e-4, rtol=2e-3,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}")


def test_interleaved_forward_matches_sequential():
    """Per-logit forward parity for the interleaved schedule."""
    from tony_tpu.models.llama import (
        get_config, llama_forward, llama_forward_pipelined, llama_init,
    )

    mesh = make_mesh(plan_mesh(8, pp=2, fsdp=2, dp=2))
    config = get_config("tiny", n_layers=4)
    params = llama_init(config, jax.random.PRNGKey(3))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0,
                                config.vocab_size, jnp.int32)
    want = llama_forward(params, tokens, config)
    got = llama_forward_pipelined(params, tokens, config, mesh,
                                  n_micro=2, n_virtual=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-3)


def test_llama_pipelined_interleaved_composes_with_sp():
    """Interleaved (v=2) schedule with ring/ulysses attention running
    inside the widened {pp, sp} manual region — gradient parity against
    sequential AD for BOTH sp flavors, same acceptance as the v=1
    pp-x-sp composition."""
    from functools import partial

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_loss_pipelined,
    )

    base = get_config("tiny", n_layers=4)
    params = llama_init(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                base.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want = jax.jit(jax.grad(partial(llama_loss, config=base)))(params,
                                                               batch)
    mesh = make_mesh(plan_mesh(8, pp=2, sp=2, fsdp=2))
    for sp_mode in ("ring", "ulysses"):
        config = get_config("tiny", n_layers=4, sp_mode=sp_mode)
        got = jax.jit(jax.grad(partial(
            llama_loss_pipelined, config=config, mesh=mesh, n_micro=2,
            n_virtual=2)))(params, batch)
        for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=2e-4, rtol=2e-3,
                                       err_msg=f"sp_mode={sp_mode}")


def test_pipeline_on_bare_pp_only_mesh():
    """make_pipelined_fn is public API accepting ANY mesh: a hand-built
    Mesh with only a pp axis (no dp/fsdp) maps "batch" to an empty spec
    — constrain_mb must treat that as unsharded, not IndexError
    (r4 advisor)."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("pp",))
    per_stage = make_params(jax.random.PRNGKey(7))
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(8), (8, DIM))
    got = make_pipelined_fn(stage_fn, mesh, n_micro=4)(stacked, x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(sequential(per_stage, x)),
                               atol=1e-5, rtol=1e-5)
