"""Goodput ledger / MFU / SLO watchdog / on-demand profiler (PR 5).

Unit layer: the perf.py state machines with fake clocks. E2E layer: the
genuine client → AM → executor → user-python chain on the local backend
— the ledger invariant in history's goodput.json, relaunch downtime
under a chaos kill, and the full request_profile workflow (RPC →
heartbeat piggyback → executor file relay → ProfileCapture → metrics
RPC publish → history artifact + event, idempotent on double-request).
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from tony_tpu import constants as C
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.handler import parse_events
from tony_tpu.events.history import read_goodput_file
from tony_tpu.events.schema import EventType
from tony_tpu.observability import perf

pytestmark = pytest.mark.profiling

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def tick(self, s: float) -> None:
        self.t += s


# ---------------------------------------------------------------------------
# goodput ledger units
# ---------------------------------------------------------------------------

def test_ledger_phases_sum_to_wall_exactly():
    clock = FakeClock()
    ledger = perf.GoodputLedger(clock=clock)
    clock.tick(1.0)
    ledger.transition("compile")
    clock.tick(2.0)
    ledger.transition("train_step")
    clock.tick(5.0)
    ledger.carve("input_stall", 0.75)
    clock.tick(1.0)
    ledger.transition("idle")
    clock.tick(0.5)
    snap = ledger.snapshot()
    assert snap["wall_s"] == pytest.approx(9.5)
    assert sum(snap["phases"].values()) == pytest.approx(snap["wall_s"])
    assert snap["phases"]["init"] == pytest.approx(1.0)
    assert snap["phases"]["compile"] == pytest.approx(2.0)
    # carve moved stall seconds OUT of train_step, not on top of it
    assert snap["phases"]["input_stall"] == pytest.approx(0.75)
    assert snap["phases"]["train_step"] == pytest.approx(6.0 - 0.75)
    assert snap["phases"]["idle"] == pytest.approx(0.5)


def test_ledger_carve_from_closed_source_phase():
    """The end-of-run flush runs from idle but late stall seconds must
    still come out of train_step — carve(source=...) reattributes from a
    CLOSED phase without breaking the sum-to-wall invariant."""
    clock = FakeClock()
    ledger = perf.GoodputLedger(clock=clock)
    ledger.transition("train_step")
    clock.tick(4.0)
    ledger.transition("idle")
    clock.tick(0.1)
    ledger.carve("input_stall", 0.5, source="train_step")
    snap = ledger.snapshot()
    assert snap["phases"]["train_step"] == pytest.approx(3.5)
    assert snap["phases"]["input_stall"] == pytest.approx(0.5)
    assert snap["phases"]["idle"] == pytest.approx(0.1)
    assert sum(snap["phases"].values()) == pytest.approx(snap["wall_s"])


def test_ledger_open_phase_counts_mid_flight():
    clock = FakeClock()
    ledger = perf.GoodputLedger(clock=clock)
    clock.tick(3.0)
    snap = ledger.snapshot()   # "init" still open
    assert snap["phases"]["init"] == pytest.approx(3.0)
    assert sum(snap["phases"].values()) == pytest.approx(snap["wall_s"])


def test_ledger_seed_extends_wall():
    """The executor's localization/rendezvous seed is closed time that
    the trainer-side ledger's wall must include — the handoff preserves
    the sum-to-wall invariant across two processes."""
    clock = FakeClock()
    ledger = perf.GoodputLedger(
        clock=clock, seed={"localization": 2.0, "rendezvous_wait": 1.5})
    clock.tick(4.0)
    ledger.transition("idle")
    snap = ledger.snapshot()
    assert snap["wall_s"] == pytest.approx(7.5)
    assert snap["phases"]["localization"] == pytest.approx(2.0)
    assert snap["phases"]["rendezvous_wait"] == pytest.approx(1.5)
    assert sum(snap["phases"].values()) == pytest.approx(snap["wall_s"])


def test_ledger_from_env_and_metrics_roundtrip():
    env = {C.TONY_GOODPUT_SEED:
           json.dumps({"localization": 1.25, "rendezvous_wait": 0.5})}
    ledger = perf.GoodputLedger.from_env(env)
    metrics = ledger.metrics()
    gauges = {m["name"]: m["value"] for m in metrics}
    assert gauges[perf.goodput_metric_name("localization")] == 1.25
    parsed = perf.parse_goodput_gauges(gauges)
    assert parsed["phases"]["localization"] == 1.25
    assert parsed["wall_s"] == pytest.approx(gauges[
        perf.GOODPUT_WALL_METRIC])
    # garbage env never breaks a trainer
    assert perf.GoodputLedger.from_env(
        {C.TONY_GOODPUT_SEED: "not json"}).snapshot()["wall_s"] >= 0


def test_aggregate_goodput_math():
    per_task = {
        "worker:0": {
            perf.goodput_metric_name("train_step"): 8.0,
            perf.goodput_metric_name("compile"): 1.0,
            perf.goodput_metric_name("idle"): 1.0,
            perf.GOODPUT_WALL_METRIC: 10.0,
            "TRAIN_MFU_PCT": 45.0,
        },
        "worker:1": {
            perf.goodput_metric_name("train_step"): 6.0,
            perf.goodput_metric_name("input_stall"): 4.0,
            perf.GOODPUT_WALL_METRIC: 10.0,
        },
        "ps:0": {"SOME_OTHER_GAUGE": 3.0},   # no ledger -> excluded
    }
    out = perf.aggregate_goodput(per_task, relaunch_downtime_s=5.0)
    assert set(out["tasks"]) == {"worker:0", "worker:1"}
    assert out["tasks"]["worker:0"]["mfu_pct"] == 45.0
    job = out["job"]
    assert job["productive_s"] == pytest.approx(14.0)
    assert job["wall_s"] == pytest.approx(25.0)
    assert job["relaunch_downtime_s"] == 5.0
    assert job["goodput_pct"] == pytest.approx(100.0 * 14.0 / 25.0,
                                               abs=0.01)


def test_goodput_report_table():
    from tools.goodput_report import format_report
    out = perf.aggregate_goodput({
        "worker:0": {perf.goodput_metric_name("train_step"): 9.0,
                     perf.goodput_metric_name("idle"): 1.0,
                     perf.GOODPUT_WALL_METRIC: 10.0,
                     "TRAIN_MFU_PCT": 50.0}})
    text = format_report(out)
    assert "train_step" in text and "90.0%" in text
    assert "job goodput" in text and "50.00%" in text


# ---------------------------------------------------------------------------
# MFU units
# ---------------------------------------------------------------------------

class _Dev:
    def __init__(self, platform="tpu", kind="TPU v5e"):
        self.platform = platform
        self.device_kind = kind


def test_peak_flops_and_mfu_shared_definition():
    assert perf.peak_flops(_Dev()) == 197e12
    assert perf.peak_flops(_Dev(kind="TPU v5p")) == 459e12
    assert perf.peak_flops(_Dev(platform="cpu")) == perf.CPU_PEAK
    # bench re-exports the SAME objects — one definition repo-wide
    import bench
    assert bench.peak_flops is perf.peak_flops
    assert bench.PEAK_FLOPS is perf.PEAK_FLOPS
    mfu = perf.mfu_pct(1000.0, 197e6, _Dev())
    assert mfu == pytest.approx(0.1)
    assert perf.mfu_pct(1000.0, 0.0, _Dev()) == 0.0


def test_mfu_reported_for_llama_and_moe():
    """Acceptance: MFU inputs exist for BOTH model families, and the MoE
    config accounts ACTIVE params (top_k of n_experts), not total."""
    from tony_tpu.models.llama import get_config
    from tony_tpu.models.moe import get_moe_config
    llama = get_config("tiny")
    moe = get_moe_config("moe_tiny")
    assert llama.flops_per_token(64) > 0
    assert moe.flops_per_token(64) > 0
    assert moe.active_params() < moe.num_params()
    # flops derive from active params: an all-experts accounting would
    # exceed this bound
    d, f, L = moe.dim, moe.ffn_dim, moe.n_layers
    dense_total = 6.0 * moe.num_params() + 12 * L * d * 64
    assert moe.flops_per_token(64) < dense_total
    expected_active = (type(llama).num_params(moe)
                       + L * ((moe.top_k - 1) * 3 * d * f
                              + d * moe.n_experts))
    assert moe.active_params() == expected_active


def test_tokens_in_batch_shapes():
    import numpy as np
    batch = {"inputs": np.zeros((4, 128)), "targets": np.zeros((4, 128))}
    assert perf.tokens_in_batch(batch) == 512
    assert perf.tokens_in_batch({"tokens": np.zeros((2, 65))}) == 130
    assert perf.tokens_in_batch({"images": np.zeros((8,))}) == 0
    assert perf.tokens_in_batch(None) == 0


# ---------------------------------------------------------------------------
# SLO watchdog units
# ---------------------------------------------------------------------------

def _series(values):
    return [[i, v] for i, v in enumerate(values)]


def test_slo_step_regression_latches_and_rearms():
    dog = perf.SloWatchdog(step_regression_pct=50.0)
    healthy = {"worker:0": _series([100, 101, 99, 100, 100, 102])}
    assert dog.check(healthy) == []
    slow = {"worker:0": _series([100, 101, 99, 100, 100, 180])}
    hits = dog.check(slow)
    assert len(hits) == 1 and hits[0]["kind"] == "step_time_regression"
    assert hits[0]["task_id"] == "worker:0"
    # latched: the same ongoing violation emits no second event
    assert dog.check(slow) == []
    assert dog.active() == ["step_time:worker:0"]
    # recovery re-arms the latch; a new regression fires again
    assert dog.check(healthy) == []
    assert dog.active() == []
    assert len(dog.check(slow)) == 1


def test_slo_goodput_floor_and_disabled_checks():
    dog = perf.SloWatchdog(goodput_floor_pct=60.0)
    assert dog.check({}, goodput_pct=75.0) == []
    hits = dog.check({}, goodput_pct=42.0)
    assert len(hits) == 1 and hits[0]["kind"] == "goodput_floor"
    assert dog.check({}, goodput_pct=41.0) == []     # latched
    assert dog.check({}, goodput_pct=80.0) == []     # recovered
    assert dog.active() == []
    # thresholds <= 0 disable everything
    off = perf.SloWatchdog()
    assert off.check({"w:0": _series([1, 1, 1, 1, 1, 99])},
                     goodput_pct=0.1) == []


# ---------------------------------------------------------------------------
# profile capture units
# ---------------------------------------------------------------------------

def _write_request(tmp_path, rid, steps=3):
    with open(os.path.join(tmp_path, C.PROFILE_REQUEST_FILE), "w",
              encoding="utf-8") as f:
        json.dump({"request_id": rid, "num_steps": steps}, f)


def test_profile_capture_counts_steps_and_publishes(tmp_path):
    started, stopped, published = [], [], []
    pc = perf.ProfileCapture(cwd=str(tmp_path), publish=published.append,
                             start_fn=started.append,
                             stop_fn=lambda: stopped.append(True))
    pc.poll()
    assert not pc.active and not started       # no request file yet
    _write_request(tmp_path, "req1", steps=3)
    pc.poll()
    assert pc.active and len(started) == 1
    assert started[0].endswith(os.path.join(C.PROFILES_DIR_NAME, "req1"))
    pc.on_step(); pc.on_step()
    assert pc.active and not published
    pc.on_step()
    assert not pc.active and stopped
    assert len(published) == 1
    pd = published[0]
    assert pd["request_id"] == "req1" and pd["num_steps"] == 3
    assert os.path.isdir(pd["path"])


def test_profile_capture_idempotent_per_request_id(tmp_path):
    started, published = [], []
    pc = perf.ProfileCapture(cwd=str(tmp_path), publish=published.append,
                             start_fn=started.append,
                             stop_fn=lambda: None)
    _write_request(tmp_path, "dup", steps=1)
    pc.poll(); pc.on_step()
    assert len(published) == 1
    # the request file is still on disk — the same id must never restart
    pc.poll()
    assert not pc.active and len(started) == 1
    # a NEW id does
    _write_request(tmp_path, "dup2", steps=1)
    pc.poll(); pc.on_step()
    assert len(published) == 2


# ---------------------------------------------------------------------------
# e2e: the genuine chain on the local backend
# ---------------------------------------------------------------------------

def _fast_conf(tmp_path, **overrides) -> TonyConfiguration:
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 500, "test")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "test")
    conf.set(K.CONTAINER_ALLOCATION_TIMEOUT, 60_000, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "test")
    for k, v in overrides.items():
        conf.set(k, v, "test")
    return conf


def test_goodput_ledger_e2e_sums_to_wall(tmp_path):
    """Acceptance: a local-backend run's goodput.json holds a ledger
    whose phases sum to wall-clock within 1%, with the executor's
    localization/rendezvous seed folded in and input_stall carved out;
    the AM derives a job goodput_pct from it."""
    from tony_tpu.client.tony_client import TonyClient
    hist = str(tmp_path / "hist-int")
    conf = _fast_conf(tmp_path,
                      **{"tony.history.intermediate": hist})
    client = TonyClient(conf)
    client.init(["--executes", script("goodput_task.py"),
                 "--conf", "tony.worker.instances=1"])
    assert client.run() is True, client.final_message

    goodput = read_goodput_file(os.path.join(hist, client.app_id))
    assert "worker:0" in goodput["tasks"], goodput
    entry = goodput["tasks"]["worker:0"]
    phases, wall = entry["phases"], entry["wall_s"]
    assert wall > 0
    assert abs(sum(phases.values()) - wall) <= 0.01 * wall, entry
    # the executor seed and the carve both made it into the books
    assert phases.get("rendezvous_wait", -1) >= 0
    assert phases["input_stall"] == pytest.approx(0.05, abs=0.01)
    assert phases["train_step"] > 0
    assert entry["mfu_pct"] == 41.5
    job = goodput["job"]
    assert job["relaunch_downtime_s"] == 0
    assert 0 < job["goodput_pct"] <= 100
    assert job["productive_s"] == pytest.approx(phases["train_step"],
                                                rel=0.01)


@pytest.mark.chaos
def test_relaunch_downtime_attributed_under_chaos_kill(tmp_path):
    """Acceptance: a chaos-harness mid-run kill's relaunch gap lands in
    goodput.json as job-level relaunch_downtime_s > 0 (wall-clock no
    task process existed to account for, charged against goodput)."""
    from tests.chaos import ChaosRun, KillTask
    run = ChaosRun(tmp_path, seed=11)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.task.max-task-attempts=2"],
        injections=[KillTask("worker", 1, run.delay_ms(800, 1200),
                             attempt=0)])
    assert run.final_status == "SUCCEEDED", run.all_logs()
    assert len(run.relaunches()) == 1
    history_dir = os.path.join(run.client.app_dir, C.HISTORY_DIR_NAME,
                               run.client.app_id)
    goodput = read_goodput_file(history_dir)
    assert goodput["job"]["relaunch_downtime_s"] > 0, goodput


def test_request_profile_e2e(tmp_path):
    """Acceptance: request_profile against a live AM rides the heartbeat
    to the executor, the ProfileCapture state machine captures + ships
    the artifact over the metrics RPC, and the AM links it into history
    (profiles/<rid>/ + PROFILE_CAPTURED event). A double-request while
    in flight returns the same request_id and yields ONE artifact."""
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.rpc.client import ClusterServiceClient
    hist = str(tmp_path / "hist-int")
    conf = _fast_conf(tmp_path,
                      **{"tony.history.intermediate": hist,
                         "tony.profiling.default-steps": 2})
    client = TonyClient(conf)
    client.init(["--executes", script("profile_capture_task.py"),
                 "--conf", "tony.worker.instances=1"])
    result = {}

    def _run():
        result["ok"] = client.run()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    # wait for the AM's RPC endpoint, then request a profile (twice)
    rpc = None
    first = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and first is None:
        hostport = os.path.join(client.app_dir or "", C.AM_HOSTPORT_FILE)
        if client.app_dir and os.path.exists(hostport):
            if rpc is None:
                with open(hostport, "r", encoding="utf-8") as f:
                    host, _, port = f.read().strip().rpartition(":")
                rpc = ClusterServiceClient(host, int(port))
            resp = rpc.request_profile()
            if not resp.get("error"):
                first = resp
        time.sleep(0.1)
    assert first is not None, "request_profile never succeeded"
    assert first["task_id"] == "worker:0"
    assert first["num_steps"] == 2
    # idempotent while in flight: same id, flagged duplicate
    second = rpc.request_profile()
    assert second["request_id"] == first["request_id"]
    assert second.get("duplicate") is True
    rpc.close()
    t.join(timeout=120)
    assert result.get("ok") is True, client.final_message

    rid = first["request_id"]
    history_dir = os.path.join(hist, client.app_id)
    artifact = os.path.join(history_dir, C.PROFILES_DIR_NAME, rid,
                            "trace.xplane.pb")
    assert os.path.isfile(artifact), os.listdir(history_dir)
    finals = [os.path.join(history_dir, f)
              for f in os.listdir(history_dir) if f.endswith(".jhist")]
    assert len(finals) == 1
    captured = [e for e in parse_events(finals[0])
                if e.type == EventType.PROFILE_CAPTURED]
    assert len(captured) == 1, captured
    ev = captured[0].payload
    assert ev.request_id == rid
    assert (ev.task_type, ev.task_index) == ("worker", 0)
    assert ev.path == os.path.join(C.PROFILES_DIR_NAME, rid)
    assert ev.num_steps == 2


def test_portal_profile_post_rejects_finished_job(tmp_path):
    """The portal's one write route: a finished (or AM-less) job answers
    409, not a hang — the AM address file is only meaningful while the
    job runs."""
    import urllib.error
    import urllib.request
    from tony_tpu.events.handler import EventHandler
    from tony_tpu.events.history import JobMetadata
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer

    inter = tmp_path / "inter"
    app = "application_perf_1"
    md = JobMetadata(application_id=app, started=1000)
    handler = EventHandler(str(inter / app), md)
    handler.start()
    handler.stop("SUCCEEDED")
    cache = PortalCache(str(inter), str(tmp_path / "fin"))
    server = PortalServer(cache, port=0, host="127.0.0.1")
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/jobs/{app}/profile",
            data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 409
        body = json.loads(exc.value.read())
        assert "running" in body["error"]
    finally:
        server.stop()
