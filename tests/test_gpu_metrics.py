"""GPU sampler tests (reference: TestGpuDiscoverer + TaskMonitor GPU
metrics, GpuDiscoverer.java:43-209, TaskMonitor.java:116-170)."""

import os
import stat
import textwrap

from tony_tpu.conf import keys as K
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.executor.gpu_metrics import (
    MAX_REPEATED_ERRORS, GpuSampler, find_nvidia_smi, maybe_gpu_sampler,
    parse_gpu_xml,
)

SAMPLE_XML = textwrap.dedent("""\
    <?xml version="1.0" ?>
    <nvidia_smi_log>
      <attached_gpus>2</attached_gpus>
      <gpu id="00000000:03:00.0">
        <fb_memory_usage>
          <total>16160 MiB</total>
          <used>8080 MiB</used>
          <free>8080 MiB</free>
        </fb_memory_usage>
        <bar1_memory_usage>
          <total>16384 MiB</total>
          <used>4096 MiB</used>
        </bar1_memory_usage>
        <utilization>
          <gpu_util>90 %</gpu_util>
          <memory_util>30 %</memory_util>
        </utilization>
      </gpu>
      <gpu id="00000000:04:00.0">
        <fb_memory_usage>
          <total>16160 MiB</total>
          <used>1616 MiB</used>
          <free>14544 MiB</free>
        </fb_memory_usage>
        <bar1_memory_usage>
          <total>16384 MiB</total>
          <used>0 MiB</used>
        </bar1_memory_usage>
        <utilization>
          <gpu_util>10 %</gpu_util>
          <memory_util>1 %</memory_util>
        </utilization>
      </gpu>
    </nvidia_smi_log>
""")


def fake_smi(tmp_path, body: str) -> str:
    path = tmp_path / "nvidia-smi"
    path.write_text(f"#!/bin/sh\n{body}\n")
    path.chmod(path.stat().st_mode | stat.S_IXUSR)
    return str(path)


def test_parse_gpu_xml():
    gpus = parse_gpu_xml(SAMPLE_XML)
    assert len(gpus) == 2
    assert gpus[0].utilization_pct == 90.0
    assert gpus[0].fb_pct == 50.0
    assert gpus[0].bar1_pct == 25.0
    assert gpus[1].utilization_pct == 10.0
    assert gpus[1].fb_pct == 10.0
    assert gpus[1].bar1_pct == 0.0


def test_sampler_aggregates(tmp_path):
    xml_file = tmp_path / "out.xml"
    xml_file.write_text(SAMPLE_XML)
    sampler = GpuSampler(fake_smi(tmp_path, f'cat "{xml_file}"'))
    s = sampler()
    assert s["util_max"] == 90.0
    assert s["util_avg"] == 50.0
    assert s["fb_pct_max"] == 50.0
    assert s["fb_pct_avg"] == 30.0
    assert s["main_pct_max"] == 25.0
    assert s["main_pct_avg"] == 12.5


def test_sampler_error_cap(tmp_path):
    sampler = GpuSampler(fake_smi(tmp_path, "exit 9"))
    for _ in range(MAX_REPEATED_ERRORS + 3):
        assert sampler() == {}
    assert sampler._errors == MAX_REPEATED_ERRORS  # capped, not unbounded


def test_maybe_gpu_sampler_gating(tmp_path):
    binary = fake_smi(tmp_path, "echo '<nvidia_smi_log/>'")
    conf = TonyConfiguration()
    # no gpus requested -> no sampler even with a binary available
    conf.set(K.GPU_PATH_TO_EXEC, binary, "test")
    assert maybe_gpu_sampler(conf, "worker") is None
    # gpus requested + binary -> sampler
    conf.set(K.gpus_key("worker"), 2, "test")
    assert isinstance(maybe_gpu_sampler(conf, "worker"), GpuSampler)
    # disabled by the reference's kill-switch key
    conf.set(K.TASK_GPU_METRICS_ENABLED, False, "test")
    assert maybe_gpu_sampler(conf, "worker") is None


def test_find_nvidia_smi_override_must_be_executable(tmp_path):
    plain = tmp_path / "not-exec"
    plain.write_text("")
    assert find_nvidia_smi(str(plain)) is None
    assert find_nvidia_smi(fake_smi(tmp_path, "true")) is not None


def test_monitor_reports_gpu_metrics(tmp_path):
    from tony_tpu.executor.task_monitor import (
        AVG_GPU_UTILIZATION, MAX_GPU_FB_MEMORY_USAGE, MAX_GPU_UTILIZATION,
        TaskMonitor,
    )

    class _Client:
        def update_metrics(self, *a):
            pass

    xml_file = tmp_path / "out.xml"
    xml_file.write_text(SAMPLE_XML)
    mon = TaskMonitor(_Client(), "worker", 0, pid_fn=lambda: os.getpid(),
                      gpu_sampler=GpuSampler(
                          fake_smi(tmp_path, f'cat "{xml_file}"')))
    mon._sample_and_push()
    named = {m["name"]: m["value"] for m in mon.snapshot()}
    assert named[MAX_GPU_UTILIZATION] == 90.0
    assert named[AVG_GPU_UTILIZATION] == 50.0
    assert named[MAX_GPU_FB_MEMORY_USAGE] == 50.0
