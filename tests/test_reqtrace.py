"""Request-scoped distributed-tracing tests (observability/reqtrace.py
and its serving-fleet wiring).

The load-bearing contract: one trace context per request, minted at the
router's ingress (or adopted from the client's X-Tony-Trace header) and
propagated on every replica-to-replica hop, with ZERO added per-request
RPCs — hops accumulate in-process, a tail sampler keeps only the traces
that matter, and export is pull-only (/v1/traces) plus the metrics-RPC
piggyback. The slow e2e proves the whole story on a real disaggregated
fleet: router → prefill replica → /v1/migrate → decode replica, one
stitched trace spanning all three processes, the chaos-delayed decode
hop dominating, and both offline renderers (cli trace, portal) showing
the same waterfall.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu import constants as C
from tony_tpu.models.generate import generate
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.observability import reqtrace
from tony_tpu.observability.reqtrace import (
    HEADER, ReqTraceCollector, RequestTrace, TailSampler, TraceContext,
    adopt_or_mint, attribution_from_handle, parse_header,
    record_engine_phases, slowest_table, stitch, TtftAttribution,
)

pytestmark = pytest.mark.reqtrace

TID = "feedface" * 4          # a well-formed 32-hex client trace id
SPAN = "ab" * 8               # a well-formed 16-hex parent span id


# ---------------------------------------------------------------------------
# header adopt / mint
# ---------------------------------------------------------------------------

def test_header_roundtrip_with_route_ms():
    ctx = TraceContext(TID, SPAN, route_ms=12.5)
    got = parse_header(ctx.header_value())
    assert (got.trace_id, got.parent_span_id, got.route_ms) == \
        (TID, SPAN, 12.5)


def test_header_omits_route_ms_when_zero():
    assert TraceContext(TID, SPAN).header_value() == f"{TID}:{SPAN}"


def test_garbage_headers_mint_fresh_roots():
    for bad in (None, "", "   ", "xyz!:-", "GHIJKL:" + SPAN,
                "a" * 33, f"{TID}:ZZZZ", f"{TID}:{'c' * 17}"):
        ctx, adopted = adopt_or_mint(bad)
        assert not adopted
        assert len(ctx.trace_id) == 32 and ctx.parent_span_id == ""
    ctx, adopted = adopt_or_mint(f"{TID}:{SPAN}:7.25")
    assert adopted and ctx.trace_id == TID and ctx.route_ms == 7.25


def test_non_numeric_route_ms_degrades_to_zero():
    ctx = parse_header(f"{TID}:{SPAN}:fast")
    assert ctx is not None and ctx.route_ms == 0.0


# ---------------------------------------------------------------------------
# tail sampler
# ---------------------------------------------------------------------------

def test_sampler_unconditional_keeps_beat_the_threshold():
    s = TailSampler(slow_threshold_ms=1000.0)
    assert s.keep(1.0, error=True) == "error"
    assert s.keep(1.0, spilled=True) == "spill"
    assert s.keep(1.0, migrated=True) == "migrated"
    assert s.keep(1.0) is None


def test_sampler_slowest_k_displaces_the_windows_fastest():
    now = [0.0]
    s = TailSampler(slow_threshold_ms=10.0, slowest_k=2,
                    window_ms=60_000.0, clock=lambda: now[0])
    assert s.keep(20.0) == "slow"
    assert s.keep(30.0) == "slow"
    # window full at k=2; floor is 20 — a 25 displaces it...
    assert s.keep(25.0) == "slow"
    # ...and a 15 (above threshold, below the new floor of 25) drops
    assert s.keep(15.0) is None


def test_sampler_window_expiry_refills_the_budget():
    now = [0.0]
    s = TailSampler(slow_threshold_ms=10.0, slowest_k=1, window_ms=1000.0,
                    clock=lambda: now[0])
    assert s.keep(50.0) == "slow"
    assert s.keep(12.0) is None          # budget spent, below the floor
    now[0] = 2.0                          # 2s later: window rolled over
    assert s.keep(12.0) == "slow"


def test_sampler_errors_do_not_consume_the_slow_budget():
    s = TailSampler(slow_threshold_ms=10.0, slowest_k=1)
    for _ in range(5):
        assert s.keep(9999.0, error=True) == "error"
    assert s.keep(20.0) == "slow"        # slot still free


# ---------------------------------------------------------------------------
# collector: bounding, export vs drain, redaction
# ---------------------------------------------------------------------------

def _kept_trace(coll, trace_id, duration_ms=50.0):
    tr = coll.trace(TraceContext(trace_id))
    return coll.finish(tr, duration_ms, migrated=True)


def test_collector_bounded_buffer_drops_oldest():
    coll = ReqTraceCollector("p", max_traces=2)
    for tid in ("aa", "bb", "cc"):
        assert _kept_trace(coll, tid) == "migrated"
    ids = [t["trace_id"] for t in coll.export()]
    assert ids == ["bb", "cc"]           # "aa" (oldest) was evicted


def test_collector_export_is_nondestructive_drain_is_not():
    coll = ReqTraceCollector("p")
    _kept_trace(coll, "aa")
    assert len(coll.export()) == 1
    assert len(coll.export()) == 1
    assert [t["trace_id"] for t in coll.drain()] == ["aa"]
    assert coll.export() == []


def test_disabled_collector_is_a_cheap_noop():
    coll = ReqTraceCollector("p", enabled=False)
    assert coll.trace(TraceContext.mint()) is None
    assert coll.finish(None, 1e9, error=True) is None
    assert coll.export() == []


def test_export_redacts_secret_shaped_hop_attrs():
    coll = ReqTraceCollector("p", sampler=TailSampler(slow_threshold_ms=0.0))
    tr = coll.trace(TraceContext(TID))
    tr.hop("router.route", 0, 5,
           attrs={"target": "api_key=hunter2hunter2", "attempts": 1})
    coll.finish(tr, 50.0)
    attrs = coll.export()[0]["hops"][0]["attrs"]
    assert "hunter2" not in attrs["target"]
    assert attrs["attempts"] == 1        # non-strings pass through


# ---------------------------------------------------------------------------
# stitching + the slowest table
# ---------------------------------------------------------------------------

def _record(trace_id, process, reason, duration, hops):
    return {"trace_id": trace_id, "request_id": "7", "process": process,
            "kept_reason": reason, "duration_ms": duration,
            "hops": [{"trace_id": trace_id, "span_id": sid,
                      "parent_id": "", "name": name, "process": process,
                      "start_ms": a, "end_ms": b, "status": "OK",
                      "attrs": {}} for sid, name, a, b in hops]}


def test_stitch_merges_processes_dedupes_spans_ranks_reasons():
    pre = _record("t1", "prefill:0", "slow", 100.0,
                  [("s1", "queue_wait", 0, 10),
                   ("s2", "prefill_suffix", 10, 40)])
    dec = _record("t1", "decode:0", "migrated", 400.0,
                  [("s2", "prefill_suffix", 10, 40),   # duplicate span
                   ("s3", "decode", 40, 400)])
    other = _record("t2", "prefill:0", "slow", 50.0,
                    [("s9", "queue_wait", 0, 50)])
    out = stitch([[pre, other], [dec]])
    assert [t["trace_id"] for t in out] == ["t1", "t2"]  # slowest first
    t1 = out[0]
    assert t1["kept_reason"] == "migrated"               # outranks slow
    assert t1["duration_ms"] == 400.0                    # max observed
    assert set(t1["processes"]) == {"prefill:0", "decode:0"}
    assert [h["span_id"] for h in t1["hops"]] == ["s1", "s2", "s3"]


def test_slowest_table_names_the_dominant_hop_and_process():
    dec = _record("t1", "decode:0", "migrated", 400.0,
                  [("s1", "queue_wait", 0, 10), ("s2", "decode", 10, 400)])
    rows = slowest_table(stitch([[dec]]))
    assert rows[0]["dominant_hop"] == "decode"
    assert rows[0]["dominant_process"] == "decode:0"
    assert rows[0]["dominant_ms"] == 390
    assert rows[0]["hop_count"] == 2


# ---------------------------------------------------------------------------
# engine-phase hop recording + TTFT attribution (duck-typed handles)
# ---------------------------------------------------------------------------

class _Handle:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def _local_handle():
    return _Handle(submitted_at=100.0, queue_wait_s=0.010,
                   kv_match_s=0.002, kv_matched_tokens=3,
                   prefill_s=0.020, prompt=list(range(8)),
                   first_token_at=100.035, finished_at=100.095,
                   tokens=[1, 2, 3, 4], finish_reason="length",
                   ttft_s=0.035, migrated_in=False)


def test_record_engine_phases_local_path():
    trace = RequestTrace(TraceContext(TID), process="p")
    record_engine_phases(trace, _local_handle())
    names = [h["name"] for h in trace.hops]
    assert names == ["queue_wait", "kv_match", "prefill_suffix", "decode"]
    by = {h["name"]: h for h in trace.hops}
    assert by["kv_match"]["attrs"]["matched_tokens"] == 3
    assert by["prefill_suffix"]["attrs"] == {"prompt_tokens": 8,
                                             "suffix_tokens": 5}
    dec = by["decode"]["attrs"]
    assert dec["tokens"] == 4 and dec["finish_reason"] == "length"
    assert dec["itl_ms"] == pytest.approx(20.0, abs=0.5)


def test_record_engine_phases_migrated_in_path():
    h = _local_handle()
    h.migrated_in = True
    h.first_token_at = None              # decode never started here
    trace = RequestTrace(TraceContext(TID), process="d")
    record_engine_phases(trace, h)
    names = [x["name"] for x in trace.hops]
    assert names == ["queue_wait", "migrate.install"]
    assert trace.hops[1]["attrs"] == {"pos": 8}


def test_attribution_from_handle_decode_is_the_ttft_remainder():
    comp = attribution_from_handle(_local_handle(), route_ms=4.0)
    assert comp["route_ms"] == 4.0
    assert comp["queue_ms"] == pytest.approx(10.0)
    assert comp["prefill_ms"] == pytest.approx(20.0)
    assert comp["decode_ms"] == pytest.approx(5.0)   # 35 - 10 - 20
    h = _local_handle()
    h.ttft_s = None                       # never produced a token
    assert attribution_from_handle(h)["decode_ms"] == 0.0


def test_ttft_attribution_gauges_only_for_sampled_components():
    att = TtftAttribution()
    assert att.gauges() == {}
    att.record({"queue_ms": 5.0, "prefill_ms": 10.0})
    g = att.gauges()
    assert g["ttft_attr_queue_ms_p50"] == 5.0
    assert g["ttft_attr_prefill_ms_p95"] == 10.0
    assert not any(k.startswith("ttft_attr_route") for k in g)


def test_ttft_attribution_window_is_bounded():
    att = TtftAttribution(maxlen=4)
    for v in range(100):
        att.record({"queue_ms": float(v)})
    assert att.gauges()["ttft_attr_queue_ms_p50"] >= 96.0


# ---------------------------------------------------------------------------
# metrics-RPC piggyback (zero new channels)
# ---------------------------------------------------------------------------

def test_reporter_piggybacks_drained_traces_on_the_metrics_push():
    from tony_tpu.train.metrics import ServingMetricsReporter
    coll = ReqTraceCollector("prefill:0",
                             sampler=TailSampler(slow_threshold_ms=0.0))
    _kept_trace(coll, TID)
    env = {C.AM_HOST: "127.0.0.1", C.METRICS_RPC_PORT: "1",
           C.JOB_NAME: "server"}
    rep = ServingMetricsReporter(
        lambda: [{"name": "tokens_emitted", "value": 1}], env=env,
        interval_sec=3600.0, trace_source=coll.drain)
    pushed = []
    rep._enqueue = pushed.append
    rep.report_now()
    assert pushed[0]["serving_traces"][0]["trace_id"] == TID
    assert coll.export() == []            # drained, not copied
    rep.report_now()                      # nothing new: no traces field
    assert "serving_traces" not in pushed[1]


# ---------------------------------------------------------------------------
# router relay: ingress adoption, route hop, error keeps, /metrics text
# ---------------------------------------------------------------------------

def test_router_relay_adopts_client_trace_and_keeps_errors():
    from tony_tpu.serve.router import FleetRouter, router_prometheus_text
    router = FleetRouter(endpoints=[], port=0, host="127.0.0.1")
    try:
        sent = []
        router.relay(json.dumps({"prompt": [1, 2]}).encode(),
                     lambda status, headers, body: sent.append(status),
                     headers={HEADER: f"{TID}:{SPAN}"})
        assert sent == [503]              # no replica anywhere
        records = router.collector.export()
        assert records[0]["trace_id"] == TID
        assert records[0]["kept_reason"] == "error"
        hop = records[0]["hops"][0]
        assert hop["name"] == "router.route"
        assert hop["status"] == "ERROR"
        assert hop["attrs"]["http_status"] == 503
        bundle = router.collect_traces()
        assert bundle["traces"][0]["trace_id"] == TID
        assert bundle["pulled"] == {}
        text = router_prometheus_text(router)
        assert "tony_router_requests_failed_total 1" in text
        assert "tony_router_requests_routed_total 0" in text
    finally:
        router._httpd.server_close()


# ---------------------------------------------------------------------------
# offline renderers on synthetic records (fast; the e2e re-checks them
# on real fleet output)
# ---------------------------------------------------------------------------

def _sidecar_records():
    pre = _record(TID, "prefill:0", "migrated", 600.0,
                  [("s1", "queue_wait", 1000, 1010),
                   ("s2", "prefill_suffix", 1010, 1050),
                   ("s3", "migrate.transfer", 1050, 1070)])
    dec = _record(TID, "decode:0", "migrated", 580.0,
                  [("s4", "migrate.install", 1070, 1090),
                   ("s5", "decode", 1090, 1600)])
    return [pre, dec]


def test_cli_trace_renders_the_waterfall_offline(tmp_path, capsys):
    from tony_tpu.cli.__main__ import trace as cli_trace
    from tony_tpu.events.history import write_serving_traces_file
    write_serving_traces_file(str(tmp_path), _sidecar_records())
    assert cli_trace([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "1 sampled request trace(s)" in out
    assert "waterfall" in out and TID[:12] in out
    assert "decode [decode:0]" in out
    assert "dominant: decode (decode:0" in out
    # --json mode round-trips the stitched bundle
    assert cli_trace([str(tmp_path), "--json"]) == 0
    bundle = json.loads(capsys.readouterr().out)
    assert bundle["slowest"][0]["dominant_process"] == "decode:0"
    # --trace-id filters; an unmatched prefix is a clean non-zero exit
    assert cli_trace([str(tmp_path), "--trace-id", "0000"]) == 1


def test_cli_trace_missing_sidecar_exits_nonzero(tmp_path, capsys):
    from tony_tpu.cli.__main__ import trace as cli_trace
    assert cli_trace([str(tmp_path)]) == 1
    assert "no serving traces" in capsys.readouterr().err


def test_portal_requests_api_and_job_page(tmp_path):
    from test_portal import make_app_history
    from tony_tpu.events.history import write_serving_traces_file
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.mover import ensure_history_dirs
    from tony_tpu.portal.server import PortalServer
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    app_dir = make_app_history(inter, "app_rt")
    write_serving_traces_file(app_dir, _sidecar_records())
    server = PortalServer(PortalCache(inter, fin), port=0,
                          host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}"
                f"/api/jobs/app_rt/requests") as r:
            bundle = json.loads(r.read())
        assert bundle["traces"][0]["trace_id"] == TID
        assert set(bundle["traces"][0]["processes"]) == \
            {"prefill:0", "decode:0"}
        assert bundle["slowest"][0]["dominant_hop"] == "decode"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/jobs/app_rt") as r:
            page = r.read().decode()
        assert "Slowest requests" in page
        assert "Request waterfall" in page
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# single-replica integration: header adoption, /v1/traces pull, gauges
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    cfg = get_config("tiny")
    return llama_init(cfg, jax.random.PRNGKey(0)), cfg


def _prompts(cfg, lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
            for n in lengths]


def _oracle(params, cfg, prompt, n, **kw):
    out = generate(params, cfg, jnp.asarray([prompt], jnp.int32), n, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _post_json(url, body, headers=None, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def test_frontend_traces_one_request_end_to_end(model):
    from tony_tpu.serve.engine import ContinuousBatchingEngine
    from tony_tpu.serve.frontend import ServeFrontend, \
        install_engine_tracing
    params, cfg = model
    prompt = _prompts(cfg, (6,), seed=11)[0]
    engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                      token_budget=32, queue_depth=8)
    coll = ReqTraceCollector(
        "replica:0", sampler=TailSampler(slow_threshold_ms=0.0))
    install_engine_tracing(engine, coll)
    engine.start()
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1",
                             collector=coll)
    frontend.start()
    base = f"http://127.0.0.1:{frontend.port}"
    try:
        want = _oracle(params, cfg, prompt, 4)
        resp = json.loads(_post_json(
            base + "/v1/generate",
            {"prompt": prompt, "max_new_tokens": 4},
            headers={HEADER: f"{TID}:{SPAN}"}).read())
        assert resp["tokens"] == want     # tracing never bends tokens
        # the engine callback finishes the trace asynchronously
        deadline = time.time() + 10
        records = []
        while time.time() < deadline:
            records = [t for t in _get_json(base + "/v1/traces")["traces"]
                       if t["trace_id"] == TID]
            if records:
                break
            time.sleep(0.05)
        assert records, "adopted trace never reached /v1/traces"
        names = [h["name"] for h in records[0]["hops"]]
        assert names == ["queue_wait", "kv_match", "prefill_suffix",
                         "decode"]
        assert all(h["parent_id"] == SPAN for h in records[0]["hops"])
        # the pull surface audits itself: per-path request counts
        snap = _get_json(base + "/v1/traces")
        assert snap["process"] == "replica:0"
        assert snap["http_requests"]["/v1/generate"] == 1
        assert snap["http_requests"]["/v1/traces"] >= 2
        # TTFT-attribution gauges joined the metrics snapshot
        metrics = _get_json(base + "/v1/metrics")
        assert "ttft_attr_queue_ms_p50" in metrics
        assert "ttft_attr_prefill_ms_p95" in metrics
        # a budget-rejected request is an unconditional "error" keep
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(base + "/v1/generate",
                       {"prompt": prompt, "max_new_tokens": 9999})
        assert e.value.code == 400
        deadline = time.time() + 10
        rejected = []
        while time.time() < deadline:
            rejected = [t for t in _get_json(base + "/v1/traces")["traces"]
                        if t["kept_reason"] == "error"]
            if rejected:
                break
            time.sleep(0.05)
        assert rejected[0]["hops"][0]["name"] == "frontend.reject"
    finally:
        frontend.stop()
        engine.stop()


# ---------------------------------------------------------------------------
# THE disaggregated e2e: router → prefill → /v1/migrate → decode, with a
# chaos-delayed decode step; one trace spans all three processes, the
# hop-sum matches the client's observed TTFT, tokens stay bit-identical,
# trace export adds zero per-request RPCs, and both offline renderers
# show the guilty replica.
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.serving
def test_disaggregated_trace_spans_three_processes(model, monkeypatch,
                                                   tmp_path, capsys):
    from tony_tpu.serve.engine import ContinuousBatchingEngine
    from tony_tpu.serve.frontend import ServeFrontend, \
        install_engine_tracing
    from tony_tpu.serve.router import FleetRouter
    params, cfg = model
    prompt = _prompts(cfg, (8,), seed=3)[0]
    max_new = 6
    want = _oracle(params, cfg, prompt, max_new)

    # chaos: every decode step on the DECODE replica sleeps 100 ms (read
    # once at engine construction, so only this engine is delayed)
    monkeypatch.setenv(C.TEST_SERVE_DECODE_DELAY, "100")
    dec_engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                          token_budget=64, queue_depth=8,
                                          role="decode")
    monkeypatch.delenv(C.TEST_SERVE_DECODE_DELAY)
    pre_engine = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                          token_budget=64, queue_depth=8,
                                          role="prefill")
    keep_all = dict(slow_threshold_ms=0.0, slowest_k=64)
    pre_coll = ReqTraceCollector("prefill:0",
                                 sampler=TailSampler(**keep_all))
    dec_coll = ReqTraceCollector("decode:0",
                                 sampler=TailSampler(**keep_all))
    install_engine_tracing(pre_engine, pre_coll)
    install_engine_tracing(dec_engine, dec_coll)
    dec_engine.start()
    pre_engine.start()
    dec_front = ServeFrontend(dec_engine, port=0, host="127.0.0.1",
                              collector=dec_coll)
    dec_front.start()
    dec_url = f"http://127.0.0.1:{dec_front.port}"
    pre_front = ServeFrontend(pre_engine, port=0, host="127.0.0.1",
                              migrate_targets=[dec_url],
                              collector=pre_coll)
    pre_front.start()
    pre_url = f"http://127.0.0.1:{pre_front.port}"
    # the router's own view must be sampled too: it cannot know the
    # request migrated downstream, so keep-everything is the test's lever
    router = FleetRouter(
        endpoints=[{"url": pre_url, "role": "prefill"},
                   {"url": dec_url, "role": "decode"}],
        port=0, host="127.0.0.1",
        collector=ReqTraceCollector("router",
                                    sampler=TailSampler(**keep_all)))
    router.start()
    router_url = f"http://127.0.0.1:{router.port}"
    try:
        # warmup absorbs both engines' compiles (and proves the
        # blocking migrated path while at it)
        warm = json.loads(_post_json(
            router_url + "/v1/generate",
            {"prompt": prompt, "max_new_tokens": 3}).read())
        assert warm["migrated"] is True

        # measured request: the CLIENT mints the trace id, so adoption
        # is proven at the router's ingress; stream to observe TTFT
        req = urllib.request.Request(
            router_url + "/v1/generate",
            data=json.dumps({"prompt": prompt, "max_new_tokens": max_new,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json",
                     HEADER: f"{TID}:{SPAN}"})
        t_send = time.monotonic()
        ttft_s = None
        toks, done = [], None
        with urllib.request.urlopen(req, timeout=120) as r:
            for raw in r:
                if ttft_s is None:
                    ttft_s = time.monotonic() - t_send
                obj = json.loads(raw)
                if obj.get("done"):
                    done = obj
                    break
                toks.append(int(obj["token"]))
        client_ttft_ms = 1000.0 * ttft_s
        # tokens bit-identical to the untraced offline oracle
        assert toks == want
        assert done["migrated"] is True and done["n_tokens"] == max_new

        # ZERO per-request trace RPCs: before any operator pull, neither
        # replica has ever seen a /v1/traces request — only the data
        # plane (2 generates on prefill, 2 migrates on decode)
        assert pre_front.request_counts.get("/v1/traces", 0) == 0
        assert dec_front.request_counts.get("/v1/traces", 0) == 0
        assert pre_front.request_counts.get("/v1/generate") == 2
        assert dec_front.request_counts.get("/v1/migrate") == 2

        # pull-and-stitch at the router until all three processes'
        # views of OUR trace have landed
        ours = None
        pulls = 0
        deadline = time.time() + 30
        while time.time() < deadline:
            pulls += 1
            bundle = _get_json(router_url + "/v1/traces")
            got = [t for t in bundle["traces"]
                   if t["trace_id"] == TID]
            if got and set(got[0]["processes"]) >= \
                    {"router", "prefill:0", "decode:0"}:
                ours = got[0]
                break
            time.sleep(0.2)
        assert ours is not None, "stitched trace never spanned the fleet"
        assert ours["kept_reason"] == "migrated"
        # every /v1/traces hit on the replicas is one of OUR pulls: the
        # export path is pull-only, never per-request
        assert pre_front.request_counts.get("/v1/traces") == pulls
        assert dec_front.request_counts.get("/v1/traces") == pulls
        assert set(bundle["pulled"]) == {pre_url, dec_url}

        by_name: dict = {}
        for h in ours["hops"]:
            by_name[h["name"]] = by_name.get(h["name"], 0) + \
                int(h["end_ms"]) - int(h["start_ms"])
        assert {"router.route", "queue_wait", "kv_match",
                "prefill_suffix", "migrate.pack", "migrate.transfer",
                "migrate.install", "decode"} <= set(by_name)

        # TTFT composition: the client saw its first token right after
        # the migrate handoff — route + queue + kv + prefill + pack +
        # transfer must reproduce the observed TTFT (10% / 75 ms floor
        # for scheduler jitter); the decode delay must NOT be in it
        hop_sum = sum(by_name[n] for n in
                      ("router.route", "queue_wait", "kv_match",
                       "prefill_suffix", "migrate.pack",
                       "migrate.transfer"))
        assert abs(hop_sum - client_ttft_ms) <= \
            max(0.10 * client_ttft_ms, 75.0), \
            f"hop sum {hop_sum:.1f} ms vs client TTFT " \
            f"{client_ttft_ms:.1f} ms"
        # the chaos delay lands squarely in decode: ~5 delayed steps
        assert by_name["decode"] >= 400.0
        assert by_name["decode"] > 3 * hop_sum

        # the slowest-requests table names the guilty replica
        row = next(r for r in bundle["slowest"]
                   if r["trace_id"] == TID)
        assert row["dominant_hop"] == "decode"
        assert row["dominant_process"] == "decode:0"

        # both offline renderers consume the drained records: the same
        # serving_traces.json sidecar path history flushes through
        from tony_tpu.events.history import write_serving_traces_file
        records = (pre_coll.drain() + dec_coll.drain()
                   + router.collector.drain())
        from test_portal import make_app_history
        from tony_tpu.portal.cache import PortalCache
        from tony_tpu.portal.mover import ensure_history_dirs
        from tony_tpu.portal.server import PortalServer
        inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
        ensure_history_dirs(inter, fin)
        app_dir = make_app_history(inter, "app_e2e")
        write_serving_traces_file(app_dir, records)

        from tony_tpu.cli.__main__ import trace as cli_trace
        assert cli_trace([app_dir, "--trace-id", TID[:8]]) == 0
        out = capsys.readouterr().out
        assert "waterfall" in out and TID[:12] in out
        assert "decode [decode:0]" in out

        portal = PortalServer(PortalCache(inter, fin), port=0,
                              host="127.0.0.1")
        portal.start()
        try:
            api = _get_json(f"http://127.0.0.1:{portal.port}"
                            f"/api/jobs/app_e2e/requests")
            mine = [t for t in api["traces"] if t["trace_id"] == TID]
            assert mine and set(mine[0]["processes"]) >= \
                {"router", "prefill:0", "decode:0"}
        finally:
            portal.stop()
    finally:
        router.stop()
        pre_front.stop()
        dec_front.stop()
        pre_engine.stop()
        dec_engine.stop()
