"""Config system tests, incl. the defaults↔keys drift check.

Reference test model: TestTonyConfigurationFields.java:13-66 (drift),
TestUtils.java memory parsing, TonyClient.initTonyConf merge order
(TonyClient.java:483-517).
"""

import json
import os

import pytest

from tony_tpu.conf import TonyConfiguration, keys as K, parse_memory_mb, parse_time_ms
from tony_tpu.conf.defaults import DEFAULTS, NO_DEFAULT_KEYS


def _static_keys():
    """Every static key constant declared in tony_tpu.conf.keys."""
    out = set()
    for name in dir(K):
        if name.isupper() and not name.endswith("_RE") and name not in (
                "TONY_PREFIX", "MULTI_VALUE_CONF", "RESERVED_SEGMENTS",
                "MAX_TOTAL_RESOURCES_PREFIX"):
            val = getattr(K, name)
            if isinstance(val, str) and val.startswith("tony."):
                out.add(val)
    return out


def test_defaults_drift():
    """Every static key has a default or is explicitly default-free, and every
    default maps to a declared key — the TestTonyConfigurationFields analogue."""
    declared = _static_keys()
    missing = declared - set(DEFAULTS) - NO_DEFAULT_KEYS
    assert not missing, f"keys with neither default nor NO_DEFAULT entry: {missing}"
    unknown = set(DEFAULTS) - declared
    assert not unknown, f"defaults for undeclared keys: {unknown}"
    overlap = set(DEFAULTS) & NO_DEFAULT_KEYS
    assert not overlap, f"keys both defaulted and NO_DEFAULT: {overlap}"


def test_merge_order(tmp_path):
    conf = TonyConfiguration()
    job = tmp_path / "tony.json"
    job.write_text(json.dumps({
        "tony.application.name": "from-file",
        "tony.worker.instances": 4,
    }))
    conf.merge_file(str(job))
    assert conf.get_str(K.APPLICATION_NAME) == "from-file"
    conf.merge_cli(["tony.application.name=from-cli"])
    assert conf.get_str(K.APPLICATION_NAME) == "from-cli"
    assert conf.source_of(K.APPLICATION_NAME) == "cli"
    assert conf.get_int("tony.worker.instances") == 4


def test_properties_file(tmp_path):
    props = tmp_path / "tony.properties"
    props.write_text("# comment\ntony.worker.instances=2\ntony.application.queue=ml\n")
    conf = TonyConfiguration()
    conf.merge_file(str(props))
    assert conf.get_int("tony.worker.instances") == 2
    assert conf.get_str(K.APPLICATION_QUEUE) == "ml"


def test_site_file_merged_last(tmp_path, monkeypatch):
    site_dir = tmp_path / "confdir"
    site_dir.mkdir()
    (site_dir / "tony-site.json").write_text(json.dumps(
        {"tony.application.queue": "site-queue"}))
    monkeypatch.setenv("TONY_CONF_DIR", str(site_dir))
    conf = TonyConfiguration()
    conf.merge_cli(["tony.application.queue=cli-queue"])
    conf.merge_site()
    assert conf.get_str(K.APPLICATION_QUEUE) == "site-queue"


def test_multi_value_append():
    conf = TonyConfiguration()
    conf.set(K.CONTAINERS_RESOURCES, "a.zip,b.txt", source="file")
    conf.set(K.CONTAINERS_RESOURCES, "c.txt,a.zip", source="cli")
    assert conf.get_strings(K.CONTAINERS_RESOURCES) == ["a.zip", "b.txt", "c.txt"]


def test_job_types_discovery():
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.ps.instances", 1)
    conf.set("tony.evaluator.instances", 0)
    # reserved segments never parse as jobtypes
    conf.set("tony.task.heartbeat-interval-ms", 500)
    assert conf.job_types() == ["evaluator", "ps", "worker"]


def test_typed_getters():
    conf = TonyConfiguration()
    conf.set("x.time", "5s")
    conf.set("x.mem", "2g")
    conf.set("x.bool", "TRUE")
    assert conf.get_time_ms("x.time") == 5000
    assert conf.get_memory_mb("x.mem") == 2048
    assert conf.get_bool("x.bool") is True
    assert conf.get_bool("x.unset", True) is True


@pytest.mark.parametrize("raw,ms", [
    ("500ms", 500), ("2m", 120000), (1500, 1500), ("1h", 3600000), ("0.5s", 500)])
def test_parse_time(raw, ms):
    assert parse_time_ms(raw) == ms


@pytest.mark.parametrize("raw,mb", [
    ("2g", 2048), ("512m", 512), ("512", 512), (1024, 1024), ("1t", 1048576)])
def test_parse_memory(raw, mb):
    assert parse_memory_mb(raw) == mb


def test_final_conf_roundtrip(tmp_path):
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 3, source="file")
    path = str(tmp_path / "sub" / "tony-final.json")
    conf.write(path)
    loaded = TonyConfiguration.read(path)
    assert loaded.get_int("tony.worker.instances") == 3
    assert loaded.source_of("tony.worker.instances") == "file"
    assert loaded.get_int(K.TASK_HEARTBEAT_INTERVAL_MS) == 1000


def test_version_stamping():
    """Build metadata injected at submission (reference: VersionInfo,
    TonyClient.java:152)."""
    from tony_tpu.version import VERSION, stamp_conf
    conf = TonyConfiguration()
    stamp_conf(conf)
    assert conf.get_str("tony.version") == VERSION
    assert conf.get_str("tony.version.git-ref")
    assert conf.get_str("tony.version.user")
    # version keys must never parse as jobtypes
    assert "version" not in conf.job_types()


def test_config_docs_current():
    """Docs drift check (reference: TestTonyConfigurationFields asserting
    code<->tony-default.xml parity; here code<->docs/configuration.md)."""
    import subprocess, sys, os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "gen_config_docs.py"),
         "--check"], capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr


def test_queue_quota_validation():
    """VERDICT r4 item 5: tony.queues.<name>.max-tpus is enforced, and an
    undeclared queue is a loud error once any queue exists."""
    from tony_tpu.conf.queues import (
        configured_queues, validate_queue_quota,
    )

    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 4, "t")
    conf.set("tony.worker.tpus", 4, "t")
    validate_queue_quota(conf)           # no queues declared: tag only

    conf.set("tony.queues.default.max-tpus", 8, "t")
    conf.set("tony.queues.big.max-tpus", 32, "t")
    assert configured_queues(conf) == {"default": 8, "big": 32}
    with pytest.raises(ValueError, match="'default'.*16 TPUs.*quota of 8"):
        validate_queue_quota(conf)       # 4x4=16 > default's 8
    conf.set(K.APPLICATION_QUEUE, "big", "t")
    validate_queue_quota(conf)           # fits big's 32
    conf.set(K.APPLICATION_QUEUE, "nosuch", "t")
    with pytest.raises(ValueError, match="unknown queue 'nosuch'"):
        validate_queue_quota(conf)
    # "queues" never becomes a jobtype
    assert "queues" not in conf.job_types()
