"""Live log streaming + failure diagnostics (observability/logs.py).

Unit tier: redaction shapes, the error-signature table, exit/signal
decoding, the bounded LogTail cursor contract, structured JSON-lines
logging, and the control-plane hygiene static checks (no bare print;
every event type has a renderer).

E2E tier (chaos marker): a TEST_TASK_KILL-ed job's diagnostics.json
names the correct first-failing task + signature with a redacted tail; a
self-SIGKILLed victim pins signal attribution through the executor's own
report; `logs --follow` streams a RUNNING task live through the AM with
config-bounded chunks on every hop.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants as C
from tony_tpu.observability.logs import (
    LogTail, SIGNATURES, StructuredLogHandler, classify,
    classify_container_failure, configure_structured_logging, decode_exit,
    parse_structured_line, redact, tail_excerpt,
)

from tests.chaos import ChaosRun, KillTask, fast_conf, script

pytestmark = pytest.mark.logs

PLANTED = "deadbeef" * 8      # 64-hex: the token scheme's shape


# ---------------------------------------------------------------------------
# redaction
# ---------------------------------------------------------------------------

def test_redact_token_shapes():
    assert PLANTED not in redact(f"boot with {PLANTED} inline")
    assert PLANTED not in redact(f"TONY_SECURITY_TOKEN={PLANTED}")
    assert PLANTED not in redact(f"Authorization: Bearer {PLANTED}")
    assert "secret" not in redact("api_key=secret").split("=", 1)[1]
    assert redact("my-password: hunter2").endswith("<redacted>")
    # non-credentials survive
    assert redact("loss at step 100: 2.345") == "loss at step 100: 2.345"
    # 40-hex (not the token shape) survives — no overzealous scrubbing
    sha = "a" * 40
    assert sha in redact(f"commit {sha}")


def test_redact_is_idempotent_and_line_safe():
    once = redact(f"x={PLANTED}\nBearer {PLANTED}\nplain line")
    assert redact(once) == once
    assert "plain line" in once


# ---------------------------------------------------------------------------
# signature classification + exit decoding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("line,expected", [
    ("RESOURCE_EXHAUSTED: out of memory allocating 16G", "device_oom"),
    ("jaxlib.xla_extension.XlaRuntimeError: INTERNAL: Mosaic failed",
     "xla_compile_failure"),
    ("ERROR: gang rendezvous timed out after 300s", "rendezvous_timeout"),
    ("step 400: loss became NaN", "nan_loss"),
    ("bash: line 1: 723 Killed  python train.py", "preempted"),
    ("ModuleNotFoundError: No module named 'flash_attn'", "import_error"),
])
def test_classifier_signatures(line, expected):
    got = classify(f"benign preamble\n{line}\ntrailing info")
    assert got is not None and got["signature"] == expected
    assert got["hint"]


def test_classifier_last_match_wins_and_redacts():
    text = (f"ImportError: early noise\n"
            f"token={PLANTED}\n"
            f"RESOURCE_EXHAUSTED: out of memory (token={PLANTED})")
    got = classify(text)
    assert got["signature"] == "device_oom"   # the LAST error line wins
    assert PLANTED not in got["line"]


def test_classify_none_on_benign_output():
    assert classify("step 1 ok\nstep 2 ok\n") is None


def test_decode_exit_signal_attribution():
    assert decode_exit(-9)["signal_name"] == "SIGKILL"
    assert decode_exit(137)["signal_name"] == "SIGKILL"
    assert decode_exit(-15)["signal_name"] == "SIGTERM"
    assert decode_exit(1) == {"exit_code": 1, "signal": 0,
                              "signal_name": ""}
    assert decode_exit(None)["signal"] == 0


# ---------------------------------------------------------------------------
# LogTail: bounded offset-cursor reads
# ---------------------------------------------------------------------------

def test_logtail_cursor_contract(tmp_path):
    path = tmp_path / "stderr"
    lines = [f"line {i:04d}" for i in range(200)]
    path.write_text("\n".join(lines) + "\n")
    tail = LogTail(str(path), tail_bytes=4096, chunk_bytes=256)

    # fresh cursor starts AT MOST tail_bytes back, never at 0
    first = tail.read_chunk(offset=-1)
    assert first["offset"] >= tail.size() - 4096
    # every chunk obeys the cap no matter what the caller asks
    big = tail.read_chunk(offset=0, max_bytes=10_000_000)
    assert big["next_offset"] - big["offset"] <= 256

    # cursor walk reassembles the stream exactly (from the first offset)
    out, offset = [], first["offset"]
    for _ in range(100):
        chunk = tail.read_chunk(offset=offset, final=True)
        if not chunk["data"] and chunk["eof"]:
            break
        out.append(chunk["data"])
        offset = chunk["next_offset"]
    text = "".join(out)
    assert text.endswith("line 0199\n")
    assert "line 0190" in text


def test_logtail_holds_back_partial_lines_until_final(tmp_path):
    path = tmp_path / "stderr"
    # credential split across a chunk boundary must never ship
    # half-redacted: the unterminated line is held back entirely
    path.write_text(f"complete line\npartial token={PLANTED}")
    tail = LogTail(str(path), chunk_bytes=1 << 16)
    live = tail.read_chunk(offset=0, final=False)
    assert live["data"] == "complete line\n"
    assert PLANTED not in live["data"]
    done = tail.read_chunk(offset=0, final=True)
    assert "partial token=" in done["data"]
    assert PLANTED not in done["data"]       # redacted once complete
    assert done["eof"] is True


def test_logtail_never_splits_a_credential_across_chunks(tmp_path):
    """Mid-FILE chunk boundaries (not just EOF) end on line boundaries:
    a token straddling byte `chunk_bytes` must arrive intact in one
    chunk and be redacted — both for live follows and for final reads of
    large completed logs."""
    path = tmp_path / "stderr"
    pad = "x" * 240
    path.write_text(f"{pad}\ntoken={PLANTED}\n" + "tail line\n" * 50)
    for final in (False, True):
        out, offset = [], 0
        for _ in range(100):
            chunk = LogTail(str(path), chunk_bytes=256).read_chunk(
                offset=offset, final=final)
            if not chunk["data"]:
                break
            out.append(chunk["data"])
            offset = chunk["next_offset"]
        text = "".join(out)
        assert PLANTED not in text, f"token leaked (final={final})"
        assert "token=<redacted>" in text, text[:400]
        assert text.count("tail line") == 50


def test_tail_excerpt_and_container_classification(tmp_path):
    cdir = tmp_path / "worker_1_s0"
    cdir.mkdir()
    (cdir / "stdout").write_text("model compiled\n")
    (cdir / "stderr").write_text(
        f"TONY_SECURITY_TOKEN={PLANTED}\n"
        + "\n".join(f"noise {i}" for i in range(300))
        + "\nRESOURCE_EXHAUSTED: out of memory\n")
    record = classify_container_failure(str(cdir), exit_code=1,
                                        max_lines=50)
    assert record["signature"] == "device_oom"
    assert record["exit_code"] == 1 and record["signal"] == 0
    assert len(record["tail"]["stderr"]) == 50       # line budget
    dumped = json.dumps(record)
    assert PLANTED not in dumped
    # SIGKILL with no matching line still classifies as preemption
    (cdir / "stderr").write_text("running fine\n")
    record = classify_container_failure(str(cdir), exit_code=-9,
                                        max_lines=50)
    assert record["signature"] == "preempted"
    assert record["signal_name"] == "SIGKILL"
    # excerpt primitive drops empty/missing streams
    excerpt = tail_excerpt(str(cdir), 10)
    assert set(excerpt) == {"stdout", "stderr"}


# ---------------------------------------------------------------------------
# structured JSON-lines logging
# ---------------------------------------------------------------------------

def test_structured_handler_stamps_context():
    stream = io.StringIO()
    logger = logging.getLogger("test.structured")
    logger.propagate = False
    handler = StructuredLogHandler(
        {"app_id": "app_1", "task_type": "worker", "index": 1,
         "attempt": 2, "trace_id": "app_1"}, stream=stream)
    logger.addHandler(handler)
    try:
        logger.warning("heartbeat failed (%d consecutive)", 3)
    finally:
        logger.removeHandler(handler)
    entry = parse_structured_line(stream.getvalue())
    assert entry is not None
    assert entry["message"] == "heartbeat failed (3 consecutive)"
    assert entry["level"] == "WARNING"
    assert (entry["app_id"], entry["task_type"], entry["index"],
            entry["attempt"], entry["trace_id"]) \
        == ("app_1", "worker", 1, 2, "app_1")
    assert entry["ts_ms"] > 0


def test_configure_structured_logging_reads_env_contract():
    env = {C.APP_ID: "app_9", C.JOB_NAME: "worker", C.TASK_INDEX: "3",
           C.TASK_ATTEMPT: "1", C.TONY_TRACE_ID: "app_9"}
    root = logging.getLogger()
    saved = root.handlers[:]
    try:
        handler = configure_structured_logging(env=env,
                                               stream=io.StringIO())
        assert isinstance(handler, StructuredLogHandler)
        assert handler.context["app_id"] == "app_9"
        assert handler.context["index"] == 3
        assert handler.context["attempt"] == 1
    finally:
        root.handlers[:] = saved


def test_plain_log_opt_out():
    root = logging.getLogger()
    saved = root.handlers[:]
    try:
        root.handlers[:] = []
        handler = configure_structured_logging(
            env={"TONY_LOG_PLAIN": "1"})
        assert not isinstance(handler, StructuredLogHandler)
    finally:
        root.handlers[:] = saved


# ---------------------------------------------------------------------------
# static checks (tier-1 CI hygiene) — migrated to tonylint
# (tools/tonylint/rules_legacy.py); these wrappers keep the coverage
# anchored here while the implementation lives with the other rules
# ---------------------------------------------------------------------------

def test_control_plane_emits_through_the_structured_logger():
    """No bare print() in am/, executor/, rpc/, portal/, serve/ — those
    processes log through observability/logs.py. Now a tonylint rule
    (`print-ban`, same `log-ok:` escape)."""
    from tools.tonylint import findings_for
    assert findings_for("print-ban") == []


def test_every_event_type_has_a_renderer():
    """Every EventType renders non-empty text on an empty payload. Now a
    tonylint rule (`renderer-coverage`)."""
    from tools.tonylint import findings_for
    assert findings_for("renderer-coverage") == []


def test_log_chunk_message_roundtrip():
    from tony_tpu.rpc.messages import LogChunk
    chunk = LogChunk(task_id="worker:0", stream="stdout", data="x\n",
                     offset=10, next_offset=12, size=12, eof=True,
                     source="aggregated")
    assert LogChunk.from_dict(chunk.to_dict()) == chunk
    assert LogChunk.from_dict({}).stream == "stderr"


# ---------------------------------------------------------------------------
# CLI diagnose (bundle file level)
# ---------------------------------------------------------------------------

def test_cli_diagnose_prints_bundle(tmp_path, capsys):
    from tony_tpu.cli.__main__ import diagnose
    bundle = {
        "app_id": "app_42", "status": "FAILED", "message": "boom",
        "first_failure": {
            "task_id": "worker:1", "attempt": 0, "exit_code": -9,
            "signal_name": "SIGKILL", "signature": "device_oom",
            "hint": "shrink the batch",
            "reason": "executor reported exit -9",
            "tail": {"stderr": ["RESOURCE_EXHAUSTED: oom",
                                "TONY_SECURITY_TOKEN=<redacted>"]},
        },
        "failures": [
            {"task_id": "worker:1", "attempt": 0},
            {"task_id": "worker:0", "attempt": 0,
             "reason": "collateral", "signature": ""},
        ],
    }
    path = tmp_path / "history" / "app_42" / C.DIAGNOSTICS_FILE
    path.parent.mkdir(parents=True)
    path.write_text(json.dumps(bundle))
    # app-dir resolution (the documented operator entrypoint)
    assert diagnose([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for needle in ("worker:1", "SIGKILL", "device_oom",
                   "RESOURCE_EXHAUSTED", "1 further failure"):
        assert needle in out, out
    assert diagnose([str(path), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["app_id"] == "app_42"
    assert diagnose([str(tmp_path / "nosuch")]) == 1


# ---------------------------------------------------------------------------
# chaos e2e: the acceptance pins
# ---------------------------------------------------------------------------

chaos = pytest.mark.chaos


@chaos
def test_chaos_killed_job_diagnostics_bundle(tmp_path):
    """Acceptance: a TEST_TASK_KILL-ed job (no relaunch budget) FAILS and
    its diagnostics.json names the correct first-failing task with the
    matched signature and a REDACTED tail excerpt; DIAGNOSTICS_READY
    lands in history; `cli diagnose` prints the same story; the portal
    renders the root-cause panel."""
    run = ChaosRun(tmp_path, seed=11)
    run.run(
        ["--executes", script("chaos_diag_worker.py"),
         "--conf", "tony.worker.instances=2",
         # short-circuit on the victim's failure instead of waiting for
         # the sleeping survivor — keeps the tier-1 case fast
         "--conf", "tony.application.fail-on-worker-failure-enabled=true"],
        injections=[KillTask("worker", 1, run.delay_ms(700, 1100),
                             attempt=0)],
        extra_env={"CHAOS_DIAG_VICTIM": "worker:1",
                   "CHAOS_PLANTED_TOKEN": PLANTED})
    assert run.final_status == "FAILED", run.all_logs()

    bundle = run.diagnostics()
    assert bundle, "diagnostics.json missing from history"
    first = bundle["first_failure"]
    assert first["task_id"] == "worker:1", bundle
    assert first["attempt"] == 0
    assert first["signature"] == "device_oom", first
    assert first["tail"]["stderr"], first
    dumped = json.dumps(bundle)
    assert PLANTED not in dumped, "planted token leaked into diagnostics"
    assert "<redacted>" in dumped

    # DIAGNOSTICS_READY rode the event log
    from tony_tpu.events.schema import EventType
    ready = run.events_of_type(EventType.DIAGNOSTICS_READY)
    assert len(ready) == 1
    assert ready[0].payload.first_failing_task == "worker:1"
    assert ready[0].payload.signature == "device_oom"
    assert ready[0].payload.path == C.DIAGNOSTICS_FILE

    # CLI prints the same bundle
    from tony_tpu.cli.__main__ import diagnose
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert diagnose([run.client.app_dir]) == 0
    out = buf.getvalue()
    assert "worker:1" in out and "device_oom" in out
    assert PLANTED not in out

    # portal failure panel over the same history tree
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    app_id = os.path.basename(run.app_history_dir())
    cache = PortalCache(os.path.dirname(run.app_history_dir()),
                        str(tmp_path / "finished"))
    server = PortalServer(cache, port=0, host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/jobs/{app_id}") as resp:
            page = resp.read().decode()
        assert "Root cause" in page
        assert "worker:1" in page and "device_oom" in page
        assert PLANTED not in page
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}"
                f"/api/jobs/{app_id}/diagnostics") as resp:
            api = json.loads(resp.read())
        assert api["first_failure"]["task_id"] == "worker:1"
    finally:
        server.stop()


@chaos
def test_sigkill_victim_pins_signal_through_executor_report(tmp_path):
    """A victim that dies BY SIGNAL (self-SIGKILL) reaches the bundle
    through the executor's own register_execution_result diagnostics:
    signal attribution, executor source, redacted tail."""
    run = ChaosRun(tmp_path, seed=12)
    run.run(
        ["--executes", script("chaos_diag_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.fail-on-worker-failure-enabled=true"],
        extra_env={"CHAOS_DIAG_VICTIM": "worker:1",
                   "CHAOS_DIAG_MODE": "sigkill",
                   "CHAOS_PLANTED_TOKEN": PLANTED})
    assert run.final_status == "FAILED", run.all_logs()
    bundle = run.diagnostics()
    first = bundle["first_failure"]
    assert first["task_id"] == "worker:1"
    assert first["signal_name"] == "SIGKILL", first
    assert first["source"] == "executor", first
    assert first["signature"] == "device_oom", first
    assert PLANTED not in json.dumps(bundle)


@chaos
def test_live_follow_streams_running_task(tmp_path):
    """Acceptance: `logs --follow` semantics against a live job — the
    offset-cursor loop streams a RUNNING task's stderr through the AM
    (live from the executor), every chunk stays under the configured
    cap, planted credentials never ship, and the cursor keeps working
    across task completion (aggregated source)."""
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.rpc.client import ClusterServiceClient

    conf = fast_conf(tmp_path, **{"tony.logs.chunk-bytes": 2048})
    os.environ["CHAOS_PLANTED_TOKEN"] = "cafebabe" * 8
    try:
        client = TonyClient(conf)
        client.init(["--executes", script("log_stream_task.py"),
                     "--conf", "tony.worker.instances=1"])
        result = {}

        def _run():
            result["ok"] = client.run()

        t = threading.Thread(target=_run, daemon=True)
        t.start()
        rpc = None
        collected, sources = [], set()
        offset, chunk_caps_ok = -1, True
        deadline = time.monotonic() + 90
        try:
            while time.monotonic() < deadline:
                if rpc is None:
                    hostport = os.path.join(client.app_dir or "",
                                            C.AM_HOSTPORT_FILE)
                    if not (client.app_dir and os.path.exists(hostport)):
                        time.sleep(0.1)
                        continue
                    with open(hostport, "r", encoding="utf-8") as f:
                        host, _, port = f.read().strip().rpartition(":")
                    rpc = ClusterServiceClient(host, int(port))
                try:
                    chunk = rpc.read_task_logs(stream="stderr",
                                               offset=offset)
                except Exception:  # noqa: BLE001 — AM gone: job finished
                    break
                if (chunk or {}).get("error"):
                    time.sleep(0.1)
                    continue
                if chunk.get("data"):
                    collected.append(chunk["data"])
                    sources.add(chunk.get("source"))
                    if chunk["next_offset"] - chunk["offset"] > 2048:
                        chunk_caps_ok = False
                offset = int(chunk.get("next_offset", offset))
                if "stream done" in "".join(collected[-3:]):
                    break
                time.sleep(0.05)
        finally:
            if rpc is not None:
                rpc.close()
        text = "".join(collected)
        assert "logline 0" in text and "logline 49" in text, text[-2000:]
        assert "stream done" in text
        assert "live" in sources, sources
        assert chunk_caps_ok, "a chunk exceeded tony.logs.chunk-bytes"
        assert "cafebabe" * 8 not in text
        assert "api_key=<redacted>" in text
        t.join(timeout=60)
        assert result.get("ok") is True
    finally:
        os.environ.pop("CHAOS_PLANTED_TOKEN", None)


@chaos
def test_superseded_attempt_logs_aggregated_at_relaunch(tmp_path):
    """Incremental aggregation: when a relaunch supersedes an attempt,
    the dead attempt's logs are copied into history AT THAT MOMENT (not
    only at application finish) — the evidence survives an AM crash. The
    job itself SUCCEEDS via the relaunch."""
    run = ChaosRun(tmp_path, seed=13)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.task.max-task-attempts=2"],
        injections=[KillTask("worker", 1, run.delay_ms(800, 1200),
                             attempt=0)])
    assert run.final_status == "SUCCEEDED", run.all_logs()
    logs_root = os.path.join(run.app_history_dir(),
                             C.HISTORY_LOGS_DIR_NAME)
    dirs = sorted(os.listdir(logs_root))
    # attempt 0's dir and the replacement's attempt-suffixed dir are
    # both in history
    assert "worker_1_s0" in dirs, dirs
    assert "worker_1_s0_a1" in dirs, dirs
