"""KV-cache generation (models/generate.py) vs the no-cache oracle: greedy
decode must match re-running the full training forward on the growing
sequence exactly (tiny config is f32 end to end)."""

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import decode_step, generate, prefill
from tony_tpu.models.llama import get_config, llama_forward, llama_init


def _setup(seed=0, b=2, p=8):
    cfg = get_config("tiny")
    params = llama_init(cfg, jax.random.PRNGKey(seed))
    prompt = jax.random.randint(jax.random.PRNGKey(seed + 1), (b, p), 0,
                                cfg.vocab_size, jnp.int32)
    return cfg, params, prompt


def _oracle_greedy(params, cfg, prompt, n):
    """No-cache reference: full forward over the growing sequence."""
    seq = prompt
    out = []
    for _ in range(n):
        logits = llama_forward(params, seq, cfg)
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
    return jnp.stack(out, axis=1)                          # (B, N)


def test_greedy_generate_matches_oracle():
    cfg, params, prompt = _setup()
    n = 6
    got = generate(params, cfg, prompt, n)
    want = _oracle_greedy(params, cfg, prompt, n)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prefill_logits_match_forward():
    cfg, params, prompt = _setup()
    logits, cache = prefill(params, prompt, cfg, cache_len=16)
    full = llama_forward(params, prompt, cfg)[:, -1]
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=2e-5, rtol=2e-5)
    # prompt K/V written, padding rows zero
    assert cache["k"].shape[3] == 16
    assert not np.allclose(np.asarray(cache["k"][:, :, :, :8]), 0.0)
    np.testing.assert_array_equal(
        np.asarray(cache["k"][:, :, :, 8:]), 0.0)


def test_decode_step_matches_forward_next_position():
    """One cached decode step == the full forward's logits at that spot."""
    cfg, params, prompt = _setup()
    logits, cache = prefill(params, prompt, cfg, cache_len=16)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_logits, _ = decode_step(params, cfg, cache, tok, jnp.int32(8))
    seq = jnp.concatenate([prompt, tok[:, None]], axis=1)
    want = llama_forward(params, seq, cfg)[:, -1]
    np.testing.assert_allclose(np.asarray(step_logits), np.asarray(want),
                               atol=3e-5, rtol=3e-5)


def test_eos_latches():
    """Once eos is emitted the rest of the row is eos."""
    cfg, params, prompt = _setup()
    want = _oracle_greedy(params, cfg, prompt, 8)
    eos = int(np.asarray(want)[0, 2])   # force an 'eos' mid-stream
    got = np.asarray(generate(params, cfg, prompt, 8, eos_id=eos))
    row = got[0]
    hits = np.where(row == eos)[0]
    assert hits.size, "chosen eos never emitted?"
    first = hits[0]
    assert (row[first:] == eos).all()


def test_sampled_generation_valid_and_reproducible():
    cfg, params, prompt = _setup()
    k = jax.random.PRNGKey(7)
    a = generate(params, cfg, prompt, 5, temperature=0.8, top_k=4, key=k)
    b = generate(params, cfg, prompt, 5, temperature=0.8, top_k=4, key=k)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < cfg.vocab_size)).all()


def test_top_p_nucleus_semantics():
    """top_p truncation: only tokens inside the smallest prefix whose
    probability mass reaches top_p can ever be sampled, the most
    probable token always survives, and top_p=1.0 is exactly the
    untruncated distribution."""
    from tony_tpu.models.generate import _sample

    probs = jnp.array([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    # mass 0.6 -> keep {0 (cum-p=0), 1 (cum-p=0.5)}; 2 (0.8) is out
    seen = {int(_sample(logits, 1.0, 0, jax.random.PRNGKey(i),
                        top_p=0.6)[0]) for i in range(64)}
    assert seen <= {0, 1} and 1 in seen, seen
    # a tiny mass keeps only the argmax — sampling degenerates to greedy
    seen = {int(_sample(logits, 1.0, 0, jax.random.PRNGKey(i),
                        top_p=1e-6)[0]) for i in range(16)}
    assert seen == {0}, seen
    # top_p=0 (CLI-reachable) must degrade to the argmax too, never to
    # a fully-masked row that categorical samples uniformly
    seen = {int(_sample(logits, 1.0, 0, jax.random.PRNGKey(i),
                        top_p=0.0)[0]) for i in range(16)}
    assert seen == {0}, seen
    # top_p=1.0 is a no-op: identical draws to the plain path per key
    for i in range(8):
        k = jax.random.PRNGKey(100 + i)
        assert int(_sample(logits, 1.0, 0, k, top_p=1.0)[0]) == \
            int(_sample(logits, 1.0, 0, k)[0])
    # end-to-end through generate(): reproducible and in-range
    cfg, params, prompt = _setup()
    k = jax.random.PRNGKey(8)
    a = generate(params, cfg, prompt, 5, temperature=0.9, top_p=0.8,
                 key=k)
    b = generate(params, cfg, prompt, 5, temperature=0.9, top_p=0.8,
                 key=k)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < cfg.vocab_size)).all()


def test_generate_budget_guard():
    cfg, params, prompt = _setup()
    import pytest
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, cfg, prompt, cfg.max_seq)


def test_generate_text_ragged_prompts_unaffected_by_batchmates():
    """Ragged prompts are grouped by length: a short prompt's output must
    equal generating it alone (no pad-token contamination)."""
    cfg, params, _ = _setup()

    class IdTok:
        def encode(self, s):
            return [int(c) % cfg.vocab_size for c in s.encode()]

        def decode(self, ids):
            return ",".join(str(i) for i in ids)

    from tony_tpu.models.generate import generate_text

    tok = IdTok()
    short, long_ = "ab", "abcdefgh"
    together = generate_text(params, cfg, [short, long_], tok,
                             max_new_tokens=4)
    alone = generate_text(params, cfg, [short], tok, max_new_tokens=4)
    assert together[0] == alone[0]


def test_generate_on_tp_mesh_matches_single_device():
    """Greedy decode with tp/fsdp-sharded params under an ambient mesh
    must produce the same tokens as the unsharded path (serving-style
    sharded inference; XLA inserts the collectives from shardings)."""
    from tony_tpu.models.llama import llama_param_axes
    from tony_tpu.parallel import make_mesh, plan_mesh, shard_pytree

    cfg, params, prompt = _setup()
    want = generate(params, cfg, prompt, 6)
    mesh = make_mesh(plan_mesh(8, tp=2))
    sharded = shard_pytree(params, llama_param_axes(cfg), mesh)
    with jax.set_mesh(mesh):
        got = generate(sharded, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
