"""MoE model tests: routing invariants, learning, expert-parallel step."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tony_tpu.models.moe import (
    get_moe_config, moe_init, moe_loss, moe_mlp, moe_param_axes,
)
from tony_tpu.parallel import make_mesh, plan_mesh, shard_pytree
from tony_tpu.train.step import make_train_step


def test_moe_mlp_routing_invariants():
    config = get_moe_config("moe_tiny", capacity_factor=10.0)  # no drops
    params = moe_init(config, jax.random.PRNGKey(0))
    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, config.dim))
    out, aux = moe_mlp(x, layer0, config)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))
    # aux loss is ~1 for perfectly balanced routing, bounded below by 1
    assert 0.5 < float(aux) < float(config.n_experts)


def test_moe_capacity_drops_tokens_but_stays_finite():
    config = get_moe_config("moe_tiny", capacity_factor=0.1)  # heavy drops
    params = moe_init(config, jax.random.PRNGKey(0))
    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, config.dim))
    out, _ = moe_mlp(x, layer0, config)
    assert np.all(np.isfinite(np.asarray(out)))
    # dropped tokens produce zero MLP output rows
    norms = np.linalg.norm(np.asarray(out).reshape(-1, config.dim), axis=-1)
    assert (norms == 0).any()


def test_moe_learns():
    config = get_moe_config("moe_tiny")
    params = moe_init(config, jax.random.PRNGKey(0))
    optimizer = optax.adam(3e-3)
    step = make_train_step(partial(moe_loss, config=config), optimizer)
    opt_state = jax.jit(optimizer.init)(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                config.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.5, (first, float(loss))


def test_moe_expert_parallel_step():
    """Full train step on a mesh with a real ep axis."""
    mesh = make_mesh(plan_mesh(8, ep=2, tp=2))
    config = get_moe_config("moe_tiny")
    params = moe_init(config, jax.random.PRNGKey(0))
    params = shard_pytree(params, moe_param_axes(config), mesh)
    # expert bank leading (layers, expert, ...) dims: expert dim on ep
    we_spec = params["layers"]["we_gate"].sharding.spec
    assert we_spec[1] == "ep", we_spec
    optimizer = optax.adam(1e-3)
    step = make_train_step(partial(moe_loss, config=config), optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                config.vocab_size, jnp.int32)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(optimizer.init)(params)
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": tokens})
    assert np.isfinite(float(loss))
