"""MoE model tests: routing invariants, learning, expert-parallel step."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tony_tpu.models.moe import (
    get_moe_config, moe_init, moe_loss, moe_mlp, moe_param_axes,
)
from tony_tpu.parallel import make_mesh, plan_mesh, shard_pytree
from tony_tpu.train.step import make_train_step


def test_moe_mlp_routing_invariants():
    config = get_moe_config("moe_tiny", capacity_factor=10.0)  # no drops
    params = moe_init(config, jax.random.PRNGKey(0))
    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, config.dim))
    out, aux = moe_mlp(x, layer0, config)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))
    # aux loss is ~1 for perfectly balanced routing, bounded below by 1
    assert 0.5 < float(aux) < float(config.n_experts)


def test_moe_capacity_drops_tokens_but_stays_finite():
    config = get_moe_config("moe_tiny", capacity_factor=0.1)  # heavy drops
    params = moe_init(config, jax.random.PRNGKey(0))
    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, config.dim))
    out, _ = moe_mlp(x, layer0, config)
    assert np.all(np.isfinite(np.asarray(out)))
    # dropped tokens produce zero MLP output rows
    norms = np.linalg.norm(np.asarray(out).reshape(-1, config.dim), axis=-1)
    assert (norms == 0).any()


def test_moe_learns():
    config = get_moe_config("moe_tiny")
    params = moe_init(config, jax.random.PRNGKey(0))
    optimizer = optax.adam(3e-3)
    step = make_train_step(partial(moe_loss, config=config), optimizer)
    opt_state = jax.jit(optimizer.init)(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                config.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.5, (first, float(loss))


def test_sparse_dense_dispatch_parity():
    """The sparse slot-indexed dispatch and the dense one-hot einsum
    formulation implement identical routing semantics — same outputs,
    including capacity drops (VERDICT r2 item 4)."""
    for cap in (10.0, 0.5):     # no drops / heavy drops (sentinel path)
        sparse_cfg = get_moe_config("moe_tiny", capacity_factor=cap)
        dense_cfg = get_moe_config("moe_tiny", capacity_factor=cap,
                                   dispatch_mode="dense")
        params = moe_init(sparse_cfg, jax.random.PRNGKey(0))
        layer0 = jax.tree.map(lambda p: p[0], params["layers"])
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32,
                                                      sparse_cfg.dim))
        out_s, aux_s = moe_mlp(x, layer0, sparse_cfg)
        out_d, aux_d = moe_mlp(x, layer0, dense_cfg)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux_s) == float(aux_d)


def test_sparse_dispatch_flops_near_ideal():
    """VERDICT r2 item 4 acceptance: at E=8/top-2 the sparse dispatch's
    compiled FLOPs stay within 1.3x of ideal (router + expert matmuls),
    while the dense one-hot dispatch costs O(k*T^2*D) extra."""
    T, D, F, E, k = 1024, 256, 512, 8, 2
    config = get_moe_config(
        "moe_tiny", dim=D, ffn_dim=F, n_experts=E, top_k=k)
    params = moe_init(config, jax.random.PRNGKey(0))
    layer0 = jax.tree.map(lambda p: p[0], params["layers"])
    x = jnp.zeros((2, T // 2, D), jnp.float32)
    C = max(1, int(config.capacity_factor * T * k / E))

    def flops(cfg):
        compiled = jax.jit(
            partial(moe_mlp, layer=layer0, config=cfg)).lower(x).compile()
        analysis = compiled.cost_analysis()
        analysis = analysis[0] if isinstance(analysis, list) else analysis
        return float(analysis["flops"])

    ideal = 2 * T * D * E + 3 * 2 * E * C * D * F   # router + expert bank
    sparse = flops(config)
    dense = flops(get_moe_config(
        "moe_tiny", dim=D, ffn_dim=F, n_experts=E, top_k=k,
        dispatch_mode="dense"))
    assert sparse <= 1.3 * ideal, (sparse, ideal)
    # the dense path's dispatch/combine einsums alone add ~2*2*T*E*C*D
    assert dense >= sparse + 2 * T * E * C * D, (dense, sparse)


def test_moe_expert_parallel_step():
    """Full train step on a mesh with a real ep axis (sparse dispatch —
    the default — compiling and executing under an ep-sharded bank)."""
    mesh = make_mesh(plan_mesh(8, ep=2, tp=2))
    config = get_moe_config("moe_tiny")
    params = moe_init(config, jax.random.PRNGKey(0))
    params = shard_pytree(params, moe_param_axes(config), mesh)
    # expert bank leading (layers, expert, ...) dims: expert dim on ep
    we_spec = params["layers"]["we_gate"].sharding.spec
    assert we_spec[1] == "ep", we_spec
    optimizer = optax.adam(1e-3)
    step = make_train_step(partial(moe_loss, config=config), optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                config.vocab_size, jnp.int32)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(optimizer.init)(params)
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": tokens})
    assert np.isfinite(float(loss))
