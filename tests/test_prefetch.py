"""Overlapped input pipeline contracts (docs/HOTLOOP.md):

- PrefetchIterator yields a byte-identical stream to the synchronous
  global_batch_iterator path (same seed/step/process_index determinism);
- an early close never leaks the producer thread;
- the device queue is bounded at `depth` (the producer blocks, it never
  runs ahead unboundedly);
- producer-side exceptions and exhaustion surface on the consumer;
- the vectorized synthetic_tokens matches the O(seq) loop reference
  bit-for-bit and beats it by >=5x host-side at long sequence lengths.
"""

import queue
import threading
import time

import numpy as np
import pytest

from tony_tpu.train.data import (
    PrefetchIterator, _synthetic_tokens_loop, global_batch_iterator,
    synthetic_linreg, synthetic_mnist, synthetic_tokens,
)


def _host(batch):
    return {k: np.asarray(v) for k, v in batch.items()}


# --------------------------------------------------------------------------
# PrefetchIterator
# --------------------------------------------------------------------------

def test_prefetch_byte_identical_to_sync_path():
    """Same (seed, step, process_index) source -> identical streams; the
    background thread must consume the local iterator strictly in order."""
    kw = dict(batch_size=4, seq_len=33, vocab_size=256, seed=5,
              process_index=2)
    sync = global_batch_iterator(synthetic_tokens(**kw))
    with PrefetchIterator(synthetic_tokens(**kw), depth=3) as pre:
        for _ in range(8):
            a, b = _host(next(sync)), _host(next(pre))
            assert a.keys() == b.keys()
            for k in a:
                assert a[k].dtype == b[k].dtype
                np.testing.assert_array_equal(a[k], b[k])


def test_prefetch_identical_for_all_synthetic_families():
    for make in (lambda: synthetic_mnist(8, seed=1),
                 lambda: synthetic_linreg(8, seed=1)):
        sync = global_batch_iterator(make())
        with PrefetchIterator(make()) as pre:
            for _ in range(3):
                a, b = _host(next(sync)), _host(next(pre))
                for k in a:
                    np.testing.assert_array_equal(a[k], b[k])


def test_prefetch_bounds_queue_depth():
    """With no consumer, the producer may be at most depth batches in the
    queue plus one in flight — never further into the source."""
    pulled = [0]

    def counting():
        while True:
            pulled[0] += 1
            yield {"x": np.zeros(4, np.float32)}

    with PrefetchIterator(counting(), depth=2,
                          transfer=lambda b: b) as pre:
        deadline = time.monotonic() + 2.0
        while pulled[0] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)   # would overshoot here if the queue were unbounded
        assert pulled[0] <= 3, pulled[0]
        # draining frees slots and the producer advances again
        for _ in range(4):
            next(pre)
        deadline = time.monotonic() + 2.0
        while pulled[0] < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pulled[0] >= 5


def test_prefetch_early_close_joins_thread():
    """close() mid-stream (producer blocked on a full queue) must stop and
    join the thread — no leak, and it must be idempotent."""
    pre = PrefetchIterator(synthetic_tokens(2, 16, 64), depth=1,
                           transfer=lambda b: b)
    next(pre)
    thread = pre._thread
    assert thread.is_alive()
    pre.close()
    assert not thread.is_alive()
    pre.close()   # idempotent
    with pytest.raises(StopIteration):
        next(pre)
    assert all(t.name != "tony-prefetch" for t in threading.enumerate())


def test_prefetch_propagates_producer_exception():
    def boom():
        yield {"x": np.zeros(1)}
        raise RuntimeError("generator exploded")

    with PrefetchIterator(boom(), transfer=lambda b: b) as pre:
        next(pre)
        with pytest.raises(RuntimeError, match="generator exploded"):
            next(pre)


def test_prefetch_finite_source_stops_cleanly():
    src = [{"x": np.full(2, i, np.int32)} for i in range(3)]
    with PrefetchIterator(iter(src), transfer=lambda b: b) as pre:
        got = list(pre)
    assert [int(b["x"][0]) for b in got] == [0, 1, 2]


def test_prefetch_stall_accounting():
    with PrefetchIterator(synthetic_tokens(2, 8, 64),
                          transfer=lambda b: b) as pre:
        s0, n0 = pre.stall_snapshot()
        assert (s0, n0) == (0.0, 0)
        next(pre)
        next(pre)
        s1, n1 = pre.stall_snapshot()
        assert n1 == 2 and s1 >= 0.0


def test_prefetch_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        PrefetchIterator(iter([]), depth=0)


def test_prefetch_close_hands_undelivered_batches_to_successor():
    """Batches the producer pulled from the shared source but never
    yielded survive close() on .leftover; a successor constructed with
    initial=leftover resumes the stream with no gap and no duplicates —
    regardless of how far the producer had run ahead."""
    src = iter([{"x": np.full(1, i, np.int32)} for i in range(6)])
    pre = PrefetchIterator(src, depth=2, transfer=lambda b: b)
    assert not pre.closed
    first = next(pre)
    assert int(first["x"][0]) == 0
    time.sleep(0.2)   # let the producer run ahead into the queue
    pre.close()
    assert pre.closed
    with PrefetchIterator(src, depth=2, transfer=lambda b: b,
                          initial=pre.leftover) as succ:
        rest = [int(b["x"][0]) for b in succ]
    assert rest == [1, 2, 3, 4, 5]


def test_prefetch_terminal_item_survives_get_timeout_race():
    """The lost-wakeup interleaving: the consumer's queue poll times out
    just as the producer enqueues its terminal item and exits. The final
    non-blocking drain must still observe it — a producer error must
    never be swallowed as clean exhaustion."""
    def boom():
        raise RuntimeError("terminal explosion")
        yield  # pragma: no cover — makes this a generator

    pre = PrefetchIterator(boom(), transfer=lambda b: b)
    pre._thread.join(2.0)
    assert not pre._thread.is_alive()
    real_get = pre._q.get

    def raced_get(*args, **kwargs):
        if kwargs.get("timeout") is not None:
            raise queue.Empty       # the poll that lost the race
        return real_get(*args, **kwargs)

    pre._q.get = raced_get
    try:
        with pytest.raises(RuntimeError, match="terminal explosion"):
            next(pre)
    finally:
        pre._q.get = real_get
        pre.close()


# --------------------------------------------------------------------------
# synthetic_tokens vectorization
# --------------------------------------------------------------------------

@pytest.mark.parametrize("batch,seq,vocab", [
    (4, 1, 7), (3, 37, 256), (2, 128, 2), (2, 100, 128256), (1, 64, 3),
])
def test_vectorized_tokens_match_loop_exactly(batch, seq, vocab):
    """The affine prefix scan must be BIT-identical to the loop reference
    — same RNG draw order, same int32 result — across vocab sizes
    including tiny moduli and odd (non-power-of-2) sequence lengths."""
    vec = synthetic_tokens(batch, seq, vocab, seed=9, process_index=3)
    ref = _synthetic_tokens_loop(batch, seq, vocab, seed=9,
                                 process_index=3)
    for _ in range(4):
        a, b = next(vec)["tokens"], next(ref)["tokens"]
        assert a.dtype == b.dtype == np.int32
        np.testing.assert_array_equal(a, b)


def test_vectorized_tokens_obey_recurrence():
    toks = next(synthetic_tokens(4, 50, 101, seed=2))["tokens"]
    assert ((0 <= toks) & (toks < 101)).all()
    diff = (toks[:, 1:] - 3 * toks[:, :-1]) % 101
    assert np.isin(diff, (0, 1)).all()


def test_vectorized_tokens_speedup_at_long_seq():
    """The acceptance bar: >=5x host-side batch generation at
    seq_len >= 1024. The loop reference pays O(seq) numpy dispatches per
    batch; the scan pays ~2*log2(seq). Median-of-3 timing to keep the
    assertion robust on loaded CI hosts (observed ~10-20x)."""
    batch, seq, vocab = 4, 2048, 128256
    vec = synthetic_tokens(batch, seq, vocab)
    ref = _synthetic_tokens_loop(batch, seq, vocab)
    next(vec), next(ref)   # warm allocators

    def med3(it):
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            next(it)
            times.append(time.perf_counter() - t0)
        return sorted(times)[1]

    t_ref, t_vec = med3(ref), med3(vec)
    assert t_ref / t_vec >= 5.0, (
        f"vectorized synthetic_tokens only {t_ref / t_vec:.1f}x faster "
        f"(loop {t_ref * 1e3:.2f} ms vs vec {t_vec * 1e3:.2f} ms)")
