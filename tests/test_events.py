"""Event history tests (reference model: events/TestEventHandler.java:76-136,
util/TestHistoryFileUtils.java)."""

import os

from tony_tpu.events import (
    Event, EventType, ApplicationInited, ApplicationFinished,
    ServingEndpointRegistered, TaskStarted, TaskFinished, EventHandler,
    JobMetadata, history_file_name, parse_history_file_name,
)
from tony_tpu.events.handler import parse_events
from tony_tpu.events.history import inprogress_file_name


def test_filename_codec_roundtrip():
    md = JobMetadata(application_id="application_123_456", started=1000,
                     completed=2000, user="alice", status="SUCCEEDED")
    name = history_file_name(md)
    assert name == "application_123_456-1000-2000-alice-SUCCEEDED.jhist"
    back = parse_history_file_name(name)
    assert back == md


def test_inprogress_filename_roundtrip():
    md = JobMetadata(application_id="app_1", started=5, user="bob")
    name = inprogress_file_name(md)
    assert name == "app_1-5-bob.jhist.inprogress"
    back = parse_history_file_name(name)
    assert back.application_id == "app_1"
    assert back.started == 5
    assert back.user == "bob"
    assert back.status == "RUNNING"


def test_event_handler_e2e(tmp_path):
    md = JobMetadata(application_id="app_42", started=100, user="carol")
    handler = EventHandler(str(tmp_path), md)
    handler.start()
    handler.emit(Event(EventType.APPLICATION_INITED,
                       ApplicationInited("app_42", 2, "amhost")))
    handler.emit(Event(EventType.TASK_STARTED, TaskStarted("worker", 0, "h0")))
    handler.emit(Event(EventType.TASK_FINISHED,
                       TaskFinished("worker", 0, "SUCCEEDED",
                                    [{"name": "m", "value": 1.0}])))
    handler.emit(Event(EventType.APPLICATION_FINISHED,
                       ApplicationFinished("app_42", "SUCCEEDED")))
    final = handler.stop("SUCCEEDED")

    assert os.path.basename(final).startswith("app_42-100-")
    assert final.endswith("-carol-SUCCEEDED.jhist")
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           inprogress_file_name(md)))
    events = parse_events(final)
    assert [e.type for e in events] == [
        EventType.APPLICATION_INITED, EventType.TASK_STARTED,
        EventType.TASK_FINISHED, EventType.APPLICATION_FINISHED]
    assert events[2].payload.metrics == [{"name": "m", "value": 1.0}]


def test_serving_endpoint_event_roundtrip(tmp_path):
    """The serving subsystem's schema entry: SERVING_ENDPOINT_REGISTERED
    survives the write→parse roundtrip with its payload intact."""
    md = JobMetadata(application_id="app_srv", started=7, user="eve")
    handler = EventHandler(str(tmp_path), md)
    handler.start()
    handler.emit(Event(EventType.SERVING_ENDPOINT_REGISTERED,
                       ServingEndpointRegistered(
                           "serving", 0, "http://h1:8080")))
    final = handler.stop("KILLED")
    events = parse_events(final)
    assert [e.type for e in events] == [
        EventType.SERVING_ENDPOINT_REGISTERED]
    p = events[0].payload
    assert isinstance(p, ServingEndpointRegistered)
    assert (p.task_type, p.task_index, p.url) == \
        ("serving", 0, "http://h1:8080")
    # dict-level codec (what the portal's event cache serves)
    back = Event.from_dict(events[0].to_dict())
    assert back.payload == p


def test_emit_after_stop_drops(tmp_path):
    md = JobMetadata(application_id="app_9", started=1, user="d")
    handler = EventHandler(str(tmp_path), md)
    handler.start()
    handler.stop("FAILED")
    # must not raise
    handler.emit(Event(EventType.TASK_STARTED, TaskStarted("w", 0, "h")))
