"""Cross-task skew analytics + straggler detection (ISSUE 7).

Unit layer: the QuantileSketch's accuracy/memory contract, the
SkewTracker's windowing (cumulative deltas, heatmap, O(buckets)
accounting), the StragglerAnalyzer's latch/clear/remediation state
machine, event schema + renderers, the MetricsStore skew sink, the
portal's /api/jobs/:id/skew, and the CLI `stragglers` offline renderer.

E2E layer (chaos): a TEST_TRAINER_STEP_DELAY-injected straggler in an
8-task gang on the genuine client → AM → executor → user-python chain —
detected with the right task id and steady-state phase attribution,
rendered by portal + CLI from history; a healthy gang of the same width
stays silent; and with the remediation knob set, the straggler is
relaunched through the task-attempt machinery and the latch clears.
"""

from __future__ import annotations

import json
import os
import random
import urllib.request

import pytest

from tony_tpu.events.schema import EventType
from tony_tpu.observability.skew import (
    QuantileSketch, SkewTracker, StragglerAnalyzer,
)

pytestmark = pytest.mark.stragglers

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def tick(self, s: float) -> None:
        self.t += s


# ---------------------------------------------------------------------------
# quantile sketch
# ---------------------------------------------------------------------------

def test_sketch_quantiles_within_bucket_error():
    sk = QuantileSketch(buckets=96)
    rng = random.Random(7)
    values = sorted(rng.uniform(50.0, 5000.0) for _ in range(20_000))
    for v in values:
        sk.add(v)
    for q in (0.5, 0.95, 0.99):
        exact = values[int(q * len(values)) - 1]
        assert sk.quantile(q) == pytest.approx(exact, rel=0.15), q
    assert sk.count == len(values)
    assert sk.mean == pytest.approx(sum(values) / len(values), rel=1e-6)


def test_sketch_memory_is_buckets_not_samples():
    sk = QuantileSketch(buckets=64)
    cells0 = sk.cells()
    for i in range(100_000):
        sk.add(float(i % 977) + 0.5)
    assert sk.cells() == cells0 == 66       # 64 + under/overflow
    assert sk.count == 100_000


def test_sketch_under_overflow_and_merge():
    sk = QuantileSketch(buckets=16, lo=1.0, hi=1000.0)
    sk.add(0.001)           # underflow
    sk.add(5e6)             # overflow
    sk.add(100.0)
    assert sk.count == 3
    assert sk.quantile(0.0) == pytest.approx(0.001)
    assert sk.quantile(1.0) == pytest.approx(5e6)
    other = QuantileSketch(buckets=16, lo=1.0, hi=1000.0)
    other.add(200.0)
    sk.merge(other)
    assert sk.count == 4
    with pytest.raises(ValueError):
        sk.merge(QuantileSketch(buckets=8, lo=1.0, hi=1000.0))


def test_sketch_ignores_nan_inf():
    sk = QuantileSketch()
    sk.add(float("nan"))
    sk.add(float("inf"))
    assert sk.count == 0
    assert sk.quantile(0.5) == 0.0


# ---------------------------------------------------------------------------
# skew tracker
# ---------------------------------------------------------------------------

def _fill_window(tr, width=8, slow_index=None, slow_ms=300.0,
                 base_ms=100.0, samples=4):
    for i in range(width):
        v = slow_ms if i == slow_index else base_ms
        for _ in range(samples):
            tr.observe_metric(f"worker:{i}", "TRAIN_STEP_TIME_MS", v)


def test_tracker_windows_and_heatmap():
    clock = FakeClock()
    tr = SkewTracker(buckets=32, heatmap_windows=3, clock=clock)
    for w in range(5):
        _fill_window(tr, slow_index=7)
        clock.tick(1.0)
        closed = tr.maybe_roll(window_ms=500)
        assert closed is not None
        gang = closed["step_time_ms"]["gang"]
        assert gang["count"] == 32
        assert closed["step_time_ms"]["tasks"]["worker:7"] == 300.0
    hm = tr.heatmap("step_time_ms")
    # bounded by heatmap_windows, newest retained
    assert len(hm["window_ends_ms"]) == 3
    assert hm["tasks"]["worker:0"] == [100.0, 100.0, 100.0]
    assert hm["tasks"]["worker:7"] == [300.0, 300.0, 300.0]


def test_tracker_roll_respects_window_age():
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    tr.observe("worker:0", "step_time_ms", 10.0)
    # window just opened: too young to close
    assert tr.maybe_roll(window_ms=5000) is None
    clock.tick(10.0)
    assert tr.maybe_roll(window_ms=5000) is not None
    # nothing observed since: nothing to roll even with force
    assert tr.maybe_roll(window_ms=0, force=True) is None


def test_tracker_cumulative_gauge_folds_deltas():
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    # GOODPUT_INPUT_STALL_SECONDS is cumulative: 1.0s then 1.5s -> the
    # second window must see the 0.5s delta (500 ms), not 1500 ms
    tr.observe_metric("worker:0", "GOODPUT_INPUT_STALL_SECONDS", 1.0)
    clock.tick(1.0)
    first = tr.maybe_roll(window_ms=500)
    assert first["input_stall_ms"]["tasks"]["worker:0"] == 1000.0
    tr.observe_metric("worker:0", "GOODPUT_INPUT_STALL_SECONDS", 1.5)
    clock.tick(1.0)
    second = tr.maybe_roll(window_ms=500)
    assert second["input_stall_ms"]["tasks"]["worker:0"] == \
        pytest.approx(500.0)


def test_tracker_startup_values_and_clear_task():
    tr = SkewTracker()
    tr.observe_metric("worker:3", "GOODPUT_LOCALIZATION_SECONDS", 9.0)
    tr.observe_metric("worker:3", "GOODPUT_COMPILE_SECONDS", 2.0)
    sv = tr.startup_values()
    assert sv["localization_ms"]["worker:3"] == 9000.0
    assert sv["compile_ms"]["worker:3"] == 2000.0
    tr.clear_task("worker:3")
    assert tr.startup_values()["localization_ms"] == {}


def test_tracker_state_is_o_buckets_not_o_width():
    """The tentpole's memory contract at width 1024: sketch cells pinned
    at the width-independent ceiling, per-task retention a few scalars
    per window — never a sample list."""
    clock = FakeClock()
    buckets = 64
    tr = SkewTracker(buckets=buckets, heatmap_windows=4, clock=clock)
    width = 1024
    for w in range(6):
        for i in range(width):
            for _ in range(50):     # 50 samples/task/window
                tr.observe_metric(f"worker:{i}", "TRAIN_STEP_TIME_MS",
                                  100.0 + i % 7)
        assert tr.sketch_cells() <= tr.max_sketch_cells()
        assert tr.max_sketch_cells() == 3 * (buckets + 2)
        clock.tick(1.0)
        tr.maybe_roll(window_ms=500)
    # retained per task: heatmap means only (windows are closed) — far
    # below the 50 samples/window that were offered
    assert tr.per_task_cells() <= width * 4 * 3


# ---------------------------------------------------------------------------
# straggler analyzer
# ---------------------------------------------------------------------------

def _closed(width=8, slow_index=None, slow_ms=300.0, base_ms=100.0):
    tasks = {f"worker:{i}": (slow_ms if i == slow_index else base_ms)
             for i in range(width)}
    return {"step_time_ms": {"start_ms": 0, "end_ms": 1000,
                             "gang": {}, "tasks": tasks}}


def test_analyzer_latches_after_consecutive_windows():
    an = StragglerAnalyzer(threshold_pct=50, windows=3, min_tasks=3)
    for w in range(2):
        actions, rem = an.analyze(_closed(slow_index=5))
        assert actions == [] and rem == []
    actions, _ = an.analyze(_closed(slow_index=5))
    assert len(actions) == 1
    a = actions[0]
    assert (a["action"], a["task_id"], a["phase"]) == \
        ("detected", "worker:5", "steady_state")
    assert a["signal"] == "step_time_ms"
    assert a["value_ms"] == 300.0
    assert a["gang_median_ms"] == 100.0
    assert a["z_score"] > 2
    assert an.active()[0]["task_id"] == "worker:5"
    # latched: no duplicate event while the condition persists
    actions, _ = an.analyze(_closed(slow_index=5))
    assert actions == []


def test_analyzer_clears_after_recovery():
    an = StragglerAnalyzer(threshold_pct=50, windows=2, min_tasks=3)
    an.analyze(_closed(slow_index=1))
    an.analyze(_closed(slow_index=1))
    assert an.active()
    an.analyze(_closed())               # healthy window 1
    actions, _ = an.analyze(_closed())  # healthy window 2 -> cleared
    assert [a["action"] for a in actions] == ["cleared"]
    assert actions[0]["reason"] == "recovered"
    # the clear reports the lagging streak that was latched, not the 0
    # the healthy run-up reset lag_windows to
    assert actions[0]["windows"] == 2
    assert an.active() == []
    log = an.log()
    assert [e["action"] for e in log] == ["detected", "cleared"]


def test_analyzer_false_positive_guards():
    # below min_tasks: silence
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=4)
    actions, _ = an.analyze(_closed(width=3, slow_index=0))
    assert actions == []
    # tiny absolute excess over a ~0 median: silence (min_excess_ms)
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=3)
    actions, _ = an.analyze(_closed(slow_index=2, slow_ms=0.04,
                                    base_ms=0.01))
    assert actions == []
    # healthy jitter under the threshold: silence
    actions, _ = an.analyze(_closed(slow_index=2, slow_ms=130.0))
    assert actions == []


def test_analyzer_startup_attribution():
    an = StragglerAnalyzer(threshold_pct=50, windows=2, min_tasks=3)
    startup = {"localization_ms": {f"worker:{i}": 500.0 for i in range(8)},
               "compile_ms": {f"worker:{i}": 1000.0 for i in range(8)}}
    startup["localization_ms"]["worker:6"] = 9000.0
    actions, _ = an.analyze({}, startup)
    assert len(actions) == 1
    a = actions[0]
    assert (a["action"], a["task_id"], a["phase"], a["signal"]) == \
        ("detected", "worker:6", "startup", "startup_ms")
    # one-shot: the same startup evidence never re-fires
    actions, _ = an.analyze({}, startup)
    assert actions == []
    # ...INCLUDING after a recovered-clear: healthy steady-state windows
    # release the latch, but the unchanged startup totals must not
    # re-detect (the clear/detect pair would otherwise flap forever)
    an.analyze(_closed(), startup)
    actions, _ = an.analyze(_closed(), startup)
    assert [x["action"] for x in actions] == ["cleared"]
    for _ in range(3):
        actions, _ = an.analyze(_closed(), startup)
        assert actions == []
    # a relaunch (clear_task) re-arms startup detection for the fresh
    # attempt — it localizes and compiles anew
    an.clear_task("worker:6")
    actions, _ = an.analyze({}, startup)
    assert [x["action"] for x in actions] == ["detected"]


def test_analyzer_startup_jitter_below_floor_is_silent():
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=3)
    startup = {"localization_ms": {f"worker:{i}": 20.0 for i in range(8)},
               "compile_ms": {}}
    startup["localization_ms"]["worker:1"] = 500.0   # < 1s absolute floor
    actions, _ = an.analyze({}, startup)
    assert actions == []


def test_tracker_rejects_non_finite_observations():
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    tr.observe("worker:0", "step_time_ms", float("-inf"))
    tr.observe("worker:0", "step_time_ms", float("nan"))
    tr.observe_metric("worker:0", "TRAIN_STEP_TIME_MS", float("inf"))
    assert tr.maybe_roll(window_ms=0, force=True) is None
    tr.observe("worker:0", "step_time_ms", 10.0)
    clock.tick(1.0)
    closed = tr.maybe_roll(window_ms=0, force=True)
    assert closed["step_time_ms"]["tasks"]["worker:0"] == 10.0


def test_analyzer_remediation_nomination_and_clear_task():
    an = StragglerAnalyzer(threshold_pct=50, windows=2, min_tasks=3,
                           relaunch_after_windows=4)
    rem = []
    for _ in range(4):
        _, rem = an.analyze(_closed(slow_index=3))
    assert [r["task_id"] for r in rem] == ["worker:3"]
    assert rem[0]["windows"] == 4
    cleared = an.clear_task("worker:3", reason="relaunched")
    assert cleared["action"] == "cleared"
    assert cleared["reason"] == "relaunched"
    assert an.active() == []
    # clearing an already-cleared slot is silent
    assert an.clear_task("worker:3") is None


def test_analyzer_latch_survives_gang_shrinking_below_min_tasks():
    """A still-slow latched straggler must not be auto-'recovered' when
    its healthy peers complete and the reporting gang falls below
    min_tasks — sub-min_tasks windows can neither latch nor clear."""
    an = StragglerAnalyzer(threshold_pct=50, windows=2, min_tasks=3)
    an.analyze(_closed(slow_index=2))
    an.analyze(_closed(slow_index=2))
    assert an.active()
    # peers finished: only the straggler still reports, at 300 ms
    shrunk = {"step_time_ms": {"start_ms": 0, "end_ms": 1000, "gang": {},
                               "tasks": {"worker:2": 300.0}}}
    for _ in range(5):
        actions, _ = an.analyze(shrunk)
        assert actions == []
    assert an.active(), "latch must survive an unjudgeable gang"


def test_analyzer_relaunch_disabled_by_default():
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=3)
    for _ in range(10):
        _, rem = an.analyze(_closed(slow_index=0))
        assert rem == []


# ---------------------------------------------------------------------------
# events + renderers + metrics-store sink
# ---------------------------------------------------------------------------

def test_straggler_events_roundtrip_and_render():
    from tony_tpu.events.render import render_event
    from tony_tpu.events.schema import (
        Event, StragglerCleared, StragglerDetected,
    )
    ev = Event(EventType.STRAGGLER_DETECTED,
               StragglerDetected("worker", 5, attempt=1,
                                 signal="step_time_ms",
                                 phase="steady_state", value_ms=300.0,
                                 gang_median_ms=100.0, z_score=2.6,
                                 windows=3, span_ids=["abc"]))
    back = Event.from_dict(ev.to_dict())
    assert back.payload == ev.payload
    text = render_event(ev.type.value, ev.to_dict()["payload"])
    assert "worker:5" in text and "steady_state" in text
    ev2 = Event(EventType.STRAGGLER_CLEARED,
                StragglerCleared("worker", 5, reason="relaunched",
                                 windows_lagging=4))
    assert "relaunched" in render_event(ev2.type.value,
                                        ev2.to_dict()["payload"])


def test_metrics_store_feeds_skew_sink():
    from tony_tpu.am.application_master import MetricsStore
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    store = MetricsStore()
    store.skew_sink = tr.observe_metric
    store.update_metrics(
        {"task_type": "worker", "index": 2,
         "metrics": [{"name": "TRAIN_STEP_TIME_MS", "value": 123.0},
                     {"name": "SOMETHING_ELSE", "value": 1.0},
                     {"name": "GOODPUT_COMPILE_SECONDS", "value": 3.0}]})
    clock.tick(1.0)
    closed = tr.maybe_roll(window_ms=500)
    assert closed["step_time_ms"]["tasks"]["worker:2"] == 123.0
    assert tr.startup_values()["compile_ms"]["worker:2"] == 3000.0


def test_bundle_shape_for_surfaces():
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=3)
    _fill_window(tr, slow_index=4)
    clock.tick(1.0)
    an.analyze(tr.maybe_roll(window_ms=500), tr.startup_values())
    bundle = tr.bundle(an)
    assert bundle["heatmap"]["signal"] == "step_time_ms"
    assert "worker:4" in bundle["heatmap"]["tasks"]
    assert bundle["stragglers"][0]["task_id"] == "worker:4"
    assert bundle["detections"][0]["action"] == "detected"
    gang = bundle["signals"]["step_time_ms"]["windows"][-1]["gang"]
    assert gang["count"] == 32
    assert json.loads(json.dumps(bundle)) == bundle   # JSON-clean


# ---------------------------------------------------------------------------
# surfaces: CLI + portal (sidecar level)
# ---------------------------------------------------------------------------

def _sample_bundle():
    clock = FakeClock()
    tr = SkewTracker(clock=clock)
    an = StragglerAnalyzer(threshold_pct=50, windows=1, min_tasks=3)
    for _ in range(3):
        _fill_window(tr, slow_index=4)
        clock.tick(1.0)
        an.analyze(tr.maybe_roll(window_ms=500), tr.startup_values())
    return tr.bundle(an)


def test_cli_stragglers_renders_bundle_offline(tmp_path, capsys):
    from tony_tpu.cli.__main__ import stragglers
    from tony_tpu.events.history import write_skew_file
    hist = tmp_path / "history" / "application_x_1"
    write_skew_file(str(hist), _sample_bundle())
    assert stragglers([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "worker:4" in out
    assert "steady_state" in out
    assert "heatmap" in out
    # --json dumps the raw bundle
    assert stragglers([str(tmp_path), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["stragglers"]


def test_cli_stragglers_missing_bundle(tmp_path, capsys):
    from tony_tpu.cli.__main__ import stragglers
    assert stragglers([str(tmp_path)]) == 1
    assert "no skew bundle" in capsys.readouterr().err


def test_portal_serves_skew_api_and_panel(tmp_path):
    from tony_tpu.events.handler import EventHandler
    from tony_tpu.events.history import JobMetadata, write_skew_file
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    inter = tmp_path / "inter"
    app = "application_skew_1"
    md = JobMetadata(application_id=app, started=1000)
    handler = EventHandler(str(inter / app), md)
    handler.start()
    handler.stop("SUCCEEDED")
    write_skew_file(str(inter / app), _sample_bundle())
    cache = PortalCache(str(inter), str(tmp_path / "fin"))
    server = PortalServer(cache, port=0, host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/jobs/{app}/skew",
                timeout=10) as resp:
            bundle = json.loads(resp.read())
        assert bundle["source"] == "history"
        assert bundle["stragglers"][0]["task_id"] == "worker:4"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/jobs/{app}",
                timeout=10) as resp:
            page = resp.read().decode()
        assert "Cross-task skew" in page
        assert "worker:4" in page
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# chaos e2e: detection, attribution, silence, remediation
# ---------------------------------------------------------------------------

def _skew_overrides(**extra):
    over = {
        "tony.straggler.window-ms": 400,
        "tony.straggler.windows": 2,
        "tony.straggler.threshold-pct": 50,
        "tony.straggler.min-tasks": 3,
    }
    over.update(extra)
    return over


def _skew_env(run_seconds=4.0):
    return {"SKEW_STEP_MS": 30, "SKEW_PUSH_MS": 150,
            "SKEW_RUN_SECONDS": run_seconds}


@pytest.mark.chaos
def test_straggler_detected_with_attribution_e2e(tmp_path):
    """Acceptance: a TEST_TRAINER_STEP_DELAY-injected straggler in an
    8-task gang is detected within tony.straggler.windows windows with
    the correct task id and steady-state phase attribution; the event
    carries the evidence; skew.json renders through the CLI."""
    from tests.chaos import ChaosRun, StepDelay
    run = ChaosRun(tmp_path, seed=21)
    run.run(
        ["--executes", script("skew_gang_worker.py"),
         "--conf", "tony.worker.instances=8"],
        injections=[StepDelay("worker", 5, 120)],
        conf_overrides=_skew_overrides(),
        extra_env=_skew_env(run_seconds=4.0))
    assert run.final_status == "SUCCEEDED", run.all_logs()
    detected = [e for e in run.events_of_type(EventType.STRAGGLER_DETECTED)
                if e.payload.phase == "steady_state"]
    assert detected, run.all_logs()
    p = detected[0].payload
    assert (p.task_type, p.task_index) == ("worker", 5)
    assert p.phase == "steady_state"
    assert p.signal == "step_time_ms"
    assert p.value_ms > p.gang_median_ms * 1.5
    assert p.windows >= 2
    # no relaunch without the remediation knob
    assert run.relaunches() == []
    # the bundle landed in history and the CLI renders it offline
    from tony_tpu.events.history import read_skew_file
    bundle = read_skew_file(run.app_history_dir())
    assert any(s["task_id"] == "worker:5"
               for s in bundle.get("stragglers", [])), bundle
    assert "worker:5" in bundle["heatmap"]["tasks"]
    from tony_tpu.cli.__main__ import stragglers as cli_stragglers
    assert cli_stragglers([run.app_history_dir()]) == 0


@pytest.mark.chaos
def test_healthy_gang_produces_zero_detections_e2e(tmp_path):
    """Acceptance (false-positive silence): an equal-width healthy gang
    over the same run produces zero STRAGGLER_* events."""
    from tests.chaos import ChaosRun
    run = ChaosRun(tmp_path, seed=22)
    run.run(
        ["--executes", script("skew_gang_worker.py"),
         "--conf", "tony.worker.instances=8"],
        conf_overrides=_skew_overrides(),
        extra_env=_skew_env(run_seconds=4.0))
    assert run.final_status == "SUCCEEDED", run.all_logs()
    assert run.events_of_type(EventType.STRAGGLER_DETECTED) == []
    assert run.events_of_type(EventType.STRAGGLER_CLEARED) == []


@pytest.mark.chaos
def test_straggler_relaunched_and_latch_clears_e2e(tmp_path):
    """Acceptance (remediation): with tony.straggler.relaunch-after-
    windows set, the persistent steady-state straggler is relaunched
    through the task-attempt machinery (reason on the TASK_RELAUNCHED
    event), STRAGGLER_CLEARED lands with reason=relaunched, the healthy
    replacement keeps the gang green, and the job SUCCEEDS."""
    from tests.chaos import ChaosRun, StepDelay
    run = ChaosRun(tmp_path, seed=23)
    run.run(
        ["--executes", script("skew_gang_worker.py"),
         "--conf", "tony.worker.instances=8",
         "--conf", "tony.task.max-task-attempts=2"],
        injections=[StepDelay("worker", 2, 120, attempt=0)],
        conf_overrides=_skew_overrides(
            **{"tony.straggler.relaunch-after-windows": 3}),
        extra_env=_skew_env(run_seconds=6.0))
    assert run.final_status == "SUCCEEDED", run.all_logs()
    detected = [e for e in run.events_of_type(EventType.STRAGGLER_DETECTED)
                if e.payload.phase == "steady_state"]
    assert detected and detected[0].payload.task_type == "worker"
    assert detected[0].payload.task_index == 2
    relaunches = run.relaunches()
    assert len(relaunches) == 1, run.all_logs()
    assert relaunches[0].task_index == 2
    assert "straggler" in relaunches[0].reason
    cleared = run.events_of_type(EventType.STRAGGLER_CLEARED)
    assert cleared, run.all_logs()
    assert cleared[0].payload.reason == "relaunched"
    assert cleared[0].payload.task_index == 2
    # the replacement ran healthy: exactly one relaunch, no re-detection
    # of the replacement attempt afterwards
    post = [e for e in detected
            if e.timestamp > cleared[0].timestamp]
    assert post == [], post
