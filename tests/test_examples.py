"""Example smoke tests through the real client→AM→executor chain.

The reference's E2E suite ran its example-shaped scripts on the
MiniCluster (TestTonyE2E.java:89-484); same pattern: each example submits
through TonyClient on the local backend with a trimmed workload.
"""

import os

import pytest

from tony_tpu import constants as C
from tony_tpu.client.tony_client import TonyClient
from tony_tpu.conf import keys as K
from tony_tpu.conf.configuration import TonyConfiguration

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def run_example(tmp_path, argv, extra_conf=()):
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path / "cluster"), "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 200, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 2000, "test")
    # Safety net: a wedged user process (e.g. a hung cross-process CPU
    # collective) must FAIL the app, not hang the suite forever — the AM
    # enforces this exactly like the reference's monitor timeout check
    # (ApplicationMaster.java:580-658).
    conf.set(K.APPLICATION_TIMEOUT, 300_000, "test")
    for k, v in extra_conf:
        conf.set(k, v, "test")
    client = TonyClient(conf)
    client.init(argv)
    client.run()
    return client


def _logs(client):
    out = []
    croot = os.path.join(client.app_dir, C.CONTAINERS_DIR_NAME)
    for d, _, files in os.walk(croot):
        for f in files:
            if f in ("stdout", "stderr"):
                p = os.path.join(d, f)
                out.append(f"==== {p}\n" + open(p).read()[-2000:])
    return "\n".join(out)


def test_mnist_jax_example_two_workers(tmp_path):
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "mnist-jax",
                                    "mnist_distributed.py"),
         "--task_params", "--steps 60",
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=jax",
         # 2 virtual CPU devices per worker, not the conftest's 8: the
         # cross-process Gloo mesh drops from 16 ranks to 4, which cuts
         # the first-collective compile (the observed wedge point under
         # concurrent load) by an order of magnitude
         "--conf", ("tony.execution.env=XLA_FLAGS="
                    "--xla_force_host_platform_device_count=2")])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_mnist_pytorch_example_two_workers(tmp_path):
    pytest.importorskip("torch")
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "mnist-pytorch",
                                    "mnist_distributed.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=pytorch"])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_mnist_tensorflow_example_env_only(tmp_path):
    """Runs everywhere — even TF-less images: the example validates the
    rendered TF_CONFIG/CLUSTER_SPEC contract and exits 0 when the
    tensorflow import fails."""
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "mnist-tensorflow",
                                    "mnist_distributed.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=tensorflow"])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_mnist_tensorflow_example_really_trains(tmp_path):
    """VERDICT r4 item 7: the reference's flagship workload
    (TestTonyE2E + tony-examples/mnist-tensorflow) ACTUALLY trains the
    moment TensorFlow exists in the image — MultiWorkerMirroredStrategy
    across a 2-worker gang, loss threshold enforced by the script
    itself. Skips cleanly where TF is absent (importorskip)."""
    pytest.importorskip("tensorflow")
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "mnist-tensorflow",
                                    "mnist_distributed.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=tensorflow"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    # real training evidence, not just env validation: both workers
    # logged epoch losses under the MWMS strategy
    outs = _worker_stdouts(client)
    assert sum("epoch 1 loss" in s for s in outs) == 2, outs


def _worker_stdouts(client):
    import glob as _glob

    return [open(p).read() for p in _glob.glob(
        os.path.join(client.app_dir, "containers", "worker_*", "stdout"))]


def test_mxnet_linreg_example(tmp_path):
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "linearregression-mxnet",
                                    "linreg_dmlc.py"),
         "--conf", "tony.scheduler.instances=1",
         "--conf", "tony.server.instances=1",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=mxnet"])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_allreduce_resnet_example_two_workers(tmp_path):
    """Horovod-equivalent contract: framework=horovod renders NO env, the
    script rendezvouses from CLUSTER_SPEC alone and all-reduce-trains the
    conv model across 2 processes."""
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "allreduce-resnet",
                                    "train_allreduce.py"),
         "--task_params", "--steps 8 --batch-size 8",
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=horovod",
         "--conf", ("tony.execution.env=XLA_FLAGS="
                    "--xla_force_host_platform_device_count=2")])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_allreduce_vit_example(tmp_path):
    """The same all-reduce DP harness drives the attention image model
    (--model vit): ViT through the orchestrated chain."""
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "allreduce-resnet",
                                    "train_allreduce.py"),
         "--task_params", "--model vit --steps 8 --batch-size 8",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=horovod"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    assert "final loss" in _logs(client)


def test_multirole_example(tmp_path):
    role = os.path.join(EXAMPLES, "multirole", "role.py")
    client = run_example(
        tmp_path,
        ["--conf", "tony.head.instances=1",
         "--conf", f"tony.head.command=python {role} --role head",
         "--conf", "tony.worker.instances=2",
         "--conf", f"tony.worker.command=python {role} --role worker"])
    assert client.final_status == "SUCCEEDED", _logs(client)


@pytest.mark.slow
def test_train_then_generate_lifecycle(tmp_path):
    """Full model lifecycle through the real chain: pretrain with
    checkpointing, then a second app restores that checkpoint and runs
    the KV-cache decode loop (examples/llama-generate). slow: two full
    apps incl. a CPU decode loop (~25 s) — the lifecycle's fast
    coverage lives in test_llama_pretrain_example_tiny + test_generate."""
    ckpt = str(tmp_path / "ckpts")
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-pretrain",
                                    "pretrain.py"),
         "--task_params",
         f"--config tiny --steps 3 --batch-size 2 --seq-len 64 "
         f"--checkpoint-dir {ckpt} --checkpoint-every 3",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)

    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-generate",
                                    "generate_demo.py"),
         "--task_params",
         f"--config tiny --checkpoint-dir {ckpt} --max-new 8",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    assert "GENERATE_OK" in _logs(client)

    # same restored checkpoint through the int8 weight-only decode path
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-generate",
                                    "generate_demo.py"),
         "--task_params",
         f"--config tiny --checkpoint-dir {ckpt} --max-new 8 "
         "--quant int8",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    logs = _logs(client)
    assert "GENERATE_OK" in logs and "int8 weight-only" in logs

    # speculative decoding from the same checkpoint (random-init draft —
    # lossless mechanism through the real chain, not a speedup claim)
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-generate",
                                    "generate_demo.py"),
         "--task_params",
         f"--config tiny --checkpoint-dir {ckpt} --max-new 8 "
         "--draft-config tiny --gamma 3",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    logs = _logs(client)
    assert "GENERATE_OK" in logs and "speculative: draft=tiny" in logs


@pytest.mark.slow
def test_moe_train_then_generate_lifecycle(tmp_path):
    """The expert family end to end through the real chain: MoE pretrain
    (router + expert banks, aux loss) checkpoints, then the generate
    demo restores it and runs the shared KV-cache decode stack. slow:
    two full apps incl. a CPU MoE decode loop (~14 s)."""
    ckpt = str(tmp_path / "ckpts")
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-pretrain",
                                    "pretrain.py"),
         "--task_params",
         f"--config moe_tiny --steps 3 --batch-size 2 --seq-len 64 "
         f"--checkpoint-dir {ckpt} --checkpoint-every 3",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    assert "final loss" in _logs(client)

    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-generate",
                                    "generate_demo.py"),
         "--task_params",
         f"--config moe_tiny --checkpoint-dir {ckpt} --max-new 8",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)
    assert "GENERATE_OK" in _logs(client)


def test_longcontext_ring_example(tmp_path):
    """Ring-attention pretrain through the real chain: sp=2 mesh rendered
    by the orchestrator (TPU_MESH_*), sequence sharded, 3 steps."""
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "longcontext-ring",
                                    "pretrain_long.py"),
         "--task_params",
         "--config tiny --steps 3 --batch-size 2 --seq-len 256",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax",
         "--conf", "tony.tpu.mesh-shape=2,2",
         "--conf", "tony.tpu.mesh-axes=fsdp,sp",
         # 4 local virtual devices to match the 2x2 mesh
         "--conf", ("tony.execution.env=XLA_FLAGS="
                    "--xla_force_host_platform_device_count=4")])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_llama_pretrain_example_tiny(tmp_path):
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-pretrain",
                                    "pretrain.py"),
         "--task_params",
         "--config tiny --steps 4 --batch-size 2 --seq-len 64",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax"])
    assert client.final_status == "SUCCEEDED", _logs(client)


def test_llama_pretrain_pipelined_interleaved(tmp_path):
    """Pipeline-parallel training through the REAL chain: the
    orchestrator renders a pp mesh (TPU_MESH_*), and the example selects
    the interleaved (v=2) 1F1B pipelined loss — submit -> AM -> executor
    -> pipelined train steps."""
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-pretrain",
                                    "pretrain.py"),
         "--task_params",
         "--config tiny --steps 3 --batch-size 4 --seq-len 64 "
         "--n-layers 4 --pp-micro 2 --pp-virtual 2",
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.application.framework=jax",
         "--conf", "tony.tpu.mesh-shape=2,1",
         "--conf", "tony.tpu.mesh-axes=pp,fsdp",
         "--conf", ("tony.execution.env=XLA_FLAGS="
                    "--xla_force_host_platform_device_count=2")])
    assert client.final_status == "SUCCEEDED", _logs(client)
    assert "final loss" in _logs(client)


def test_llama_pretrain_native_data_two_workers(tmp_path):
    """The flagship through the REAL host data plane (VERDICT r3 weak
    #5): submit -> AM -> executors launch 2 workers that train
    llama-pretrain from an on-disk token shard via train/native_data's
    prefetching loader — per-process streams (seed = JAX_PROCESS_ID),
    not synthetic_tokens. The native double-buffer thread must be active
    in the executor-launched processes, proven by the loader's marker
    line in each worker's container log."""
    import numpy as np

    from tony_tpu.train.native_data import write_token_file

    shard = str(tmp_path / "corpus.bin")
    write_token_file(
        shard, np.random.default_rng(0).integers(
            0, 256, 100_000).astype(np.int32))
    client = run_example(
        tmp_path,
        ["--executes", os.path.join(EXAMPLES, "llama-pretrain",
                                    "pretrain.py"),
         "--task_params",
         f"--config tiny --steps 3 --batch-size 2 --seq-len 64 "
         f"--data {shard}",
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.application.framework=jax",
         # 1 virtual CPU device per worker: global batch 4 must divide
         # the mesh, and the 2-rank Gloo mesh keeps the first-collective
         # compile cheap (see the mnist-jax test above)
         "--conf", ("tony.execution.env=XLA_FLAGS="
                    "--xla_force_host_platform_device_count=1")])
    assert client.final_status == "SUCCEEDED", _logs(client)
    logs = _logs(client)
    markers = logs.count("native prefetching loader active")
    assert markers >= 2, f"native loader ran in {markers}/2 workers:\n{logs}"
    # per-process streams: each worker seeds with its process index
    assert "seed 0" in logs and "seed 1" in logs, logs
