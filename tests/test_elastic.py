"""Elastic gang resize (cluster/elastic.py).

Unit layer: mesh scaling, session membership surgery (trailing-slot
removal + membership-aware spec diffs), the ElasticCoordinator state
machine against a stub AM (quiesce gating, grow/shrink reshape, the
grow rollback arm, cooldown), the arbiter's reclaim-instead-of-evict
preference with victim minimality, the annotated idle-chips alert, the
goodput `resize` phase, fleet width surfaces, and the executor's
resize-ask handling.

E2E layer (chaos): a running gang of real executors grows 2→4 and
shrinks 4→2 through the full request_resize round trip (quiesce acks on
heartbeats, membership diffs, zero relaunch budget); and — slow — a
real mnist trainer re-meshes 4→8→4 chips mid-training with its loss
trajectory bit-consistent against the checkpoint-stop-restart
(evict-and-resume) twin at the same width schedule, downtime priced as
the `resize` goodput phase.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

import pytest

from tony_tpu import constants as C
from tony_tpu.cluster.arbiter import (
    ADMIT, PREEMPT, QUEUE, RECLAIM, Arbiter, GangAsk,
)
from tony_tpu.cluster.elastic import (
    ElasticCoordinator, find_widenable, reclaim_rpc_args,
    scale_mesh_shape,
)
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.schema import EventType
from tony_tpu.session.session import TonySession

pytestmark = pytest.mark.elastic

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


def _wait_for(predicate, timeout_s: float, what: str = ""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# mesh scaling
# ---------------------------------------------------------------------------

def test_scale_mesh_shape_prefers_data_axes_and_validates():
    assert scale_mesh_shape("4", "fsdp", 4, 8) == "8"
    assert scale_mesh_shape("8", "fsdp", 8, 4) == "4"
    # dp wins over fsdp; model axes never scale
    assert scale_mesh_shape("2,4,2", "dp,fsdp,tp", 16, 32) == "4,4,2"
    assert scale_mesh_shape("2,4,2", "fsdp,tp,pp", 16, 8) == "1,4,2"
    # no axes names: the largest dim scales
    assert scale_mesh_shape("2,8", "", 16, 32) == "2,16"
    with pytest.raises(ValueError):
        scale_mesh_shape("5", "fsdp", 2, 1)       # 5*1 % 2 != 0
    with pytest.raises(ValueError):
        scale_mesh_shape("2,2,2", "tp,sp,pp", 8, 10)  # 2*10 % 8 != 0


def test_scale_mesh_shape_empty_is_noop():
    assert scale_mesh_shape("", "fsdp", 4, 8) == ""


# ---------------------------------------------------------------------------
# session membership surgery
# ---------------------------------------------------------------------------

def _steady_session(width: int = 4, tpus: int = 2) -> TonySession:
    conf = TonyConfiguration()
    conf.set(K.instances_key("worker"), width, "test")
    conf.set(K.tpus_key("worker"), tpus, "test")
    session = TonySession(conf)
    session.num_expected_tasks = width
    for i in range(width):
        task = session.get_task("worker", i)
        task.container_id = f"c{i}"
        session.register_worker_spec_with_generation(
            f"worker:{i}", f"h{i}:1")
    assert session.all_tasks_registered()
    return session


def test_remove_task_slots_pops_trailing_and_accounts():
    session = _steady_session(4)
    removed = session.remove_task_slots("worker", 2)
    assert [t.index for t in removed] == [3, 2]
    assert session.requests["worker"].num_instances == 2
    assert session.num_expected_tasks == 2
    assert session.all_tasks_registered()
    assert json.loads(session.cluster_spec_json()) == {
        "worker": ["h0:1", "h1:1"]}
    # never below one instance
    assert len(session.remove_task_slots("worker", 9)) == 1
    assert session.requests["worker"].num_instances == 1


def test_resize_bump_serves_membership_diffs_both_directions():
    from tony_tpu.executor.task_executor import apply_spec_diff
    session = _steady_session(2)
    g0 = session.spec_generation
    held = json.loads(session.cluster_spec_json())
    # grow 2 -> 4: new slots register, ONE bump carries the additions
    for _ in range(2):
        t = session.add_task_instance("worker")
        session.num_expected_tasks += 1
        session.register_worker_spec_with_generation(
            t.task_id, f"h{t.index}:1")
    session.resize_bump_generation({"worker:2", "worker:3"}, {})
    diff, refetch = session.spec_diff_since(g0)
    assert not refetch
    assert diff["changed"] == {"worker": {"2": "h2:1", "3": "h3:1"}}
    assert "removed" not in diff
    held = apply_spec_diff(held, diff["changed"], diff.get("removed"))
    assert json.dumps(held) == session.cluster_spec_json()
    g1 = session.spec_generation
    # shrink 4 -> 2: the removal rides the diff (not just rebinds)
    removed = session.remove_task_slots("worker", 2)
    session.resize_bump_generation(set(),
                                   {"worker": {t.index for t in removed}})
    diff, refetch = session.spec_diff_since(g1)
    assert not refetch
    assert diff["removed"] == {"worker": [2, 3]}
    held = apply_spec_diff(held, diff["changed"], diff.get("removed"))
    assert json.dumps(held) == session.cluster_spec_json()
    # a straggler who missed BOTH bumps nets out: add then remove
    diff, refetch = session.spec_diff_since(g0)
    assert not refetch
    assert diff.get("removed", {}) == {"worker": [2, 3]}
    assert "worker:2" not in str(diff["changed"])


def test_apply_spec_diff_removal_of_unknown_index_is_noop():
    from tony_tpu.executor.task_executor import apply_spec_diff
    spec = {"worker": ["h0:1", "h1:1"]}
    out = apply_spec_diff(spec, {}, {"worker": [2, 3], "ps": [0]})
    assert out == {"worker": ["h0:1", "h1:1"]}


# ---------------------------------------------------------------------------
# the coordinator against a stub AM
# ---------------------------------------------------------------------------

class _StubScheduler:
    def __init__(self, session):
        self.session = session
        self.scale_ups = []

    def schedule_scale_up(self, job):
        self.session.num_expected_tasks += 1
        self.scale_ups.append(job)


class _StubBackend:
    def __init__(self):
        self.stopped = []

    def stop_container(self, cid):
        self.stopped.append(cid)


class _StubHbMonitor:
    def __init__(self):
        self.unregistered = []

    def unregister(self, task_id):
        self.unregistered.append(task_id)


class _StubEvents:
    def __init__(self):
        self.events = []

    def emit(self, event):
        self.events.append(event)

    def of_type(self, etype):
        return [e for e in self.events if e.type == etype]


class _StubAM:
    def __init__(self, conf, width: int = 4, tpus: int = 2):
        self.conf = conf
        self.app_id = "app-elastic"
        self.session = _steady_session(width, tpus)
        # rebuild against THIS conf (mesh keys etc.)
        self.session.conf = conf
        self.scheduler = _StubScheduler(self.session)
        self.backend = _StubBackend()
        self.hb_monitor = _StubHbMonitor()
        self.event_handler = _StubEvents()
        self._wake = threading.Event()
        self._alloc_timeout_ms = 60_000
        self._preemption = None
        self.relaunched = []

    def _maybe_relaunch_task(self, task, reason, count_failure=True,
                             force=False):
        self.relaunched.append((task.task_id, reason, count_failure,
                                force))
        return True


def _elastic_conf(**overrides) -> TonyConfiguration:
    conf = TonyConfiguration()
    conf.set(K.ELASTIC_ENABLED, True, "test")
    conf.set(K.instances_key("worker"), 4, "test")
    conf.set(K.tpus_key("worker"), 2, "test")
    for k, v in overrides.items():
        conf.set(k, v, "test")
    return conf


def test_request_resize_validation():
    conf = _elastic_conf(**{K.ELASTIC_MIN_WIDTH: 2, K.ELASTIC_MAX_WIDTH: 8})
    am = _StubAM(conf)
    coord = ElasticCoordinator(am)
    assert "error" in coord.request_resize({})             # no target
    assert "below" in coord.request_resize({"width": 1})["error"]
    assert "above" in coord.request_resize({"width": 9})["error"]
    assert "already" in coord.request_resize({"width": 4})["error"]
    assert "error" in coord.request_resize(
        {"width": 6, "tpus_per_task": 4})                  # both
    assert "serving" in coord.request_resize(
        {"job_name": "serving", "width": 2})["error"]
    # disabled entirely
    off = ElasticCoordinator(_StubAM(TonyConfiguration()))
    assert "disabled" in off.request_resize({"width": 2})["error"]
    # a real ask arms the machine and a second one reports the in-flight
    ok = coord.request_resize({"width": 6, "requested_by": "operator"})
    assert ok.get("error") is None and ok["to_width"] == 6
    dup = coord.request_resize({"width": 8})
    assert dup.get("duplicate") is True


def test_coordinator_grow_gates_on_acks_then_reshapes_and_completes():
    am = _StubAM(_elastic_conf())
    coord = ElasticCoordinator(am)
    resp = coord.request_resize({"width": 6, "reason": "offer"})
    assert resp["from_width"] == 4 and resp["to_width"] == 6
    assert am.event_handler.of_type(EventType.RESIZE_REQUESTED)
    assert am.event_handler.of_type(EventType.RESIZE_STARTED)
    ask = coord.heartbeat_fields("worker:0")
    assert ask and ask["release"] is False and ask["width"] == 6
    rid = ask["id"]
    # membership must NOT change until every member acked the quiesce
    coord.check()
    assert len(am.session.job_tasks["worker"]) == 4
    for i in range(4):
        coord.note_quiesced(f"worker:{i}", rid)
    coord.check()
    assert len(am.session.job_tasks["worker"]) == 6
    assert am.scheduler.scale_ups == ["worker", "worker"]
    # the barrier reopened for the newcomers; no completion yet
    assert not am.session.all_tasks_registered()
    coord.check()
    assert coord.resizes_total == 0
    for i in (4, 5):
        am.session.register_worker_spec_with_generation(
            f"worker:{i}", f"h{i}:1")
    # barrier closed, but the resize (and its downtime clock) only
    # settles once every SURVIVOR reports holding the new generation —
    # i.e. it actually re-rendezvoused, not merely the books changed
    coord.check()
    assert coord.resizes_total == 0 and coord.active
    for i in range(4):
        coord.note_generation(f"worker:{i}",
                              am.session.spec_generation)
    coord.check()
    assert coord.resizes_total == 1
    done = am.event_handler.of_type(EventType.RESIZE_COMPLETED)
    assert done and done[0].payload.added_tasks == 2
    assert coord.downtime_s() > 0.0
    assert not coord.active


def test_coordinator_shrink_drains_victims_and_serves_removal_diff():
    am = _StubAM(_elastic_conf())
    coord = ElasticCoordinator(am)
    g0 = am.session.spec_generation
    coord.request_resize({"width": 2, "reason": "reclaim",
                          "requested_by": "operator"})
    assert coord.heartbeat_fields("worker:3")["release"] is True
    assert coord.heartbeat_fields("worker:0")["release"] is False
    rid = coord.heartbeat_fields("worker:0")["id"]
    coord.note_quiesced("worker:0", rid)
    coord.note_quiesced("worker:1", rid)
    assert coord.note_released("worker:2", "c2")
    assert coord.note_released("worker:3", "c3")
    # while quiescing the width surface shows the in-flight target...
    assert coord.width_fields(4)["requested_width"] == 2
    coord.check()       # reshape: trailing slots leave, containers stop
    assert len(am.session.job_tasks["worker"]) == 2
    assert sorted(am.backend.stopped) == ["c2", "c3"]
    assert coord.is_released_container("c3")
    # ...and once the membership changed, current IS requested (a
    # second delta application would render "2>0")
    assert coord.width_fields(2)["requested_width"] == 2
    diff, refetch = am.session.spec_diff_since(g0)
    assert not refetch and diff["removed"] == {"worker": [2, 3]}
    for i in range(2):
        coord.note_generation(f"worker:{i}", am.session.spec_generation)
    coord.check()       # barrier already closed at the new width
    assert coord.resizes_total == 1
    done = am.event_handler.of_type(EventType.RESIZE_COMPLETED)
    assert done and done[0].payload.removed_tasks == 2
    # a release with no resize in flight is refused (abort race cover)
    assert coord.note_released("worker:1", "c1") is False


def test_coordinator_grow_rolls_back_when_containers_never_register():
    am = _StubAM(_elastic_conf())
    am._alloc_timeout_ms = 1       # rollback arms ~immediately
    coord = ElasticCoordinator(am)
    coord.request_resize({"width": 6})
    rid = coord.heartbeat_fields("worker:0")["id"]
    for i in range(4):
        coord.note_quiesced(f"worker:{i}", rid)
    coord.check()                  # reshape: slots added, barrier open
    assert len(am.session.job_tasks["worker"]) == 6
    time.sleep(0.05)
    coord.check()                  # rollback: abandon back to old width
    assert len(am.session.job_tasks["worker"]) == 4
    assert am.session.all_tasks_registered()
    failed = am.event_handler.of_type(EventType.RESIZE_FAILED)
    assert failed and failed[0].payload.rolled_back is True
    assert not coord.active        # no mesh override: settles directly
    assert coord.resizes_total == 0
    assert coord.downtime_s() > 0.0


def test_coordinator_quiesce_timeout_aborts_without_failing_the_app():
    am = _StubAM(_elastic_conf())
    coord = ElasticCoordinator(am)
    coord.request_resize({"width": 6, "grace_ms": 1})
    time.sleep(0.05)
    coord.check()
    failed = am.event_handler.of_type(EventType.RESIZE_FAILED)
    assert failed and failed[0].payload.rolled_back is False
    assert len(am.session.job_tasks["worker"]) == 4
    from tony_tpu.session.session import FinalStatus
    assert am.session.final_status == FinalStatus.UNDEFINED


def test_shrink_with_completed_trailing_victim_needs_no_ghost_release():
    """A trailing slot that already completed sends no heartbeats and
    can never report a release — a shrink over it must not burn the
    quiesce grace waiting for a ghost; the slot simply pops."""
    am = _StubAM(_elastic_conf())
    am.session.get_task("worker", 3).set_exit_status(0)
    coord = ElasticCoordinator(am)
    coord.request_resize({"width": 2})
    # only the LIVE victim gets a release ask
    assert coord.heartbeat_fields("worker:2")["release"] is True
    assert coord.heartbeat_fields("worker:3") is None
    rid = coord.heartbeat_fields("worker:0")["id"]
    coord.note_quiesced("worker:0", rid)
    coord.note_quiesced("worker:1", rid)
    assert coord.note_released("worker:2", "c2")
    coord.check()
    assert len(am.session.job_tasks["worker"]) == 2
    for i in range(2):
        coord.note_generation(f"worker:{i}", am.session.spec_generation)
    coord.check()
    assert coord.resizes_total == 1


def test_grow_rollback_watches_added_slots_not_the_whole_barrier():
    """An unrelated survivor relaunch past the rollback deadline must
    not roll back a grow whose added containers DID register."""
    am = _StubAM(_elastic_conf())
    am._alloc_timeout_ms = 1
    coord = ElasticCoordinator(am)
    coord.request_resize({"width": 6})
    rid = coord.heartbeat_fields("worker:0")["id"]
    for i in range(4):
        coord.note_quiesced(f"worker:{i}", rid)
    coord.check()                  # reshape
    for i in (4, 5):
        am.session.register_worker_spec_with_generation(
            f"worker:{i}", f"h{i}:1")
    # survivor worker:1 crashes and relaunches: barrier reopens, but
    # the grow's own slots are all registered — no rollback, ever
    am.session.relaunch_task("worker", 1)
    time.sleep(0.05)
    coord.check()
    assert len(am.session.job_tasks["worker"]) == 6
    assert not am.event_handler.of_type(EventType.RESIZE_FAILED)
    # the replacement re-registers; once every survivor reports the
    # current generation the grow completes normally
    am.session.register_worker_spec_with_generation(
        "worker:1", "r1:2", expected_attempt=1)
    for i in range(4):
        coord.note_generation(f"worker:{i}", am.session.spec_generation)
    coord.check()
    assert coord.resizes_total == 1


def test_quiesce_abort_wakes_survivors_and_heals_released_victims():
    """A shrink victim that released BEFORE the quiesce aborted must
    not be left as a silent hole in the resumed gang: the abort bumps
    the generation (diff-waiting survivors wake immediately instead of
    idling out to the full-poll fallback) and the released victim is
    healed through the budget-exempt lifecycle relaunch."""
    am = _StubAM(_elastic_conf())
    coord = ElasticCoordinator(am)
    coord.request_resize({"width": 2, "grace_ms": 40})
    rid = coord.heartbeat_fields("worker:0")["id"]
    coord.note_quiesced("worker:0", rid)
    # victim 2 releases; victim 3 and survivor 1 never respond
    assert coord.note_released("worker:2", "c2")
    g_before = am.session.spec_generation
    time.sleep(0.08)
    coord.check()
    failed = am.event_handler.of_type(EventType.RESIZE_FAILED)
    assert failed and failed[0].payload.rolled_back is False
    # survivors woken by an empty bump, released victim force-relaunched
    assert am.session.spec_generation == g_before + 1
    assert [(tid, cf, force) for tid, _, cf, force in am.relaunched] \
        == [("worker:2", False, True)]
    assert len(am.session.job_tasks["worker"]) == 4


def test_arbiter_cooldown_applies_to_automatic_triggers_only():
    am = _StubAM(_elastic_conf(**{K.ELASTIC_COOLDOWN_MS: "60s"}))
    coord = ElasticCoordinator(am)
    coord._last_done = time.monotonic()
    refused = coord.request_resize({"width": 6, "requested_by": "arbiter"})
    assert "cooldown" in refused["error"]
    ok = coord.request_resize({"width": 6, "requested_by": "operator"})
    assert ok.get("error") is None


def test_remesh_resize_scales_tpus_and_mesh():
    conf = _elastic_conf()
    conf.set(K.TPU_MESH_SHAPE, "8", "test")
    conf.set(K.TPU_MESH_AXES, "fsdp", "test")
    am = _StubAM(conf)
    coord = ElasticCoordinator(am)
    resp = coord.request_resize({"tpus_per_task": 4})
    assert resp["to_chips"] == 16 and resp["from_chips"] == 8
    ask = coord.heartbeat_fields("worker:0")
    assert ask["mesh_shape"] == "16"
    for i in range(4):
        coord.note_quiesced(f"worker:{i}", ask["id"])
    coord.check()                  # reshape (membership unchanged)
    assert am.session.requests["worker"].tpus == 4
    for i in range(4):
        coord.note_generation(f"worker:{i}", am.session.spec_generation)
    coord.check()                  # barrier closed + gang back: complete
    assert coord.resizes_total == 1
    assert coord.mesh_override() == "16"
    # a later container launch renders the settled mesh
    assert coord.width_fields(4)["requested_width"] == 4


# ---------------------------------------------------------------------------
# arbiter: reclaim-instead-of-evict
# ---------------------------------------------------------------------------

def _elastic_ask(app, chips, width, min_chips, priority=0, started=0,
                 am_addr="h:1"):
    return GangAsk(app, chips, priority=priority, started_ms=started,
                   elastic_job="worker", elastic_min_chips=min_chips,
                   gang_width=width, am_addr=am_addr)


def test_reclaim_preferred_over_evicting_non_elastic():
    """Victim-minimality acceptance: a slice reclaimed from an elastic
    job beats fully evicting a non-elastic one."""
    arb = Arbiter(total_chips=8)
    arb.running = {
        "ela": _elastic_ask("ela", 6, width=3, min_chips=2, started=5),
        "rigid": GangAsk("rigid", 2, priority=0, started_ms=9),
    }
    decision = arb.decide(GangAsk("hi", 4, priority=5))
    assert decision.action == RECLAIM
    assert decision.victims == []
    assert [(a.app_id, chips) for a, chips in decision.reclaims] == \
        [("ela", 4)]
    # minimality: a smaller ask reclaims fewer whole task slices
    small = arb.decide(GangAsk("hi2", 2, priority=5))
    assert small.action == RECLAIM
    assert [(a.app_id, chips) for a, chips in small.reclaims] == \
        [("ela", 2)]


def test_reclaim_respects_floor_and_falls_back_to_eviction():
    arb = Arbiter(total_chips=8)
    arb.running = {
        "ela": _elastic_ask("ela", 4, width=2, min_chips=2, started=5),
        "rigid": GangAsk("rigid", 4, priority=0, started_ms=9),
    }
    # reclaimable is only 2 (floor 2): a 6-chip ask can't be satisfied
    # by reclaim alone — full eviction is the fallback
    decision = arb.decide(GangAsk("hi", 6, priority=5))
    assert decision.action == PREEMPT
    assert {v.app_id for v in decision.victims} <= {"ela", "rigid"}
    # and priority still gates everything: equal priority queues
    assert arb.decide(GangAsk("peer", 6, priority=0)).action == QUEUE


def test_reclaim_granularity_is_whole_task_slices():
    arb = Arbiter(total_chips=8)
    arb.running = {
        "ela": _elastic_ask("ela", 6, width=3, min_chips=2),
    }
    # 2 chips already free; the 3 missing ones round UP to two whole
    # 2-chip task slices
    decision = arb.decide(GangAsk("hi", 5, priority=5))
    assert decision.action == RECLAIM
    assert decision.reclaims[0][1] == 4


def test_reclaim_rpc_args_sizes_width_or_mesh():
    multi = {"gang_width": 4, "allocated_chips": 8, "elastic_job": "worker"}
    assert reclaim_rpc_args(multi, 4) == {"job_name": "worker", "width": 2}
    single = {"gang_width": 1, "allocated_chips": 8,
              "elastic_job": "worker"}
    assert reclaim_rpc_args(single, 4) == {"job_name": "worker",
                                           "tpus_per_task": 4}
    assert reclaim_rpc_args({"gang_width": 2, "allocated_chips": 4,
                             "elastic_job": ""}, 2) is None


def test_reclaim_arithmetic_is_scoped_to_the_elastic_jobtype():
    """A mixed-jobtype app (4x4-chip workers + 2x1-chip serving): the
    reclaim must size slices by the WORKER's chips-per-task, never the
    blended app-wide ratio, and never count serving chips reclaimable."""
    summary = {"gang_width": 6, "allocated_chips": 18,
               "elastic_job": "worker", "elastic_width": 4,
               "elastic_chips_per_task": 4, "elastic_min_chips": 4,
               "app_id": "mixed", "state": "RUNNING"}
    ask = GangAsk.from_summary(summary)
    assert ask.chips_per_task == 4          # not 18 // 6 == 3
    assert ask.reclaimable_chips == 12      # 16 worker chips - 4 floor
    # freeing 12 chips shrinks the WORKER gang 4 -> 1
    assert reclaim_rpc_args(summary, 12) == {"job_name": "worker",
                                             "width": 1}
    # widenable discovery judges the ELASTIC jobtype's width too: the
    # blended gang_width (6) sits above a max-width of 6, but the
    # worker gang itself (4) still has room
    capped = dict(summary, elastic_max_width=6)
    assert find_widenable([capped]) == [capped]


class _ResizeRecorder:
    """Minimal cluster-service handler recording request_resize asks
    (the reclaim/offer delivery edges' far side)."""

    def __init__(self):
        self.asks = []

    def request_resize(self, req):
        self.asks.append(req)
        return {"app_id": "victim", "from_width": 4,
                "to_width": int(req.get("width", 0) or 0)}

    def __getattr__(self, name):
        # every other cluster method: inert stub
        return lambda req: {}


@pytest.fixture
def resize_server():
    from tony_tpu.rpc.service import serve
    handler = _ResizeRecorder()
    server, port = serve(cluster_handler=handler)
    yield handler, port
    server.stop(grace=None)


def test_execute_reclaims_delivers_resize_shrinks(resize_server):
    from tony_tpu.cluster.arbiter import execute_reclaims
    handler, port = resize_server
    victim = _elastic_ask("victim", 8, width=4, min_chips=2,
                          am_addr=f"127.0.0.1:{port}")
    reached = execute_reclaims([(victim, 4)], grace_ms=1234,
                               reason="admit hi-gang")
    assert reached == ["victim"]
    assert handler.asks == [{
        "job_name": "worker", "width": 2, "tpus_per_task": 0,
        "grace_ms": 1234, "reason": "admit hi-gang",
        "requested_by": "arbiter", "session_attempt": -1}]


def test_offer_idle_chips_grows_widenable_jobs(resize_server):
    from tony_tpu.cluster.arbiter import offer_idle_chips
    from tony_tpu.observability import fleet
    handler, port = resize_server
    summary = fleet.job_summary(
        "ela", "u", "q", "RUNNING", gang_width=2, allocated_chips=4,
        elastic_job="worker", elastic_min_width=1, elastic_max_width=8,
        am_addr=f"127.0.0.1:{port}")
    delivered = offer_idle_chips([summary], idle_chips=5)
    # 5 idle chips at 2 chips/task -> grow by 2 tasks (2 -> 4)
    assert delivered == [{"app_id": "ela", "job_name": "worker",
                          "width": 4}]
    assert handler.asks[0]["width"] == 4
    assert handler.asks[0]["requested_by"] == "arbiter"


def test_gang_ask_from_summary_carries_elastic_surface():
    from tony_tpu.observability import fleet
    summary = fleet.job_summary(
        "a", "u", "q", "RUNNING", gang_width=4, allocated_chips=8,
        elastic_job="worker", elastic_min_width=1, elastic_max_width=8,
        elastic_min_chips=2, resizes=1, requested_width=6)
    ask = GangAsk.from_summary(summary)
    assert ask.elastic_job == "worker"
    assert ask.elastic_min_chips == 2
    assert ask.chips_per_task == 2
    assert ask.reclaimable_chips == 6
    assert summary["requested_width"] == 6 and summary["resizes"] == 1
    assert fleet.JOB_GAUGES["tony_job_resizes_total"] == "resizes"
    # widenable discovery (the alert annotation's candidate source)
    assert find_widenable([summary]) == [summary]
    capped = dict(summary, gang_width=8)
    assert find_widenable([capped]) == []


# ---------------------------------------------------------------------------
# annotated idle-chips alert (the offer loop's payload)
# ---------------------------------------------------------------------------

def test_idle_chips_alert_names_widenable_job_and_idle_count():
    import tony_tpu.observability.alerts as A
    from tony_tpu.observability import fleet
    queued = fleet.job_summary("queued", "u", "prod", "RUNNING",
                               gang_width=2, requested_chips=8,
                               allocated_chips=0, started_ms=1)
    elastic = fleet.job_summary("ela", "u", "prod", "RUNNING",
                                gang_width=2, requested_chips=4,
                                allocated_chips=4, started_ms=2,
                                elastic_job="worker",
                                elastic_min_width=1, elastic_max_width=8)
    ctx = A.AlertContext(now_ms=0, fleet={
        "queues": {"prod": 32},
        "jobs": [queued, elastic]})
    obs = A.idle_chips_rule().evaluate(ctx)
    assert [o["key"] for o in obs] == ["job:queued"]
    ann = obs[0]["annotations"]
    # 32-chip quota minus the 12 chips_of in use (queued 8 + elastic 4)
    assert ann["idle_chips"] == 20
    assert ann["widenable_job"] == "ela"
    assert ann["widenable_jobtype"] == "worker"
    assert "could widen" in obs[0]["message"]
    # annotations survive into the engine's transitions + bundle
    engine = A.AlertEngine([A.idle_chips_rule(for_ms=0)],
                           default_for_ms=0)
    transitions = list(engine.evaluate(ctx))
    transitions += engine.evaluate(
        A.AlertContext(now_ms=10_000, fleet=ctx.fleet))
    firing = [t for t in transitions if t["status"] == "firing"]
    assert firing and firing[0]["annotations"]["widenable_job"] == "ela"


# ---------------------------------------------------------------------------
# goodput + security + CLI surfaces
# ---------------------------------------------------------------------------

def test_aggregate_goodput_prices_resize_downtime():
    from tony_tpu.observability.perf import PHASES, aggregate_goodput
    assert "resize" in PHASES
    per_task = {"worker:0": {"GOODPUT_TRAIN_STEP_SECONDS": 90.0,
                             "GOODPUT_WALL_SECONDS": 90.0}}
    out = aggregate_goodput(per_task, resize_downtime_s=10.0)
    assert out["job"]["resize_downtime_s"] == 10.0
    assert out["job"]["goodput_pct"] == 90.0


def test_request_resize_is_client_plane_only():
    from tony_tpu.rpc.service import CLUSTER_METHODS
    from tony_tpu.security.tokens import TASK_METHOD_IDENTITY
    assert "request_resize" in CLUSTER_METHODS
    assert "request_resize" not in TASK_METHOD_IDENTITY


def test_request_resize_session_attempt_fence(tmp_path):
    """The RPC handler's attempt fence: an ask computed against a stale
    registry entry must not fire on a superseded session attempt."""
    from tony_tpu.am.application_master import ApplicationMaster
    conf = _elastic_conf()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    am = ApplicationMaster(conf, "app-fence", str(tmp_path))
    am.session = _steady_session(4)
    resp = am.request_resize({"width": 6, "session_attempt": 7})
    assert "stale session attempt" in resp["error"]
    resp = am.request_resize({"width": 6, "session_attempt": 0})
    assert resp.get("error") is None
    am.elastic.reset()


def test_cli_top_frame_shows_current_and_requested_width():
    from tony_tpu.cli.__main__ import _render_fleet_frame
    from tony_tpu.observability import fleet

    class _Registry:
        def jobs(self):
            return [fleet.job_summary("app-resizing", "u", "q", "RUNNING",
                                      gang_width=4, requested_width=8,
                                      allocated_chips=8),
                    fleet.job_summary("app-static", "u", "q", "RUNNING",
                                      gang_width=2, allocated_chips=2)]

    class _View:
        location = "loc"
        registry = _Registry()
        queues = {}

    frame = _render_fleet_frame(_View())
    assert "4>8" in frame
    lines = [ln for ln in frame.splitlines() if "app-static" in ln]
    assert lines and " 2 " in lines[0] and ">" not in lines[0]


def test_events_render_and_roundtrip():
    from tony_tpu.events.render import render_event
    from tony_tpu.events.schema import (
        Event, ResizeCompleted, ResizeFailed, ResizeRequested,
        ResizeStarted,
    )
    for etype, payload in (
            (EventType.RESIZE_REQUESTED,
             ResizeRequested("a", "worker", 4, 8, from_chips=8,
                             to_chips=16, requested_by="arbiter")),
            (EventType.RESIZE_STARTED,
             ResizeStarted("a", "worker", 4, 8, members=4)),
            (EventType.RESIZE_COMPLETED,
             ResizeCompleted("a", "worker", 4, 8, duration_ms=1234,
                             added_tasks=4)),
            (EventType.RESIZE_FAILED,
             ResizeFailed("a", "worker", 4, 8, reason="no containers",
                          rolled_back=True))):
        ev = Event(etype, payload)
        back = Event.from_dict(ev.to_dict())
        assert back.payload == payload
        line = render_event(etype.value, ev.to_dict()["payload"])
        assert "resize" in line and "worker" in line


# ---------------------------------------------------------------------------
# executor: the resize ask
# ---------------------------------------------------------------------------

def _executor(tmp_path, **conf_overrides):
    from tony_tpu.executor.task_executor import TaskExecutor
    conf = TonyConfiguration()
    for k, v in conf_overrides.items():
        conf.set(k, v, "test")
    conf_path = str(tmp_path / "tony-final.json")
    conf.write(conf_path)
    env = {
        C.JOB_NAME: "worker", C.TASK_INDEX: "0", C.TASK_NUM: "1",
        C.IS_CHIEF: "false", C.SESSION_ID: "0", C.TASK_ATTEMPT: "0",
        C.AM_HOST: "127.0.0.1", C.AM_PORT: "1",
        C.TASK_COMMAND: "true", C.TONY_CONF_PATH: conf_path,
    }
    return TaskExecutor(env=env)


class _FakeProc:
    def __init__(self):
        self.pid = 2**31 - 1
        self.signals: list = []
        self._dead = False

    def poll(self):
        return 0 if self._dead else None

    def terminate(self):
        self.signals.append("TERM")
        self._dead = True

    def kill(self):
        self.signals.append("KILL")
        self._dead = True

    def wait(self, timeout=None):
        if self._dead:
            return 0
        import subprocess
        raise subprocess.TimeoutExpired("fake", timeout)


def test_executor_resize_ask_is_one_shot_per_id_and_acks(tmp_path):
    ex = _executor(tmp_path)
    proc = _FakeProc()
    ex._user_proc = proc
    ex._on_resize_request({"id": 1, "width": 8, "grace_ms": 200,
                           "mesh_shape": "8", "release": False})
    _wait_for(lambda: ex._resize_ack == 1, 5, "quiesce ack")
    assert proc.signals == ["TERM"]
    assert ex._respec_pending is True
    assert ex._mesh_override == "8"
    # resend of the same id: no second TERM
    ex._on_resize_request({"id": 1, "width": 8, "grace_ms": 200,
                           "mesh_shape": "8", "release": False})
    time.sleep(0.1)
    assert proc.signals == ["TERM"]
    # a corrective ask under a FRESH id re-triggers and reverts the mesh
    proc2 = _FakeProc()
    ex._user_proc = proc2
    ex._on_resize_request({"id": 2, "width": 4, "grace_ms": 200,
                           "mesh_shape": "", "release": False})
    _wait_for(lambda: ex._resize_ack == 2, 5, "revert ack")
    assert proc2.signals == ["TERM"]
    assert ex._mesh_override is None


def test_executor_release_ask_marks_resized(tmp_path):
    ex = _executor(tmp_path)
    proc = _FakeProc()
    ex._user_proc = proc
    ex._on_resize_request({"id": 3, "width": 2, "grace_ms": 100,
                           "release": True})
    _wait_for(lambda: ex._resize_ack == 3, 5, "release ack")
    assert ex._resize_release is True
    assert ex._respec_pending is False   # a victim never re-rendezvouses


# ---------------------------------------------------------------------------
# chaos e2e: membership grow/shrink over real executors
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_membership_resize_grow_shrink_e2e(tmp_path):
    """Acceptance (control-plane half): a RUNNING gang of real executors
    grows 2→4 and shrinks 4→2 through the full `cli resize` →
    request_resize → quiesce-ack → membership-diff round trip. Survivor
    containers never restart (one TASK_STARTED each), no relaunch or
    crash-attempt budget is spent, zero session retries, the RESIZE
    event trail lands in history, downtime is priced as the `resize`
    goodput phase, and the jobstate width fields settle."""
    from tests.chaos import ChaosRun
    from tony_tpu.cli.__main__ import main as cli_main
    from tony_tpu.events.history import read_goodput_file

    run = ChaosRun(tmp_path, seed=11)
    done = {}

    def _run():
        try:
            run.run(
                ["--executes", script("elastic_gang_worker.py"),
                 "--conf", "tony.worker.instances=2",
                 "--conf", "tony.worker.tpus=1",
                 "--conf", "tony.elastic.enabled=true",
                 "--conf", "tony.elastic.max-width=4",
                 "--conf", "tony.elastic.quiesce-grace-ms=20s",
                 "--conf", "tony.task.max-task-attempts=3"])
        finally:
            done["x"] = True

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    _wait_for(lambda: run.client is not None
              and run.markers("worker", 0) and run.markers("worker", 1),
              60, "gang running")
    app_dir = run.client.app_dir

    def _resize_rpc(**kwargs):
        # retried with response introspection: a `duplicate` answer means
        # the PREVIOUS resize is still settling, not that this one armed
        from tony_tpu.rpc.client import ClusterServiceClient
        with open(os.path.join(app_dir, C.AM_HOSTPORT_FILE)) as f:
            host, _, port = f.read().strip().rpartition(":")
        client = ClusterServiceClient(host, int(port))
        try:
            def attempt():
                resp = client.request_resize(**kwargs) or {}
                return not resp.get("error") and not resp.get("duplicate")
            _wait_for(attempt, 30, f"resize {kwargs} accepted")
        finally:
            client.close()

    # -- grow 2 -> 4 (through the operator CLI verb — nothing in flight,
    # so exit code 0 means armed)
    assert cli_main(["resize", app_dir, "worker", "4",
                     "--reason", "e2e grow"]) == 0
    _wait_for(lambda: run.markers("worker", 2) and run.markers("worker", 3)
              and len(run.markers("worker", 0)) >= 2, 60,
              "grown gang re-rendezvoused")
    assert run.markers("worker", 0)[-1]["spec_width"] == 4
    assert run.markers("worker", 2)[-1]["spec_width"] == 4

    # -- shrink 4 -> 2 (the victims are the highest-index tasks)
    _resize_rpc(job_name="worker", width=2, reason="e2e shrink")
    _wait_for(lambda: len(run.markers("worker", 0)) >= 3, 60,
              "shrunk gang re-rendezvoused")
    assert run.markers("worker", 0)[-1]["spec_width"] == 2

    # the resize settles only once the survivors' heartbeats report the
    # new generation — probe with a no-op ask: `duplicate` while in
    # flight, an "already at width" refusal once settled
    def _settled():
        from tony_tpu.rpc.client import ClusterServiceClient
        with open(os.path.join(app_dir, C.AM_HOSTPORT_FILE)) as f:
            host, _, port = f.read().strip().rpartition(":")
        probe = ClusterServiceClient(host, int(port))
        try:
            resp = probe.request_resize(job_name="worker", width=2) or {}
            return "already at width" in str(resp.get("error", ""))
        finally:
            probe.close()
    _wait_for(_settled, 30, "shrink resize settled")

    # -- finish cleanly
    os.makedirs(run.marker_dir, exist_ok=True)
    with open(os.path.join(run.marker_dir, "done"), "w") as f:
        f.write("done")
    _wait_for(lambda: done.get("x"), 60, "application finish")
    t.join(timeout=10)

    assert run.final_status == "SUCCEEDED", run.all_logs()
    # zero relaunches / crash budget / session retries
    assert run.relaunches() == []
    assert run.session_retry_backoffs_ms() == []
    assert all(m["attempt"] == 0
               for i in range(2) for m in run.markers("worker", i))
    # survivors kept their ONE container across both resizes
    assert len(run.task_starts("worker", 0)) == 1
    assert len(run.task_starts("worker", 1)) == 1
    # victims started exactly once and left without a completion story
    assert len(run.task_starts("worker", 2)) == 1
    assert len(run.markers("worker", 2)) == 1
    # the event trail: two full resize cycles
    for etype in (EventType.RESIZE_REQUESTED, EventType.RESIZE_STARTED,
                  EventType.RESIZE_COMPLETED):
        events = run.events_of_type(etype)
        assert len(events) == 2, (etype, events)
    grow, shrink = run.events_of_type(EventType.RESIZE_COMPLETED)
    assert (grow.payload.from_width, grow.payload.to_width) == (2, 4)
    assert (shrink.payload.from_width, shrink.payload.to_width) == (4, 2)
    assert not run.events_of_type(EventType.RESIZE_FAILED)
    # downtime priced as the resize goodput phase
    goodput = read_goodput_file(run.app_history_dir())
    assert goodput["job"]["resize_downtime_s"] > 0.0
    # jobstate width fields settled at the final width
    jobstate = json.load(open(os.path.join(run.app_history_dir(),
                                           C.JOBSTATE_FILE)))
    assert jobstate["gang_width"] == 2
    assert jobstate["requested_width"] == 2
    assert jobstate["resizes"] == 2
    assert jobstate["elastic_job"] == "worker"
    assert jobstate["gauges"]["tony_job_resizes_total"] == 2.0


# ---------------------------------------------------------------------------
# chaos e2e: mid-training re-mesh with bit-consistent loss (slow)
# ---------------------------------------------------------------------------

def _segments(report_dir: str, name: str) -> list[dict]:
    out = []
    for path in sorted(glob.glob(os.path.join(report_dir,
                                              f"{name}_s*.json"))):
        with open(path) as f:
            out.append(json.load(f))
    return out


@pytest.mark.chaos
@pytest.mark.slow
def test_resize_remesh_grow_shrink_bit_consistent_e2e(tmp_path):
    """Acceptance (training half): a real mnist trainer resizes
    mid-training in BOTH directions — 4→8 chips (grow) then 8→4
    (shrink) — via `cli resize --tpus-per-task`, each time quiescing
    through the TERM→emergency-checkpoint path (no process teardown of
    the executor), re-rendezvousing behind the generation bump, and
    reshard-restoring onto the new mesh. The loss trajectory is
    bit-consistent against the checkpoint-stop-restart twin at the SAME
    width schedule — i.e. the in-place resize is exactly equivalent to
    the full evict-and-resume round trip it replaces, minus the
    eviction. Zero relaunches, zero session retries, downtime in the
    `resize` goodput phase."""
    from tests.chaos import ChaosRun
    from tony_tpu.cli.__main__ import main as cli_main
    from tony_tpu.events.history import read_goodput_file
    from tony_tpu.train.checkpoint import latest_step

    ckpt_a = str(tmp_path / "ckpt-a")
    reports = str(tmp_path / "reports")
    total = 24
    run = ChaosRun(tmp_path, seed=23)
    done = {}

    def _run():
        try:
            run.run(
                ["--executes", script("elastic_trainer.py"),
                 "--conf", "tony.worker.instances=1",
                 "--conf", "tony.worker.tpus=4",
                 "--conf", "tony.tpu.mesh-shape=4",
                 "--conf", "tony.tpu.mesh-axes=fsdp",
                 "--conf", "tony.elastic.enabled=true",
                 "--conf", "tony.elastic.quiesce-grace-ms=60s",
                 "--conf", f"tony.execution.env=CKPT_DIR={ckpt_a}",
                 "--conf", f"tony.execution.env=REPORT_DIR={reports}",
                 "--conf", "tony.execution.env=REPORT_NAME=runA",
                 "--conf", f"tony.execution.env=TONY_REPO_ROOT={REPO}",
                 "--conf", f"tony.execution.env=TOTAL_STEPS={total}",
                 "--conf", "tony.execution.env="
                           "TONY_TRAINER_STEP_DELAY_MS=150",
                 "--conf", ("tony.execution.env=XLA_FLAGS="
                            "--xla_force_host_platform_device_count=8")])
        finally:
            done["a"] = True

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    app_dir_ready = _wait_for(
        lambda: run.client is not None and os.path.isfile(
            os.path.join(run.client.app_dir, C.AM_HOSTPORT_FILE)),
        120, "AM up")
    assert app_dir_ready
    app_dir = run.client.app_dir

    # grow 4 -> 8 chips once real progress is on disk
    _wait_for(lambda: (latest_step(ckpt_a) or 0) >= 3, 180,
              "pre-resize checkpoints")
    assert cli_main(["resize", app_dir, "worker", "0",
                     "--tpus-per-task", "8",
                     "--reason", "e2e grow"]) == 0
    seg1 = _wait_for(lambda: _segments(reports, "runA"), 120,
                     "quiesce segment report")[0]
    r1 = seg1["stopped_at"]
    # shrink 8 -> 4 once the wide mesh trained a few steps further
    _wait_for(lambda: (latest_step(ckpt_a) or 0) >= r1 + 3, 180,
              "post-grow progress")

    from tony_tpu.rpc.client import ClusterServiceClient
    with open(os.path.join(app_dir, C.AM_HOSTPORT_FILE)) as f:
        host, _, port = f.read().strip().rpartition(":")
    client = ClusterServiceClient(host, int(port))
    try:
        def _shrink():
            resp = client.request_resize(job_name="worker",
                                         tpus_per_task=4,
                                         reason="e2e shrink") or {}
            return not resp.get("error") and not resp.get("duplicate")
        _wait_for(_shrink, 120, "shrink accepted")
    finally:
        client.close()
    _wait_for(lambda: done.get("a"), 300, "run A completion")
    t.join(timeout=10)
    assert run.final_status == "SUCCEEDED", run.all_logs()

    segments = _segments(reports, "runA")
    assert len(segments) == 3, segments
    r1, r2 = segments[0]["stopped_at"], segments[1]["stopped_at"]
    assert segments[0]["mesh_width"] == 4
    assert segments[1]["resumed_from"] == r1
    assert segments[1]["mesh_width"] == 8
    assert segments[2]["resumed_from"] == r2
    assert segments[2]["mesh_width"] == 4
    assert segments[2]["stopped_at"] == total
    # no data loss at either quiesce: the exact dying step is committed
    assert segments[0]["preempted"] and segments[1]["preempted"]

    # zero relaunches / retries / crash budget; full event trail
    assert run.relaunches() == []
    assert run.session_retry_backoffs_ms() == []
    assert len(run.task_starts("worker", 0)) == 1
    assert len(run.events_of_type(EventType.RESIZE_COMPLETED)) == 2
    assert not run.events_of_type(EventType.RESIZE_FAILED)
    goodput = read_goodput_file(run.app_history_dir())
    assert goodput["job"]["resize_downtime_s"] > 0.0

    # -- the evict-and-resume twin: stop/restart at the SAME width
    # schedule through plain submits (what a resize replaces). Its
    # trajectory must match run A's bit for bit.
    from test_e2e import _dump_logs, run_job
    ckpt_t = str(tmp_path / "ckpt-twin")

    def twin_argv(name, stop_at, mesh):
        return [
            "--executes", script("elastic_trainer.py"),
            "--conf", "tony.worker.instances=1",
            "--conf", f"tony.worker.tpus={mesh}",
            "--conf", f"tony.tpu.mesh-shape={mesh}",
            "--conf", "tony.tpu.mesh-axes=fsdp",
            "--conf", f"tony.execution.env=CKPT_DIR={ckpt_t}",
            "--conf", f"tony.execution.env=REPORT_DIR={reports}",
            "--conf", f"tony.execution.env=REPORT_NAME={name}",
            "--conf", f"tony.execution.env=TONY_REPO_ROOT={REPO}",
            # identical optimizer horizon; only the stop point moves
            "--conf", f"tony.execution.env=TOTAL_STEPS={total}",
            "--conf", f"tony.execution.env=STOP_AT_STEP={stop_at}",
            "--conf", ("tony.execution.env=XLA_FLAGS="
                       "--xla_force_host_platform_device_count=8")]

    for name, stop_at, mesh in (("runT1", r1, 4), ("runT2", r2, 8),
                                ("runT3", total, 4)):
        client = run_job(tmp_path, twin_argv(name, stop_at, mesh))
        assert client.final_status == "SUCCEEDED", _dump_logs(client)

    twin_losses: dict[int, float] = {}
    for name in ("runT1", "runT2", "runT3"):
        segs = _segments(reports, name)
        assert len(segs) == 1
        twin_losses.update({s: v for s, v in segs[0]["losses"]})
    resized_losses = {s: v for seg in segments
                      for s, v in seg["losses"]}
    assert resized_losses, "resized run logged no losses"
    # BIT-consistent: every step the resized run logged matches the
    # evict-and-resume twin exactly. (The quiesce-interrupted step's
    # loss is one-interval-latent and not logged — at most one logging
    # gap per resize, never a training gap: the checkpoint/restore
    # chain above already proved the step itself committed.)
    assert len(resized_losses) >= total - 2
    for step_n, loss in sorted(resized_losses.items()):
        assert twin_losses.get(step_n) == loss, (
            step_n, loss, twin_losses.get(step_n))
