"""Control-plane token-auth tests (reference model: secure-mode wiring,
ApplicationMaster.java:432-452 + TonyClient token plumbing)."""

import os
import stat

import grpc
import pytest

from tony_tpu.rpc.client import ClusterServiceClient
from tony_tpu.rpc.service import serve
from tony_tpu.security import (
    generate_token, read_token_file, write_token_file,
)


class FakeHandler:
    def __init__(self):
        self.heartbeats = 0

    def get_task_infos(self, req):
        return []

    def get_cluster_spec(self, req):
        return {"spec": None}

    def register_worker_spec(self, req):
        return {"spec": None}

    def register_tensorboard_url(self, req):
        return {}

    def register_serving_endpoint(self, req):
        return {}

    def register_execution_result(self, req):
        return {}

    def finish_application(self, req):
        return {}

    def task_executor_heartbeat(self, req):
        self.heartbeats += 1
        return {}

    def request_profile(self, req):
        return {"request_id": "fake"}

    def report_serving_migrated(self, req):
        return {}

    def get_skew(self, req):
        return {"stragglers": []}

    def get_alerts(self, req):
        return {"firing": [], "log": []}

    def get_profile(self, req):
        return {"folded": "", "process": "fake"}

    def read_task_logs(self, req):
        return {"data": "", "next_offset": 0, "eof": False}

    def request_preemption(self, req):
        return {"app_id": "fake", "grace_ms": 1000, "deadline_ms": 1000}

    def request_rolling_update(self, req):
        return {"app_id": "fake", "generation": 1, "replicas": 0}

    def request_resize(self, req):
        return {"app_id": "fake", "from_width": 1, "to_width": 1}


def test_token_file_roundtrip_and_mode(tmp_path):
    token = generate_token()
    path = write_token_file(str(tmp_path), token)
    assert read_token_file(str(tmp_path)) == token
    mode = stat.S_IMODE(os.stat(path).st_mode)
    assert mode == 0o600


def test_server_rejects_missing_and_wrong_token():
    token = generate_token()
    handler = FakeHandler()
    server, port = serve(cluster_handler=handler, auth_token=token)
    try:
        no_token = ClusterServiceClient("localhost", port, retries=1,
                                        timeout_sec=5.0)
        with pytest.raises(grpc.RpcError) as exc:
            no_token.get_task_infos()
        assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED
        no_token.close()

        wrong = ClusterServiceClient("localhost", port, retries=1,
                                     timeout_sec=5.0, auth_token="nope")
        with pytest.raises(grpc.RpcError):
            wrong.get_task_infos()
        wrong.close()

        good = ClusterServiceClient("localhost", port, retries=1,
                                    timeout_sec=5.0, auth_token=token)
        assert good.get_task_infos() == []
        good.task_executor_heartbeat("worker:0")
        assert handler.heartbeats == 1
        good.close()
    finally:
        server.stop(grace=None)


def test_task_token_scoped_to_task_methods():
    """VERDICT-r2 item 6: a container's derived token authenticates
    task-plane RPCs but cannot call client-only methods or pose as a
    different task — a leaked container env no longer equals the client
    secret."""
    from tony_tpu.security.tokens import derive_task_token

    secret = generate_token()
    handler = FakeHandler()
    server, port = serve(cluster_handler=handler, auth_token=secret)
    try:
        task_tok = derive_task_token(secret, "worker:0")
        as_task = ClusterServiceClient("localhost", port, retries=1,
                                       timeout_sec=5.0, auth_token=task_tok,
                                       task_auth_id="worker:0")
        as_task.task_executor_heartbeat("worker:0")   # task plane: allowed
        assert handler.heartbeats == 1
        for call in (as_task.get_task_infos, as_task.finish_application):
            with pytest.raises(grpc.RpcError) as exc:
                call()
            assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        # payload identity is bound to the authenticated task: worker:0's
        # token cannot heartbeat ON BEHALF OF worker:1 (review finding)
        with pytest.raises(grpc.RpcError) as exc:
            as_task.task_executor_heartbeat("worker:1")
        assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        assert handler.heartbeats == 1
        # mixed-identity forgery: a benign task_id alongside a forged
        # job_name/job_index must not satisfy the bind — EVERY identity
        # shape in the payload is checked (review finding)
        with pytest.raises(grpc.RpcError) as exc:
            as_task.call("register_execution_result", {
                "task_id": "worker:0", "job_name": "worker",
                "job_index": 1, "exit_code": 1, "session_id": 0})
        assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        # identity-free payload on a task-plane method: denied, not
        # fail-open
        with pytest.raises(grpc.RpcError) as exc:
            as_task.call("task_executor_heartbeat", {})
        assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        as_task.close()

        # the derived token is useless under any OTHER task identity
        imposter = ClusterServiceClient("localhost", port, retries=1,
                                        timeout_sec=5.0, auth_token=task_tok,
                                        task_auth_id="worker:1")
        with pytest.raises(grpc.RpcError) as exc:
            imposter.task_executor_heartbeat("worker:1")
        assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED
        imposter.close()
    finally:
        server.stop(grace=None)


def test_secure_job_end_to_end(tmp_path):
    """Full chain with security on: client mints token, AM requires it,
    executors authenticate through env (TestTonyE2E secure-mode analogue)."""
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.conf import keys as K
    from tony_tpu.conf.configuration import TonyConfiguration

    script = os.path.join(os.path.dirname(__file__), "scripts", "exit_0.py")
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path / "cluster"), "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 200, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 2000, "test")
    conf.set(K.APPLICATION_SECURITY_ENABLED, True, "test")
    client = TonyClient(conf)
    client.init(["--executes", script, "--conf", "tony.worker.instances=2"])
    assert client.run() is True
    assert client.final_status == "SUCCEEDED"
    # token file exists, owner-only
    token_path = os.path.join(client.app_dir, ".tony-token")
    assert os.path.isfile(token_path)
    assert stat.S_IMODE(os.stat(token_path).st_mode) == 0o600


def test_planted_token_never_ships_in_tails_or_diagnostics(tmp_path):
    """Redaction contract (observability/logs.py): REAL token-scheme
    material — the app secret, a derived per-task token, env-assignment
    and Bearer forms — planted in user-process output never appears in a
    live tail chunk, a diagnostics tail excerpt, or the assembled
    failure record. This is the gate that makes shipping tails off the
    container safe at all."""
    import json

    from tony_tpu.observability.logs import (
        LogTail, classify_container_failure,
    )
    from tony_tpu.security.tokens import TOKEN_ENV, derive_task_token

    secret = generate_token()
    task_token = derive_task_token(secret, "worker:0")
    cdir = tmp_path / "worker_0_s0"
    cdir.mkdir()
    (cdir / "stderr").write_text(
        f"{TOKEN_ENV}={secret}\n"
        f"curl -H 'Authorization: Bearer {task_token}' http://am:1234\n"
        f"stray token in a traceback: {task_token}\n"
        "RuntimeError: RESOURCE_EXHAUSTED: out of memory\n")
    (cdir / "stdout").write_text(f"debug dump: secret={secret}\n")

    # live-tail chunk (the executor's read_log path)
    chunk = LogTail(str(cdir / "stderr")).read_chunk(offset=-1, final=True)
    assert secret not in chunk["data"] and task_token not in chunk["data"]
    assert "<redacted>" in chunk["data"]
    assert "RESOURCE_EXHAUSTED" in chunk["data"]   # signal survives

    # diagnostics record (executor failure report / AM fallback path)
    record = classify_container_failure(str(cdir), exit_code=1,
                                        max_lines=200)
    dumped = json.dumps(record)
    assert secret not in dumped and task_token not in dumped
    assert record["signature"] == "device_oom"


def test_planted_token_never_ships_through_alert_sinks(tmp_path):
    """Webhook-sink security (observability/alerts.py): REAL
    token-scheme material — a 64-hex app secret and a Bearer credential
    — planted in an alert annotation/message must be redacted in the
    payload delivered to BOTH the webhook POST body and the file sink;
    and a webhook pointed at a dead endpoint retries a bounded number
    of times within bounded time, then gives up."""
    import http.server
    import json
    import threading
    import time

    from tony_tpu.observability.alerts import (
        AlertContext, AlertEngine, AlertRule, FileSink, WebhookSink,
    )
    from tony_tpu.security.tokens import derive_task_token

    secret = generate_token()
    task_token = derive_task_token(secret, "worker:0")

    received = []

    class _Hook(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0) or 0)
            received.append(self.rfile.read(length).decode())
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), _Hook)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    sink_file = tmp_path / "alerts.jsonl"

    def leaky(ctx):
        return [{"key": "worker:0", "value": 1.0, "threshold": 0.0,
                 "message": f"task env held TONY_SECURITY_TOKEN={secret}",
                 "annotations": {
                     "header": f"Authorization: Bearer {task_token}",
                     "stray": task_token}}]

    engine = AlertEngine(
        [AlertRule("leak.test", leaky, for_ms=0)],
        default_for_ms=0, flap_suppress_ms=0,
        sinks=[WebhookSink(f"http://127.0.0.1:{httpd.server_port}/hook",
                           timeout_s=5.0, retries=0),
               FileSink(str(sink_file))])
    try:
        transitions = engine.evaluate(AlertContext(now_ms=1000))
        assert [t["status"] for t in transitions] == ["firing"]
        assert engine.drain(timeout_s=10.0)
        deadline = time.monotonic() + 10.0
        while (not received or not sink_file.exists()) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert received and sink_file.exists()
        for shipped in (received[0], sink_file.read_text()):
            assert secret not in shipped
            assert task_token not in shipped
            assert "<redacted>" in shipped
            payload = json.loads(shipped)
            assert payload["rule_id"] == "leak.test"   # shape survives
    finally:
        engine.close()
        httpd.shutdown()

    # bounded retry-then-give-up: nothing listens on the target; 2
    # retries at 0.2s timeout + 0.05s backoff must fail within ~2s
    dead = WebhookSink("http://127.0.0.1:9/never", timeout_s=0.2,
                       retries=2, backoff_s=0.05)
    t0 = time.monotonic()
    assert dead.deliver({"rule_id": "x"}) is False
    assert time.monotonic() - t0 < 5.0
