"""Task-level fault tolerance: bounded relaunch, jittered backoff, chaos.

The e2e cases drive real client → AM → executor → user-python chains on the
LocalClusterBackend through the deterministic chaos harness (tests/chaos.py);
the unit cases pin the decision-path mechanics (attempt budgets, backoff
shapes, liveliness gating, executor re-rendezvous) without processes.

Recovery paths proven end-to-end:
- container crash without a registered result  → relaunch (completion path)
- executor-reported non-zero exit              → relaunch (result path)
- heartbeat expiry (wedged/silent task)        → relaunch (liveliness path)
- attempt budget exhausted                     → whole-session retry w/ backoff
- app-wide failure circuit breaker             → stop relaunching, fail
"""

from __future__ import annotations

import time

import pytest

from tony_tpu import constants as C
from tony_tpu.am.application_master import (
    ApplicationMaster, session_retry_backoff_sec,
)
from tony_tpu.am.liveliness import LivelinessMonitor
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.events.schema import EventType
from tony_tpu.executor.task_executor import TaskExecutor
from tony_tpu.rpc.client import _JsonRpcClient
from tony_tpu.rpc.service import CLUSTER_SERVICE, CLUSTER_METHODS
from tony_tpu.session.session import TonySession

from tests.chaos import (
    ChaosRun, CrashAM, DelayCompletionNotification, KillTask, MissHeartbeats,
    SilenceHeartbeats, TerminateWorkers, script,
)


# ---------------------------------------------------------------------------
# chaos e2e: the relaunch decision paths (tentpole acceptance)
# ---------------------------------------------------------------------------

chaos = pytest.mark.chaos


@chaos
def test_worker_killed_midrun_is_relaunched_within_budget(tmp_path):
    """The headline scenario: a worker container hard-crashes mid-run
    (no result registered), the AM relaunches ONLY that task, the survivor
    re-rendezvouses on the bumped generation in its original container, and
    the job succeeds."""
    run = ChaosRun(tmp_path, seed=1)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.task.max-task-attempts=2"],
        injections=[KillTask("worker", 1, run.delay_ms(800, 1200),
                             attempt=0)])
    assert run.final_status == "SUCCEEDED", run.all_logs()

    rel = run.relaunches()
    assert len(rel) == 1, run.all_logs()
    assert (rel[0].task_type, rel[0].task_index) == ("worker", 1)
    assert rel[0].attempt == 1          # replacement runs as attempt 1
    assert rel[0].generation == 2       # relaunch bumped the spec generation
    assert "exited with code" in rel[0].reason

    # the victim got a replacement container; the survivor kept its own
    assert len(run.task_starts("worker", 1)) == 2
    assert len(run.task_starts("worker", 0)) == 1

    # survivor's user process restarted against the new generation — same
    # attempt (same container), new spec
    survivor = run.markers("worker", 0)
    assert [m["generation"] for m in survivor] == [1, 2], run.all_logs()
    assert [m["attempt"] for m in survivor] == [0, 0]
    # the replacement attempt launched against the post-relaunch spec
    assert run.markers("worker", 1)[-1] == {"attempt": 1, "generation": 2}


@chaos
def test_executor_reported_failure_is_relaunched(tmp_path):
    """A non-zero exit reported through register_execution_result (not a
    silent container crash) takes the same relaunch path. Fully
    deterministic: the victim only exits after every gang member's
    generation-1 marker exists."""
    run = ChaosRun(tmp_path, seed=2)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.task.max-task-attempts=2"],
        extra_env={"CHAOS_EXIT_ONE": "worker:1"})
    assert run.final_status == "SUCCEEDED", run.all_logs()
    rel = run.relaunches()
    assert len(rel) == 1, run.all_logs()
    assert "executor reported exit 1" in rel[0].reason
    assert [m["generation"] for m in run.markers("worker", 0)] == [1, 2]


@chaos
def test_heartbeat_expiry_is_relaunched(tmp_path):
    """A wedged task (user process alive, heartbeats silent) expires in the
    liveliness monitor and is relaunched instead of ending the app —
    the _on_task_deemed_dead path."""
    run = ChaosRun(tmp_path, seed=3)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
        # expiry window = 0.2s * 8 = 1.6s: quick for the silent victim,
        # roomy enough that a loaded machine can't expire a healthy
        # survivor whose heartbeats merely stall for a moment
         "--conf", "tony.task.max-task-attempts=2",
         "--conf", "tony.task.max-missed-heartbeats=8"],
        injections=[SilenceHeartbeats("worker", 1, attempt=0)])
    assert run.final_status == "SUCCEEDED", run.all_logs()
    rel = run.relaunches()
    assert len(rel) == 1, run.all_logs()
    assert "missed" in rel[0].reason and "heartbeats" in rel[0].reason
    assert len(run.task_starts("worker", 0)) == 1   # survivor kept container


@chaos
def test_exhausted_budget_falls_back_to_session_retry_with_backoff(tmp_path):
    """Budget exhaustion escalates to today's whole-session retry, which now
    waits a capped jittered exponential backoff. The backoff is
    deterministic per (app_id, attempt), so the observed delay must equal
    the recomputed one — the replay-exactly property."""
    run = ChaosRun(tmp_path, seed=4)
    run.run(
        ["--executes", script("exit_1.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-task-attempts=2",
         "--conf", "tony.am.retry-count=1",
         "--conf", "tony.am.retry-backoff-base-ms=400",
         "--conf", "tony.am.retry-backoff-max-ms=400"])
    assert run.final_status == "FAILED", run.all_logs()
    # each session burned the 2-attempt budget: 1 relaunch per session
    rel = run.relaunches()
    assert len(rel) == 2, run.all_logs()
    assert [r.attempt for r in rel] == [1, 1]
    # observable backoff between the sessions, inside the jitter envelope
    backoffs = run.session_retry_backoffs_ms()
    assert len(backoffs) == 1, run.am_log()[-4000:]
    assert 200 <= backoffs[0] <= 400
    expected_ms = session_retry_backoff_sec(
        run.client.app_id, 1, 400, 400) * 1000
    assert abs(backoffs[0] - expected_ms) <= 1  # log prints %.0f


@chaos
def test_total_failure_circuit_breaker_stops_relaunching(tmp_path):
    """tony.application.max-total-task-failures caps relaunches app-wide
    even when the per-task budget has room left."""
    run = ChaosRun(tmp_path, seed=5)
    run.run(
        ["--executes", script("exit_1.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-task-attempts=10",
         "--conf", "tony.application.max-total-task-failures=1"])
    assert run.final_status == "FAILED", run.all_logs()
    assert len(run.relaunches()) == 1, run.all_logs()
    assert "circuit breaker" in run.am_log()


# ---------------------------------------------------------------------------
# chaos e2e: the four pre-existing fault-injection hooks, with history and
# exit-code assertions (satellite coverage)
# ---------------------------------------------------------------------------

@chaos
def test_am_crash_fails_with_status_and_exit_code(tmp_path):
    run = ChaosRun(tmp_path, seed=6)
    run.run(["--executes", script("exit_0.py"),
             "--conf", "tony.worker.instances=1"],
            injections=[CrashAM()])
    assert run.final_status == "FAILED"
    assert "TEST_AM_CRASH" in run.final_message
    # the AM process itself died non-zero, like a real AM container crash
    assert run.client._am_proc.poll() == 1


@chaos
@pytest.mark.slow
def test_worker_termination_records_killed_tasks(tmp_path):
    run = ChaosRun(tmp_path, seed=7)
    run.run(["--executes", script("sleep_30.py"),
             "--conf", "tony.worker.instances=2"],
            injections=[TerminateWorkers()])
    assert run.final_status == "FAILED", run.all_logs()
    # AM-killed containers exit EXIT_KILLED_BY_AM → task status FINISHED
    finished = run.events_of_type(EventType.TASK_FINISHED)
    assert len(finished) == 2
    assert all(e.payload.status == "FINISHED" for e in finished)
    # an AM kill is not a task fault: no relaunch may fire
    assert run.relaunches() == []


@chaos
def test_missed_heartbeats_relaunch_then_exhaust(tmp_path):
    """TEST_TASK_EXECUTOR_NUM_HB_MISS composed with the attempt budget: the
    first expiry relaunches, the replacement (inheriting the hook) expires
    again, the exhausted budget fails the app with the classic message."""
    run = ChaosRun(tmp_path, seed=8)
    run.run(["--executes", script("sleep_30.py"),
             "--conf", "tony.worker.instances=1",
             "--conf", "tony.task.max-missed-heartbeats=5",
             "--conf", "tony.task.max-task-attempts=2"],
            injections=[MissHeartbeats(100)])
    assert run.final_status == "FAILED", run.all_logs()
    assert "missed" in run.final_message and "[5]" in run.final_message
    rel = run.relaunches()
    assert len(rel) == 1 and "missed" in rel[0].reason


@chaos
def test_delayed_completion_is_neither_failure_nor_relaunch(tmp_path):
    """A clean exit whose container-completion callback arrives late must
    stay a success — and must not be mistaken for a crash to relaunch."""
    run = ChaosRun(tmp_path, seed=9)
    run.run(["--executes", script("exit_0.py"),
             "--conf", "tony.worker.instances=1",
             "--conf", "tony.task.max-task-attempts=3"],
            injections=[DelayCompletionNotification(2)])
    assert run.final_status == "SUCCEEDED", run.all_logs()
    name, _ = run.history_events()
    assert "SUCCEEDED" in name
    finished = run.events_of_type(EventType.TASK_FINISHED)
    assert [e.payload.status for e in finished] == ["SUCCEEDED"]
    assert run.relaunches() == []


def test_chaos_harness_is_seed_deterministic(tmp_path):
    """Replay-exactly: the same seed yields the same injection timings (and
    exports TONY_TEST_SEED so child-process rpc jitter is pinned too)."""
    a, b = ChaosRun(tmp_path, seed=7), ChaosRun(tmp_path, seed=7)
    other = ChaosRun(tmp_path, seed=8)
    seq = [a.delay_ms(100, 1000) for _ in range(5)]
    assert seq == [b.delay_ms(100, 1000) for _ in range(5)]
    assert seq != [other.delay_ms(100, 1000) for _ in range(5)]
    kill = KillTask("worker", 1, seq[0], attempt=0)
    assert kill.env() == {C.TEST_TASK_KILL: f"worker#1#{seq[0]}#0"}


# ---------------------------------------------------------------------------
# unit: AM decision path + satellite regressions
# ---------------------------------------------------------------------------

class _StubBackend:
    off_host = False

    def __init__(self):
        self.stopped = []

    def set_callbacks(self, *a, **k): ...
    def start(self): ...
    def stop(self): ...

    def stop_container(self, cid):
        self.stopped.append(cid)

    def release_container(self, cid): ...
    def request_containers(self, *a, **k): ...


class _StubScheduler:
    def __init__(self):
        self.replacements = []

    def schedule_replacement(self, job_name):
        self.replacements.append(job_name)


def _make_am(tmp_path, **conf_kv):
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 1, "test")
    for k, v in conf_kv.items():
        conf.set(k, v, "test")
    am = ApplicationMaster(conf, "app_test_1", str(tmp_path),
                           backend=_StubBackend())
    am.session = TonySession(conf, session_id=0)
    am.scheduler = _StubScheduler()
    return am


def test_stale_session_result_keeps_liveliness_registration(tmp_path):
    """Satellite regression: register_execution_result must validate the
    session id BEFORE unregistering from the liveliness monitor — a stale
    previous-session executor reporting a same-named task must not strip
    the current session's task from monitoring."""
    am = _make_am(tmp_path)
    am.hb_monitor.register("worker:0")
    am.register_execution_result({"job_name": "worker", "job_index": 0,
                                  "exit_code": 0, "session_id": 99})
    assert am.hb_monitor.registered("worker:0"), \
        "stale-session result stripped the live task from monitoring"
    # the genuine session's result does unregister and complete the task
    am.register_execution_result({"job_name": "worker", "job_index": 0,
                                  "exit_code": 0, "session_id": 0})
    assert not am.hb_monitor.registered("worker:0")
    assert am.session.get_task("worker", 0).completed


def test_superseded_attempt_result_is_ignored(tmp_path):
    """A zombie executor of a relaunched-past attempt reporting its exit
    must not complete (or fail) the replacement attempt."""
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 3})
    am.session.relaunch_task("worker", 0)   # current attempt becomes 1
    am.register_execution_result({"job_name": "worker", "job_index": 0,
                                  "exit_code": 1, "session_id": 0,
                                  "task_attempt": 0})
    task = am.session.get_task("worker", 0)
    assert not task.completed and task.attempt == 1


def test_relaunch_budget_and_circuit_breaker_unit(tmp_path):
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 2})
    task = am.session.get_task("worker", 0)
    task.container_id = "c1"
    assert am._maybe_relaunch_task(task, "boom") is True
    assert am.backend.stopped == ["c1"]
    assert am.scheduler.replacements == ["worker"]
    assert task.attempt == 1 and am.session.spec_generation == 2
    # budget (2 attempts) now exhausted → falls back to session failure
    assert am._maybe_relaunch_task(task, "boom again") is False

    am2 = _make_am(tmp_path, **{
        "tony.task.max-task-attempts": 10,
        "tony.application.max-total-task-failures": 0})
    t2 = am2.session.get_task("worker", 0)
    assert am2._maybe_relaunch_task(t2, "boom") is False  # breaker at 0


def test_relaunch_fence_absorbs_second_observer_of_same_crash(tmp_path):
    """One crash has up to three observers (executor result, container
    completion, heartbeat expiry) racing without the AM lock: the second
    observer of the SAME attempt's failure must be absorbed — not burn a
    second budget slot, double-count the circuit breaker, or fail the
    in-flight replacement."""
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 2})
    task = am.session.get_task("worker", 0)
    task.container_id = "c1"
    assert am._maybe_relaunch_task(task, "crash", observed_attempt=0) is True
    assert am._maybe_relaunch_task(task, "crash", observed_attempt=0) is True
    assert task.attempt == 1                    # relaunched exactly once
    assert am._total_task_failures == 1         # counted exactly once
    assert am.scheduler.replacements == ["worker"]
    # a genuinely NEW failure of the replacement is not fenced: budget is
    # exhausted, so it falls through to the session path
    assert am._maybe_relaunch_task(task, "crash", observed_attempt=1) is False


def test_rendezvous_timeout_exit_never_relaunches(tmp_path):
    """A flagged barrier timeout signals missing allocation, not a task
    fault: spending relaunch budget on it would stop healthy containers
    and re-arm the allocation deadline exactly when the pool is
    starved."""
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 5})
    task = am.session.get_task("worker", 0)
    task.container_id = "c1"
    am.hb_monitor.register("worker:0")
    am.register_execution_result({
        "job_name": "worker", "job_index": 0, "session_id": 0,
        "exit_code": C.EXIT_RENDEZVOUS_TIMEOUT, "task_attempt": 0,
        "barrier_timeout": True})
    assert task.completed and task.attempt == 0     # no relaunch
    assert am.scheduler.replacements == []


def test_user_exit_code_10_still_relaunches(tmp_path):
    """A user process exiting with the same numeric value as
    EXIT_RENDEZVOUS_TIMEOUT is a genuine fault (no barrier_timeout flag)
    and must keep its relaunch budget — the no-relaunch decision rides
    the flag, never the exit code."""
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 5})
    task = am.session.get_task("worker", 0)
    task.container_id = "c1"
    am.register_execution_result({
        "job_name": "worker", "job_index": 0, "session_id": 0,
        "exit_code": C.EXIT_RENDEZVOUS_TIMEOUT, "task_attempt": 0})
    assert not task.completed and task.attempt == 1
    assert am.scheduler.replacements == ["worker"]


def test_relaunch_declined_once_a_tracked_peer_completed(tmp_path):
    """A completed peer cannot re-enter the barrier — relaunching the
    failed task would hang its replacement against a dead endpoint, so the
    failure falls back to the session ladder instead."""
    conf_kv = {"tony.task.max-task-attempts": 5, "tony.worker.instances": 2}
    am = _make_am(tmp_path, **conf_kv)
    done, failed = am.session.get_task("worker", 0), \
        am.session.get_task("worker", 1)
    done.set_exit_status(0)
    failed.container_id = "c2"
    assert am._maybe_relaunch_task(failed, "crash", observed_attempt=0) \
        is False
    assert failed.attempt == 0 and am.scheduler.replacements == []


def test_liveliness_register_is_attempt_monotonic():
    """A stalled registration thread of a superseded attempt must not
    downgrade the replacement's entry — the downgraded attempt would make
    the replacement's real expiry look stale and be fenced forever."""
    mon = LivelinessMonitor(hb_interval_ms=1000, max_missed=3,
                            on_expired=lambda tid, att: None)
    mon.register("worker:0", attempt=1)      # the replacement
    mon.register("worker:0", attempt=0)      # stale thread resumes late
    assert mon.entry("worker:0")[1] == 1
    mon.register("worker:0", attempt=2)      # a newer attempt upgrades
    assert mon.entry("worker:0")[1] == 2


def test_stale_session_failure_is_absorbed_not_relaunched(tmp_path):
    """A failure observer from a superseded session racing an AM retry
    must neither relaunch nor complete the NEW session's same-named
    slot."""
    am = _make_am(tmp_path, **{"tony.task.max-task-attempts": 5})
    conf = am.conf
    old_task = am.session.get_task("worker", 0)
    old_task.container_id = "c_old"
    am.session = TonySession(conf, session_id=1)      # AM retried
    fresh = am.session.get_task("worker", 0)
    assert am._maybe_relaunch_task(old_task, "stale crash",
                                   observed_attempt=0) is True  # absorbed
    assert fresh.attempt == 0 and not fresh.completed
    assert am.scheduler.replacements == []


def test_executor_bounded_rerendezvous_gives_up(monkeypatch):
    """An executor the AM answers but never accepts (superseded attempt
    that outlived its container stop) must stop polling after a bounded
    number of rounds instead of spamming the AM for the application's
    life — and its report is flagged as a barrier problem."""
    ex = _make_executor()
    reported = []
    regs = {"n": 0}

    def fake_register():
        regs["n"] += 1
        if regs["n"] == 1:
            ex._spec_generation = 1
            return {"worker": ["localhost:1"]}
        return None                      # AM keeps rejecting us

    def fake_execute(env, timeout):
        ex._on_generation(2)             # peer relaunch → respec
        return -9

    monkeypatch.setattr(ex, "localize_resources", lambda: None)
    monkeypatch.setattr(ex, "register_and_get_cluster_spec", fake_register)
    monkeypatch.setattr(ex, "_execute", fake_execute)
    monkeypatch.setattr(ex, "_report",
                        lambda rc, barrier_timeout=False, preempted=False,
                        resized=False:
                        reported.append((rc, barrier_timeout)))
    assert ex.run() == C.EXIT_FAILURE
    assert regs["n"] == 4                # 1 initial + 3 bounded rounds
    assert reported == [(C.EXIT_FAILURE, True)]


def test_untracked_and_completed_tasks_never_relaunch(tmp_path):
    am = _make_am(tmp_path, **{
        "tony.task.max-task-attempts": 5,
        "tony.application.untracked.jobtypes": "worker"})
    task = am.session.get_task("worker", 0)
    assert am._maybe_relaunch_task(task, "boom") is False
    am3 = _make_am(tmp_path, **{"tony.task.max-task-attempts": 5})
    t3 = am3.session.get_task("worker", 0)
    t3.set_exit_status(1)
    assert am3._maybe_relaunch_task(t3, "boom") is False


def test_session_relaunch_invalidates_registration_and_bumps_generation():
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 2, "test")
    session = TonySession(conf)
    session.num_expected_tasks = 2
    assert session.spec_generation == 1
    session.register_worker_spec("worker:0", "h0:1")
    spec, gen, accepted = session.register_worker_spec_with_generation(
        "worker:1", "h1:2")
    assert spec is not None and gen == 1 and accepted
    session.relaunch_task("worker", 1)
    assert session.spec_generation == 2
    assert not session.all_tasks_registered()          # barrier re-opened
    assert session.get_task("worker", 1).attempt == 1
    # a superseded attempt's in-flight registration is fenced under the
    # session lock — it must not re-fill the barrier it was evicted from
    spec, gen, accepted = session.register_worker_spec_with_generation(
        "worker:1", "h1:2", expected_attempt=0)
    assert spec is None and not accepted
    assert not session.all_tasks_registered()
    # replacement re-registers under the same id; barrier closes on gen 2
    spec, gen, accepted = session.register_worker_spec_with_generation(
        "worker:1", "h2:3", expected_attempt=1)
    assert spec is not None and gen == 2 and accepted and "h2:3" in spec


def test_max_task_attempts_per_jobtype_override():
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 1, "test")
    conf.set("tony.ps.instances", 1, "test")
    conf.set(K.TASK_MAX_TASK_ATTEMPTS, 2, "test")
    conf.set(K.max_task_attempts_key("ps"), 4, "test")
    session = TonySession(conf)
    assert session.max_task_attempts("worker") == 2
    assert session.max_task_attempts("ps") == 4
    # default (no keys) is 1 = the all-or-nothing reference behavior
    assert TonySession(TonyConfiguration()).max_task_attempts("worker") == 1


def test_liveliness_ping_never_resurrects_unknown_task():
    mon = LivelinessMonitor(hb_interval_ms=1000, max_missed=3,
                            on_expired=lambda tid, attempt: None)
    assert mon.ping("worker:0") is False     # never registered
    mon.register("worker:0")
    assert mon.ping("worker:0") is True
    mon.unregister("worker:0")
    assert mon.ping("worker:0") is False     # zombie stays dead
    assert not mon.registered("worker:0")


def test_liveliness_expiry_reports_the_silent_attempt():
    """The expiry callback carries the attempt the entry belonged to, so a
    stale expiry delivered after a relaunch can be fenced by the AM."""
    expired = []
    mon = LivelinessMonitor(hb_interval_ms=10, max_missed=3,
                            on_expired=lambda tid, att: expired.append(
                                (tid, att)))
    mon.register("worker:0", attempt=2)
    mon.start()
    try:
        deadline = time.monotonic() + 5
        while not expired and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        mon.stop()
    assert expired == [("worker:0", 2)]
    assert not mon.registered("worker:0")    # dropped before the callback


# ---------------------------------------------------------------------------
# unit: jittered backoff shapes (rpc client + session retry)
# ---------------------------------------------------------------------------

def test_rpc_backoff_is_capped_and_seed_deterministic(monkeypatch):
    monkeypatch.setenv(C.TEST_SEED, "42")
    mk = lambda: _JsonRpcClient(CLUSTER_SERVICE, CLUSTER_METHODS, "localhost", 1,
                                retry_sleep_sec=0.5, retry_max_sleep_sec=4.0)
    a, b = mk(), mk()
    try:
        seq_a = [a._backoff_sec(i) for i in range(8)]
        seq_b = [b._backoff_sec(i) for i in range(8)]
        # same seed + endpoint → identical jitter (replay-exactly)
        assert seq_a == seq_b
        for i, s in enumerate(seq_a):
            cap = min(4.0, 0.5 * 2 ** i)
            assert cap / 2 <= s <= cap     # equal-jitter envelope
    finally:
        a.close()
        b.close()


def test_rpc_backoff_unseeded_clients_decorrelate(monkeypatch):
    monkeypatch.delenv(C.TEST_SEED, raising=False)
    mk = lambda: _JsonRpcClient(CLUSTER_SERVICE, CLUSTER_METHODS, "localhost", 1,
                                retry_sleep_sec=0.5, retry_max_sleep_sec=4.0)
    a, b = mk(), mk()
    try:
        # 8 independent uniform draws colliding exactly ≈ impossible —
        # lockstep here is precisely the thundering herd being removed
        assert [a._backoff_sec(i) for i in range(8)] != \
               [b._backoff_sec(i) for i in range(8)]
    finally:
        a.close()
        b.close()


def test_heartbeat_fast_path_fails_fast_without_backoff():
    """retries=1 (the heartbeat path) must never enter the backoff sleep —
    a dead AM is detected in well under a single backoff period."""
    from tony_tpu.rpc.client import ClusterServiceClient
    from tony_tpu.utils.common import pick_free_port
    c = ClusterServiceClient("localhost", pick_free_port())
    try:
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            c.task_executor_heartbeat("worker:0")
        assert time.monotonic() - t0 < 2.0
    finally:
        c.close()


def test_session_retry_backoff_deterministic_and_capped():
    f = session_retry_backoff_sec
    assert f("app1", 1, 1000, 30_000) == f("app1", 1, 1000, 30_000)
    assert f("app1", 1, 1000, 30_000) != f("app2", 1, 1000, 30_000)
    # grows exponentially until the cap, inside the equal-jitter envelope
    for attempt in range(1, 10):
        cap = min(30.0, 1.0 * 2 ** (attempt - 1))
        got = f("app1", attempt, 1000, 30_000)
        assert cap / 2 <= got <= cap
    assert f("app1", 5, 0, 30_000) == 0.0       # base 0 disables backoff
    assert f("app1", 0, 1000, 30_000) == 0.0


# ---------------------------------------------------------------------------
# unit: executor re-rendezvous state machine + port-reservation hygiene
# ---------------------------------------------------------------------------

def _make_executor():
    return TaskExecutor(env={
        C.JOB_NAME: "worker", C.TASK_INDEX: "0",
        C.AM_HOST: "localhost", C.AM_PORT: "1",
        C.TASK_COMMAND: "true",
    })


def test_executor_releases_port_on_rendezvous_timeout(monkeypatch):
    """Satellite regression: the gang-rendezvous-timeout exit path must
    release the SO_REUSEPORT reservation like every other path."""
    ex = _make_executor()
    reported = []
    monkeypatch.setattr(ex, "localize_resources", lambda: None)
    monkeypatch.setattr(ex, "register_and_get_cluster_spec", lambda: None)
    monkeypatch.setattr(ex, "_report",
                        lambda rc, barrier_timeout=False, preempted=False,
                        resized=False: reported.append(
                            (rc, barrier_timeout)))
    assert ex.run() == C.EXIT_RENDEZVOUS_TIMEOUT
    assert reported == [(C.EXIT_RENDEZVOUS_TIMEOUT, True)]
    assert ex._port_reservation is None, "reservation leaked on timeout path"


def test_executor_respec_loop_restarts_user_process(monkeypatch):
    """A generation bump between launches sends the executor back to the
    barrier exactly once; only the final attempt's exit code is reported."""
    ex = _make_executor()
    calls = {"reg": 0, "exec": 0, "reported": []}

    def fake_register():
        calls["reg"] += 1
        ex._spec_generation = calls["reg"]
        return {"worker": ["localhost:1"]}

    def fake_execute(env, timeout):
        calls["exec"] += 1
        assert env[C.SPEC_GENERATION] == str(ex._spec_generation)
        if calls["exec"] == 1:
            ex._on_generation(2)        # peer relaunched mid-run
            return -9                   # our user proc was killed
        return 0

    monkeypatch.setattr(ex, "localize_resources", lambda: None)
    monkeypatch.setattr(ex, "register_and_get_cluster_spec", fake_register)
    monkeypatch.setattr(ex, "_execute", fake_execute)
    monkeypatch.setattr(ex, "_report",
                        lambda rc, barrier_timeout=False, preempted=False,
                        resized=False:
                        calls["reported"].append(rc))
    assert ex.run() == 0
    assert calls["reg"] == 2 and calls["exec"] == 2
    assert calls["reported"] == [0]
    assert ex._port_reservation is None


def test_executor_probes_generation_after_collateral_exit(monkeypatch):
    """A survivor whose collectives die from a peer's crash can exit
    non-zero BEFORE the next heartbeat delivers the generation bump: the
    executor probes the AM once and re-rendezvouses instead of reporting a
    failure that would burn its own attempt budget (and cascade a single
    fault into gang-wide relaunches)."""
    ex = _make_executor()
    calls = {"reg": 0, "exec": 0, "reported": []}

    def fake_register():
        calls["reg"] += 1
        ex._spec_generation = calls["reg"]
        return {"worker": ["localhost:1"]}

    def fake_execute(env, timeout):
        calls["exec"] += 1
        return 1 if calls["exec"] == 1 else 0  # collateral crash, then clean

    monkeypatch.setattr(ex, "localize_resources", lambda: None)
    monkeypatch.setattr(ex, "register_and_get_cluster_spec", fake_register)
    monkeypatch.setattr(ex, "_execute", fake_execute)
    monkeypatch.setattr(ex, "_report",
                        lambda rc, barrier_timeout=False, preempted=False,
                        resized=False:
                        calls["reported"].append(rc))
    monkeypatch.setattr(ex.client, "task_executor_heartbeat",
                        lambda tid, att=-1: {"spec_generation": 2})
    assert ex.run() == 0
    assert calls["reg"] == 2 and calls["exec"] == 2
    assert calls["reported"] == [0]


def test_executor_genuine_failure_is_still_reported(monkeypatch):
    """With no generation bump at the AM, a non-zero exit is a genuine
    fault and must be reported as such (the victim's own crash path)."""
    ex = _make_executor()
    reported = []
    monkeypatch.setattr(ex, "localize_resources", lambda: None)
    monkeypatch.setattr(ex, "register_and_get_cluster_spec",
                        lambda: (setattr(ex, "_spec_generation", 1)
                                 or {"worker": ["localhost:1"]}))
    monkeypatch.setattr(ex, "_execute", lambda env, t: 1)
    monkeypatch.setattr(ex, "_report",
                        lambda rc, barrier_timeout=False, preempted=False,
                        resized=False:
                        reported.append((rc, barrier_timeout)))
    monkeypatch.setattr(ex.client, "task_executor_heartbeat",
                        lambda tid, att=-1: {"spec_generation": 1})
    assert ex.run() == 1
    assert reported == [(1, False)]


def test_executor_generation_gating():
    """Bumps are ignored before the first barrier completes (the barrier
    itself returns the freshest spec), armed exactly once after."""
    ex = _make_executor()
    ex._on_generation(5)                      # pre-barrier: no respec
    assert not ex._respec_pending
    ex._spec_generation = 5                   # barrier done at gen 5
    ex._on_generation(5)                      # same generation: no-op
    assert not ex._respec_pending
    ex._on_generation(6)                      # peer relaunch
    assert ex._respec_pending
    assert ex._take_respec() is True
    assert ex._take_respec() is False         # consumed
