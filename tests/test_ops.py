"""Op parity tests: flash attention / rmsnorm / rope vs reference math.

The pallas kernels compile only on TPU; on the CPU test platform the
dispatcher uses the blockwise-jnp path, which shares the exact online-softmax
math with the kernel — these tests pin that math (and gradients) against the
O(S^2) oracle. The kernel itself is additionally exercised in interpret mode
for one small case.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops.attention import (
    flash_attention, reference_attention, _blockwise_forward, _pallas_forward,
)
from tony_tpu.ops.rmsnorm import rms_norm, _rms_reference
from tony_tpu.ops.rope import apply_rope, rope_frequencies


def _qkv(b=2, h=2, s=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    q, k, v = _qkv(s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(gf, gr, atol=5e-4, rtol=5e-4)


def test_flash_non_divisible_uses_small_blocks():
    # seq shorter than the default block: block size clamps to seq
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, True)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_non_divisible_long_length_pads(causal):
    """Lengths > block that don't divide it go through the pad+mask path,
    including gradients."""
    q, k, v = _qkv(b=1, s=192)
    out = flash_attention(q, k, v, causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal) ** 2))(q)
    g2 = jax.grad(
        lambda q: jnp.sum(reference_attention(q, k, v, causal) ** 2))(q)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


def test_pallas_kernel_interpret_mode():
    """Run the actual pallas kernel (interpreted on CPU) against the oracle."""
    q, k, v = _qkv(b=1, h=2, s=128, d=64)
    out, lse = _pallas_forward(q, k, v, causal=True, sm_scale=64 ** -0.5,
                               block_q=64, block_k=64, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # lse finite and ordered sanely
    assert np.isfinite(np.asarray(lse)).all()


def test_blockwise_forward_lse():
    q, k, v = _qkv(s=128)
    out, lse = _blockwise_forward(q, k, v, False, 64 ** -0.5, 64)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, v * 0 + k) * 64 ** -0.5
    ref_lse = jax.nn.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(lse, ref_lse, atol=1e-4, rtol=1e-4)


def test_flash_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, True)
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2,
                               rtol=3e-2)


def test_rms_norm_matches_reference_and_grads():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 256))
    w = jax.random.normal(jax.random.PRNGKey(1), (256,)) + 1.0
    np.testing.assert_allclose(rms_norm(x, w), _rms_reference(x, w, 1e-6),
                               atol=1e-6, rtol=1e-5)

    def loss(x, w):
        return jnp.sum(rms_norm(x, w) ** 2)

    def loss_ref(x, w):
        return jnp.sum(_rms_reference(x, w, 1e-6) ** 2)

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx, gx_r, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(gw, gw_r, atol=1e-4, rtol=1e-4)


def test_rope_properties():
    cos, sin = rope_frequencies(64, 128)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 128, 64))
    y = apply_rope(x, cos, sin)
    # norm-preserving per pair
    np.testing.assert_allclose(
        jnp.linalg.norm(y, axis=-1), jnp.linalg.norm(x, axis=-1),
        atol=1e-4, rtol=1e-4)
    # position 0 is identity
    np.testing.assert_allclose(y[:, :, 0], x[:, :, 0], atol=1e-5)
    # explicit positions reproduce the default
    pos = jnp.arange(128)
    y2 = apply_rope(x, cos, sin, positions=pos)
    np.testing.assert_allclose(y, y2, atol=1e-6)
    # batched (B, S) positions align with the batch dim, not heads
    xb = x[:2]
    pos_b = jnp.stack([jnp.arange(128), jnp.arange(10, 138)])
    yb = apply_rope(xb, cos[:256] if cos.shape[0] >= 138 else
                    rope_frequencies(64, 256)[0],
                    rope_frequencies(64, 256)[1], positions=pos_b)
    y_row0 = apply_rope(xb[:1], *rope_frequencies(64, 256),
                        positions=jnp.arange(128))
    np.testing.assert_allclose(yb[0], y_row0[0], atol=1e-6)
    # relative-position property: dot(q_m, k_n) depends only on m - n
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, 64))
    k = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, 64))
    qk = []
    for m, n in [(5, 3), (105, 103)]:
        qm = apply_rope(q, cos, sin, positions=jnp.array([m]))
        kn = apply_rope(k, cos, sin, positions=jnp.array([n]))
        qk.append(float(jnp.sum(qm * kn)))
    assert abs(qk[0] - qk[1]) < 1e-3


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_kernels_match_blockwise(causal):
    """The TPU backward kernels (interpret mode here) must match the
    blockwise-jnp backward, including padded kv_len masking."""
    from tony_tpu.ops import attention as A

    s, d, kv_len = 256, 32, 200   # kv_len < s exercises the pad mask
    ks = jax.random.split(jax.random.PRNGKey(7 + causal), 4)
    q, k, v, g = (jax.random.normal(kk, (1, 2, s, d)) for kk in ks)
    out, lse = A._blockwise_forward(q, k, v, causal, d ** -0.5, 128,
                                    kv_len=kv_len)
    want = A._blockwise_backward(q, k, v, out, lse, g, causal, d ** -0.5,
                                 128, kv_len=kv_len)
    got = A._pallas_backward(q, k, v, out, lse, g, causal, d ** -0.5,
                             128, 128, kv_len, interpret=True)
    for name, w, got_g in zip(("dq", "dk", "dv"), want, got):
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(w),
                                   atol=2e-4, rtol=2e-4, err_msg=name)

# ---------------------------------------------------------------------------
# GQA-native paths: narrow (B, Hkv, S, D) K/V through every branch
# ---------------------------------------------------------------------------

def _gqa_qkv(b=1, h=4, hk=2, s=128, d=32, seed=3):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hk, s, d))
    v = jax.random.normal(ks[2], (b, hk, s, d))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_reference(causal):
    """Blockwise path with narrow K/V vs the broadcast oracle, incl. all
    three gradients (dK/dV come back group-reduced to the narrow layout)."""
    q, k, v = _gqa_qkv()
    out = flash_attention(q, k, v, causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for name, gf, gr in zip(("dq", "dk", "dv"), g_flash, g_ref):
        assert gf.shape == gr.shape, name
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4, err_msg=name)


def test_pallas_gqa_kernels_interpret_mode():
    """The actual pallas kernels with the GQA K/V row map (interpreted on
    CPU): forward vs oracle, backward vs the blockwise backward."""
    from tony_tpu.ops import attention as A

    q, k, v = _gqa_qkv(b=2, h=4, hk=2, s=128, d=32)
    sm = 32 ** -0.5
    out, lse = A._pallas_forward(q, k, v, causal=True, sm_scale=sm,
                                 block_q=64, block_k=64, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g = jax.random.normal(jax.random.PRNGKey(9), q.shape)
    want = A._blockwise_backward(q, k, v, out, lse, g, True, sm, 64)
    got = A._pallas_backward(q, k, v, out, lse, g, True, sm, 64, 64,
                             None, interpret=True)
    for name, w, got_g in zip(("dq", "dk", "dv"), want, got):
        assert got_g.shape == w.shape, name
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(w),
                                   atol=2e-4, rtol=2e-4, err_msg=name)


def test_rope_long_context_scaling():
    """Llama-3.1 rescale: high-frequency components untouched, fully
    low-frequency ones slowed by exactly `factor`, band in between
    monotonic — and the scaled tables match unscaled inside the original
    context for local-geometry dims."""
    import numpy as np

    from tony_tpu.ops.rope import rope_frequencies, scale_rope_frequencies
    import jax.numpy as jnp

    head_dim, orig, factor = 128, 512, 8.0
    inv = 1.0 / (10_000.0 ** (jnp.arange(0, head_dim, 2,
                                         dtype=jnp.float32) / head_dim))
    scaled = scale_rope_frequencies(inv, factor, orig)
    wavelen = np.asarray(2.0 * np.pi / inv)
    s, i = np.asarray(scaled), np.asarray(inv)
    hi = wavelen < orig / 4.0          # clearly-local dims
    lo = wavelen > orig / 1.0          # never completed a period
    assert hi.any() and lo.any()
    np.testing.assert_array_equal(s[hi], i[hi])
    np.testing.assert_allclose(s[lo], i[lo] / factor, rtol=1e-6)
    mid = ~(hi | lo)
    if mid.any():                       # band interpolates within bounds
        assert (s[mid] <= i[mid] + 1e-9).all()
        assert (s[mid] >= i[mid] / factor - 1e-9).all()

    # table-level: the rescale flows into rope_frequencies — the slowest
    # component's accumulated phase at the last position shrinks by ~factor
    # (acos of its cos row recovers phase while phase < pi)
    cos_u, _ = rope_frequencies(64, 256, scaling_factor=0.0)
    cos_s, _ = rope_frequencies(64, 256, scaling_factor=8.0,
                                orig_max_seq=128)
    assert cos_u.shape == cos_s.shape
    phase_u = float(np.arccos(np.clip(np.asarray(cos_u)[255, -1], -1, 1)))
    phase_s = float(np.arccos(np.clip(np.asarray(cos_s)[255, -1], -1, 1)))
    assert 0 < phase_s < phase_u
    np.testing.assert_allclose(phase_s, phase_u / 8.0, rtol=1e-2)


def test_segmented_long_seq_flash_matches_reference(monkeypatch):
    """Sequences longer than LONG_SEQ_CHUNK split into VMEM-sized
    segments merged by the exact lse rule — forward AND gradients must
    match the unsegmented path (threshold shrunk so the segmented code
    runs at test sizes); causal, non-causal, GQA, and padded-kv cases."""
    import tony_tpu.ops.attention as att

    monkeypatch.setattr(att, "LONG_SEQ_CHUNK", 64)
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    b, h, hk, s, d = 2, 4, 2, 256, 16   # 4 segments of 64
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.float32)
    g = jax.random.normal(kg, (b, h, s, d), jnp.float32)

    for causal in (True, False):
        def loss(q, k, v, causal=causal):
            return jnp.sum(att.flash_attention(q, k, v, causal,
                                               block_q=64, block_k=64) * g)

        want_out = att.reference_attention(q, k, v, causal)
        got_out = att.flash_attention(q, k, v, causal, block_q=64,
                                      block_k=64)
        np.testing.assert_allclose(np.asarray(got_out),
                                   np.asarray(want_out), atol=2e-5,
                                   rtol=2e-5, err_msg=f"causal={causal}")
        got_grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref_loss(q, k, v, causal=causal):
            return jnp.sum(att.reference_attention(q, k, v, causal) * g)

        want_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(got_grads, want_grads, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-5, rtol=5e-4,
                err_msg=f"d{name} causal={causal}")

    # padded tail: a 224-length sequence pads to 256 inside
    # flash_attention, so the last segment runs with a partial kv_len
    s2 = 224
    q2, k2, v2 = q[:, :, :s2], k[:, :, :s2], v[:, :, :s2]
    got = att.flash_attention(q2, k2, v2, True, block_q=64, block_k=64)
    want = att.reference_attention(q2, k2, v2, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_segmented_pallas_kernels_interpret_mode(monkeypatch):
    """The REAL pallas kernels (interpret mode), forced through the full
    dispatch stack WITH segmentation: proves the segmented path composes
    with the kernels themselves, not only the blockwise fallback."""
    import tony_tpu.ops.attention as att

    monkeypatch.setattr(att, "LONG_SEQ_CHUNK", 64)
    monkeypatch.setattr(att, "_FORCE", "pallas")
    monkeypatch.setattr(att, "_INTERPRET", True)
    key = jax.random.PRNGKey(7)
    kq, kk, kv, kg = jax.random.split(key, 4)
    b, h, hk, s, d = 1, 2, 1, 128, 16    # 2 segments, GQA
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.float32)
    g = jax.random.normal(kg, (b, h, s, d), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(att.flash_attention(q, k, v, True, block_q=32,
                                           block_k=32) * g)

    got = att.flash_attention(q, k, v, True, block_q=32, block_k=32)
    want = att.reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    got_dq, got_dk, got_dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def ref_loss(q, k, v):
        return jnp.sum(att.reference_attention(q, k, v, True) * g)

    want_g = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for got_, want_, name in zip((got_dq, got_dk, got_dv), want_g, "qkv"):
        np.testing.assert_allclose(np.asarray(got_), np.asarray(want_),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")
