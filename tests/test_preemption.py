"""Checkpoint-then-evict preemption (PR 10).

Unit layer: the admission arbiter's gang-atomic decisions (queues,
shares, per-user quotas, priority, minimal victim sets), checkpoint
retention GC on both commit protocols, the trainer's emergency-save
paths, the executor's configurable TERM grace, and the goodput
aggregation's preemption-downtime pricing.

E2E layer (chaos): a lower-priority running trainer is selected as the
victim by the arbiter over the LIVE fleet registry, drained via
request_preemption (TERM → emergency checkpoint inside the grace window
→ PREEMPTED result — no SIGKILL data loss), then re-admitted at a
NARROWER width whose mesh restores through the resharding path, with
the eviction→resume gap priced in goodput.json and the whole story on
the history/event surfaces.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import threading
import time

import jax.numpy as jnp
import pytest

from tony_tpu import constants as C
from tony_tpu.cluster.arbiter import (
    ADMIT, PREEMPT, QUEUE, Arbiter, GangAsk, execute_preemption,
)
from tony_tpu.conf import TonyConfiguration, keys as K

pytestmark = pytest.mark.preemption

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


# ---------------------------------------------------------------------------
# arbiter: gang-atomic admission
# ---------------------------------------------------------------------------

def test_gang_admission_is_all_or_nothing_no_deadlock():
    """ROADMAP item 1's canonical case: a 48-wide ask never deadlocks
    against two 32-wide ones, because chips are never partially held —
    the ask queues whole and admits whole once both release."""
    arb = Arbiter(total_chips=64)
    assert arb.admit(GangAsk("a", 32, priority=1, started_ms=1)).admitted
    assert arb.admit(GangAsk("b", 32, priority=1, started_ms=2)).admitted
    decision = arb.decide(GangAsk("c", 48, priority=1))
    assert decision.action == QUEUE
    assert not decision.victims            # nothing partially granted
    arb.release("a")
    # 32 free < 48: STILL queued whole — no incremental hold
    assert arb.decide(GangAsk("c", 48, priority=1)).action == QUEUE
    assert arb.used_chips() == 32
    arb.release("b")
    assert arb.decide(GangAsk("c", 48, priority=1)).action == ADMIT


def test_victim_selection_lowest_priority_then_youngest_minimal():
    arb = Arbiter(total_chips=8)
    arb.admit(GangAsk("low-old", 2, priority=0, started_ms=10))
    arb.admit(GangAsk("low-young", 2, priority=0, started_ms=20))
    arb.admit(GangAsk("mid", 4, priority=3, started_ms=5))
    # 2-chip ask: ONE victim suffices — the youngest lowest-priority job
    d = arb.decide(GangAsk("hi", 2, priority=5))
    assert d.action == PREEMPT
    assert [v.app_id for v in d.victims] == ["low-young"]
    # 4-chip ask: both priority-0 jobs, never the mid-priority one
    d = arb.decide(GangAsk("hi4", 4, priority=5))
    assert sorted(v.app_id for v in d.victims) == ["low-old", "low-young"]
    # equal priority is never a victim: a priority-3 ask can only evict
    # the priority-0 jobs, not its peer
    d = arb.decide(GangAsk("peer", 4, priority=3))
    assert d.action == PREEMPT
    assert "mid" not in [v.app_id for v in d.victims]
    # 8-chip ask at priority 4: even evicting every lower-priority job
    # (2+2) cannot free 8 while mid (priority 3... eligible) — all three
    # eligible frees the pool
    d = arb.decide(GangAsk("all", 8, priority=4))
    assert d.action == PREEMPT
    assert sorted(v.app_id for v in d.victims) == [
        "low-old", "low-young", "mid"]
    # priority 0 ask can evict nobody
    assert arb.decide(GangAsk("meek", 8, priority=0)).action == QUEUE


def test_victim_set_is_minimal_when_sizes_differ():
    """The greedy pass may over-collect; the reverse pass must drop any
    victim the final set doesn't need."""
    arb = Arbiter(total_chips=6)
    arb.admit(GangAsk("small", 2, priority=0, started_ms=20))   # youngest
    arb.admit(GangAsk("big", 4, priority=0, started_ms=10))
    d = arb.decide(GangAsk("hi", 4, priority=5))
    # greedy picks small (youngest) first, then big; minimality drops
    # small because big alone frees enough
    assert d.action == PREEMPT
    assert [v.app_id for v in d.victims] == ["big"]


def test_preemption_disabled_queues_instead():
    arb = Arbiter(total_chips=4, preemption_enabled=False)
    arb.admit(GangAsk("low", 4, priority=0))
    assert arb.decide(GangAsk("hi", 2, priority=9)).action == QUEUE


def test_queue_capacity_shares_and_user_quota():
    conf = TonyConfiguration()
    conf.set("tony.queues.prod.capacity-share", 75, "t")
    conf.set("tony.queues.dev.capacity-share", 25, "t")
    conf.set("tony.queues.dev.max-tpus-per-user", 2, "t")
    conf.set(K.ARBITER_TOTAL_TPUS, 16, "t")
    arb = Arbiter.from_conf(conf)
    assert arb.total_chips == 16
    assert arb.admit(
        GangAsk("d1", 2, queue="dev", user="u1", priority=0)).admitted
    d = arb.decide(GangAsk("d2", 2, queue="dev", user="u1"))
    assert d.action == QUEUE and "quota" in d.reason
    # another user still fits inside dev's 4-chip share...
    assert arb.decide(GangAsk("d3", 2, queue="dev", user="u2")).admitted
    # ...but not past it
    d = arb.decide(GangAsk("d4", 4, queue="dev", user="u2"))
    assert d.action == QUEUE and "capacity" in d.reason
    assert arb.decide(GangAsk("p1", 12, queue="prod", user="u1")).admitted
    d = arb.decide(GangAsk("x", 1, queue="nosuch"))
    assert d.action == QUEUE and "unknown queue" in d.reason


def test_hierarchical_queue_child_share_of_parent():
    conf = TonyConfiguration()
    conf.set("tony.queues.root.capacity-share", 100, "t")
    conf.set("tony.queues.child.parent", "root", "t")
    conf.set("tony.queues.child.capacity-share", 50, "t")
    conf.set(K.ARBITER_TOTAL_TPUS, 8, "t")
    arb = Arbiter.from_conf(conf)
    d = arb.decide(GangAsk("c", 6, queue="child"))
    assert d.action == QUEUE and "child" in d.reason
    assert arb.admit(GangAsk("c", 4, queue="child")).admitted
    # child usage charges the parent: 4 in child + 5 in root > 8
    d = arb.decide(GangAsk("r", 5, queue="root"))
    assert d.action == QUEUE


def test_queue_spec_parsing_rejects_bad_hierarchy():
    from tony_tpu.conf.queues import queue_specs, validate_queue_quota
    conf = TonyConfiguration()
    conf.set("tony.queues.a.parent", "nosuch", "t")
    with pytest.raises(ValueError, match="unknown parent"):
        queue_specs(conf)
    conf = TonyConfiguration()
    conf.set("tony.queues.a.parent", "b", "t")
    conf.set("tony.queues.b.parent", "a", "t")
    with pytest.raises(ValueError, match="cycle"):
        queue_specs(conf)
    # a share-only queue is still a declared queue for submission
    conf = TonyConfiguration()
    conf.set("tony.queues.prod.capacity-share", 50, "t")
    conf.set(K.APPLICATION_QUEUE, "prod", "t")
    conf.set("tony.worker.instances", 1, "t")
    conf.set("tony.worker.tpus", 4, "t")
    validate_queue_quota(conf)             # no max-tpus: uncapped per-app


def test_arbiter_sync_from_fleet_and_inventory_fallback():
    from tony_tpu.observability import fleet
    conf = TonyConfiguration()
    conf.set("tony.queues.a.max-tpus", 8, "t")
    conf.set("tony.queues.b.max-tpus", 8, "t")
    arb = Arbiter.from_conf(conf)
    assert arb.total_chips == 16           # summed root quotas
    running = fleet.job_summary(
        "app_1", "alice", "a", "RUNNING", gang_width=2,
        requested_chips=4, allocated_chips=4, started_ms=5,
        priority=1, am_addr="h:1")
    done = fleet.job_summary("app_0", "bob", "b", "SUCCEEDED",
                             requested_chips=8)
    arb.sync_from_fleet([running, done])
    assert set(arb.running) == {"app_1"}   # terminal holds no chips
    ask = arb.running["app_1"]
    assert (ask.chips, ask.priority, ask.user, ask.am_addr) == \
        (4, 1, "alice", "h:1")
    d = arb.decide(GangAsk("hi", 16, queue="b", priority=9))
    assert d.action == PREEMPT
    assert [v.app_id for v in d.victims] == ["app_1"]


# ---------------------------------------------------------------------------
# checkpoint retention GC
# ---------------------------------------------------------------------------

def _mesh(**axes):
    import numpy as np
    from jax.sharding import Mesh
    import jax
    if not axes:
        axes = {"fsdp": 8}
    devs = np.array(jax.devices()[: int(np.prod(list(axes.values())))])
    return Mesh(devs.reshape(tuple(axes.values())), tuple(axes))


def _state(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return {"w": jax.device_put(jnp.arange(64.0).reshape(8, 8),
                                NamedSharding(mesh, P("fsdp"))),
            "step": 4}


def test_checkpoint_gc_local_keeps_newest_and_pinned(tmp_path):
    from tony_tpu.train.checkpoint import (
        committed_steps, latest_step, restore_checkpoint, save_checkpoint,
    )
    mesh = _mesh()
    state = _state(mesh)
    for step in (1, 2, 3):
        save_checkpoint(str(tmp_path), step, state)
    # commit with keep=2: steps 1 survives only if pinned
    save_checkpoint(str(tmp_path), 4, state, keep=2, pinned=1)
    assert committed_steps(str(tmp_path)) == [1, 3, 4]
    save_checkpoint(str(tmp_path), 5, state, keep=2, pinned=1)
    assert committed_steps(str(tmp_path)) == [1, 4, 5]
    assert latest_step(str(tmp_path)) == 5
    # the pinned restore target stays loadable after every prune
    assert restore_checkpoint(str(tmp_path), 1)["step"] == 4


def test_checkpoint_gc_never_deletes_below_keep(tmp_path):
    from tony_tpu.train.checkpoint import committed_steps, prune_checkpoints
    from tony_tpu.train.checkpoint import save_checkpoint
    mesh = _mesh()
    state = _state(mesh)
    save_checkpoint(str(tmp_path), 1, state)
    save_checkpoint(str(tmp_path), 2, state)
    assert prune_checkpoints(str(tmp_path), keep=3) == []
    assert prune_checkpoints(str(tmp_path), keep=0) == []   # 0 = keep all
    assert committed_steps(str(tmp_path)) == [1, 2]


def test_checkpoint_gc_on_store_deletes_commit_marker_first(tmp_path,
                                                           fake_gcs):
    """gs:// protocol: GC removes the COMMIT marker first (a racing
    reader sees a cleanly-uncommitted step, never a half one), then the
    shard objects; the pinned step survives."""
    from tony_tpu.train.checkpoint import (
        committed_steps, restore_checkpoint, save_checkpoint,
    )
    base = "gs://bkt/gc-ckpts"
    mesh = _mesh()
    state = _state(mesh)
    for step in (1, 2, 3):
        save_checkpoint(base, step, state)
    save_checkpoint(base, 4, state, keep=2, pinned=1)
    assert committed_steps(base) == [1, 3, 4]
    root = fake_gcs / "bkt" / "gc-ckpts"
    assert not (root / "step_2" / "COMMIT").exists()
    # the pruned step's shard OBJECTS are gone too, not just unmarked
    # (empty dirs may linger on the fake-fs shim; object stores have none)
    assert not any(p.is_file() for p in (root / "step_2").rglob("*"))
    assert restore_checkpoint(base, 1)["step"] == 4
    assert restore_checkpoint(base)["step"] == 4


# ---------------------------------------------------------------------------
# trainer emergency-save paths
# ---------------------------------------------------------------------------

def _tiny_trainer(ckpt_dir: str, num_steps: int = 50, data_iter=None,
                  checkpoint_every: int = 1):
    from tony_tpu.models.mnist import mnist_init, mnist_loss
    from tony_tpu.train.data import synthetic_mnist
    from tony_tpu.train.trainer import Trainer, TrainerConfig
    return Trainer(
        loss_fn=mnist_loss, init_fn=mnist_init,
        data_iter=data_iter if data_iter is not None
        else synthetic_mnist(16),
        config=TrainerConfig(num_steps=num_steps, log_every=1,
                             checkpoint_every=checkpoint_every,
                             checkpoint_dir=ckpt_dir, learning_rate=1e-2,
                             warmup_steps=1, prefetch_depth=0))


def test_emergency_checkpoint_on_unhandled_exception(tmp_path):
    """The trainer.py:493 gap: a run that raises mid-epoch used to keep
    only cadence checkpoints — now the emergency path commits the
    CURRENT step on the way out, and the error still propagates."""
    from tony_tpu.train.checkpoint import latest_step
    from tony_tpu.train.data import synthetic_mnist

    def poisoned():
        src = synthetic_mnist(16)
        for i in range(10_000):
            if i == 7:
                raise RuntimeError("data pipeline exploded")
            yield next(src)

    ckpt = str(tmp_path / "ck")
    trainer = _tiny_trainer(ckpt, num_steps=50, data_iter=poisoned(),
                            checkpoint_every=5)
    with pytest.raises(RuntimeError, match="exploded"):
        trainer.run()
    assert trainer.step == 7
    # not just the step-5 cadence save: the dying step is committed
    assert latest_step(ckpt) == 7


def test_emergency_checkpoint_on_sigterm_exits_preempted(tmp_path):
    """The TERM→checkpoint→KILL contract, trainer side: SIGTERM raises
    TrainerPreempted in the main thread, the emergency save commits the
    current step, and the process exit code is EXIT_PREEMPTED."""
    from tony_tpu.train.checkpoint import latest_step
    from tony_tpu.train.data import synthetic_mnist

    def term_after():
        src = synthetic_mnist(16)
        for i in range(10_000):
            if i == 5:
                os.kill(os.getpid(), signal.SIGTERM)
                # the raise lands at a bytecode boundary — give it one
                time.sleep(0.5)
            yield next(src)

    ckpt = str(tmp_path / "ck")
    trainer = _tiny_trainer(ckpt, num_steps=50, data_iter=term_after())
    old = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as exc:
            trainer.run()
    finally:
        signal.signal(signal.SIGTERM, old)
    assert exc.value.code == C.EXIT_PREEMPTED
    assert trainer.preempted is True
    assert latest_step(ckpt) == trainer.step == 5


def test_ledger_pins_checkpoint_phase_under_one_percent(tmp_path,
                                                        monkeypatch):
    """ROADMAP item 4's stated pin, ledger-asserted: with async saves on
    a realistic cadence, the synchronous checkpoint_save phase (snapshot
    + final commit — the only part the hot loop pays) stays under 1% of
    the run's wall clock. Steps carry the standard ~30 ms test delay so
    the ratio reflects a real step cadence, not a microbenchmark where
    the fixed snapshot cost dominates a near-zero wall."""
    monkeypatch.setenv(C.TRAINER_STEP_DELAY_MS, "50")
    trainer = _tiny_trainer(str(tmp_path / "ck"), num_steps=120,
                            checkpoint_every=60)
    trainer.run()
    snap = trainer.ledger.snapshot()
    wall = snap["wall_s"]
    assert wall > 0
    assert snap["phases"].get("checkpoint_save", 0.0) < 0.01 * wall, snap


# ---------------------------------------------------------------------------
# executor drain + term grace
# ---------------------------------------------------------------------------

def _executor(tmp_path, **conf_overrides):
    from tony_tpu.executor.task_executor import TaskExecutor
    conf = TonyConfiguration()
    for k, v in conf_overrides.items():
        conf.set(k, v, "test")
    conf_path = str(tmp_path / "tony-final.json")
    conf.write(conf_path)
    env = {
        C.JOB_NAME: "worker", C.TASK_INDEX: "0", C.TASK_NUM: "1",
        C.IS_CHIEF: "false", C.SESSION_ID: "0", C.TASK_ATTEMPT: "0",
        C.AM_HOST: "127.0.0.1", C.AM_PORT: "1",
        C.TASK_COMMAND: "true", C.TONY_CONF_PATH: conf_path,
    }
    return TaskExecutor(env=env)


class _FakeProc:
    def __init__(self, exits_after_term: bool = True):
        self.pid = 2**31 - 1                  # killpg ESRCH → fallback
        self.signals: list = []
        self.wait_timeouts: list = []
        self._exits_after_term = exits_after_term
        self._dead = False

    def poll(self):
        return 0 if self._dead else None

    def terminate(self):
        self.signals.append("TERM")
        if self._exits_after_term:
            self._dead = True

    def kill(self):
        self.signals.append("KILL")
        self._dead = True

    def wait(self, timeout=None):
        self.wait_timeouts.append(timeout)
        if self._dead:
            return 0
        import subprocess
        raise subprocess.TimeoutExpired("fake", timeout)


def test_term_grace_is_configurable_and_used(tmp_path):
    ex = _executor(tmp_path, **{K.TASK_TERM_GRACE_MS: "250ms"})
    assert ex._term_grace_sec == pytest.approx(0.25)
    proc = _FakeProc(exits_after_term=False)
    ex._user_proc = proc
    ex._terminate_user_proc()
    # TERM, waited the configured grace, then escalated to KILL
    assert proc.signals[0] == "TERM"
    assert proc.wait_timeouts == [pytest.approx(0.25)]
    assert "KILL" in proc.signals

    ex2 = _executor(tmp_path)                 # default sizes for a ckpt
    assert ex2._term_grace_sec == pytest.approx(15.0)


def test_drain_request_is_one_shot_and_marks_preempted(tmp_path):
    ex = _executor(tmp_path, **{K.TASK_TERM_GRACE_MS: "100ms"})
    proc = _FakeProc(exits_after_term=True)
    ex._user_proc = proc
    ex._on_drain_request({"grace_ms": 120, "reason": "arbiter"})
    ex._on_drain_request({"grace_ms": 120, "reason": "dup"})   # no-op
    deadline = time.monotonic() + 5
    while not proc.signals and time.monotonic() < deadline:
        time.sleep(0.01)
    assert proc.signals == ["TERM"]           # graceful, no KILL needed
    assert ex._drain_requested is True


def test_heartbeater_forwards_drain_ask(tmp_path):
    from tony_tpu.executor.task_executor import Heartbeater

    class _Client:
        def task_executor_heartbeat(self, *a, **kw):
            return {"spec_generation": 1,
                    "drain": {"grace_ms": 500, "reason": "r"}}

    seen = []
    hb = Heartbeater(_Client(), "worker:0", 0.01,
                     on_drain=seen.append)
    hb.start()
    deadline = time.monotonic() + 5
    while not seen and time.monotonic() < deadline:
        time.sleep(0.01)
    hb.stop()
    assert seen and seen[0]["grace_ms"] == 500


# ---------------------------------------------------------------------------
# session + goodput accounting
# ---------------------------------------------------------------------------

def test_session_preempted_tasks_are_terminal_not_failures():
    from tony_tpu.rpc.messages import TaskStatus
    from tony_tpu.session.session import FinalStatus, TonySession
    conf = TonyConfiguration()
    conf.set("tony.worker.instances", 2, "t")
    session = TonySession(conf)
    session.on_task_completed("worker", 0, C.EXIT_PREEMPTED,
                              preempted=True)
    task = session.get_task("worker", 0)
    assert task.status == TaskStatus.PREEMPTED and task.completed
    # no stop-on-failure short-circuit fired
    assert not session.training_finished
    assert session.final_status == FinalStatus.UNDEFINED
    session.set_final_status(FinalStatus.PREEMPTED, "drained")
    # PREEMPTED is sticky against the aggregation pass
    session.update_session_status()
    assert session.final_status == FinalStatus.PREEMPTED
    assert session.num_failed_tasks() == 0


def test_aggregate_goodput_prices_preemption_downtime():
    from tony_tpu.observability.perf import aggregate_goodput
    gauges = {"worker:0": {
        "GOODPUT_WALL_SECONDS": 80.0,
        "GOODPUT_TRAIN_STEP_SECONDS": 80.0}}
    base = aggregate_goodput(gauges)
    priced = aggregate_goodput(gauges, preemption_downtime_s=20.0)
    assert base["job"]["goodput_pct"] == pytest.approx(100.0)
    assert priced["job"]["preemption_downtime_s"] == 20.0
    assert priced["job"]["goodput_pct"] == pytest.approx(80.0)
    assert priced["job"]["wall_s"] == pytest.approx(100.0)


def test_resume_conf_overrides_roundtrip():
    from tony_tpu.cluster.arbiter import resume_conf_overrides
    from tony_tpu.observability import fleet
    summary = fleet.job_summary("app_a", "u", "q", "PREEMPTED",
                                requested_chips=4, preemptions=2,
                                heartbeat_ms=1234)
    over = resume_conf_overrides(summary)
    assert over[K.APPLICATION_RESUMED_FROM] == "app_a"
    assert over[K.APPLICATION_PREEMPTED_AT_MS] == "1234"
    assert over[K.APPLICATION_PREEMPT_COUNT] == "2"


def test_request_preemption_is_client_plane_only():
    """Task tokens are confined to the TASK_METHOD_IDENTITY allowlist;
    request_preemption must stay off it — a compromised container must
    not be able to evict its own (or any) application."""
    from tony_tpu.rpc.service import CLUSTER_METHODS
    from tony_tpu.security.tokens import TASK_METHOD_IDENTITY
    assert "request_preemption" in CLUSTER_METHODS
    assert "request_preemption" not in TASK_METHOD_IDENTITY


def test_fleet_preempted_state_is_terminal_and_gauge_mapped():
    from tony_tpu.observability import fleet
    assert "PREEMPTED" in fleet.TERMINAL_STATES
    assert "PREEMPTED" in fleet.STATE_ORDER
    assert fleet.JOB_GAUGES["tony_job_preemptions_total"] == "preemptions"
    summary = fleet.job_summary("a", "u", "q", "PREEMPTED",
                                preemptions=1, priority=7,
                                am_addr="h:42")
    assert summary["preemptions"] == 1 and summary["priority"] == 7
    assert summary["am_addr"] == "h:42"


# ---------------------------------------------------------------------------
# operator CLI verbs
# ---------------------------------------------------------------------------

def test_cli_arbiter_verdict_over_fleet_registry(tmp_path, capsys):
    from tony_tpu.cli.__main__ import arbiter as arbiter_cmd
    from tony_tpu.observability import fleet
    staging = tmp_path / "staging"
    (staging / "app_lo" / "fleet").mkdir(parents=True)
    summary = fleet.job_summary("app_lo", "alice", "default", "RUNNING",
                                requested_chips=2, allocated_chips=2,
                                priority=1, am_addr="nowhere:1")
    (staging / "app_lo" / "fleet" / "jobstate.json").write_text(
        json.dumps(summary))
    qconf = tmp_path / "queues.json"
    qconf.write_text(json.dumps({K.ARBITER_TOTAL_TPUS: 3}))
    rc = arbiter_cmd([str(staging), "--chips", "2", "--priority", "5",
                      "--queues-conf", str(qconf)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["action"] == "preempt"
    assert out["victims"] == ["app_lo"]
    # same ask at equal priority: queued whole, nothing granted
    rc = arbiter_cmd([str(staging), "--chips", "2", "--priority", "1",
                      "--queues-conf", str(qconf)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["action"] == "queue" and out["victims"] == []


def test_cli_preempt_delivers_rpc(tmp_path, capsys):
    from test_rpc import FakeClusterHandler
    from tony_tpu.cli.__main__ import preempt as preempt_cmd
    from tony_tpu.rpc.service import serve
    handler = FakeClusterHandler()
    server, port = serve(cluster_handler=handler)
    try:
        (tmp_path / C.AM_HOSTPORT_FILE).write_text(f"localhost:{port}")
        rc = preempt_cmd([str(tmp_path), "--grace-ms", "7000",
                          "--reason", "make room"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["grace_ms"] == 7000
        assert handler.preemptions == [
            {"grace_ms": 7000, "reason": "make room",
             "requested_by": "operator"}]
    finally:
        server.stop(grace=None)


# ---------------------------------------------------------------------------
# chaos e2e: arbiter decision → drain → emergency ckpt → resume narrower
# ---------------------------------------------------------------------------

def _wait_for(predicate, timeout_s: float, what: str = ""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.chaos
def test_preempt_resume_reshard_e2e(tmp_path):
    """Acceptance: arbiter selects the lower-priority running trainer
    as the victim over the live fleet registry, the drain emergency-
    checkpoints within the grace window (no SIGKILL data loss), the job
    lands PREEMPTED on every surface, and a narrower re-admission
    resumes from that exact step through the resharding restore with
    the downtime priced in goodput.json — and the resumed trajectory is
    bit-consistent (two identical resumes produce identical losses)."""
    from tests.chaos import ChaosRun
    from tony_tpu.events.history import read_goodput_file
    from tony_tpu.events.schema import EventType
    from tony_tpu.observability.fleet import FleetRegistry
    from tony_tpu.train.checkpoint import latest_step

    staging = str(tmp_path / "staging")
    ckpt_dir = str(tmp_path / "ckpts")
    report_dir = str(tmp_path / "reports")
    run = ChaosRun(tmp_path, seed=42)

    argv_a = [
        "--executes", script("preempt_trainer.py"),
        "--conf", "tony.worker.instances=1",
        "--conf", "tony.worker.tpus=2",
        "--conf", "tony.tpu.mesh-shape=2",
        "--conf", "tony.tpu.mesh-axes=fsdp",
        "--conf", "tony.application.priority=1",
        "--conf", f"tony.staging.location={staging}",
        "--conf", "tony.fleet.publish-interval-ms=200",
        "--conf", f"tony.execution.env=CKPT_DIR={ckpt_dir}",
        "--conf", f"tony.execution.env=REPORT_DIR={report_dir}",
        "--conf", "tony.execution.env=REPORT_NAME=run_a",
        "--conf", f"tony.execution.env=TONY_REPO_ROOT={REPO}",
        "--conf", "tony.execution.env=TOTAL_STEPS=5000",
        # ~25 ms/step so the drain lands genuinely mid-run
        "--conf", "tony.execution.env=TONY_TRAINER_STEP_DELAY_MS=25",
    ]
    done = {}

    def _run_a():
        try:
            run.run(argv_a)
        finally:
            done["a"] = True

    t = threading.Thread(target=_run_a, daemon=True)
    t.start()
    # victim must have real progress on disk before the eviction
    _wait_for(lambda: (latest_step(ckpt_dir) or 0) >= 3, 90,
              "victim checkpoints")

    # -- the arbiter's call: priority-5 gang of 2 chips vs a 3-chip pool
    # occupied 2 by the priority-1 victim — minimal victim set is [A]
    registry = FleetRegistry(location=staging, stale_after_ms=30_000)
    live = _wait_for(
        lambda: (registry.refresh(force=True) or registry.live_jobs()),
        30, "victim in the fleet registry")
    arb = Arbiter(total_chips=3)
    arb.sync_from_fleet(live)
    victim_id = run.client.app_id
    assert victim_id in arb.running
    decision = arb.decide(GangAsk("hi-gang", 2, priority=5))
    assert decision.action == PREEMPT, decision
    assert [v.app_id for v in decision.victims] == [victim_id]

    # -- checkpoint-then-evict through the victim AM's control plane
    reached = execute_preemption(decision.victims, grace_ms=60_000,
                                 reason="admit hi-gang")
    assert reached == [victim_id]
    _wait_for(lambda: done.get("a"), 120, "victim drain")
    t.join(timeout=10)

    assert run.final_status == "PREEMPTED", run.all_logs()
    report_a = json.load(open(os.path.join(report_dir, "run_a.json")))
    assert report_a["preempted"] is True
    stopped_at = report_a["stopped_at"]
    assert stopped_at >= 3
    # no SIGKILL data loss: the EXACT dying step is committed
    assert latest_step(ckpt_dir) == stopped_at
    # events + terminal surfaces tell the preemption story
    requested = run.events_of_type(EventType.PREEMPTION_REQUESTED)
    preempted = run.events_of_type(EventType.PREEMPTED)
    assert requested and requested[0].payload.requested_by == "arbiter"
    assert preempted and preempted[0].payload.drained_tasks == 1
    assert preempted[0].payload.killed_tasks == 0
    jobstate = json.load(open(os.path.join(run.app_history_dir(),
                                           C.JOBSTATE_FILE)))
    assert jobstate["state"] == "PREEMPTED"
    assert jobstate["preemptions"] == 1
    # the evicted chips are free for the higher-priority gang now
    registry.refresh(force=True)
    arb.sync_from_fleet(registry.live_jobs())
    assert arb.admit(GangAsk("hi-gang", 2, priority=5)).admitted

    # -- resume at a NARROWER width (2 chips → 1): the 2-shard
    # checkpoint restores into the 1-wide mesh via the resharding path.
    # A bit-consistency twin (run C) resumes from an identical copy.
    ckpt_copy = str(tmp_path / "ckpts-copy")
    shutil.copytree(ckpt_dir, ckpt_copy)
    status_a = json.load(open(os.path.join(run.client.app_dir,
                                           C.AM_STATUS_FILE)))
    total_b = stopped_at + 3

    def resume_argv(name: str, ckpt: str) -> list:
        return [
            "--executes", script("preempt_trainer.py"),
            "--conf", "tony.worker.instances=1",
            "--conf", "tony.worker.tpus=1",
            "--conf", "tony.tpu.mesh-shape=1",
            "--conf", "tony.tpu.mesh-axes=fsdp",
            "--conf", "tony.application.priority=1",
            "--conf", f"tony.application.resumed-from={victim_id}",
            "--conf",
            f"tony.application.preempted-at-ms={status_a['completed']}",
            "--conf", "tony.application.preempt-count=1",
            "--conf", f"tony.execution.env=CKPT_DIR={ckpt}",
            "--conf", f"tony.execution.env=REPORT_DIR={report_dir}",
            "--conf", f"tony.execution.env=REPORT_NAME={name}",
            "--conf", f"tony.execution.env=TONY_REPO_ROOT={REPO}",
            "--conf", f"tony.execution.env=TOTAL_STEPS={total_b}",
        ]

    from test_e2e import run_job, _dump_logs
    hist = str(tmp_path / "hist-b")
    client_b = run_job(tmp_path, resume_argv("run_b", ckpt_dir),
                       conf_overrides={K.HISTORY_INTERMEDIATE: hist})
    assert client_b.final_status == "SUCCEEDED", _dump_logs(client_b)
    report_b = json.load(open(os.path.join(report_dir, "run_b.json")))
    assert report_b["resumed_from"] == stopped_at
    assert report_b["stopped_at"] == total_b

    # RESUMED event + downtime priced into goodput.json
    from tony_tpu.events.handler import parse_events
    hist_dir = os.path.join(hist, client_b.app_id)
    finals = [os.path.join(d, f) for d, _, fs in os.walk(hist)
              for f in fs if f.endswith(".jhist")]
    events_b = parse_events(finals[0])
    resumed = [e for e in events_b if e.type == EventType.RESUMED]
    assert resumed and resumed[0].payload.resumed_from == victim_id
    assert resumed[0].payload.downtime_ms > 0
    goodput_b = read_goodput_file(hist_dir)
    assert goodput_b["job"]["preemption_downtime_s"] > 0, goodput_b
    assert goodput_b["job"]["goodput_pct"] < 100.0

    # bit-consistent trajectory: an identical second resume from the
    # copied checkpoint reproduces run B's losses exactly
    client_c = run_job(tmp_path, resume_argv("run_c", ckpt_copy))
    assert client_c.final_status == "SUCCEEDED", _dump_logs(client_c)
    report_c = json.load(open(os.path.join(report_dir, "run_c.json")))
    assert report_c["resumed_from"] == stopped_at
    assert report_b["losses"] == report_c["losses"]
    assert report_b["losses"], "resumed run logged no losses"


@pytest.mark.chaos
def test_chaos_preempt_hook_drains_gang(tmp_path):
    """TEST_TASK_PREEMPT: the AM self-preempts mid-run — both gang
    members drain gracefully (no result-less SIGKILL), the application
    finishes PREEMPTED with the full event trail, and no relaunch/
    failure machinery fires."""
    from tests.chaos import ChaosRun, Preempt
    from tony_tpu.events.schema import EventType
    run = ChaosRun(tmp_path, seed=7)
    run.run(
        ["--executes", script("chaos_gang_worker.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.task.max-task-attempts=3"],
        injections=[Preempt(run.delay_ms(2500, 3000), grace_ms=20_000)])
    assert run.final_status == "PREEMPTED", run.all_logs()
    assert run.relaunches() == []
    requested = run.events_of_type(EventType.PREEMPTION_REQUESTED)
    assert requested and requested[0].payload.requested_by == "test"
    preempted = run.events_of_type(EventType.PREEMPTED)
    assert len(preempted) == 1
    payload = preempted[0].payload
    assert payload.drained_tasks + payload.killed_tasks == 2
