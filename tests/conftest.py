"""Test harness config.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the tony-mini / MiniYARNCluster analogue for the
compute plane — SURVEY.md §4 takeaway). Must run before the first jax import
anywhere in the test process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Child processes spawned by e2e tests inherit these via os.environ.

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)
