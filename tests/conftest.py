"""Test harness config.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the tony-mini / MiniYARNCluster analogue for the
compute plane — SURVEY.md §4 takeaway). Must run before the first jax import
anywhere in the test process.

Also scrubs single-claim accelerator-tunnel env (PALLAS_AXON_POOL_IPS-style):
the orchestrator E2E suite spawns many python processes (AM, executors, user
scripts), and a single-claim TPU tunnel hangs every process after the first
at interpreter start. Control-plane processes must never claim an
accelerator; test user-processes run on CPU.
"""

import os
import sys

# Control-plane subprocesses must not touch accelerators (children inherit).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# A TPU-tunnel sitecustomize may have imported jax at interpreter start, in
# which case jax.config already captured JAX_PLATFORMS from the pre-scrub
# env — force the platform through the config API too.
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")


# --- shared relay-test helpers (test_proxy.py + test_native.py) ----------

import socketserver  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402


class EchoHandler(socketserver.BaseRequestHandler):
    """Upper-cases everything — relay tests assert bytes crossed both ways."""

    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(data.upper())


@pytest.fixture()
def echo_server():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), EchoHandler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def recv_all(s):
    out = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            return out
        out += chunk


# --- fake gsutil (gs:// store tests across modules) ----------------------

FAKE_GSUTIL = """#!/bin/bash
# fake gsutil: maps gs://<bucket>/<key> onto $FAKE_GCS_ROOT/<bucket>/<key>
set -e
cmd=$1; shift
map() { echo "$FAKE_GCS_ROOT/${1#gs://}"; }
unmap() { echo "gs://${1#"$FAKE_GCS_ROOT/"}"; }
case "$cmd" in
  cp)
    src=$1; dst=$2
    [[ $src == gs://* ]] && src=$(map "$src")
    if [[ $dst == gs://* ]]; then dst=$(map "$dst"); mkdir -p "$(dirname "$dst")"; fi
    cp "$src" "$dst"
    ;;
  ls)
    # wildcard form prints matching object URIs (recursive **), like the
    # real CLI; the plain form is an existence check
    if [[ $1 == *'*'* ]]; then
      shopt -s globstar nullglob
      mapped=$(map "$1")
      found=0
      for p in $mapped; do
        [[ -f $p ]] && { unmap "$p"; found=1; }
      done
      [[ $found == 1 ]] || { echo "CommandException: no URLs matched" >&2; exit 1; }
    else
      p=$(map "$1"); [[ -e $p ]] || { echo "CommandException: no URLs matched" >&2; exit 1; }
    fi
    ;;
  rm)
    # single-object delete (checkpoint retention GC); already-gone is
    # the real CLI's "No URLs matched" failure
    p=$(map "$1")
    [[ -f $p ]] || { echo "CommandException: No URLs matched" >&2; exit 1; }
    rm -f "$p"
    ;;
  *) echo "unsupported: $cmd" >&2; exit 2 ;;
esac
"""


@pytest.fixture
def fake_gcs(tmp_path, monkeypatch):
    """PATH-shimmed gsutil mirroring cp/ls onto a local dir; returns the
    backing root. The canned-fixture pattern for gs:// code paths."""
    import stat

    bindir = tmp_path / "bin"
    bindir.mkdir()
    gsutil = bindir / "gsutil"
    gsutil.write_text(FAKE_GSUTIL)
    gsutil.chmod(gsutil.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCS_ROOT", str(tmp_path / "gcs"))
    return tmp_path / "gcs"
