"""Session state machine tests (reference model: TonySession semantics,
TestUtils.testParseContainerRequests)."""

import json

import pytest

from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.rpc.messages import TaskStatus
from tony_tpu.session import (
    TonySession, FinalStatus, EXIT_KILLED_BY_AM, parse_container_requests,
)


def make_conf(**jobs):
    """make_conf(worker=2, ps=1, **extra_flat_keys)"""
    conf = TonyConfiguration()
    for job, n in jobs.items():
        if job.startswith("tony_"):
            conf.set(job[5:].replace("_", "."), n)
        else:
            conf.set(f"tony.{job}.instances", n)
    return conf


def test_parse_container_requests_unique_priorities():
    conf = make_conf(worker=2, ps=1, evaluator=1)
    conf.set("tony.worker.memory", "4g")
    conf.set("tony.worker.tpus", 4)
    reqs = parse_container_requests(conf)
    assert set(reqs) == {"worker", "ps", "evaluator"}
    assert len({r.priority for r in reqs.values()}) == 3
    assert reqs["worker"].memory_mb == 4096
    assert reqs["worker"].tpus == 4
    assert reqs["ps"].num_instances == 1


def test_parse_requests_zero_instances_skipped():
    conf = make_conf(worker=2, ps=0)
    assert set(parse_container_requests(conf)) == {"worker"}


def test_parse_requests_unknown_dependency_rejected():
    conf = make_conf(worker=1)
    conf.set("tony.worker.depends-on", "ghost")
    with pytest.raises(ValueError, match="unknown"):
        parse_container_requests(conf)


def test_stage_autofill_and_deps():
    """prepare/training stages fold into depends_on
    (Utils.ensureStagedTasksIntegrity, util/Utils.java:408-426)."""
    conf = make_conf(prep=1, worker=2)
    conf.set(K.APPLICATION_TRAINING_STAGE, "worker")
    reqs = parse_container_requests(conf)
    assert reqs["worker"].depends_on == ["prep"]
    assert reqs["prep"].depends_on == []


def test_stage_integrity_violation():
    conf = make_conf(a=1, b=1, c=1)
    conf.set(K.APPLICATION_PREPARE_STAGE, "a")
    conf.set(K.APPLICATION_TRAINING_STAGE, "b")
    with pytest.raises(ValueError, match="stages"):
        parse_container_requests(conf)


def test_rendezvous_barrier_and_cluster_spec():
    session = TonySession(make_conf(worker=2, ps=1))
    session.num_expected_tasks = 3
    assert session.register_worker_spec("worker:0", "h0:1000") is None
    assert session.register_worker_spec("ps:0", "h2:3000") is None
    spec = session.register_worker_spec("worker:1", "h1:2000")
    assert json.loads(spec) == {"worker": ["h0:1000", "h1:2000"],
                                "ps": ["h2:3000"]}
    # re-registration is idempotent
    assert json.loads(session.register_worker_spec("worker:0", "h0:1000")) \
        == json.loads(spec)


def test_match_allocation_by_priority():
    session = TonySession(make_conf(worker=2, ps=1))
    prio = session.requests["worker"].priority
    t1 = session.match_allocation(prio, "c1", "hostA")
    t2 = session.match_allocation(prio, "c2", "hostB")
    t3 = session.match_allocation(prio, "c3", "hostC")  # no third worker slot
    assert t1.task_id == "worker:0" and t1.status == TaskStatus.RUNNING
    assert t2.task_id == "worker:1"
    assert t3 is None
    assert session.match_allocation(999, "c4", "hostD") is None


def test_chief_semantics():
    s = TonySession(make_conf(worker=2, ps=1))
    assert s.is_chief("worker", 0)
    assert not s.is_chief("worker", 1)
    assert not s.is_chief("ps", 0)
    s2 = TonySession(make_conf(chief=1, worker=2))
    assert s2.is_chief("chief", 0)
    assert not s2.is_chief("worker", 0)


def test_chief_failure_short_circuits():
    s = TonySession(make_conf(worker=2))
    s.on_task_completed("worker", 0, 1)
    assert s.training_finished
    assert s.final_status == FinalStatus.FAILED


def test_nonchief_failure_tolerated_by_default():
    """'succeeded with some worker failures' (TonySession.java:312-325)."""
    s = TonySession(make_conf(worker=3))
    s.on_task_completed("worker", 1, 1)
    assert not s.training_finished
    s.on_task_completed("worker", 0, 0)
    s.on_task_completed("worker", 2, 0)
    s.update_session_status()
    assert s.final_status == FinalStatus.SUCCEEDED
    assert "failedCnt=1" in s.final_message


def test_all_workers_failed_fails():
    s = TonySession(make_conf(worker=2))
    s.on_task_completed("worker", 1, 1)
    # worker:0 is chief — avoid short-circuit by failing only via index 1;
    # complete chief with AM-kill then fail the other
    s.on_task_completed("worker", 0, EXIT_KILLED_BY_AM)
    s.update_session_status()
    # killed-by-AM counts as non-zero exit in aggregation: 2 failures >= 2 tracked
    assert s.final_status == FinalStatus.FAILED


def test_fail_on_worker_failure_enabled():
    conf = make_conf(worker=3)
    conf.set(K.APPLICATION_FAIL_ON_WORKER_FAILURE, True)
    s = TonySession(conf)
    s.on_task_completed("worker", 2, 7)
    assert s.training_finished
    assert s.final_status == FinalStatus.FAILED


def test_stop_on_failure_jobtypes():
    conf = make_conf(worker=2, ps=1)
    conf.set(K.APPLICATION_STOP_ON_FAILURE_JOBTYPES, "ps")
    s = TonySession(conf)
    s.on_task_completed("ps", 0, 3)
    assert s.training_finished
    assert s.final_status == FinalStatus.FAILED


def test_untracked_jobtypes_excluded_from_aggregation():
    conf = make_conf(worker=1, tb=1)
    conf.set(K.APPLICATION_UNTRACKED_JOBTYPES, "tb")
    s = TonySession(conf)
    assert s.total_tracked_tasks() == 1
    s.on_task_completed("worker", 0, 0)
    assert s.all_tracked_tasks_completed()
    s.update_session_status()
    assert s.final_status == FinalStatus.SUCCEEDED


def test_exit_status_set_once():
    s = TonySession(make_conf(worker=1))
    t = s.get_task("worker", 0)
    t.set_exit_status(0)
    t.set_exit_status(5)  # delayed container-completion callback must not win
    assert t.exit_status == 0
    assert t.status == TaskStatus.SUCCEEDED


def test_incomplete_session_is_failed():
    s = TonySession(make_conf(worker=2))
    s.on_task_completed("worker", 0, 0)
    s.update_session_status()
    assert s.final_status == FinalStatus.FAILED
    assert "hasn't finished" in s.final_message
