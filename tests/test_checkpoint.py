"""Sharded/async checkpoint tests on the virtual 8-device CPU mesh.

Round-1 VERDICT item 4's acceptance bar: an 8-device sharded run saves
per-shard (no host ever materializes full state), restores onto a
DIFFERENT mesh layout, and the async saver overlaps IO without breaking
the donation contract."""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu.parallel.mesh import make_mesh, plan_mesh
from tony_tpu.train.checkpoint import (
    AsyncCheckpointer, latest_step, restore_checkpoint, save_checkpoint,
)


def _mesh(fsdp=8, tp=1):
    return make_mesh(plan_mesh(8, fsdp=fsdp, tp=tp))


def _sharded_state(mesh):
    w = jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8)
    b = jnp.arange(8.0, dtype=jnp.float32)
    return {
        "w": jax.device_put(w, NamedSharding(mesh, P("fsdp", "tp"))),
        "b": jax.device_put(b, NamedSharding(mesh, P(None))),
        "step": 4,
    }


def test_save_writes_one_file_per_shard_not_full_leaves(tmp_path):
    mesh = _mesh(fsdp=4, tp=2)
    state = _sharded_state(mesh)
    path = save_checkpoint(str(tmp_path), 4, state)
    shards = os.listdir(os.path.join(path, "shards"))
    # w: 4x2 shard grid = 8 files; b replicated = 1 file; step = 1 file
    assert sum(f.startswith("leaf_") and ".p0_" in f for f in shards) == 10
    # every w shard file holds a 2x4 block, never the full 8x8 — dict keys
    # flatten sorted, so w is leaf 2 after (b, step)
    manifest = json.load(open(os.path.join(path, "manifest_p0.json")))
    w_recs = [r for r in manifest["shards"] if r["leaf"] == 2]
    assert len(w_recs) == 8
    for rec in w_recs:
        data = np.load(os.path.join(path, "shards", rec["file"]))
        assert data.shape == (2, 4)


def test_restore_onto_different_mesh_layout(tmp_path):
    """Save on fsdp=4 x tp=2, restore onto fsdp=8 (and onto fsdp=2 x tp=4):
    per-shard paste, bit-exact."""
    save_mesh = _mesh(fsdp=4, tp=2)
    state = _sharded_state(save_mesh)
    save_checkpoint(str(tmp_path), 1, state)
    for fsdp, tp in ((8, 1), (2, 4), (1, 1)):
        mesh = _mesh(fsdp=fsdp, tp=tp)
        template = {
            "w": jax.device_put(jnp.zeros((8, 8)),
                                NamedSharding(mesh, P("fsdp", "tp"))),
            "b": jax.device_put(jnp.zeros(8), NamedSharding(mesh, P(None))),
            "step": 0,
        }
        restored = restore_checkpoint(str(tmp_path), 1, template=template)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(64.0).reshape(8, 8))
        np.testing.assert_array_equal(np.asarray(restored["b"]),
                                      np.arange(8.0))
        assert restored["step"] == 4
        assert restored["w"].sharding.spec == P("fsdp", "tp")


def test_restore_without_template_assembles_numpy(tmp_path):
    mesh = _mesh(fsdp=8)
    state = _sharded_state(mesh)
    save_checkpoint(str(tmp_path), 2, state)
    restored = restore_checkpoint(str(tmp_path))
    assert isinstance(restored["w"], np.ndarray)
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(64.0).reshape(8, 8))
    assert restored["step"] == 4 and isinstance(restored["step"], int)


def test_async_checkpointer_survives_donation(tmp_path):
    """save() must snapshot before returning: the caller immediately
    donates the state to the next step (buffers invalidated)."""
    mesh = _mesh(fsdp=8)
    ckpt = AsyncCheckpointer(str(tmp_path))

    @jax.jit
    def bump(x):
        return x + 1.0

    bump_donating = jax.jit(lambda x: x * 2.0, donate_argnums=0)
    with jax.set_mesh(mesh):
        x = jax.device_put(jnp.arange(16.0),
                           NamedSharding(mesh, P("fsdp")))
        for step in range(3):
            ckpt.save(step, {"x": x})
            x = bump_donating(x)   # invalidates the buffer just saved
        ckpt.close()
    assert latest_step(str(tmp_path)) == 2
    restored = restore_checkpoint(str(tmp_path), 2)
    np.testing.assert_array_equal(restored["x"], np.arange(16.0) * 4.0)


def test_am_retry_resumes_sharded_run(tmp_path):
    """VERDICT-r1 item 4 acceptance: AM retry resumes an 8-device sharded
    run from per-shard checkpoints — no full-state gather anywhere."""
    from test_e2e import run_job, script, _dump_logs

    ckpt_dir = str(tmp_path / "ckpts")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    client = run_job(
        tmp_path,
        ["--executes", script("train_crash_resume.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.am.retry-count=2",
         "--conf", f"tony.execution.env=CKPT_DIR={ckpt_dir}",
         "--conf", f"tony.execution.env=TONY_REPO_ROOT={repo}"])
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    report = json.load(open(os.path.join(ckpt_dir, "resume_report.json")))
    assert report["attempt"] == 1
    assert report["resumed_from"] == 3      # picked up attempt 0's last save
    assert report["finished_at"] == 6


def test_restore_region_walk_is_o_overlap(tmp_path, monkeypatch):
    """VERDICT-r2 item 8: restoring a many-shard checkpoint must touch
    only the saved records overlapping each target shard (grid interval
    index), not re-scan every record per target — and each shard file is
    np.load'ed exactly once across the whole restore."""
    import pickle

    from tony_tpu.train import checkpoint as ckpt_mod

    n = 512
    step_dir = tmp_path / "step_1"
    shards_dir = step_dir / "shards"
    os.makedirs(shards_dir)
    records = []
    for i in range(n):
        fname = f"leaf_0.p0_{i}.npy"
        np.save(shards_dir / fname, np.array([float(i)], np.float32))
        records.append({"leaf": 0, "file": fname, "index": [[i, i + 1]]})
    json.dump({"process": 0, "shards": records},
              open(step_dir / "manifest_p0.json", "w"))
    json.dump({"leaves": [{"shape": [n], "dtype": "float32"}]},
              open(step_dir / "index.json", "w"))
    _, treedef = jax.tree.flatten({"w": 0})
    pickle.dump(treedef, open(step_dir / "tree.pkl", "wb"))

    pastes, loads = [], []
    real_paste, real_load = ckpt_mod._paste_region, np.load
    monkeypatch.setattr(ckpt_mod, "_paste_region",
                        lambda *a, **k: (pastes.append(a[2]),
                                         real_paste(*a, **k))[1])
    monkeypatch.setattr(ckpt_mod.np, "load",
                        lambda *a, **k: (loads.append(a[0]),
                                         real_load(*a, **k))[1])

    mesh = _mesh(fsdp=8)
    template = {"w": jax.device_put(jnp.zeros(n),
                                    NamedSharding(mesh, P("fsdp")))}
    restored = restore_checkpoint(str(tmp_path), 1, template=template)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(n, dtype=np.float32))
    # 8 target shards x 64 overlapping records each = n pastes/loads total;
    # the pre-index walk would have been 8 x 512 = 4096 paste calls.
    assert len(pastes) == n
    assert len(loads) == n


def test_atomicity_partial_tmp_ignored(tmp_path):
    mesh = _mesh()
    save_checkpoint(str(tmp_path), 5, _sharded_state(mesh))
    # a crashed later save leaves only a .tmp dir — must be invisible
    os.makedirs(os.path.join(str(tmp_path), "step_9.tmp", "shards"))
    assert latest_step(str(tmp_path)) == 5
    restored = restore_checkpoint(str(tmp_path))
    assert restored["step"] == 4


def test_store_checkpoint_roundtrip_and_commit_marker(tmp_path, fake_gcs):
    """VERDICT r2 item 5: checkpoints on a gs:// store — per-shard
    uploads + COMMIT marker instead of rename, restore by URI with
    mesh resharding, and an uncommitted step is invisible."""
    base = "gs://bkt/ckpts"
    mesh = _mesh(fsdp=4, tp=2)
    save_checkpoint(base, 7, _sharded_state(mesh))
    assert latest_step(base) == 7
    # restore onto a DIFFERENT mesh layout straight from the store
    restore_mesh = _mesh(fsdp=8)
    template = {
        "w": jax.device_put(jnp.zeros((8, 8)),
                            NamedSharding(restore_mesh, P("fsdp", "tp"))),
        "b": jax.device_put(jnp.zeros(8),
                            NamedSharding(restore_mesh, P(None))),
        "step": 0,
    }
    restored = restore_checkpoint(base, template=template)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(64.0).reshape(8, 8))
    assert restored["step"] == 4
    # a later save whose COMMIT never landed must stay invisible
    save_checkpoint(base, 9, _sharded_state(mesh))
    os.remove(fake_gcs / "bkt" / "ckpts" / "step_9" / "COMMIT")
    assert latest_step(base) == 7
    restored = restore_checkpoint(base)
    assert restored["step"] == 4


def test_store_restore_ignores_stale_manifests(tmp_path, fake_gcs):
    """An aborted earlier upload of the same step can leave manifests
    from a different process count behind (no rmtree on object stores);
    the COMMIT marker names the fresh attempt's manifest set and restore
    must read EXACTLY that (review finding: merging stale manifests would
    paste stale shard data over fresh regions)."""
    base = "gs://bkt/stale-ckpts"
    mesh = _mesh(fsdp=8)
    save_checkpoint(base, 3, _sharded_state(mesh))
    # a stale manifest from a dead 2-process attempt, pointing at a
    # poisoned shard overlapping leaf regions
    step_dir = fake_gcs / "bkt" / "stale-ckpts" / "step_3"
    np.save(step_dir / "shards" / "leaf_2.p1_0.npy",
            np.full((8, 8), -1.0, np.float32))
    (step_dir / "manifest_p1.json").write_text(json.dumps({
        "process": 1, "shards": [{"leaf": 2, "file": "leaf_2.p1_0.npy",
                                  "index": [[0, 8], [0, 8]]}]}))
    restored = restore_checkpoint(base, 3)
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(64.0).reshape(8, 8))


def test_async_checkpointer_on_store(tmp_path, fake_gcs):
    base = "gs://bkt/async-ckpts"
    mesh = _mesh(fsdp=8)
    ckpt = AsyncCheckpointer(base)
    bump = jax.jit(lambda x: x * 2.0, donate_argnums=0)
    with jax.set_mesh(mesh):
        x = jax.device_put(jnp.arange(16.0), NamedSharding(mesh, P("fsdp")))
        for step in range(2):
            ckpt.save(step, {"x": x})
            x = bump(x)
        ckpt.close()
    assert latest_step(base) == 1
    restored = restore_checkpoint(base, 1)
    np.testing.assert_array_equal(restored["x"], np.arange(16.0) * 2.0)
