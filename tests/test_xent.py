"""Fused chunked cross-entropy (ops/xent.py): parity with the full-logits
path for both values and gradients, including non-divisible sequence
lengths, the llama loss integration, and the sharded path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.llama import (
    cross_entropy, get_config, llama_init, llama_loss,
)
from tony_tpu.ops.xent import fused_cross_entropy
from tony_tpu.parallel import make_mesh, plan_mesh


def _case(b=2, s=24, d=16, v=40, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(ks[0], (b, s, d), jnp.float32)
    w = jax.random.normal(ks[1], (d, v), jnp.float32) * d ** -0.5
    t = jax.random.randint(ks[2], (b, s), 0, v, jnp.int32)
    return x, w, t


def _full(x, w, t):
    logits = jnp.einsum("bsd,dv->bsv", x, w,
                        preferred_element_type=jnp.float32)
    return cross_entropy(logits, t)


@pytest.mark.parametrize("chunk", [8, 24, 7, 100])
def test_fused_xent_value_parity(chunk):
    """Chunk divides S, equals S, doesn't divide S, exceeds S."""
    x, w, t = _case()
    want = float(_full(x, w, t))
    got = float(fused_cross_entropy(x, w, t, chunk=chunk))
    assert np.isclose(got, want, rtol=1e-6, atol=1e-6), (got, want, chunk)


@pytest.mark.parametrize("chunk", [8, 7])
def test_fused_xent_grad_parity(chunk):
    x, w, t = _case()
    gx_want, gw_want = jax.grad(_full, argnums=(0, 1))(x, w, t)
    gx, gw = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, t, chunk=chunk),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_want),
                               rtol=1e-5, atol=1e-6)


def test_fused_xent_jit_and_bf16():
    """bf16 hidden/weights (the production dtype): runs under jit, grads
    come back in the param dtypes, values near the f32 oracle."""
    x, w, t = _case(s=16)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    want = float(_full(x, w, t))
    val, (gx, gw) = jax.jit(jax.value_and_grad(
        lambda x, w: fused_cross_entropy(x, w, t, chunk=8),
        argnums=(0, 1)))(xb, wb)
    assert np.isclose(float(val), want, rtol=2e-2), (float(val), want)
    assert gx.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16


def test_llama_loss_fused_matches_unfused():
    """config.xent_chunk routes llama_loss through the fused head with the
    same result (tiny config is f32 end to end, so tolerance is tight)."""
    cfg = get_config("tiny")
    cfg_fused = get_config("tiny", xent_chunk=16)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want, gw = jax.value_and_grad(llama_loss)(params, batch, cfg)
    got, gf = jax.value_and_grad(llama_loss)(params, batch, cfg_fused)
    assert np.isclose(float(got), float(want), rtol=1e-6)
    leaves_w, leaves_f = jax.tree.leaves(gw), jax.tree.leaves(gf)
    for a, b in zip(leaves_w, leaves_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_fused_xent_trains_on_tp_mesh():
    """Fused head under a dp+fsdp+tp mesh: one full jitted train step,
    finite decreasing loss (the production sharded path)."""
    import optax

    from tony_tpu.models.llama import llama_param_axes
    from tony_tpu.parallel import shard_pytree
    from tony_tpu.train.step import make_train_step

    cfg = get_config("tiny", xent_chunk=16)
    mesh = make_mesh(plan_mesh(8, tp=2))
    params = shard_pytree(llama_init(cfg, jax.random.PRNGKey(0)),
                          llama_param_axes(cfg), mesh)
    opt = optax.adam(1e-2)
    step = make_train_step(lambda p, b: llama_loss(p, b, cfg), opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size, jnp.int32)
    with jax.set_mesh(mesh):
        opt_state = jax.jit(opt.init)(params)
        losses = []
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state,
                                           {"tokens": tokens})
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_loss_fused_matches_unfused():
    """The MoE model shares _head_loss: xent_chunk must not change the
    loss or gradients (moe_tiny is f32, tolerance tight)."""
    from tony_tpu.models.moe import get_moe_config, moe_init, moe_loss

    cfg = get_moe_config("moe_tiny")
    cfg_fused = get_moe_config("moe_tiny", xent_chunk=16)
    params = moe_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size, jnp.int32)
    batch = {"tokens": tokens}
    want, gw = jax.value_and_grad(moe_loss)(params, batch, cfg)
    got, gf = jax.value_and_grad(moe_loss)(params, batch, cfg_fused)
    assert np.isclose(float(got), float(want), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gw), jax.tree.leaves(gf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)
