"""Portal tests: mover, purger, cache, and HTTP routes.

Reference models: HistoryFileMoverTest / HistoryFilePurgerTest and the
tony-portal controller tests (SURVEY.md §4 tier 4), re-targeted at the
local-filesystem history tree.
"""

import json
import os
import time
import urllib.request

import pytest

from tony_tpu import constants as C
from tony_tpu.events.handler import EventHandler
from tony_tpu.events.history import JobMetadata, history_file_name
from tony_tpu.events.schema import (
    ApplicationFinished, Event, EventType, TaskStarted,
)
from tony_tpu.portal.cache import PortalCache
from tony_tpu.portal.mover import (
    HistoryFileMover, ensure_history_dirs, finished_subdir,
)
from tony_tpu.portal.purger import HistoryFilePurger
from tony_tpu.portal.server import PortalServer


def make_app_history(intermediate, app_id, status="SUCCEEDED",
                     started=1000, completed=2000, user="alice",
                     final=True, config=None, logs=None):
    """Lay down a per-app history dir the way the AM does. `logs` maps
    container-dir -> {stream: content} (the AM's log aggregation)."""
    app_dir = os.path.join(intermediate, app_id)
    os.makedirs(app_dir, exist_ok=True)
    for cdir, streams in (logs or {}).items():
        d = os.path.join(app_dir, C.HISTORY_LOGS_DIR_NAME, cdir)
        os.makedirs(d, exist_ok=True)
        for stream, content in streams.items():
            with open(os.path.join(d, stream), "w") as f:
                f.write(content)
    md = JobMetadata(application_id=app_id, started=started,
                     completed=completed, user=user, status=status)
    handler = EventHandler(app_dir, JobMetadata(
        application_id=app_id, started=started, user=user))
    handler.start()
    handler.emit(Event(EventType.TASK_STARTED,
                       TaskStarted("worker", 0, "hostA", "container_1"),
                       timestamp=started + 1))
    handler.emit(Event(EventType.APPLICATION_FINISHED,
                       ApplicationFinished(app_id, status),
                       timestamp=completed))
    if final:
        path = handler.stop(status)
        # pin the filename's completed stamp for deterministic asserts
        want = os.path.join(app_dir, history_file_name(md))
        os.replace(path, want)
    else:
        # wait for the writer thread to land BOTH events: a late async
        # write would otherwise reset the .inprogress mtime after a test
        # back-dates it (flaky stale-mover test under load)
        inprog = handler._inprogress_path
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if sum(1 for _ in open(inprog)) >= 2:
                    break
            except OSError:
                pass
            time.sleep(0.01)
    if config is not None:
        with open(os.path.join(app_dir, C.PORTAL_CONFIG_FILE), "w") as f:
            json.dump(config, f)
    return app_dir


# ---------------------------------------------------------------------------
# mover
# ---------------------------------------------------------------------------

def test_mover_moves_final_dirs(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_1", completed=2000)
    mover = HistoryFileMover(inter, fin)
    moved = mover.move_once()
    assert len(moved) == 1
    assert not os.path.exists(os.path.join(inter, "app_1"))
    # completed=2000ms epoch → 1970/01/01
    assert moved[0] == os.path.join(fin, "1970", "01", "01", "app_1")
    assert any(f.endswith(".jhist") for f in os.listdir(moved[0]))


def test_mover_leaves_running_apps(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_run", final=False)  # inprogress, fresh mtime
    mover = HistoryFileMover(inter, fin, stale_sec=3600)
    assert mover.move_once() == []
    assert os.path.isdir(os.path.join(inter, "app_run"))


def test_mover_finalizes_stale_inprogress_as_killed(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    app_dir = make_app_history(inter, "app_dead", final=False)
    inprog = [f for f in os.listdir(app_dir)
              if f.endswith(".jhist.inprogress")]
    assert inprog
    old = time.time() - 7200
    os.utime(os.path.join(app_dir, inprog[0]), (old, old))
    mover = HistoryFileMover(inter, fin, stale_sec=3600)
    moved = mover.move_once()
    assert len(moved) == 1
    jhists = [f for f in os.listdir(moved[0]) if f.endswith(".jhist")]
    assert len(jhists) == 1 and "-KILLED." in jhists[0]


def test_mover_preserves_duplicate_outside_finished_tree(tmp_path):
    """AM-retry regenerated history must never be destroyed, and the
    parked copy must live OUTSIDE finished/ so the cache can't list it
    as a phantom app (round-1 ADVICE + review finding)."""
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_dup", completed=2000)
    mover = HistoryFileMover(inter, fin)
    assert len(mover.move_once()) == 1
    # the retry writes a fresh history dir for the same app id
    make_app_history(inter, "app_dup", completed=2000)
    assert mover.move_once() == []
    assert not os.path.exists(os.path.join(inter, "app_dup"))
    dup_root = str(tmp_path / "duplicates")
    parked = os.listdir(dup_root)
    assert len(parked) == 1 and parked[0].startswith("app_dup.dup-")
    assert any(f.endswith(".jhist")
               for f in os.listdir(os.path.join(dup_root, parked[0])))
    # nothing under finished/ besides the original app dir
    found = [d for _, ds, _ in os.walk(fin) for d in ds]
    assert "app_dup" in found and not [d for d in found if ".dup-" in d]


# ---------------------------------------------------------------------------
# purger
# ---------------------------------------------------------------------------

def test_purger_deletes_expired_and_prunes_empty_dirs(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_old", completed=2000)
    now_ms = int(time.time() * 1000)
    make_app_history(inter, "app_new", completed=now_ms)
    HistoryFileMover(inter, fin).move_once()

    purger = HistoryFilePurger(fin, retention_sec=24 * 3600)
    removed = purger.purge_once()
    assert len(removed) == 1 and removed[0].endswith("app_old")
    assert not os.path.exists(os.path.join(fin, "1970"))  # pruned
    # recent app survives
    sub = finished_subdir(fin, now_ms)
    assert os.path.isdir(os.path.join(sub, "app_new"))


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

def test_cache_lists_both_trees_and_serves_entries(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_done", completed=2000,
                     config={"tony.worker.instances": 2})
    make_app_history(inter, "app_live", final=False, started=3000)
    HistoryFileMover(inter, fin, stale_sec=3600).move_once()

    cache = PortalCache(inter, fin)
    mds = cache.list_metadata()
    assert [m.application_id for m in mds] == ["app_live", "app_done"]
    assert cache.get_metadata("app_live").status == "RUNNING"
    assert cache.get_metadata("app_done").status == "SUCCEEDED"

    events = cache.get_events("app_done")
    assert [e["type"] for e in events] == ["TASK_STARTED",
                                           "APPLICATION_FINISHED"]
    assert cache.get_config("app_done") == {"tony.worker.instances": 2}
    assert cache.get_config("app_live") == {}
    links = cache.get_log_links("app_done")
    assert links[0]["task"] == "worker:0"
    assert links[0]["host"] == "hostA"
    # no aggregated logs -> NO synthesized URL (the old NM-style links
    # pointed at servers that don't exist — VERDICT r4 item 3)
    assert links[0]["url"] == "" and links[0]["streams"] == {}
    assert cache.get_metadata("nope") is None
    assert cache.get_events("nope") == []


def test_cache_serves_aggregated_logs(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_l", completed=2000,
                     logs={"worker_0_s0": {"stdout": "trained fine\n",
                                           "stderr": "warnings\n"},
                           "am": {"stdout": "am out\n"}})
    cache = PortalCache(inter, fin)
    links = {l["task"]: l for l in cache.get_log_links("app_l")}
    w = links["worker:0"]
    assert w["url"] == "/logs/app_l/worker_0_s0/stdout"
    assert set(w["streams"]) == {"stdout", "stderr"}
    assert w["host"] == "hostA"          # enriched from TASK_STARTED
    assert links["am"]["url"] == "/logs/app_l/am/stdout"
    # content resolution + traversal containment
    p = cache.get_log_file("app_l", "worker_0_s0", "stdout")
    assert open(p).read() == "trained fine\n"
    assert cache.get_log_file("app_l", "../app_l", "stdout") is None
    assert cache.get_log_file("app_l", "worker_0_s0", "secrets") is None
    # links survive the move to finished/ (logs travel with the app dir)
    HistoryFileMover(inter, fin).move_once()
    assert cache.get_log_file("app_l", "worker_0_s0", "stdout")


# ---------------------------------------------------------------------------
# HTTP server (routes of tony-portal/conf/routes:1-5)
# ---------------------------------------------------------------------------

@pytest.fixture()
def portal(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_x", completed=2000,
                     config={"tony.am.memory": "2g"})
    server = PortalServer(PortalCache(inter, fin), port=0, host="127.0.0.1")
    server.start()
    yield server
    server.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}") as resp:
        return resp.status, resp.read().decode()


def test_portal_pages(portal):
    status, body = _get(portal, "/")
    assert status == 200 and "app_x" in body
    status, body = _get(portal, "/jobs/app_x")
    assert status == 200 and "TASK_STARTED" in body
    status, body = _get(portal, "/config/app_x")
    assert status == 200 and "tony.am.memory" in body
    status, body = _get(portal, "/logs/app_x")
    assert status == 200 and "hostA" in body


def test_portal_api(portal):
    status, body = _get(portal, "/api/jobs")
    jobs = json.loads(body)
    assert status == 200 and jobs[0]["application_id"] == "app_x"
    status, body = _get(portal, "/api/jobs/app_x/events")
    assert status == 200 and json.loads(body)[0]["type"] == "TASK_STARTED"
    status, body = _get(portal, "/api/jobs/app_x/config")
    assert json.loads(body) == {"tony.am.memory": "2g"}
    status, body = _get(portal, "/api/jobs/app_x/logs")
    assert json.loads(body)[0]["host"] == "hostA"


def test_portal_404(portal):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(portal, "/jobs/missing")
    assert exc.value.code == 404


def test_job_page_renders_serving_endpoint(tmp_path):
    """A serving job's page shows the registered endpoint URL — and links
    it through the authenticated proxy when tony.proxy.url is configured
    — instead of showing nothing actionable for serving jobs."""
    from tony_tpu.events.schema import ServingEndpointRegistered

    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    app_dir = os.path.join(inter, "app_srv")
    os.makedirs(app_dir)
    md = JobMetadata(application_id="app_srv", started=10, user="alice")
    handler = EventHandler(app_dir, md)
    handler.start()
    handler.emit(Event(EventType.TASK_STARTED,
                       TaskStarted("serving", 0, "hostB", "container_9")))
    handler.emit(Event(EventType.SERVING_ENDPOINT_REGISTERED,
                       ServingEndpointRegistered("serving", 0,
                                                 "http://hostB:9900")))
    path = handler.stop("KILLED")
    want = os.path.join(app_dir, history_file_name(JobMetadata(
        application_id="app_srv", started=10, completed=20, user="alice",
        status="KILLED")))
    os.replace(path, want)
    with open(os.path.join(app_dir, C.PORTAL_CONFIG_FILE), "w") as f:
        json.dump({"tony.proxy.url": "http://gateway:7000"}, f)

    server = PortalServer(PortalCache(inter, fin), port=0,
                          host="127.0.0.1")
    server.start()
    try:
        status, body = _get(server, "/jobs/app_srv")
    finally:
        server.stop()
    assert status == 200
    assert "Serving fleet" in body
    assert "http://hostB:9900" in body
    # linked THROUGH the configured proxy, raw URL stays visible as text
    assert 'href="http://gateway:7000"' in body
    assert "(via proxy)" in body


def test_history_store_fetcher_feeds_mover_and_cache(tmp_path, fake_gcs):
    """Off-host AM story: finished jhist published to the store is pulled
    into the intermediate dir, the mover finalizes it into finished/, and
    the cache serves it — the portal works with no shared fs to the AM."""
    from tony_tpu.portal.fetcher import HistoryStoreFetcher
    from tony_tpu.storage import GCSStore

    # an "AM on another host" published its finished history
    store = GCSStore("gs://bkt/stage/app_remote")
    hist = tmp_path / history_file_name(JobMetadata(
        application_id="app_remote", started=1000, completed=2000,
        user="bob", status="SUCCEEDED"))
    hist.write_text(json.dumps({
        "type": "APPLICATION_FINISHED", "timestamp": 2000,
        "payload": {"application_id": "app_remote",
                    "status": "SUCCEEDED"}}) + "\n")
    store.put(str(hist), f"history/{hist.name}")
    cfg = tmp_path / "cfgsnap.json"
    cfg.write_text(json.dumps({"tony.am.memory": "1g"}))
    store.put(str(cfg), f"history/{C.PORTAL_CONFIG_FILE}")
    log = tmp_path / "wstdout"
    log.write_text("remote body\n")
    store.put(str(log),
              f"history/{C.HISTORY_LOGS_DIR_NAME}/worker_0_s0/stdout")

    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    fetcher = HistoryStoreFetcher("gs://bkt/stage", inter)
    fetched = fetcher.fetch_once()
    assert len(fetched) == 3
    assert fetcher.fetch_once() == []     # idempotent: nothing new

    mover = HistoryFileMover(inter, fin)
    moved = mover.move_once()
    assert len(moved) == 1
    cache = PortalCache(inter, fin)
    md = cache.get_metadata("app_remote")
    assert md is not None and md.status == "SUCCEEDED"
    assert cache.get_config("app_remote") == {"tony.am.memory": "1g"}
    # the fetched aggregated log serves through the portal's own route
    p = cache.get_log_file("app_remote", "worker_0_s0", "stdout")
    assert p and open(p).read() == "remote body\n"


@pytest.fixture()
def secure_portal(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_x", completed=2000,
                     config={"tony.am.memory": "2g"})
    server = PortalServer(PortalCache(inter, fin), port=0, host="127.0.0.1",
                          token="sekrit-tok")
    server.start()
    yield server
    server.stop()


def test_portal_requires_token(secure_portal):
    """VERDICT-r2 item 6: every data route 401s without the bearer token —
    job configs can embed user env (tony.execution.env k=v)."""
    for path in ("/", "/jobs/app_x", "/config/app_x", "/logs/app_x",
                 "/api/jobs", "/api/jobs/app_x/config"):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(secure_portal, path)
        assert exc.value.code == 401, path
    # wrong token is still 401
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(secure_portal, "/api/jobs?token=wrong")
    assert exc.value.code == 401
    # non-ASCII token value must 401, not 500 (compare_digest TypeErrors
    # on non-ASCII str operands)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(secure_portal, "/api/jobs?token=%C3%A9")
    assert exc.value.code == 401
    # healthz stays open for liveness probes
    status, _ = _get(secure_portal, "/healthz")
    assert status == 200


def test_portal_accepts_bearer_and_query_token(secure_portal):
    req = urllib.request.Request(
        f"http://127.0.0.1:{secure_portal.port}/api/jobs",
        headers={"Authorization": "Bearer sekrit-tok"})
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200
        assert json.loads(resp.read())[0]["application_id"] == "app_x"
    status, body = _get(secure_portal, "/config/app_x?token=sekrit-tok")
    assert status == 200 and "tony.am.memory" in body


# ---------------------------------------------------------------------------
# per-user named tokens (reference multi-tenant parity:
# TonyPolicyProvider.java:23, TokenCache.java:44-72)
# ---------------------------------------------------------------------------

@pytest.fixture()
def multiuser_portal(tmp_path):
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_alice", user="alice",
                     config={"k": "va"})
    make_app_history(inter, "app_bob", user="bob", config={"k": "vb"})
    server = PortalServer(
        PortalCache(inter, fin), port=0, host="127.0.0.1",
        token="admin-tok",
        user_tokens={"alice": "tok-alice", "bob": "tok-bob"})
    server.start()
    yield server
    server.stop()


def test_portal_user_token_scopes_job_list(multiuser_portal):
    """User A cannot list (or read) user B's jobs; admin sees all."""
    status, body = _get(multiuser_portal, "/api/jobs?token=tok-alice")
    jobs = json.loads(body)
    assert status == 200
    assert [j["application_id"] for j in jobs] == ["app_alice"]
    status, body = _get(multiuser_portal, "/api/jobs?token=tok-bob")
    assert [j["application_id"] for j in json.loads(body)] == ["app_bob"]
    status, body = _get(multiuser_portal, "/api/jobs?token=admin-tok")
    assert {j["application_id"] for j in json.loads(body)} == {
        "app_alice", "app_bob"}
    # the HTML index filters the same way
    status, body = _get(multiuser_portal, "/?token=tok-alice")
    assert "app_alice" in body and "app_bob" not in body


def test_portal_user_token_cannot_read_others_job(multiuser_portal):
    """Another user's job must 404 exactly like a missing one — a scoped
    token must not even confirm existence."""
    for path in ("/jobs/app_bob", "/config/app_bob", "/logs/app_bob",
                 "/api/jobs/app_bob/config", "/api/jobs/app_bob/events"):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(multiuser_portal, f"{path}?token=tok-alice")
        assert exc.value.code == 404, path
    # while the owner reads it fine
    status, body = _get(multiuser_portal,
                        "/api/jobs/app_bob/config?token=tok-bob")
    assert status == 200 and json.loads(body) == {"k": "vb"}
    # and an unknown token is still unauthorized, not scoped-to-nothing
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(multiuser_portal, "/api/jobs?token=nope")
    assert exc.value.code == 401


def test_read_user_tokens(tmp_path):
    from tony_tpu.portal.server import read_user_tokens
    f = tmp_path / "users.txt"
    f.write_text("# comment\nalice=tok-a\n\nbob = tok-b\nbad-line\n")
    assert read_user_tokens(str(f)) == {"alice": "tok-a", "bob": "tok-b"}


def test_portal_serves_log_content_route(tmp_path):
    """/logs/:id/:dir/:stream returns the real aggregated stdout body
    (VERDICT r4 item 3 acceptance)."""
    inter, fin = str(tmp_path / "int"), str(tmp_path / "fin")
    ensure_history_dirs(inter, fin)
    make_app_history(inter, "app_lc", completed=2000,
                     logs={"worker_0_s0": {"stdout": "real body 42\n"}})
    server = PortalServer(PortalCache(inter, fin), port=0,
                          host="127.0.0.1")
    server.start()
    try:
        status, body = _get(server, "/logs/app_lc")
        assert status == 200 and "/logs/app_lc/worker_0_s0/stdout" in body
        status, body = _get(server, "/logs/app_lc/worker_0_s0/stdout")
        assert status == 200 and body == "real body 42\n"
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/logs/app_lc/worker_0_s0/stderr")
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/logs/app_lc/..%2Fworker_0_s0/stdout")
        assert exc.value.code == 404
    finally:
        server.stop()
