"""Fleet observability (PR 8): cross-job registry, chip-hour accounting,
cluster portal/CLI surfaces.

Unit layer: the fleet.py registry/ledger state machines with fake
clocks + synthetic stores (staleness → LOST, boundedness at 1k job
summaries, chip-second math against the conf/queues.py quota math,
prometheus round-trip with {app_id, queue, user} labels). Static layer:
every `tony_job_*` gauge literal the AM exports must be a key of
fleet.JOB_GAUGES — the fleet re-exposition can never silently drop a
job gauge. E2e layer: two concurrent mini-cluster apps in distinct
queues visible live on /api/fleet with correct per-queue attribution,
and an AM killed -9 whose entry goes LOST yet still lands in the final
accounting.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants as C
from tony_tpu.conf import TonyConfiguration, keys as K
from tony_tpu.observability import fleet
from tony_tpu.storage import location_store, staging_store

pytestmark = pytest.mark.fleet

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, s: float) -> None:
        self.t += s


def summary(app_id: str, state: str = "RUNNING", queue: str = "default",
            user: str = "alice", chips: int = 4, hb_ms: int = 0,
            started_ms: int = 0, **kw) -> dict:
    return fleet.job_summary(
        app_id, user, queue, state, gang_width=2, requested_chips=chips,
        started_ms=started_ms, heartbeat_ms=hb_ms, **kw)


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------

def test_registry_demotes_stale_running_to_lost():
    clock = FakeClock(1000.0)
    reg = fleet.FleetRegistry(stale_after_ms=2000, clock=clock)
    reg.observe(summary("app_a", hb_ms=1_000_000))
    assert reg.jobs()[0]["state"] == "RUNNING"
    clock.tick(1.0)                       # inside stale-after
    reg.refresh(force=True)
    assert reg.jobs()[0]["state"] == "RUNNING"
    clock.tick(2.0)                       # past it
    reg.refresh(force=True)
    job = reg.jobs()[0]
    assert job["state"] == fleet.LOST_STATE
    assert job["demoted_ms"] == int(clock() * 1000)


def test_registry_terminal_state_never_regresses():
    clock = FakeClock()
    reg = fleet.FleetRegistry(clock=clock)
    reg.observe(summary("app_a", state="SUCCEEDED", hb_ms=2_000_000))
    # a stale RUNNING file listed after the terminal entry must not
    # resurrect the job — nor may an older heartbeat overwrite a newer
    reg.observe(summary("app_a", state="RUNNING", hb_ms=3_000_000))
    reg.observe(summary("app_a", state="SUCCEEDED", hb_ms=1_000_000))
    assert reg.jobs()[0]["state"] == "SUCCEEDED"
    assert reg.jobs()[0]["heartbeat_ms"] == 2_000_000


def test_registry_and_ledger_bounded_at_1k_summaries():
    """Acceptance: memory stays bounded when 1k synthetic job summaries
    flow through — the registry caps entries (non-live evicted oldest
    first), the ledger caps per-job entries while folding evictions
    into the rollups so chip-hours are conserved."""
    clock = FakeClock()
    reg = fleet.FleetRegistry(stale_after_ms=10_000, max_jobs=64,
                              clock=clock)
    ledger = fleet.FleetLedger(history_jobs=32, clock=clock)
    total_chip_s = 0.0
    for i in range(1000):
        state = "SUCCEEDED" if i % 2 else "RUNNING"
        s = summary(f"app_{i:04d}", state=state, queue=f"q{i % 3}",
                    user=f"u{i % 5}", chips=2,
                    started_ms=i * 1000, hb_ms=i * 1000 + 10_000)
        reg.observe(s)
        entry = ledger.fold(s)
        if entry is not None:
            total_chip_s += entry["chip_seconds"]
    assert len(reg) <= 64
    assert len(ledger) <= 32
    acct = ledger.accounting()
    assert acct["folded_jobs"] == 500 - 32
    accounted = sum(b["chip_seconds"] for b in acct["queues"].values())
    assert accounted == pytest.approx(total_chip_s)
    # users rollup conserves the same total
    assert sum(b["chip_seconds"] for b in acct["users"].values()) == \
        pytest.approx(total_chip_s)
    # timeline is a decimating ring buffer, not an unbounded list
    reg.refresh(force=True)
    assert len(reg.timeline()) <= 257


def test_sort_jobs_state_then_start_time():
    jobs = [summary("a", state="SUCCEEDED", started_ms=50),
            summary("b", state="RUNNING", started_ms=10),
            summary("c", state="RUNNING", started_ms=20),
            summary("d", state=fleet.LOST_STATE, started_ms=99)]
    order = [j["app_id"] for j in fleet.sort_jobs(jobs)]
    assert order == ["c", "b", "d", "a"]


# ---------------------------------------------------------------------------
# ledger + quota math
# ---------------------------------------------------------------------------

def test_ledger_chip_second_math_prefers_final_goodput():
    clock = FakeClock()
    ledger = fleet.FleetLedger(clock=clock)
    s = summary("app_x", state="SUCCEEDED", queue="qa", user="bob",
                chips=4, started_ms=1000, hb_ms=101_000, goodput_pct=50.0)
    entry = ledger.fold(s, goodput={"job": {"goodput_pct": 75.0}})
    assert entry["chip_seconds"] == pytest.approx(4 * 100.0)
    # the published goodput.json bundle wins over the live-pushed pct
    assert entry["productive_chip_seconds"] == pytest.approx(300.0)
    assert entry["overhead_chip_seconds"] == pytest.approx(100.0)
    # idempotent per app_id
    assert ledger.fold(s) is None
    acct = ledger.accounting()
    assert acct["queues"]["qa"]["chip_hours"] == pytest.approx(400 / 3600,
                                                               abs=1e-4)
    assert acct["users"]["bob"]["jobs"] == 1


def test_ledger_refolds_lost_job_on_real_terminal(tmp_path):
    """A job provisionally folded as LOST (stalled publisher, portal
    demoted it) whose AM turns out alive and later publishes a real
    terminal state is re-accounted at its true extent — the 30-second
    stale snapshot must not stand in for hours of chip-time."""
    ledger = fleet.FleetLedger()
    lost = summary("app_r", state=fleet.LOST_STATE, queue="qa",
                   chips=4, started_ms=1000, hb_ms=41_000)
    assert ledger.fold(lost)["chip_seconds"] == pytest.approx(160.0)
    done = summary("app_r", state="SUCCEEDED", queue="qa", chips=4,
                   started_ms=1000, hb_ms=3_601_000, goodput_pct=90.0)
    assert ledger.should_fold(done)
    entry = ledger.fold(done)
    assert entry["state"] == "SUCCEEDED"
    assert entry["chip_seconds"] == pytest.approx(4 * 3600.0)
    # exactly one entry; totals reflect the replacement, not the sum
    acct = ledger.accounting()
    assert acct["queues"]["qa"]["jobs"] == 1
    assert acct["queues"]["qa"]["chip_seconds"] == pytest.approx(14400.0)
    # a second SUCCEEDED publish stays idempotent
    assert not ledger.should_fold(done)
    assert ledger.fold(done) is None


def test_ledger_unfolds_evicted_lost_ghost_without_double_count():
    """Even after the provisional LOST entry was evicted into the
    rollup accumulators, the real terminal state un-folds the stale
    extent first — totals stay conserved, never double-counted."""
    ledger = fleet.FleetLedger(history_jobs=1)
    lost = summary("app_g", state=fleet.LOST_STATE, queue="qa",
                   chips=2, started_ms=1000, hb_ms=31_000)
    ledger.fold(lost)
    # a second fold with a NEWER end evicts app_g (oldest-ended first)
    # into the rollup accumulators (history_jobs=1)
    ledger.fold(summary("app_other", state="SUCCEEDED", queue="qa",
                        chips=2, started_ms=1000, hb_ms=41_000))
    assert not ledger.has("app_g")
    done = summary("app_g", state="SUCCEEDED", queue="qa", chips=2,
                   started_ms=1000, hb_ms=3_601_000)
    assert ledger.should_fold(done)
    ledger.fold(done)
    acct = ledger.accounting()
    # 2 chips × 3600s (app_g, true extent) + 2 × 40s (app_other) —
    # the 60 chip-seconds of the stale LOST snapshot are gone
    assert acct["queues"]["qa"]["chip_seconds"] == pytest.approx(7280.0)
    assert acct["queues"]["qa"]["jobs"] == 2


def test_refresh_skips_settled_terminal_jobstate_files(tmp_path,
                                                       monkeypatch):
    """A non-LOST terminal jobstate file is immutable; the scan reads
    it once and never again (on GCS every read is a subprocess), while
    a LOST entry stays hot so a resurrected AM's republish is seen."""
    staging = str(tmp_path / "staging")
    for app, state in (("app_s", "SUCCEEDED"), ("app_l", "RUNNING")):
        store = staging_store(staging, str(tmp_path / "apps" / app))
        fleet.publish_job_state(
            store, summary(app, state=state, hb_ms=1_000), str(tmp_path))
    reg = fleet.FleetRegistry(staging, refresh_interval_ms=0,
                              stale_after_ms=1)   # RUNNING → LOST fast
    reads = []
    orig = fleet._read_json_key
    monkeypatch.setattr(
        fleet, "_read_json_key",
        lambda store, key: (reads.append(key), orig(store, key))[1])
    reg.refresh(force=True)
    states = {j["app_id"]: j["state"] for j in reg.jobs()}
    assert states["app_s"] == "SUCCEEDED"
    assert states["app_l"] == fleet.LOST_STATE
    first = reads.count(f"app_s/{fleet.JOBSTATE_KEY}")
    assert first == 1
    reg.refresh(force=True)
    reg.refresh(force=True)
    # settled file: no further reads; the LOST one is re-read each pass
    assert reads.count(f"app_s/{fleet.JOBSTATE_KEY}") == first
    assert reads.count(f"app_l/{fleet.JOBSTATE_KEY}") == 3


def test_ledger_durable_roundtrip(tmp_path):
    loc = str(tmp_path / "store")
    ledger = fleet.FleetLedger(loc)
    ledger.fold(summary("app_d", state="FAILED", queue="qz", user="eve",
                        chips=2, started_ms=1000, hb_ms=31_000))
    ledger.save()
    assert os.path.isfile(os.path.join(loc, fleet.ACCOUNTING_KEY))
    reborn = fleet.FleetLedger(loc)
    assert reborn.has("app_d")
    assert reborn.accounting()["queues"]["qz"]["chip_seconds"] == \
        pytest.approx(60.0)


def test_quota_utilization_matches_queue_conf_math():
    """The portal's quota bars and conf/queues.py must agree: a queue at
    exactly its max-tpus reads 100%."""
    from tony_tpu.conf.queues import configured_queues
    conf = TonyConfiguration()
    conf.set("tony.queues.qa.max-tpus", 8, "test")
    conf.set("tony.queues.qb.max-tpus", 4, "test")
    queues = configured_queues(conf)
    live = [summary("a1", queue="qa", chips=4),
            summary("a2", queue="qa", chips=4),
            summary("a3", queue="qb", chips=2),
            summary("a4", queue="undeclared", chips=1)]
    util = fleet.quota_utilization(queues, live)
    assert util["qa"] == {"max_tpus": 8, "chips_in_use": 8,
                          "live_jobs": 2, "utilization_pct": 100.0}
    assert util["qb"]["utilization_pct"] == 50.0
    assert util["undeclared"]["max_tpus"] == 0
    assert "utilization_pct" not in util["undeclared"]


def test_chips_of_prefers_allocation_over_ask():
    s = summary("a", chips=8)
    assert fleet.chips_of(s) == 8
    s["allocated_chips"] = 6
    assert fleet.chips_of(s) == 6


# ---------------------------------------------------------------------------
# prometheus re-exposition
# ---------------------------------------------------------------------------

def test_fleet_families_roundtrip_with_labels():
    """Acceptance: the fleet /metrics payload round-trips through the
    shared prometheus parser and every job gauge carries the
    {app_id, queue, user} label set."""
    from tony_tpu.observability.prometheus import get_sample, parse, render
    live = [summary("app_1", queue="qa", user="alice",
                    gauges={"tony_job_goodput_pct": 81.5,
                            "tony_job_straggler_count": 1.0}),
            summary("app_2", queue="qb", user="bob",
                    gauges={"tony_job_goodput_pct": 40.0})]
    text = render(fleet.fleet_families(live, queues={"qa": 8, "qb": 8}))
    parsed = parse(text)
    assert get_sample(parsed, "tony_job_goodput_pct",
                      app_id="app_1", queue="qa", user="alice") == 81.5
    assert get_sample(parsed, "tony_job_goodput_pct",
                      app_id="app_2", queue="qb", user="bob") == 40.0
    assert get_sample(parsed, "tony_job_straggler_count",
                      app_id="app_1") == 1.0
    assert get_sample(parsed, "tony_fleet_live_jobs") == 2.0
    assert get_sample(parsed, "tony_fleet_chips_in_use") == 8.0
    assert get_sample(parsed, "tony_fleet_queue_quota_tpus",
                      queue="qa") == 8.0


# ---------------------------------------------------------------------------
# tier-1 static check: the AM's job gauges vs fleet's aggregation map —
# migrated to tonylint (tools/tonylint/rules_legacy.py `gauge-registry`:
# AM tony_job_* literals ⊆ fleet.JOB_GAUGES, f-string names rejected,
# STEP_TIME_GAUGES consistency)
# ---------------------------------------------------------------------------

def test_every_am_job_gauge_is_in_the_fleet_aggregation_map():
    from tools.tonylint import findings_for
    assert findings_for("gauge-registry") == []


# ---------------------------------------------------------------------------
# e2e: real apps on the local backend, shared staging store
# ---------------------------------------------------------------------------

def _fleet_conf(tmp_path, staging: str, queue: str,
                **overrides) -> TonyConfiguration:
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path / "work"), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 300, "test")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "test")
    conf.set(K.CONTAINER_ALLOCATION_TIMEOUT, 60_000, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 3000, "test")
    conf.set(K.STAGING_LOCATION, staging, "test")
    conf.set(K.FLEET_PUBLISH_INTERVAL_MS, 200, "test")
    conf.set(K.APPLICATION_QUEUE, queue, "test")
    conf.set("tony.queues.qa.max-tpus", 4, "test")
    conf.set("tony.queues.qb.max-tpus", 8, "test")
    for k, v in overrides.items():
        conf.set(k, v, "test")
    return conf


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_two_concurrent_jobs_visible_and_accounted(tmp_path):
    """Acceptance: two concurrent mini-cluster apps in distinct queues
    → /api/fleet shows both live with correct queue/user attribution
    and quota bars matching the queues.py math; the fleet /metrics
    round-trips through the shared prometheus parser with
    {app_id,queue,user} labels; after completion the chip-hours land
    in fleet/accounting.json under the right queue and user."""
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.conf.queues import configured_queues
    from tony_tpu.observability.prometheus import get_sample, parse
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer

    staging = str(tmp_path / "staging")
    clients, threads, results = [], [], {}
    for i, queue in enumerate(("qa", "qb")):
        conf = _fleet_conf(tmp_path, staging, queue)
        client = TonyClient(conf)
        client.init(["--executes", script("fleet_task.py"),
                     "--conf", "tony.worker.instances=1",
                     "--conf", "tony.worker.tpus=2",
                     "--shell_env", "FLEET_TASK_SLEEP_SEC=4"])
        clients.append(client)

        def _run(c=client, q=queue):
            results[q] = c.run()

        threads.append(threading.Thread(target=_run, daemon=True))
    view = fleet.FleetView(
        staging,
        queues=configured_queues(_fleet_conf(tmp_path, staging, "qa")),
        stale_after_ms=30_000, refresh_interval_ms=100)
    cache = PortalCache(str(tmp_path / "int"), str(tmp_path / "fin"))
    portal = PortalServer(cache, port=0, fleet=view)
    portal.start()
    base = f"http://127.0.0.1:{portal.port}"
    try:
        for t in threads:
            t.start()
        # ...until both jobs are live on /api/fleet
        deadline = time.monotonic() + 60
        live_by_queue = {}
        while time.monotonic() < deadline:
            payload = _get_json(f"{base}/api/fleet")
            live_by_queue = {j["queue"]: j for j in payload["jobs"]
                             if j["state"] == "RUNNING"}
            if {"qa", "qb"} <= set(live_by_queue):
                break
            time.sleep(0.1)
        assert {"qa", "qb"} <= set(live_by_queue), payload
        for queue, job in live_by_queue.items():
            assert job["gang_width"] == 1
            assert fleet.chips_of(job) == 2
            assert job["user"]            # stamped with the submitter
        # quota bars match the queues.py math: 2 of 4 / 2 of 8
        queues_payload = _get_json(f"{base}/api/fleet/queues")["queues"]
        assert queues_payload["qa"]["chips_in_use"] == 2
        assert queues_payload["qa"]["utilization_pct"] == 50.0
        assert queues_payload["qb"]["utilization_pct"] == 25.0
        # fleet /metrics: shared-encoder round-trip with full labels
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            parsed = parse(r.read().decode())
        for queue, job in live_by_queue.items():
            get_sample(parsed, "tony_job_goodput_pct",
                       app_id=job["app_id"], queue=queue,
                       user=job["user"])
        assert get_sample(parsed, "tony_fleet_chips_in_use") == 4.0
        # index page renders the cluster panels + bounded directory
        with urllib.request.urlopen(f"{base}/", timeout=10) as r:
            page = r.read().decode()
        assert "fleet registry" in page and "showing" in page
    finally:
        for t in threads:
            t.join(timeout=120)
        portal.stop()
    assert results == {"qa": True, "qb": True}, \
        [c.final_message for c in clients]
    # terminal states replace the live entries; accounting settles
    view.refresh(force=True)
    states = {j["queue"]: j["state"] for j in view.registry.jobs()}
    assert states == {"qa": "SUCCEEDED", "qb": "SUCCEEDED"}
    acct = view.ledger.accounting()
    by_queue = acct["queues"]
    assert by_queue["qa"]["jobs"] == 1 and by_queue["qb"]["jobs"] == 1
    for q in ("qa", "qb"):
        assert by_queue[q]["chip_seconds"] > 0
        # the fleet_task pushed a real train_step ledger: some of the
        # chip-seconds are attributed productive
        assert by_queue[q]["productive_chip_seconds"] > 0
    import getpass
    assert acct["users"][getpass.getuser()]["jobs"] == 2
    # durable: the accounting file exists in the store and reloads
    assert os.path.isfile(os.path.join(staging, fleet.ACCOUNTING_KEY))
    reborn = fleet.FleetLedger(staging)
    assert len(reborn) == 2


@pytest.mark.chaos
def test_am_killed_minus9_goes_lost_then_accounted(tmp_path):
    """Acceptance: an AM killed -9 mid-run never publishes a terminal
    jobstate — its registry entry is demoted to LOST once the heartbeat
    stamp ages past tony.fleet.stale-after-ms, and the ledger still
    folds its chip-hours at the last known extent."""
    import signal

    from tony_tpu.client.tony_client import TonyClient

    staging = str(tmp_path / "staging")
    conf = _fleet_conf(tmp_path, staging, "qa")
    client = TonyClient(conf)
    client.init(["--executes", script("fleet_task.py"),
                 "--conf", "tony.worker.instances=1",
                 "--conf", "tony.worker.tpus=2",
                 "--shell_env", "FLEET_TASK_SLEEP_SEC=30"])
    done = {}
    t = threading.Thread(target=lambda: done.update(ok=client.run()),
                         daemon=True)
    t.start()
    view = fleet.FleetView(staging, stale_after_ms=1200,
                           refresh_interval_ms=100)
    try:
        deadline = time.monotonic() + 60
        seen_running = False
        while time.monotonic() < deadline and not seen_running:
            view.refresh(force=True)
            jobs = view.registry.jobs()
            seen_running = any(j["state"] == "RUNNING" for j in jobs)
            time.sleep(0.1)
        assert seen_running, "job never appeared live in the registry"
        # kill the AM's whole process group — no terminal publish
        os.killpg(os.getpgid(client._am_proc.pid), signal.SIGKILL)
        t.join(timeout=60)
        assert done.get("ok") is False
        deadline = time.monotonic() + 30
        lost = None
        while time.monotonic() < deadline and lost is None:
            view.refresh(force=True)
            jobs = view.registry.jobs()
            lost = next((j for j in jobs
                         if j["state"] == fleet.LOST_STATE), None)
            time.sleep(0.2)
        assert lost is not None, view.registry.jobs()
        # ...and the final accounting still lands
        acct = view.ledger.accounting()
        entry = acct["jobs"].get(lost["app_id"])
        assert entry is not None and entry["state"] == fleet.LOST_STATE
        assert entry["queue"] == "qa"
        assert entry["chip_seconds"] > 0
    finally:
        client.cleanup()
        t.join(timeout=30)


# ---------------------------------------------------------------------------
# portal index bound + cli top (file level, no live apps)
# ---------------------------------------------------------------------------

def _fake_history(tmp_path, n: int) -> tuple[str, str]:
    intermediate = str(tmp_path / "int")
    finished = str(tmp_path / "fin")
    os.makedirs(intermediate, exist_ok=True)
    for i in range(n):
        d = os.path.join(intermediate, f"app_{i:03d}")
        os.makedirs(d, exist_ok=True)
        name = f"app_{i:03d}-{1000 + i}-{2000 + i}-alice-SUCCEEDED.jhist"
        with open(os.path.join(d, name), "w", encoding="utf-8") as f:
            f.write("[]")
    return intermediate, finished


def test_index_is_bounded_with_count_footer(tmp_path):
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer
    intermediate, finished = _fake_history(tmp_path, 7)
    portal = PortalServer(PortalCache(intermediate, finished), port=0,
                          history_jobs=3)
    portal.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{portal.port}/", timeout=10) as r:
            page = r.read().decode()
    finally:
        portal.stop()
    assert "showing 3 of 7 job(s)" in page
    # newest first within the bound: app_006 renders, app_000 doesn't
    assert "app_006" in page and "app_000" not in page


def test_cli_top_renders_registry(tmp_path, capsys):
    from tony_tpu.cli.__main__ import top
    staging = str(tmp_path / "staging")
    store = staging_store(staging, str(tmp_path / "apps" / "app_live"))
    fleet.publish_job_state(
        store, summary("app_live", queue="qa", chips=2,
                       hb_ms=int(time.time() * 1000)), str(tmp_path))
    assert top([staging, "--once"]) == 0
    out = capsys.readouterr().out
    assert "app_live" in out and "RUNNING" in out and "1 live job(s)" in out
    assert top([staging, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["jobs"][0]["app_id"] == "app_live"
    assert payload["chips_in_use"] == 2


def test_fleet_store_glob_matches_only_jobstate_keys(tmp_path):
    """The registry scan must not trip over unrelated per-app keys
    (staged confs, history uploads) sharing the location."""
    staging = str(tmp_path / "staging")
    store = staging_store(staging, str(tmp_path / "apps" / "app_1"))
    fleet.publish_job_state(store, summary("app_1"), str(tmp_path))
    conf_file = tmp_path / "tony-final.json"
    conf_file.write_text("{}")
    store.put(str(conf_file), C.TONY_FINAL_CONF)
    store.put(str(conf_file), "history/config.json")
    root = location_store(staging)
    keys = root.glob(f"*/{fleet.JOBSTATE_KEY}")
    assert keys == [f"app_1/{fleet.JOBSTATE_KEY}"]
