"""Paged prefix-shared KV cache + disaggregation tests (serve/kvcache.py).

The load-bearing contracts, in order of blast radius:

- **Bit-identity.** Prefix sharing ON (paged gather + suffix-only
  prefill) produces the SAME greedy token streams as sharing OFF and as
  the offline `generate()` oracle, under staggered arrivals and slot
  recycling, with zero decode-step recompiles after warmup — reuse is a
  pure latency optimization, never a numerics fork.
- **Pool invariants.** Page ids always partition into
  {scratch} ∪ free ∪ indexed; parent child-refcounts match live
  children; pinned/interior nodes are never evicted — chaos-checked
  under random register/match/evict interleavings.
- **Migration.** pack/unpack is a byte-exact roundtrip and a prefill →
  decode handoff continues the greedy stream bit-identically to
  decoding locally.
- **Routing/scaling.** Prefix affinity prefers the advertising replica
  but NEVER overrides draining/dead/decode-role filtering; page-pool
  headroom scales the load score; prefill/decode pools file DISTINCT
  arbiter book entries and fold only their own pool's SLIs.

All CPU-backend, tier-1 fast.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu.models.generate import generate
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.serve.engine import (
    ContinuousBatchingEngine, decode_step_cache_size,
)
from tony_tpu.serve.kvcache import (
    KVPagePool, SCRATCH_PAGE, chain_hashes, pack_migration,
    unpack_migration,
)

pytestmark = pytest.mark.kv


@pytest.fixture(scope="module")
def model():
    cfg = get_config("tiny")
    return llama_init(cfg, jax.random.PRNGKey(0)), cfg


def _prompts(cfg, lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
            for n in lengths]


def _oracle(params, cfg, prompt, n, **kw):
    out = generate(params, cfg, jnp.asarray([prompt], jnp.int32), n, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _drain(engine, handles, max_steps=300):
    for _ in range(max_steps):
        if all(h.done.is_set() for h in handles):
            return
        engine.step()
    raise AssertionError("engine did not finish the workload")


# ---------------------------------------------------------------------------
# chain hashes
# ---------------------------------------------------------------------------

def test_chain_hashes_identify_full_prefixes():
    """Equal hash[i] ⇔ equal tokens[0:(i+1)*P]: the chain makes block
    identity transitive, so a mid-prompt divergence changes EVERY later
    hash — never just the diverged block's."""
    a = list(range(40))
    b = list(range(40))
    b[17] = 999                          # diverge inside block 4 (P=4)
    ha, hb = chain_hashes(a, 4), chain_hashes(b, 4)
    assert len(ha) == len(hb) == 10
    assert ha[:4] == hb[:4]
    assert all(x != y for x, y in zip(ha[4:], hb[4:]))
    # deterministic across calls (never Python hash(), which is salted)
    assert chain_hashes(a, 4) == ha
    # only COMPLETE blocks are hashed; degenerate page sizes are empty
    assert len(chain_hashes(a[:7], 4)) == 1
    assert chain_hashes(a, 0) == []


# ---------------------------------------------------------------------------
# pool refcount / COW invariants
# ---------------------------------------------------------------------------

def _register_chain(pool, hashes):
    """Seal a full chain into the index (test-side stand-in for
    _seal_prefix's bookkeeping)."""
    parent = ""
    for depth, digest in enumerate(hashes, start=1):
        if digest in pool._nodes:
            parent = digest
            continue
        pid = pool.allocate()
        if pid is None:
            return
        pool.register(parent, digest, pid, depth)
        parent = digest


def test_pool_refcount_pinning_and_eviction_order(model):
    _, cfg = model
    pool = KVPagePool(cfg, token_budget=32, page_size=4, n_pages=6,
                      n_slots=1)
    assert pool.pages_total == 5                 # scratch excluded
    ha = chain_hashes(list(range(12)), 4)        # 3-block chain
    hb = chain_hashes([7] * 8, 4)                # 2-block chain
    _register_chain(pool, ha)
    _register_chain(pool, hb)
    pool.check_invariants()
    assert pool.pages_used == 5 and pool.pages_free == 0

    # match pins the deepest node; its ancestors are held by child refs
    ids, depth = pool.match(ha)
    assert depth == 3 and len(ids) == 3
    assert pool._nodes[ha[2]].pins == 1
    # a shared shorter prefix matches the same pages
    ids2, depth2 = pool.match(chain_hashes(list(range(8)), 4))
    assert depth2 == 2 and ids2 == ids[:2]
    pool.unpin(ha[1])

    # chain A is fully pinned-or-interior; only chain B's leaf (then its
    # parent, once it becomes a leaf) is evictable
    assert pool.evictable_pages() == 1
    assert pool.headroom_pages() == 1
    p1 = pool.allocate()                         # LRU leaf hb[1] evicted
    assert p1 is not None and pool.evicted_pages == 1
    assert hb[1] not in pool._nodes and hb[0] in pool._nodes
    p2 = pool.allocate()                         # hb[0] is a leaf now
    assert p2 is not None and hb[0] not in pool._nodes
    held = [p1, p2]                              # checked out mid-admission
    # what remains is pinned/interior: allocation fails CLEANLY
    assert pool.allocate() is None
    pool.unpin(ha[2])
    held.append(pool.allocate())
    assert held[-1] is not None                  # leaf freed by unpin
    pool._free.extend(held)                      # return them unused
    pool.check_invariants()
    # advertised snapshot tracks the live index
    assert set(pool.advertised) == set(pool._nodes)


def test_pool_eviction_chaos_invariants_hold(model):
    """Random register/match/unpin/allocate interleavings on a tiny
    pool: the partition + refcount invariants hold after EVERY op and
    counters stay monotonic."""
    _, cfg = model
    pool = KVPagePool(cfg, token_budget=64, page_size=4, n_pages=9,
                      n_slots=1)
    rng = np.random.RandomState(1234)
    pinned: list[str] = []
    last_evicted = 0
    for _ in range(400):
        op = rng.randint(0, 4)
        if op == 0:                              # register a random chain
            prompt = [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                  size=rng.randint(4, 24))]
            _register_chain(pool, chain_hashes(prompt, 4))
        elif op == 1:                            # match (pins deepest)
            prompt = [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                  size=rng.randint(4, 24))]
            hashes = chain_hashes(prompt, 4)
            _, depth = pool.match(hashes)
            if depth:
                pinned.append(hashes[depth - 1])
        elif op == 2 and pinned:                 # release an old pin
            pool.unpin(pinned.pop(rng.randint(0, len(pinned))))
        else:                                    # allocate under pressure
            pid = pool.allocate()
            if pid is not None:
                assert pid != SCRATCH_PAGE
                pool._free.append(pid)           # return it unused
        pool.check_invariants()
        assert pool.evicted_pages >= last_evicted
        last_evicted = pool.evicted_pages
    for digest in pinned:
        pool.unpin(digest)
    pool.check_invariants()
    assert pool.sealed_pages > 0 and pool.evicted_pages > 0


# ---------------------------------------------------------------------------
# ON-vs-OFF bit-identity + zero decode recompiles
# ---------------------------------------------------------------------------

def test_prefix_sharing_on_equals_off_staggered_zero_decode_recompiles(
        model):
    """The tentpole contract: sharing ON (paged gather + suffix-only
    prefill) emits the SAME greedy streams as sharing OFF and as the
    offline oracle, under staggered arrivals + slot recycling, while
    the persistent decode step never recompiles — and the pool really
    did serve hits (this is not a vacuous all-miss pass)."""
    params, cfg = model
    shared = _prompts(cfg, (8,), seed=5)[0]      # two full 4-token blocks
    tails = _prompts(cfg, (5, 3, 9, 1, 6), seed=6)
    prompts = [shared + t for t in tails] + _prompts(cfg, (7,), seed=7)

    outs = {}
    for sharing in (False, True):
        engine = ContinuousBatchingEngine(
            params, cfg, n_slots=2, token_budget=32, queue_depth=16,
            prefix_sharing=sharing, kv_page_size=4)
        warm = engine.submit(prompts[0], 2)
        _drain(engine, [warm])
        decode_compiles = decode_step_cache_size()
        # staggered: two in, step a few times, then the rest
        handles = [engine.submit(p, 4) for p in prompts[:2]]
        for _ in range(3):
            engine.step()
        handles += [engine.submit(p, 4) for p in prompts[2:]]
        _drain(engine, handles)
        assert decode_step_cache_size() == decode_compiles, \
            f"decode step recompiled (sharing={sharing})"
        outs[sharing] = [h.tokens for h in handles]
        if sharing:
            pool = engine.kv_pool
            pool.check_invariants()
            assert pool.req_hits >= len(tails) - 1
            assert pool.hit_tokens >= 8 * (len(tails) - 1)
            # the probe surfaces the reuse the router keys off
            load = engine.load()
            assert load["kv_page_size"] == 4
            assert load["kv_pages_total"] > 0
            assert load["prefix_hashes"]
            assert engine.snapshot()["kv_hit_total"] == pool.hit_tokens

    assert outs[True] == outs[False]
    for toks, p in zip(outs[True], prompts):
        assert toks == _oracle(params, cfg, p, 4)


# ---------------------------------------------------------------------------
# migration: wire format + prefill→decode handoff equivalence
# ---------------------------------------------------------------------------

def test_pack_unpack_migration_roundtrip_and_validation():
    meta = {"prompt": [1, 2, 3], "max_new_tokens": 4, "pos": 3,
            "tok0": 9, "emitted": 1}
    leaves = {"k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
              "v": np.ones((2, 3, 4), np.int8)}
    body = pack_migration(meta, leaves)
    header, out = unpack_migration(body)
    assert {k: header[k] for k in meta} == meta
    assert set(out) == {"k", "v"}
    for name in leaves:
        assert out[name].dtype == leaves[name].dtype
        np.testing.assert_array_equal(out[name], leaves[name])
    with pytest.raises(ValueError):
        unpack_migration(body[:len(body) - 5])   # truncated blob
    with pytest.raises(ValueError):
        unpack_migration(b"not-json\n" + b"x" * 8)
    with pytest.raises(ValueError):
        unpack_migration(b"no header separator at all")


@pytest.mark.parametrize("quant", [False, True],
                         ids=["fp-cache", "int8-cache"])
def test_migrate_roundtrip_bit_identical_to_local_decode(model, quant):
    """A prefill-role admission that migrates out, framed through the
    wire format and installed on a second engine, continues the greedy
    stream bit-identically to the offline oracle — tok0 from the
    prefill side, the rest from the decode side, no token lost or
    doubled. Holds for the int8 quant cache too (the quantized bytes
    travel verbatim)."""
    params, cfg = model
    prompts = _prompts(cfg, (9, 6), seed=11)
    max_new = 5
    pre = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                   token_budget=32, queue_depth=8,
                                   quant_cache=quant, role="prefill")
    dec = ContinuousBatchingEngine(params, cfg, n_slots=2,
                                   token_budget=32, queue_depth=8,
                                   quant_cache=quant, role="decode")
    for p in prompts:
        h_pre = pre.submit(p, max_new, migrate_out=True)
        _drain(pre, [h_pre])
        assert h_pre.finish_reason == "migrated"
        assert h_pre.migration is not None
        # over the wire: JSON header + raw leaf bytes, byte-exact
        body = pack_migration(h_pre.migration["meta"],
                              h_pre.migration["leaves"])
        header, leaves = unpack_migration(body)
        h_dec = dec.submit_migration(header, leaves)
        _drain(dec, [h_dec])
        assert h_dec.finish_reason == "length"
        full = h_pre.tokens + h_dec.tokens
        assert len(full) == max_new
        assert full == _oracle(params, cfg, p, max_new,
                               quant_cache=quant)
    assert pre.stats.migrated_out == len(prompts)
    assert dec.stats.migrated_in == len(prompts)
    assert pre.snapshot()["migrated_out_total"] == len(prompts)


def test_submit_migration_validates_layout_and_pos(model):
    params, cfg = model
    dec = ContinuousBatchingEngine(params, cfg, n_slots=1,
                                   token_budget=32, queue_depth=4)
    from tony_tpu.serve.engine import BudgetExceededError
    good = {name: np.zeros((cfg.n_layers, cfg.n_kv_heads, 3,
                            cfg.head_dim), np.asarray(arr).dtype)
            for name, arr in dec._cache.items()}
    meta = {"prompt": [1, 2, 3], "max_new_tokens": 2, "pos": 3, "tok0": 1}
    with pytest.raises(BudgetExceededError):    # pos != prompt length
        dec.submit_migration({**meta, "pos": 2}, good)
    with pytest.raises(BudgetExceededError):    # missing leaves
        dec.submit_migration(meta, {"k": good["k"]})
    with pytest.raises(BudgetExceededError):    # budget overflow
        dec.submit_migration({**meta, "max_new_tokens": 64}, good)


# ---------------------------------------------------------------------------
# router: affinity vs draining precedence + headroom-scaled load score
# ---------------------------------------------------------------------------

def _fake_endpoint(router, url, load, draining=False, role="",
                   failures=0):
    from tony_tpu.serve.router import Endpoint
    ep = Endpoint(url=url, draining_hint=draining, role=role)
    ep.load = dict(load)
    ep.probed_at = 1.0        # cached snapshot: no bootstrap probe RPC
    ep.failures = failures
    with router._lock:
        router._endpoints[url] = ep
    return ep


def test_router_affinity_prefers_advertiser_never_overrides_draining():
    """The deepest advertised prefix match wins the ranking, but the
    state filter runs FIRST: a draining or dead replica advertising the
    whole prompt is never picked, and decode-role replicas take no
    /v1/generate traffic at all."""
    from tony_tpu.serve.router import FleetRouter
    router = FleetRouter(dead_after_failures=2)
    prompt = list(range(24))
    hashes = chain_hashes(prompt, 4)
    base = {"queue_depth": 0, "slots_free": 2, "n_slots": 2,
            "active_slots": 0, "draining": False, "kv_page_size": 4}
    # busy but advertising the full prefix
    _fake_endpoint(router, "http://affin:1",
                   {**base, "queue_depth": 3, "slots_free": 1,
                    "prefix_hashes": hashes})
    # idle, no index
    _fake_endpoint(router, "http://idle:1", base)
    # advertises everything, but draining — excluded entirely
    _fake_endpoint(router, "http://drain:1",
                   {**base, "prefix_hashes": hashes}, draining=True)
    # advertises everything, but dead — excluded entirely
    _fake_endpoint(router, "http://dead:1",
                   {**base, "prefix_hashes": hashes}, failures=99)
    # decode-role replicas only take /v1/migrate handoffs
    _fake_endpoint(router, "http://decode:1",
                   {**base, "prefix_hashes": hashes, "role": "decode"})

    ranked = router._ranked(prompt)
    assert [ep.url for ep, _ in ranked] == ["http://affin:1",
                                            "http://idle:1"]
    assert ranked[0][1] == len(hashes)           # full-depth match
    assert ranked[1][1] == 0
    # no prompt → pure least-loaded order, same exclusions
    assert [ep.url for ep in router.candidates()] == ["http://idle:1",
                                                      "http://affin:1"]
    # a shared leading block still hits (chain prefix semantics)...
    assert router._ranked(list(range(8)) + [999] * 16)[0][1] == 2
    # ...a divergent prompt falls back least-loaded with zero depth
    cold = router._ranked([999] * 24)
    assert [ep.url for ep, d in cold] == ["http://idle:1",
                                          "http://affin:1"]
    assert all(d == 0 for _, d in cold)
    router._httpd.server_close()


def test_load_score_scales_with_kv_headroom():
    """Satellite (c): /v1/load's page-pool headroom feeds the routing
    score — equal slots_free, exhausted pool loses to healthy pool; a
    poolless replica is unscaled."""
    from tony_tpu.serve.router import _effective_slots
    assert _effective_slots({"slots_free": 4}) == 4.0
    full = _effective_slots({"slots_free": 4, "kv_pages_headroom": 8,
                             "kv_pages_total": 8})
    starved = _effective_slots({"slots_free": 4, "kv_pages_headroom": 0,
                                "kv_pages_total": 8})
    assert full == 4.0 and starved == 2.0
    assert _effective_slots({"slots_free": 4, "kv_pages_headroom": 4,
                             "kv_pages_total": 8}) == 3.0


# ---------------------------------------------------------------------------
# role-split autoscaling: per-pool SLIs + distinct arbiter book entries
# ---------------------------------------------------------------------------

def test_aggregate_slis_fold_per_pool_and_carry_itl():
    from tony_tpu.serve.autoscaler import aggregate_serving_slis
    gauges = {
        "serving:0": {"SERVING_QUEUE_DEPTH": 6, "SERVING_TTFT_P95_S": 0.9,
                      "SERVING_ITL_P50_MS": 40.0,
                      "SERVING_SLOT_OCCUPANCY_PCT": 90},
        "serving:1": {"SERVING_QUEUE_DEPTH": 1, "SERVING_TTFT_P95_S": 0.1,
                      "SERVING_ITL_P50_MS": 160.0,
                      "SERVING_SLOT_OCCUPANCY_PCT": 40},
        "serving:2": {"SERVING_QUEUE_DEPTH": 2, "SERVING_TTFT_P95_S": 0.2,
                      "SERVING_SLOT_OCCUPANCY_PCT": 50},
    }
    roles = {"serving:0": "prefill", "serving:1": "decode"}
    # serving:2 has no role → "both": counts toward EVERY pool
    pre = aggregate_serving_slis(gauges, roles=roles, role="prefill")
    assert pre["queue_depth"] == 8.0
    assert pre["ttft_p95_s"] == 0.9
    assert pre["itl_p50_ms"] == 40.0
    dec = aggregate_serving_slis(gauges, roles=roles, role="decode")
    assert dec["queue_depth"] == 3.0
    assert dec["itl_p50_ms"] == 160.0
    # whole-fleet fold (no role) sees the max ITL across pools
    assert aggregate_serving_slis(gauges)["itl_p50_ms"] == 160.0


def test_itl_signal_scales_decode_pool_up():
    """The decode pool's up-signal: inter-token latency breaching
    itl-p50-up-ms drives an UP verdict even with an empty queue and a
    healthy TTFT (the prefill-side signal)."""
    from tony_tpu.serve.autoscaler import (
        AutoscalerConfig, ReplicaAutoscaler, UP,
    )
    cfg = AutoscalerConfig(itl_p50_up_ms=100.0, queue_depth_up=0,
                           reject_rate_up_pct=0, occupancy_down_pct=0,
                           hysteresis_passes=2, cooldown_ms=0,
                           max_replicas=4)
    scaler = ReplicaAutoscaler(cfg)
    slis = {"itl_p50_ms": 150.0, "ttft_p95_s": 0.01, "queue_depth": 0,
            "occupancy_pct": 80}
    assert scaler.evaluate(slis, 2, 0.0)["action"] == "hold"   # streak 1
    verdict = scaler.evaluate(slis, 2, 1.0)
    assert verdict["action"] == UP
    assert "itl_p50" in verdict["reason"]


def test_role_split_asks_are_distinct_arbiter_book_entries(monkeypatch):
    """A prefill pool's queued ask must never shadow a decode ask: the
    two pools file under role-suffixed app_ids."""
    from tony_tpu.cluster import arbiter as arb_mod
    from tony_tpu.conf import TonyConfiguration
    from tony_tpu.serve.autoscaler import replica_ask_verdict
    seen = []

    def fake_decide(self, ask):
        seen.append(ask.app_id)
        return arb_mod.Decision(action="ADMIT")

    monkeypatch.setattr(arb_mod.Arbiter, "decide", fake_decide)
    conf = TonyConfiguration()
    for role in ("prefill", "decode", None):
        d = replica_ask_verdict(conf, "app_1", chips=0, role=role)
        assert d.action == "ADMIT"
    assert seen == ["app_1/serving-scaleup-prefill",
                    "app_1/serving-scaleup-decode",
                    "app_1/serving-scaleup"]
    assert len(set(seen)) == 3


# ---------------------------------------------------------------------------
# event surface
# ---------------------------------------------------------------------------

def test_serving_migrated_event_schema_and_renderer():
    """SERVING_MIGRATED parses through the payload registry and renders
    human-readably (the all-EventTypes renderer-coverage pin lives in
    test_logs.py; this pins the CONTENT)."""
    from tony_tpu.events.render import render_event
    from tony_tpu.events.schema import EventType, ServingMigrated
    import dataclasses
    p = dataclasses.asdict(ServingMigrated("serving", 2,
                                           "http://d:8100", count=3))
    line = render_event(EventType.SERVING_MIGRATED, p)
    assert "serving:2" in line and "http://d:8100" in line
    assert "3 requests" in line
    single = dataclasses.asdict(ServingMigrated("serving", 0,
                                                "http://d:8100"))
    assert "requests" not in render_event(EventType.SERVING_MIGRATED,
                                          single)
