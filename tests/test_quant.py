"""Weight-only int8 inference quantization (models/quant.py).

No reference analogue (the reference is an orchestrator, SURVEY §2.3);
the contracts pinned here are the rebuild's own: bounded per-channel
round-trip error, near-identical logits through the REAL prefill+decode
path, the halved-bytes bandwidth claim, and end-to-end generate()."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.generate import decode_step, generate, prefill
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.models.quant import (
    dequantize, is_qtensor, quantize, quantize_params, quantized_bytes,
)


def test_roundtrip_error_bounded_per_channel():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * \
        jnp.linspace(0.1, 10.0, 32)[None, :]   # wildly varying channels
    t = quantize(w)
    assert t["int8"].dtype == jnp.int8
    err = jnp.abs(dequantize(t, jnp.float32) - w)
    # symmetric rounding: error <= scale/2 per that channel (+ eps)
    bound = t["scale"][0] / 2 + 1e-6
    assert bool(jnp.all(err <= bound)), float((err - bound).max())


def test_stacked_layers_quantize_and_slice():
    """Stacked (L, d, f) weights keep per-(layer, channel) scales and the
    scan-sliced (d, f)/(1, f) pair still broadcasts."""
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8))
    t = quantize(w)
    assert t["scale"].shape == (3, 1, 8)
    one = {"int8": t["int8"][1], "scale": t["scale"][1]}
    np.testing.assert_allclose(dequantize(one, jnp.float32),
                               dequantize(t, jnp.float32)[1], rtol=0, atol=0)


def test_quantize_params_shape_and_bytes():
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    # matmul weights quantized, norms/embed untouched
    assert is_qtensor(qparams["layers"]["wq"])
    assert is_qtensor(qparams["output"])
    assert not is_qtensor(qparams["layers"]["attn_norm"])
    assert qparams["embed"].dtype == params["embed"].dtype
    now, full = quantized_bytes(qparams)
    assert now < 0.6 * full   # the bandwidth claim: ~half the bytes


def test_prefill_and_decode_logits_parity():
    """Quantized logits through the REAL prefill + decode_step must stay
    close to full precision (normalized rmse < 5%)."""
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 0,
                                config.vocab_size, jnp.int32)

    logits, cache = prefill(params, tokens, config, cache_len=16)
    qlogits, qcache = prefill(qparams, tokens, config, cache_len=16)
    denom = float(jnp.sqrt(jnp.mean(logits ** 2)))
    rmse = float(jnp.sqrt(jnp.mean((logits - qlogits) ** 2))) / denom
    assert rmse < 0.05, rmse

    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    d_logits, _ = decode_step(params, config, cache, tok, jnp.int32(12))
    qd_logits, _ = decode_step(qparams, config, qcache, tok, jnp.int32(12))
    denom = float(jnp.sqrt(jnp.mean(d_logits ** 2)))
    rmse = float(jnp.sqrt(jnp.mean((d_logits - qd_logits) ** 2))) / denom
    assert rmse < 0.05, rmse


def test_generate_runs_quantized_and_is_deterministic():
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                config.vocab_size, jnp.int32)
    out1 = generate(qparams, config, prompt, max_new_tokens=6)
    out2 = generate(qparams, config, prompt, max_new_tokens=6)
    assert out1.shape == (2, 6)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert bool(jnp.all((out1 >= 0) & (out1 < config.vocab_size)))


def test_quant_cache_logits_parity():
    """int8 KV-cache decode (per-row scales) through REAL prefill +
    decode_step stays close to the full-precision cache."""
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0,
                                config.vocab_size, jnp.int32)
    logits, cache = prefill(params, tokens, config, cache_len=16)
    qlogits, qcache = prefill(params, tokens, config, cache_len=16,
                              quant_cache=True)
    # prefill logits don't touch the cache: identical
    np.testing.assert_allclose(np.asarray(logits), np.asarray(qlogits),
                               rtol=0, atol=0)
    assert qcache["k"].dtype == jnp.int8
    assert qcache["k_scale"].shape == qcache["k"].shape[:-1] + (1,)

    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    d, _ = decode_step(params, config, cache, tok, jnp.int32(12))
    qd, qc2 = decode_step(params, config, qcache, tok, jnp.int32(12))
    assert qc2["k"].dtype == jnp.int8   # cache stays int8 step to step
    denom = float(jnp.sqrt(jnp.mean(d ** 2)))
    rmse = float(jnp.sqrt(jnp.mean((d - qd) ** 2))) / denom
    assert rmse < 0.05, rmse


def test_generate_quant_cache_and_composed():
    """generate(quant_cache=True) end to end, alone and composed with
    int8 weight-only params."""
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0,
                                config.vocab_size, jnp.int32)
    out = generate(params, config, prompt, max_new_tokens=6,
                   quant_cache=True)
    assert out.shape == (2, 6)
    assert bool(jnp.all((out >= 0) & (out < config.vocab_size)))
    both = generate(quantize_params(params), config, prompt,
                    max_new_tokens=6, quant_cache=True)
    assert both.shape == (2, 6)
    assert bool(jnp.all((both >= 0) & (both < config.vocab_size)))


def test_generate_quantized_tracks_full_precision():
    """Greedy decode with a REAL margin: sharpen the tiny model's logits
    by scaling the LM head so argmax is decisive, then quantized greedy
    must match full-precision greedy exactly."""
    config = get_config("tiny")
    params = llama_init(config, jax.random.PRNGKey(0))
    params = dict(params, output=params["output"] * 8.0)
    qparams = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0,
                                config.vocab_size, jnp.int32)
    full = generate(params, config, prompt, max_new_tokens=8)
    quant = generate(qparams, config, prompt, max_new_tokens=8)
    agree = float(jnp.mean((full == quant).astype(jnp.float32)))
    assert agree >= 0.75, (agree, np.asarray(full), np.asarray(quant))
