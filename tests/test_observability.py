"""Observability subsystem tests (ISSUE 4).

Covers: Prometheus exposition round-trip (label escaping, NaN/±Inf),
the TimeSeries ring buffer's decimation, the MetricsStore timeseries +
copy-semantics regression, span recorder/store bounding, the
TpuMetricsReporter drop counter + bounded close, liveliness
detection-latency numbers, the AM /metrics scrape server, the serving
frontend's content-negotiated exposition — and one full-stack e2e run
proving trace-context propagation client → AM → executor → trainer on
the local backend, with the portal serving the waterfall and
/jobs/:id/metrics.json out of the flushed history.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants as C
from tony_tpu.observability import prometheus as prom
from tony_tpu.observability.metrics import (
    REGISTRY, MetricsRegistry, TimeSeries,
)
from tony_tpu.observability.trace import Span, SpanRecorder, SpanStore

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def script(name: str) -> str:
    return os.path.join(SCRIPTS, name)


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------

def test_exposition_roundtrip_values_and_labels():
    families = [
        {"name": "tony_test_gauge", "type": "gauge", "help": "a gauge",
         "samples": [
             ({"task_type": "worker", "index": "0"}, 1.5),
             ({"task_type": "worker", "index": "1"}, 3.0),
             ({}, 42.0),
         ]},
        {"name": "tony_test_total", "type": "counter", "help": "",
         "samples": [({"status": "ok"}, 7.0)]},
    ]
    parsed = prom.parse(prom.render(families))
    assert prom.get_sample(parsed, "tony_test_gauge",
                           task_type="worker", index="0") == 1.5
    assert prom.get_sample(parsed, "tony_test_gauge", index="1") == 3.0
    assert parsed[("tony_test_gauge", ())] == 42.0
    assert prom.get_sample(parsed, "tony_test_total", status="ok") == 7.0


def test_exposition_label_escaping_roundtrip():
    ugly = 'a"b\\c\nd'
    text = prom.render([{"name": "m", "type": "gauge", "help": "",
                         "samples": [({"k": ugly}, 1.0)]}])
    parsed = prom.parse(text)
    assert parsed[("m", (("k", ugly),))] == 1.0


def test_exposition_nan_and_inf():
    text = prom.render([{"name": "m", "type": "gauge", "help": "",
                         "samples": [({"v": "nan"}, float("nan")),
                                     ({"v": "pinf"}, float("inf")),
                                     ({"v": "ninf"}, float("-inf"))]}])
    parsed = prom.parse(text)
    assert math.isnan(prom.get_sample(parsed, "m", v="nan"))
    assert prom.get_sample(parsed, "m", v="pinf") == float("inf")
    assert prom.get_sample(parsed, "m", v="ninf") == float("-inf")


def test_exposition_name_sanitization():
    assert prom.sanitize_metric_name("9bad-name!x") == "_9bad_name_x"
    assert prom.sanitize_metric_name("") == "_"
    assert prom.task_metric_name("SERVING_TTFT_P50_S") == \
        "tony_serving_ttft_p50_s"
    assert prom.task_metric_name("tony_already") == "tony_already"
    # a hostile gauge name renders into a parseable line
    text = prom.render([{"name": "1 weird{name}", "type": "gauge",
                         "help": "", "samples": [({}, 1.0)]}])
    assert prom.parse(text)  # does not raise


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        prom.parse("this is { not exposition\n")


# ---------------------------------------------------------------------------
# timeseries ring buffer + registry
# ---------------------------------------------------------------------------

def test_timeseries_bounded_with_full_run_coverage():
    ts = TimeSeries(max_points=16)
    for i in range(5000):
        ts.append(i, float(i))
    pts = ts.to_list()
    assert len(pts) <= 17                      # bounded (+ live tail)
    assert pts[0] == [0, 0.0]                  # run start survives
    assert pts[-1] == [4999, 4999.0]           # newest always present
    assert ts.stride > 1                       # it actually decimated
    assert [p[0] for p in pts] == sorted(p[0] for p in pts)


def test_timeseries_short_series_keeps_everything():
    ts = TimeSeries(max_points=64)
    ts.append(10, 1.0)
    ts.append(20, 2.0)
    assert ts.to_list() == [[10, 1.0], [20, 2.0]]


def test_timeseries_ignores_non_finite():
    ts = TimeSeries(max_points=8)
    ts.append(1, float("nan"))
    ts.append(2, float("inf"))
    assert ts.to_list() == []


def test_timeseries_decimation_under_width_1k_load():
    """ROADMAP item 3's 'verify it under load': a width-1024 gang's worth
    of MetricsStore series, each appended 8x past its cap, stays pinned
    at <= max_points per series (+ the live tail) with the run's start
    and newest sample both retained."""
    from tony_tpu.am.application_master import MetricsStore
    width, cap = 1024, 64
    store = MetricsStore(history_points=cap)
    batch = 16
    for i in range(width):
        for k in range(8 * cap // batch):
            store.update_metrics(
                {"task_type": "worker", "index": i,
                 "metrics": [{"name": "TRAIN_STEP_TIME_MS",
                              "value": float(k * batch + j)}
                             for j in range(batch)]})
    series = store.timeseries_dict()
    assert len(series) == width
    max_pts = max(len(per["TRAIN_STEP_TIME_MS"]) for per in series.values())
    assert max_pts <= cap + 1, max_pts
    # the series still covers the whole run, not just the last N minutes
    sample = series["worker:0"]["TRAIN_STEP_TIME_MS"]
    assert sample[0][1] == 0.0
    assert sample[-1][1] == float(8 * cap - 1)


def test_span_store_bounded_under_width_1k_load():
    """SpanStore at width-1k: 1024 tasks x 16 spans against a 512 cap —
    held count pinned at the cap, every overflow counted, never grown."""
    cap = 512
    store = SpanStore(max_spans=cap)
    for i in range(1024):
        store.add([{"name": "user_process", "span_id": f"s{i}-{j}",
                    "trace_id": "t", "task_id": f"worker:{i}",
                    "start_ms": j, "end_ms": j + 1, "status": "OK"}
                   for j in range(16)])
    assert len(store) == cap
    assert store.dropped == 1024 * 16 - cap


def test_registry_families_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("tony_x_total", status="ok").inc()
    reg.counter("tony_x_total", status="ok").inc(2)
    reg.gauge("tony_g").set(5.5)
    reg.summary("tony_lat_seconds", method="m").observe(0.2)
    reg.summary("tony_lat_seconds", method="m").observe(0.4)
    parsed = prom.parse(prom.render(reg.families()))
    assert prom.get_sample(parsed, "tony_x_total", status="ok") == 3.0
    assert prom.get_sample(parsed, "tony_g") == 5.5
    assert prom.get_sample(parsed, "tony_lat_seconds_count",
                           method="m") == 2.0
    assert prom.get_sample(parsed, "tony_lat_seconds_sum",
                           method="m") == pytest.approx(0.6)
    assert prom.get_sample(parsed, "tony_lat_seconds_max",
                           method="m") == pytest.approx(0.4)


def test_summary_quantiles_bounded_and_exposed():
    """ISSUE 7 satellite: Summary tracks p50/p95/p99 through the
    fixed-width sketch (never a sample list) and exposes them as
    quantile-labeled samples that round-trip the exposition."""
    reg = MetricsRegistry()
    s = reg.summary("tony_rt_seconds", method="m")
    for i in range(1, 1001):
        s.observe(i / 1000.0)           # 1ms .. 1s, uniform
    assert s.sketch.cells() == s.SKETCH_BUCKETS + 2   # memory is fixed
    assert s.quantile(0.5) == pytest.approx(0.5, rel=0.35)
    assert s.quantile(0.99) == pytest.approx(0.99, rel=0.35)
    parsed = prom.parse(prom.render(reg.families()))
    p50 = prom.get_sample(parsed, "tony_rt_seconds",
                          method="m", quantile="0.5")
    p99 = prom.get_sample(parsed, "tony_rt_seconds",
                          method="m", quantile="0.99")
    assert p50 == pytest.approx(s.quantile(0.5))
    assert p99 == pytest.approx(s.quantile(0.99))
    assert p50 < p99
    # quantiles sit inside the observed range
    assert 0.001 <= p50 <= 1.0 and 0.001 <= p99 <= 1.0


# ---------------------------------------------------------------------------
# MetricsStore: copy regression (satellite 1) + timeseries + exposition
# ---------------------------------------------------------------------------

def _store(**kw):
    from tony_tpu.am.application_master import MetricsStore
    return MetricsStore(**kw)


def test_get_metrics_returns_copies_not_aliases():
    """Regression: the returned list used to share the stored dicts, so a
    caller mutating a metric corrupted the store."""
    store = _store()
    store.update_metrics({"task_type": "worker", "index": 0,
                          "metrics": [{"name": "G", "value": 1.0}]})
    out = store.get_metrics("worker", 0)
    out[0]["value"] = 999.0
    out[0]["name"] = "EVIL"
    again = store.get_metrics("worker", 0)
    assert again == [{"name": "G", "value": 1.0}]


def test_metrics_store_accumulates_timeseries():
    store = _store(history_points=8)
    for v in (1.0, 2.0, 3.0):
        store.update_metrics({"task_type": "worker", "index": 0,
                              "metrics": [{"name": "STEP_TIME",
                                           "value": v}]})
    hist = store.get_history("worker", 0)
    assert [p[1] for p in hist["STEP_TIME"]] == [1.0, 2.0, 3.0]
    assert store.timeseries_dict()["worker:0"]["STEP_TIME"] == \
        hist["STEP_TIME"]
    # the merged latest-gauge view is unchanged by the timeseries layer
    assert store.get_metrics("worker", 0) == [{"name": "STEP_TIME",
                                               "value": 3.0}]


def test_metrics_store_prometheus_families_with_attempt_label():
    store = _store()
    store.update_metrics({"task_type": "worker", "index": 1, "attempt": 2,
                          "metrics": [{"name": "TPU_UTILIZATION",
                                       "value": 88.0}]})
    parsed = prom.parse(prom.render(store.prometheus_families("app_7")))
    assert prom.get_sample(parsed, "tony_tpu_utilization", app_id="app_7",
                           task_type="worker", index="1", attempt="2") \
        == 88.0


def test_span_only_pushes_do_not_feed_wedge_detection():
    """Span piggyback traffic (metrics=[]) is trace transport, not a
    metrics interval — it must not count as a missing-duty sample for
    the heartbeating-but-idle detector."""
    store = _store(low_util_intervals=2)
    store.update_metrics({"task_type": "worker", "index": 0,
                          "metrics": [{"name": "TPU_UTILIZATION",
                                       "value": 60.0}]})
    for _ in range(5):   # busy phase emitting only spans
        store.update_metrics({"task_type": "worker", "index": 0,
                              "metrics": [],
                              "spans": [{"name": "checkpoint_save",
                                         "start_ms": 1, "end_ms": 2}]})
    assert store.low_utilization_tasks() == []


def test_metrics_store_routes_spans_to_sink():
    store = _store()
    got: list[dict] = []
    store.span_sink = got.extend
    store.update_metrics({"task_type": "worker", "index": 0, "metrics": [],
                          "spans": [{"name": "s", "start_ms": 1,
                                     "end_ms": 2}]})
    assert [s["name"] for s in got] == ["s"]


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_recorder_parentage_and_env_propagation():
    rec = SpanRecorder(trace_id="app_1", task_id="worker:0", attempt=1,
                       parent_id="rootspan")
    outer = rec.start("user_process")
    env = rec.env(outer)
    assert env == {C.TONY_TRACE_ID: "app_1",
                   C.TONY_PARENT_SPAN: outer.span_id}
    child_rec = SpanRecorder.from_env(env, task_id="worker:0")
    inner = child_rec.start("trainer_setup")
    child_rec.end(inner)
    rec.end(outer, "ERROR", attrs={"exit_code": 1})
    [inner_d] = child_rec.drain()
    assert inner_d["parent_id"] == outer.span_id
    assert inner_d["trace_id"] == "app_1"
    [outer_d] = rec.drain()
    assert outer_d["parent_id"] == "rootspan"
    assert outer_d["status"] == "ERROR"
    assert outer_d["attrs"]["exit_code"] == 1
    assert outer_d["end_ms"] >= outer_d["start_ms"]
    # ending twice is a no-op, not a new record
    rec.end(outer)
    assert rec.drain() == []


def test_span_recorder_without_context_is_local_only():
    rec = SpanRecorder.from_env({})
    assert not rec.enabled
    assert rec.env() == {}
    with rec.span("anything"):
        pass
    assert len(rec.drain()) == 1   # still records locally


def test_span_store_is_bounded():
    store = SpanStore(max_spans=3)
    store.add([Span(name=f"s{i}", start_ms=i, end_ms=i + 1).to_dict()
               for i in range(5)])
    assert len(store) == 3
    assert store.dropped == 2
    assert [s["name"] for s in store.to_list()] == ["s0", "s1", "s2"]
    # junk entries are ignored, not stored
    store2 = SpanStore(max_spans=10)
    store2.add([{"no_name": True}, "not-a-dict", None])
    assert len(store2) == 0


def test_span_dict_roundtrip():
    s = Span(name="x", trace_id="t", parent_id="p", task_id="worker:0",
             attempt=2, start_ms=10, end_ms=30, status="OK",
             attrs={"k": "v"})
    assert Span.from_dict(s.to_dict()).to_dict() == s.to_dict()
    assert s.duration_ms == 20


# ---------------------------------------------------------------------------
# TpuMetricsReporter drops + bounded close (satellite 2)
# ---------------------------------------------------------------------------

def _reporter():
    from tony_tpu.train.metrics import TpuMetricsReporter
    return TpuMetricsReporter(env={C.AM_HOST: "127.0.0.1", C.AM_PORT: "1",
                                   C.JOB_NAME: "worker", C.TASK_INDEX: "0",
                                   C.TASK_ATTEMPT: "0"})


def test_reporter_counts_drops_and_close_is_bounded():
    reporter = _reporter()
    release = threading.Event()
    started = threading.Event()

    def wedged_push(payload):
        started.set()
        release.wait(10)

    reporter._push = wedged_push
    before = REGISTRY.counter("tony_metrics_push_dropped_total").value
    # worker takes the first payload and wedges; maxsize-2 queue fills
    # with the next two; everything after that is a counted drop
    for i in range(6):
        reporter._enqueue({"metrics": [{"name": "G", "value": float(i)}]})
    assert started.wait(5)
    deadline = time.monotonic() + 5
    while reporter.dropped == 0 and time.monotonic() < deadline:
        reporter._enqueue({"metrics": [{"name": "G", "value": 0.0}]})
        time.sleep(0.01)
    assert reporter.dropped >= 1
    assert REGISTRY.counter("tony_metrics_push_dropped_total").value \
        > before
    # queue.Full path of close(): the wedged worker still gets a BOUNDED
    # join — close must return promptly, not hang and not skip the join
    t0 = time.monotonic()
    reporter.close(timeout=0.3)
    assert time.monotonic() - t0 < 3.0
    assert reporter._worker is None
    release.set()


def test_reporter_clean_close_joins_worker():
    reporter = _reporter()
    reporter._push = lambda payload: None
    reporter._enqueue({"metrics": [{"name": "G", "value": 1.0}]})
    worker = reporter._worker
    reporter.close(timeout=5)
    assert not worker.is_alive()


def test_reporter_spans_ride_the_push_payload():
    reporter = _reporter()
    pushed: list[dict] = []
    reporter._push = pushed.append
    reporter.report_spans([{"name": "s", "start_ms": 1, "end_ms": 2}])
    deadline = time.monotonic() + 5
    while not pushed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pushed and pushed[0]["spans"][0]["name"] == "s"
    reporter.close(timeout=5)


# ---------------------------------------------------------------------------
# liveliness: heartbeat lag + detection latency (satellite 3)
# ---------------------------------------------------------------------------

def test_liveliness_records_ping_lag_and_detection_latency():
    from tony_tpu.am.liveliness import LivelinessMonitor

    expired = threading.Event()
    monitor = LivelinessMonitor(hb_interval_ms=50, max_missed=3,
                                on_expired=lambda tid, att: expired.set())
    monitor.start()
    try:
        monitor.register("worker:0", attempt=0)
        time.sleep(0.12)
        assert monitor.ping("worker:0")
        # the gap ran ~70ms past the 50ms cadence
        assert monitor.last_ping_lag_sec == pytest.approx(0.07, abs=0.05)
        # silence → expiry; detection latency >= the 150ms window
        assert expired.wait(5), "expiry never fired"
        assert monitor.last_detection_latency_sec >= 0.15
        # and it lands in the registry for the /metrics scrape
        parsed = prom.parse(prom.render(REGISTRY.families()))
        assert prom.get_sample(
            parsed, "tony_liveliness_detection_latency_seconds_count") >= 1
        assert prom.get_sample(
            parsed, "tony_heartbeat_lag_seconds_count") >= 1
    finally:
        monitor.stop()


# ---------------------------------------------------------------------------
# scrape endpoints
# ---------------------------------------------------------------------------

def test_metrics_http_server_serves_valid_exposition():
    from tony_tpu.observability.http import MetricsHTTPServer

    store = _store()
    store.update_metrics({"task_type": "worker", "index": 0, "attempt": 0,
                          "metrics": [{"name": "TOKENS_PER_SEC",
                                       "value": 123.0}]})
    server = MetricsHTTPServer(
        lambda: prom.render(store.prometheus_families("app_x")
                            + REGISTRY.families()),
        port=0, host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics",
                timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            parsed = prom.parse(resp.read().decode("utf-8"))
        assert prom.get_sample(parsed, "tony_tokens_per_sec",
                               app_id="app_x", task_type="worker") == 123.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10) as _:
            pytest.fail("404 expected")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        server.stop()


class _FakeEngine:
    """Snapshot-only stand-in — frontend GETs never touch the compute
    plane, so the exposition path is testable without a model."""
    n_slots = 2
    token_budget = 32
    queue_depth = 8
    temperature = 0.0

    def snapshot(self):
        return {"tokens_per_sec": 10.0, "slot_occupancy_pct": 50.0,
                "queue_depth": 1, "queue_depth_max": 3,
                "ttft_p50_s": None, "token_budget": 32}


def test_serving_frontend_content_negotiation():
    from tony_tpu.serve.frontend import ServeFrontend

    frontend = ServeFrontend(_FakeEngine(), port=0, host="127.0.0.1")
    frontend.start()
    base = f"http://127.0.0.1:{frontend.port}"
    try:
        # default stays JSON (existing tooling contract)
        with urllib.request.urlopen(f"{base}/v1/metrics", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["tokens_per_sec"] == 10.0
        # a Prometheus scraper's Accept header gets text exposition
        req = urllib.request.Request(
            f"{base}/v1/metrics",
            headers={"Accept": "application/openmetrics-text;q=0.9,"
                               "text/plain;version=0.0.4"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            parsed = prom.parse(r.read().decode())
        assert prom.get_sample(parsed,
                               "tony_serving_tokens_per_sec") == 10.0
        assert prom.get_sample(parsed,
                               "tony_serving_slot_occupancy_pct") == 50.0
        # no-traffic gauges are NaN, not absent
        assert math.isnan(prom.get_sample(parsed,
                                          "tony_serving_ttft_p50_s"))
        # ?format=prometheus forces it; bare /metrics always exposition
        for url in (f"{base}/v1/metrics?format=prometheus",
                    f"{base}/metrics"):
            with urllib.request.urlopen(url, timeout=10) as r:
                prom.parse(r.read().decode())   # valid exposition
    finally:
        frontend.stop()


# ---------------------------------------------------------------------------
# docs drift (satellite 6): new keys documented
# ---------------------------------------------------------------------------

def test_new_observability_keys_are_documented():
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "configuration.md"), encoding="utf-8").read()
    for key in ("tony.metrics.history-points", "tony.metrics.port",
                "tony.trace.enabled", "tony.trace.max-spans"):
        assert key in doc, f"{key} missing from docs/configuration.md"


# ---------------------------------------------------------------------------
# e2e: trace context propagates client → AM → executor → trainer, and the
# portal serves the waterfall + metrics.json from the flushed history
# ---------------------------------------------------------------------------

def _fast_conf(tmp_path, **overrides):
    from tony_tpu.conf import TonyConfiguration, keys as K
    conf = TonyConfiguration()
    conf.set(K.CLUSTER_WORKDIR, str(tmp_path), "test")
    conf.set(K.AM_MONITOR_INTERVAL_MS, 100, "test")
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 200, "test")
    conf.set(K.TASK_METRICS_INTERVAL_MS, 500, "test")
    conf.set(K.TASK_REGISTRATION_TIMEOUT_SEC, 60, "test")
    conf.set(K.AM_STOP_POLL_TIMEOUT_MS, 2000, "test")
    for k, v in overrides.items():
        conf.set(k, v, "test")
    return conf


def test_e2e_trace_metrics_and_portal(tmp_path):
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.events.history import read_metrics_file, read_spans_file
    from tony_tpu.portal.cache import PortalCache
    from tony_tpu.portal.server import PortalServer

    hist_inter = str(tmp_path / "hist-int")
    conf = _fast_conf(tmp_path, **{"tony.history.intermediate": hist_inter})
    client = TonyClient(conf)
    client.init(["--executes", script("emit_observability.py"),
                 "--conf", "tony.worker.instances=1"])
    result = {}

    def _run():
        result["ok"] = client.run()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    # while the worker sleeps, scrape the LIVE AM /metrics endpoint
    am_scrape = None
    port_file = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and am_scrape is None:
        if port_file is None and client.app_dir:
            candidate = os.path.join(client.app_dir,
                                     C.AM_METRICS_PORT_FILE)
            if os.path.exists(candidate):
                port_file = candidate
        if port_file is not None:
            try:
                with open(port_file) as f:
                    port = int(f.read().strip())
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=5) as resp:
                    am_scrape = resp.read().decode("utf-8")
            except (OSError, ValueError):
                pass
        time.sleep(0.05)
    t.join(timeout=120)
    assert result.get("ok") is True, client.final_message
    # the live scrape happened and was valid exposition
    assert am_scrape is not None, "never reached the AM /metrics endpoint"
    prom.parse(am_scrape)

    history_dir = os.path.join(hist_inter, client.app_id)
    # --- spans flushed next to the event log, full parent chain ---------
    spans = read_spans_file(history_dir)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], s)
    for name in ("application", "client_submit", "rendezvous",
                 "task:worker:0", "executor_localization",
                 "rendezvous_wait", "user_process", "trainer_setup"):
        assert name in by_name, (name, sorted(by_name))
    assert all(s["trace_id"] == client.app_id for s in spans), spans
    root = by_name["application"]
    task = by_name["task:worker:0"]
    proc = by_name["user_process"]
    trainer = by_name["trainer_setup"]
    assert task["parent_id"] == root["span_id"]
    assert proc["parent_id"] == task["span_id"]
    assert trainer["parent_id"] == proc["span_id"]
    assert by_name["client_submit"]["start_ms"] <= root["start_ms"]
    assert by_name["rendezvous"]["status"] == "OK"
    assert proc["status"] == "OK" and proc["end_ms"] > proc["start_ms"]
    assert task["task_id"] == "worker:0"

    # --- metrics.json: >= 2 points per pushed gauge ---------------------
    series = read_metrics_file(history_dir)
    points = series["worker:0"]["E2E_TEST_GAUGE"]
    assert len(points) >= 2
    assert [p[1] for p in points[:2]] == [1.0, 2.0]

    # --- portal: waterfall on the job page + metrics.json route ---------
    server = PortalServer(PortalCache(hist_inter, str(tmp_path / "fin")),
                          port=0, host="127.0.0.1")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(
                f"{base}/jobs/{client.app_id}/metrics.json",
                timeout=10) as resp:
            served = json.loads(resp.read())
        assert len(served["worker:0"]["E2E_TEST_GAUGE"]) >= 2
        with urllib.request.urlopen(f"{base}/jobs/{client.app_id}",
                                    timeout=10) as resp:
            page = resp.read().decode("utf-8")
        assert "Lifecycle waterfall" in page
        assert "trainer_setup" in page and "rendezvous" in page
        assert "spanbar" in page
        with urllib.request.urlopen(
                f"{base}/api/jobs/{client.app_id}/spans",
                timeout=10) as resp:
            api_spans = json.loads(resp.read())
        assert {s["name"] for s in api_spans} >= {"application",
                                                  "user_process"}
    finally:
        server.stop()
