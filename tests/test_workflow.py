"""Workflow adapter tests (reference model: tony-azkaban TestTonyJob-style
prop→conf mapping plus an end-to-end run on the local backend)."""

import json
import os

from tony_tpu.workflow import TonyWorkflowJob

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


def test_tony_props_pass_through_and_specials_become_argv(tmp_path):
    job = TonyWorkflowJob({
        "tony.worker.instances": "2",
        "tony.am.memory": "1g",
        "type": "tony",                      # engine-internal, dropped
        "executes": "python train.py",
        "task_params": "--epochs 1",
        "src_dir": "/src",
    }, working_dir=str(tmp_path))
    assert job.tony_conf_entries() == {
        "tony.worker.instances": "2", "tony.am.memory": "1g"}
    argv = job.build_argv()
    conf_path = os.path.join(str(tmp_path), "tony.json")
    assert argv[:2] == ["--conf_file", conf_path]
    with open(conf_path) as f:
        assert json.load(f)["tony.worker.instances"] == "2"
    assert argv[argv.index("--executes") + 1] == "python train.py"
    assert argv[argv.index("--task_params") + 1] == "--epochs 1"
    assert argv[argv.index("--src_dir") + 1] == "/src"


def test_workflow_job_runs_end_to_end(tmp_path):
    workdir = tmp_path / "wd"
    job = TonyWorkflowJob({
        "tony.worker.instances": "1",
        "tony.cluster.workdir": str(tmp_path / "cluster"),
        "tony.task.heartbeat-interval-ms": "200",
        "tony.am.monitor-interval-ms": "200",
        "tony.am.stop-poll-timeout-ms": "2000",
        "executes": os.path.join(SCRIPTS, "exit_0.py"),
    }, working_dir=str(workdir))
    assert job.run() == 0
    assert job.client.final_status == "SUCCEEDED"


def test_workflow_job_propagates_failure(tmp_path):
    job = TonyWorkflowJob({
        "tony.worker.instances": "1",
        "tony.cluster.workdir": str(tmp_path / "cluster"),
        "tony.task.heartbeat-interval-ms": "200",
        "tony.am.monitor-interval-ms": "200",
        "tony.am.stop-poll-timeout-ms": "2000",
        "executes": os.path.join(SCRIPTS, "exit_1.py"),
    }, working_dir=str(tmp_path / "wd"))
    assert job.run() == 1
    assert job.client.final_status == "FAILED"
