"""MoE serving: KV-cache decode for the expert family (models/generate.py
_mlp dispatch).

The oracle is the same one test_generate.py uses for the dense family:
greedy decode must equal argmaxing the full training forward re-run on
the growing sequence. For MoE that identity only holds when no expert
queue overflows — each call routes over its own tokens, so a decode
step's queues start empty while the full forward fills them across the
sequence. capacity_factor = n_experts / top_k guarantees no drops in
either path (see _mlp's docstring), which is also the recommended
inference setting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.generate import generate
from tony_tpu.models.llama import get_config, llama_init
from tony_tpu.models.moe import get_moe_config, moe_forward, moe_init

# no-drop capacity: capacity >= T*k/E for any routing
CFG = get_moe_config("moe_tiny", capacity_factor=4 / 2)
PARAMS = moe_init(CFG, jax.random.PRNGKey(0))


def _prompt(key, b=2, p=8):
    return jax.random.randint(jax.random.PRNGKey(key), (b, p), 0,
                              CFG.vocab_size, jnp.int32)


def test_moe_greedy_decode_matches_forward_rerun():
    prompt = _prompt(1)
    n = 6
    got = generate(PARAMS, CFG, prompt, max_new_tokens=n)
    # oracle: grow the sequence one token at a time through the full
    # training forward
    seq = prompt
    want = []
    for _ in range(n):
        logits, _aux = moe_forward(PARAMS, seq, CFG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(jnp.stack(want, axis=1)),
                                  np.asarray(got))


def test_moe_decode_int8_and_quant_cache_run():
    """int8 weights (attention + head + expert banks) and the int8 KV
    cache both run for MoE; logits stay close through real prefill."""
    from tony_tpu.models.generate import prefill
    from tony_tpu.models.quant import is_qtensor, quantize_params

    qparams = quantize_params(PARAMS)
    assert is_qtensor(qparams["layers"]["we_gate"])
    assert not is_qtensor(qparams["layers"]["router"])
    prompt = _prompt(2)
    logits, _ = prefill(PARAMS, prompt, CFG, cache_len=16)
    qlogits, _ = prefill(qparams, prompt, CFG, cache_len=16)
    denom = float(jnp.sqrt(jnp.mean(logits ** 2)))
    rmse = float(jnp.sqrt(jnp.mean((logits - qlogits) ** 2))) / denom
    assert rmse < 0.05, rmse
    out = generate(qparams, CFG, prompt, max_new_tokens=5,
                   quant_cache=True)
    assert out.shape == (2, 5)
    assert bool(jnp.all((out >= 0) & (out < CFG.vocab_size)))


def test_moe_speculative_lossless():
    """Speculative decode with a dense-Llama draft over a MoE target:
    the lossless identity holds across families (shared vocab)."""
    from tony_tpu.models.speculative import speculative_generate

    draft_cfg = get_config("tiny")          # vocab 256 == moe_tiny's
    draft = llama_init(draft_cfg, jax.random.PRNGKey(5))
    prompt = _prompt(3)
    want = generate(PARAMS, CFG, prompt, max_new_tokens=8)
    got = speculative_generate(PARAMS, draft, CFG, draft_cfg, prompt,
                               max_new_tokens=8, gamma=3)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    # below no-drop capacity the identity cannot hold (window vs
    # single-token routing drops different tokens) — refused loudly
    lossy_cfg = get_moe_config("moe_tiny", capacity_factor=1.0)
    lossy = moe_init(lossy_cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="no-drop capacity"):
        speculative_generate(lossy, draft, lossy_cfg, draft_cfg, prompt,
                             max_new_tokens=4, gamma=2)
