"""The bench measurement contract (VERDICT r3 weak #2): the driver keeps
only a ~2 KB tail of stdout and parses the final line from it, so that
line must be ONE compact JSON object. BENCH_r03 arrived as a 4 KB line
(embedded stack dumps) and parsed as null."""

import importlib.util
import json
import os
import sys

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_bench_paths(tmp_path, monkeypatch):
    """EVERY snapshot path bench can write rides through these module
    globals; redirecting them wholesale means no test can ever leak a
    fabricated measurement into the real tools/ evidence directory
    (r5: a 70.0 'partial' from this file briefly landed there)."""
    tools = tmp_path / "tools"
    tools.mkdir()
    monkeypatch.setattr(bench, "_TOOLS_DIR", str(tools))
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH",
                        str(tools / "last_good_bench.json"))
    monkeypatch.setattr(bench, "_DIAG_LOG_PATH",
                        str(tools / "bench_diag.log"))
    monkeypatch.setattr(bench, "_HEAD_PARTIAL_AUTO_PATH",
                        str(tools / "bench_head_partial_auto.json"))
    monkeypatch.setattr(bench, "_HISTORY_PATH",
                        str(tools / "bench_history.jsonl"))
    monkeypatch.setattr(bench, "_commit_stamp", lambda: "testhead")
    yield tools


def test_compact_is_single_bounded_line():
    s = bench._compact("a\nb\r\n  c  \n" + "x" * 500, 40)
    assert "\n" not in s and len(s) <= 40
    assert bench._compact("short", 100) == "short"


def test_emit_line_is_bounded_and_parseable(capsys):
    result = {
        "metric": bench.METRIC, "value": 0.0, "unit": "%MFU",
        "vs_baseline": 0.0,
        "tpu_error": "e" * 2000,
        "cpu_error": "c" * 2000,
        "last_good_tpu_measurement": {"value": 68.08, "pad": "p" * 2000},
        "am_startup_latency": {"runs": 3, "pad": "q" * 2000},
        "error": "z" * 2000,
    }
    bench._emit(result)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(line) <= 1500, len(line)
    parsed = json.loads(line)
    # the headline fields survive every truncation
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in parsed, key
    # dropped fields are recorded
    assert "truncated" in parsed


def test_emit_small_result_untouched(capsys):
    result = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
              "vs_baseline": 1.702}
    bench._emit(result)
    line = capsys.readouterr().out.strip()
    assert json.loads(line) == result


def test_record_last_good_partial_never_shadows_complete(tmp_path,
                                                         monkeypatch):
    """r5 regression: a deadline-killed (partial) or degraded-kernel
    measurement overwrote the clean 68.08 record."""
    path = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", str(path))
    complete = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
                "device": "TPU v5 lite"}
    bench._record_last_good(dict(complete))
    assert bench._load_last_good()["value"] == 68.08

    # partial must NOT overwrite a complete record — even a faster one
    bench._record_last_good({"metric": bench.METRIC, "value": 70.0,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 164s"})
    assert bench._load_last_good()["value"] == 68.08
    assert "partial" not in bench._load_last_good()

    # a new complete record DOES overwrite
    bench._record_last_good({"metric": bench.METRIC, "value": 69.5,
                             "unit": "%MFU", "device": "TPU v5 lite"})
    assert bench._load_last_good()["value"] == 69.5

    # cpu-device results are never recorded
    bench._record_last_good({"metric": bench.METRIC, "value": 99.0,
                             "unit": "%MFU", "device": "cpu"})
    assert bench._load_last_good()["value"] == 69.5


def test_record_last_good_partial_upgrades_partial(tmp_path, monkeypatch):
    """Partials may replace partials (a better one is strictly more
    evidence) but the 'partial' label must survive into the compact
    embed so the driver record never presents one as complete."""
    path = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", str(path))
    bench._record_last_good({"metric": bench.METRIC, "value": 50.0,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 100s"})
    bench._record_last_good({"metric": bench.METRIC, "value": 58.5,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 164s"})
    last = bench._load_last_good()
    assert last["value"] == 58.5
    assert bench._compact_last_good(last)["partial"] \
        == "timed out after 164s"


def test_head_partial_recency_gate(_isolated_bench_paths):
    """Only snapshots written in the last 48h qualify as at-HEAD
    evidence; the newest fresh one wins by mtime, not filename."""
    tools = _isolated_bench_paths
    stale = tools / "bench_head_partial_r5.json"
    stale.write_text(json.dumps({"value": 11.1, "commit": "old"}))
    os.utime(stale, (0, 0))   # epoch: far past the 48h window
    assert bench._head_partial() is None

    # a fresh snapshot qualifies; r10 vs r5 must sort by mtime not name
    fresh = tools / "bench_head_partial_r10.json"
    fresh.write_text(json.dumps({"value": 58.53, "commit": "3bc892f",
                                 "partial": "contended", "extra": "x"}))
    got = bench._head_partial()
    assert got["value"] == 58.53 and got["commit"] == "3bc892f"
    assert "extra" not in got


def test_partial_auto_persists_to_head_partial(_isolated_bench_paths):
    """A deadline-truncated on-chip measurement is live at-HEAD evidence:
    _record_last_good must side-channel it to bench_head_partial_auto.json
    (without letting it shadow the complete last-good); a lower fresh
    partial from the SAME commit must not replace a higher one, but after
    the code changes the fresh measurement always wins."""
    tools = _isolated_bench_paths
    complete = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
                "device": "TPU v5 lite"}
    bench._record_last_good(dict(complete))

    partial = {"metric": bench.METRIC, "value": 58.53, "unit": "%MFU",
               "device": "TPU v5 lite", "batch_tokens": 32768,
               "partial": "timed out after 164s"}
    bench._record_last_good(dict(partial))
    # last-good untouched, head-partial written with stamps
    assert bench._load_last_good()["value"] == 68.08
    auto = json.loads((tools / "bench_head_partial_auto.json").read_text())
    assert auto["value"] == 58.53 and auto["partial"]
    assert auto["measured_at"] and auto["commit"] == "testhead"
    assert bench._head_partial()["value"] == 58.53

    # a LOWER fresh partial from the same commit must not replace it
    bench._record_last_good({"metric": bench.METRIC, "value": 30.0,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 60s"})
    assert bench._head_partial()["value"] == 58.53

    # a higher partial upgrades it
    bench._record_last_good({"metric": bench.METRIC, "value": 61.2,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 200s",
                             "kernel_fallback": "blockwise"})
    got = bench._head_partial()
    # the degraded-kernel marker must survive persist AND read-back
    assert got["value"] == 61.2 and got["kernel_fallback"] == "blockwise"

    # after a code change (different commit), a lower fresh partial WINS:
    # stale evidence must not masquerade as at-HEAD
    bench._commit_stamp = lambda: "newhead"
    bench._record_last_good({"metric": bench.METRIC, "value": 44.0,
                             "unit": "%MFU", "device": "TPU v5 lite",
                             "partial": "timed out after 90s"})
    assert bench._head_partial()["value"] == 44.0

    # cpu-device partials never persist
    bench._record_last_good({"metric": bench.METRIC, "value": 99.0,
                             "unit": "%MFU", "device": "cpu",
                             "partial": "x"})
    assert bench._head_partial()["value"] == 44.0


def test_input_stall_field_from_prefetch_feed():
    """The overlapped-input contract: the bench's timed region must pull
    its batches through the prefetch path, and the stall helper turns its
    accounting into the headline `input_stall_ms_per_step` field."""
    from tony_tpu.train.data import PrefetchIterator

    feed = PrefetchIterator(bench._lm_feed(64, 2, 8), depth=2,
                            transfer=lambda b: b)
    try:
        for _ in range(2):        # warmup pulls, outside the timed region
            next(feed)
        snap = feed.stall_snapshot()
        for _ in range(3):
            batch = next(feed)
        assert set(batch) == {"inputs", "targets"}
        assert batch["inputs"].shape == (2, 8)
        stall = bench._input_stall_ms_per_step(feed, snap, 3)
        assert stall >= 0.0
    finally:
        feed.close()


def test_input_stall_fails_loudly_when_prefetch_bypassed():
    """A plain iterator silently replacing the prefetch path must raise,
    not report an MFU that hides input serialization."""
    with pytest.raises(TypeError, match="prefetch"):
        bench._input_stall_ms_per_step(iter([{"inputs": None}]), (0.0, 0),
                                       1)
    # a feed that exists but starved/was not consumed also fails
    from tony_tpu.train.data import PrefetchIterator

    feed = PrefetchIterator(bench._lm_feed(64, 2, 8), depth=1,
                            transfer=lambda b: b)
    try:
        with pytest.raises(ValueError, match="bypassed or starved"):
            bench._input_stall_ms_per_step(feed, feed.stall_snapshot(), 3)
    finally:
        feed.close()


def test_emit_preserves_input_stall_field(capsys):
    """input_stall_ms_per_step is a headline field: it must survive
    _emit's truncation ladder (it is not in drop_order) and ride into the
    head-partial snapshot keep-list."""
    result = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
              "vs_baseline": 1.702, "input_stall_ms_per_step": 0.41,
              "prefetch_depth": 2,
              "tpu_error": "e" * 2000, "error": "z" * 2000}
    bench._emit(result)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["input_stall_ms_per_step"] == 0.41
    assert parsed["prefetch_depth"] == 2


def test_head_partial_snapshot_keeps_input_stall(_isolated_bench_paths):
    bench._record_last_good({
        "metric": bench.METRIC, "value": 58.53, "unit": "%MFU",
        "device": "TPU v5 lite", "input_stall_ms_per_step": 1.2,
        "partial": "timed out after 164s"})
    auto = json.loads(
        (_isolated_bench_paths / "bench_head_partial_auto.json")
        .read_text())
    assert auto["input_stall_ms_per_step"] == 1.2


def test_compact_last_good_keeps_headline_only():
    last = {"metric": "m", "value": 68.08, "unit": "%MFU",
            "commit": "abc", "measured_at": "t", "step_time_s": 1.0,
            "tokens_per_sec_per_chip": 15897.0,
            "llama3_8b_layer_step_ms": 63.08, "generate_batch": 8}
    out = bench._compact_last_good(last)
    assert out["value"] == 68.08 and out["commit"] == "abc"
    assert "llama3_8b_layer_step_ms" not in out
    assert len(json.dumps(out)) < 300


def test_history_append_and_regression_verdict(_isolated_bench_paths,
                                               capsys):
    """Self-defending bench: every _emit appends a commit-stamped line
    to bench_history.jsonl, and bench_compare flags a >2% drop vs the
    best same-backend baseline (value<=0 fallback markers are skipped
    both as baseline and as the judged entry)."""
    from tools.bench_compare import compare, load_history
    good = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
            "vs_baseline": 1.702, "device": "TPU v5 lite"}
    wedged = {"metric": bench.METRIC, "value": 0.0, "unit": "%MFU",
              "vs_baseline": 0.0, "backend": "tpu"}
    bad = {"metric": bench.METRIC, "value": 60.0, "unit": "%MFU",
           "vs_baseline": 1.5, "device": "TPU v5 lite"}
    for r in (good, wedged, bad):
        bench._emit(r)
    capsys.readouterr()
    entries = load_history(str(_isolated_bench_paths
                               / "bench_history.jsonl"))
    assert len(entries) == 3
    assert all(e["commit"] == "testhead" for e in entries)
    verdicts = compare(entries, threshold_pct=2.0)
    assert len(verdicts) == 1          # one (metric, backend) group
    v = verdicts[0]
    assert v["backend"] == "tpu" and v["regression"] is True
    assert v["baseline"] == 68.08 and v["value"] == 60.0
    # within threshold → no regression
    ok = compare([good, dict(good, value=67.5)], threshold_pct=2.0)
    assert ok[0]["regression"] is False
    # lower-is-better units judge in the other direction
    lat = [{"metric": "p99", "value": 1.0, "unit": "s", "backend": "cpu"},
           {"metric": "p99", "value": 1.5, "unit": "s", "backend": "cpu"}]
    assert compare(lat, threshold_pct=2.0)[0]["regression"] is True
    # bytes (the control-plane spec fan-out gate) are lower-is-better
    # too: a chatty regression — spec bytes creeping back up — must fail
    fanout = [{"metric": "control_plane_spec_bytes", "value": 1.0e6,
               "unit": "bytes", "backend": "cpu"},
              {"metric": "control_plane_spec_bytes", "value": 1.2e6,
               "unit": "bytes", "backend": "cpu"}]
    assert compare(fanout, threshold_pct=2.0)[0]["regression"] is True
    assert compare(list(reversed(fanout)),
                   threshold_pct=2.0)[0]["regression"] is False


def test_fleet_headlines_append_and_compare_round_trip(tmp_path,
                                                       monkeypatch):
    """serve_bench --fleet's two headlines ride the same history →
    bench_compare gate as bench.py's: the throughput entry (tok/s)
    judges higher-is-better, the TTFT tail entry (unit "s") judges
    lower-is-better, and both carry the commit stamp + the cpu-by-
    contract tpu_unavailable_reason marker."""
    import tools.serve_bench as sb
    from tools.bench_compare import compare, load_history

    hist = tmp_path / "bench_history.jsonl"
    monkeypatch.setattr(sb, "HISTORY_PATH", str(hist))
    monkeypatch.setattr(sb, "_commit_stamp", lambda: "fleethead")
    sb.append_history({"metric": "serving_fleet_tokens_per_sec",
                       "value": 400.0, "unit": "tok/s", "replicas": 4})
    sb.append_history({"metric": "serving_fleet_ttft_p95_s",
                       "value": 0.10, "unit": "s", "replicas": 4})
    # a later, worse run: slower fleet AND a fatter TTFT tail
    sb.append_history({"metric": "serving_fleet_tokens_per_sec",
                       "value": 300.0, "unit": "tok/s", "replicas": 4})
    sb.append_history({"metric": "serving_fleet_ttft_p95_s",
                       "value": 0.15, "unit": "s", "replicas": 4})
    entries = load_history(str(hist))
    assert len(entries) == 4
    assert all(e["commit"] == "fleethead" and e["backend"] == "cpu"
               and e["tpu_unavailable_reason"].startswith("not-applicable")
               for e in entries)
    verdicts = {v["metric"]: v for v in compare(entries, threshold_pct=2.0)}
    assert verdicts["serving_fleet_tokens_per_sec"]["regression"] is True
    assert verdicts["serving_fleet_ttft_p95_s"]["regression"] is True
    # ...and an IMPROVED run passes both gates (ttft lower = better)
    sb.append_history({"metric": "serving_fleet_tokens_per_sec",
                       "value": 450.0, "unit": "tok/s", "replicas": 4})
    sb.append_history({"metric": "serving_fleet_ttft_p95_s",
                       "value": 0.08, "unit": "s", "replicas": 4})
    verdicts = {v["metric"]: v
                for v in compare(load_history(str(hist)),
                                 threshold_pct=2.0)}
    assert verdicts["serving_fleet_tokens_per_sec"]["regression"] is False
    assert verdicts["serving_fleet_ttft_p95_s"]["regression"] is False


@pytest.mark.warmpool
def test_coldstart_headline_units_gate_lower_is_better():
    """The cold-start demolition's two new headlines —
    control_plane_real_all_running and resize_grow_latency — carry unit
    "s" so bench_compare judges them lower-is-better, and value<=0
    fallback markers are skipped both as baseline and as the judged
    entry."""
    from tools.bench_compare import compare

    for metric in ("control_plane_real_all_running", "resize_grow_latency"):
        fast = {"metric": metric, "value": 3.2, "unit": "s",
                "backend": "cpu", "width": 256, "warm_pool": True}
        slow = {"metric": metric, "value": 4.5, "unit": "s",
                "backend": "cpu", "width": 256, "warm_pool": True}
        # got slower later → regression
        v = compare([fast, slow], threshold_pct=2.0)
        assert len(v) == 1 and v[0]["regression"] is True, metric
        # got faster later → pass
        v = compare([slow, fast], threshold_pct=2.0)
        assert v[0]["regression"] is False, metric
        # a value<=0 marker (failed/withheld run) never judges...
        marker = {"metric": metric, "value": 0.0, "unit": "s",
                  "backend": "cpu"}
        v = compare([fast, slow, marker], threshold_pct=2.0)
        assert v[0]["regression"] is True     # latest MEASURABLE judged
        # ...and never serves as a flattering baseline
        v = compare([marker, slow], threshold_pct=2.0)
        assert v[0]["regression"] is False
        assert v[0].get("note") == "no prior baseline"


@pytest.mark.recovery
def test_am_recovery_headline_gate_lower_is_better():
    """The AM-kill leg's control_plane_am_recovery headline carries unit
    "s" so bench_compare judges it lower-is-better (recovery got SLOWER
    later = regression), and value<=0 markers from failed/withheld runs
    never judge and never serve as a baseline."""
    from tools.bench_compare import compare

    fast = {"metric": "control_plane_am_recovery", "value": 3.1,
            "unit": "s", "backend": "cpu", "width": 8,
            "adopted": 8, "lost": 0, "replayed_records": 25}
    slow = dict(fast, value=5.0)
    v = compare([fast, slow], threshold_pct=2.0)
    assert len(v) == 1 and v[0]["regression"] is True
    v = compare([slow, fast], threshold_pct=2.0)
    assert v[0]["regression"] is False
    marker = dict(fast, value=0.0)
    v = compare([fast, slow, marker], threshold_pct=2.0)
    assert v[0]["regression"] is True       # latest MEASURABLE judged
    v = compare([marker, slow], threshold_pct=2.0)
    assert v[0]["regression"] is False
    assert v[0].get("note") == "no prior baseline"


@pytest.mark.recovery
def test_am_recovery_disclosure_stamps_adoption_fields():
    """Every control_plane_am_recovery history entry discloses what the
    recovery actually did — a fast downtime number that relaunched the
    gang (or replayed an empty journal) must be distinguishable from a
    genuine full adoption."""
    row = {"width": 8, "kill_after_ms": 4000, "recovery_s": 3.102,
           "adopted": 8, "lost": 0, "replayed_records": 25,
           "relaunches": 0, "am_attempt": 1}
    d = bench._am_recovery_disclosure(row)
    assert d == {"adopted": 8, "lost": 0, "replayed_records": 25,
                 "relaunches": 0, "kill_after_ms": 4000}
    # a degraded run's entry would say so on its face
    d = bench._am_recovery_disclosure({"adopted": 6, "lost": 2,
                                       "relaunches": 2})
    assert d["lost"] == 2 and d["relaunches"] == 2
    assert d["replayed_records"] == 0


@pytest.mark.warmpool
def test_cp_disclosure_stamps_warm_fields():
    """Every control-plane bench line discloses whether it rode the warm
    pool and what the caches did — a warm headline that hid its lease
    and hit counts would be indistinguishable from a cold one."""
    row = {"warm": True, "warm_leases": 4, "warm_misses": 1,
           "spawn_s": 0.202, "loc_cache_hits": 256, "loc_cache_misses": 0,
           "submit_to_all_running_s": 3.9}
    d = bench._cp_disclosure(row, cold_baseline_s=4.4)
    assert d == {"warm_pool": True, "warm_leases": 4, "warm_misses": 1,
                 "spawn_s": 0.202, "loc_cache_hits": 256,
                 "loc_cache_misses": 0, "cold_baseline_s": 4.4}
    # cold rows disclose too (warm_pool False, no baseline field)
    d = bench._cp_disclosure({"warm": False, "spawn_s": 0.6})
    assert d["warm_pool"] is False
    assert "cold_baseline_s" not in d


@pytest.mark.kv
def test_prefix_reuse_headlines_gate_units_and_disclosure(tmp_path,
                                                          monkeypatch):
    """serve_bench --prefix-reuse appends ONLY a strict double win (ON
    beats OFF on throughput AND TTFT), every line carries the KV
    hit-rate disclosure next to the number it justifies, and the two
    headlines ride the same bench_compare gate: tok/s judged
    higher-is-better, unit "s" judged lower-is-better."""
    import tools.serve_bench as sb
    from tools.bench_compare import compare, load_history

    on = {"tokens_per_sec": 120.0, "ttft_p95_s": 0.040,
          "kv_hit_rate_pct": 55.4, "requests_errored": 0}
    off = {"tokens_per_sec": 100.0, "ttft_p95_s": 0.050,
           "requests_errored": 0}
    entries = sb.build_prefix_history_entries(on, off, "bench_350m", 0.6)
    assert [e["metric"] for e in entries] == [
        "serving_prefix_tokens_per_sec", "serving_prefix_ttft_p95_s"]
    assert entries[0]["unit"] == "tok/s" and entries[0]["value"] == 120.0
    assert entries[1]["unit"] == "s" and entries[1]["value"] == 0.040
    for e in entries:
        # the disclosure contract: hit rate + baseline on EVERY line
        assert e["kv_hit_rate_pct"] == 55.4
        assert e["reuse_ratio"] == 0.6
        assert e["baseline_tokens_per_sec"] == 100.0
        assert e["baseline_ttft_p95_s"] == 0.050
        assert e["model"] == "bench_350m"

    # the gate: a tps win with a ttft LOSS appends nothing (and vice
    # versa) — half-wins would poison the baseline for later commits
    assert sb.build_prefix_history_entries(
        {**on, "ttft_p95_s": 0.060}, off, "bench_350m", 0.6) == []
    assert sb.build_prefix_history_entries(
        {**on, "tokens_per_sec": 90.0}, off, "bench_350m", 0.6) == []
    # degenerate measurements and errored rounds append nothing
    assert sb.build_prefix_history_entries(
        {**on, "tokens_per_sec": 0.0}, off, "bench_350m", 0.6) == []
    assert sb.build_prefix_history_entries(
        on, {**off, "ttft_p95_s": 0.0}, "bench_350m", 0.6) == []
    assert sb.build_prefix_history_entries(
        {**on, "requests_errored": 2}, off, "bench_350m", 0.6) == []
    assert sb.build_prefix_history_entries(
        on, {**off, "requests_errored": 1}, "bench_350m", 0.6) == []

    # append → bench_compare round trip: a later WORSE run regresses on
    # both gates, a later better run passes both
    hist = tmp_path / "bench_history.jsonl"
    monkeypatch.setattr(sb, "HISTORY_PATH", str(hist))
    monkeypatch.setattr(sb, "_commit_stamp", lambda: "prefixhead")
    for e in entries:
        sb.append_history(e)
    worse = sb.build_prefix_history_entries(
        {"tokens_per_sec": 101.0, "ttft_p95_s": 0.049,
         "kv_hit_rate_pct": 12.0, "requests_errored": 0},
        off, "bench_350m", 0.6)
    for e in worse:
        sb.append_history(e)
    loaded = load_history(str(hist))
    assert len(loaded) == 4
    assert all(e["commit"] == "prefixhead" and e["backend"] == "cpu"
               for e in loaded)
    verdicts = {v["metric"]: v for v in compare(loaded, threshold_pct=2.0)}
    assert verdicts["serving_prefix_tokens_per_sec"]["regression"] is True
    assert verdicts["serving_prefix_ttft_p95_s"]["regression"] is True
    for e in sb.build_prefix_history_entries(
            {"tokens_per_sec": 130.0, "ttft_p95_s": 0.035,
             "kv_hit_rate_pct": 60.0, "requests_errored": 0},
            off, "bench_350m", 0.6):
        sb.append_history(e)
    verdicts = {v["metric"]: v
                for v in compare(load_history(str(hist)),
                                 threshold_pct=2.0)}
    assert verdicts["serving_prefix_tokens_per_sec"]["regression"] is False
    assert verdicts["serving_prefix_ttft_p95_s"]["regression"] is False


@pytest.mark.reqtrace
def test_ttft_attribution_stamps_are_sum_consistent():
    """Every serve_bench JSON line's TTFT-attribution disclosure must be
    sum-consistent AS EMITTED: the rounded components plus unattributed
    equal the rounded total exactly, so a reader can audit where the p95
    first-token time went without re-deriving anything."""
    import tools.serve_bench as sb

    attr = sb.ttft_attribution(0.050, queue_wait_s=0.010,
                               prefill_s=0.020, route_ms=4.0,
                               migrate_ms=3.0)
    keys = {"ttft_attr_route_ms", "ttft_attr_queue_ms",
            "ttft_attr_prefill_ms", "ttft_attr_migrate_ms",
            "ttft_attr_decode_ms", "ttft_attr_unattributed_ms",
            "ttft_attr_total_ms"}
    assert set(attr) == keys
    assert attr["ttft_attr_route_ms"] == 4.0
    assert attr["ttft_attr_queue_ms"] == pytest.approx(10.0)
    assert attr["ttft_attr_decode_ms"] == pytest.approx(17.0)  # remainder
    assert attr["ttft_attr_total_ms"] == pytest.approx(54.0)   # route+ttft
    # the contract: rounded parts sum to the rounded total EXACTLY
    parts = sum(v for k, v in attr.items() if k != "ttft_attr_total_ms")
    assert parts == attr["ttft_attr_total_ms"]

    # phase breakdown unknown (fleet path through the router): nothing
    # is guessed — decode stays 0 and the gap lands in unattributed
    blind = sb.ttft_attribution(0.050)
    assert blind["ttft_attr_decode_ms"] == 0.0
    assert blind["ttft_attr_unattributed_ms"] == pytest.approx(50.0)
    parts = sum(v for k, v in blind.items() if k != "ttft_attr_total_ms")
    assert parts == blind["ttft_attr_total_ms"]

    # awkward floats cannot break the emitted-sum identity
    messy = sb.ttft_attribution(0.0333333, queue_wait_s=0.0111111,
                                prefill_s=0.0077777, route_ms=1.2345678)
    parts = sum(v for k, v in messy.items() if k != "ttft_attr_total_ms")
    assert round(parts, 3) == messy["ttft_attr_total_ms"]


@pytest.mark.reqtrace
@pytest.mark.serving
def test_serve_bench_single_engine_line_carries_attribution(monkeypatch,
                                                            capsys):
    """The single-engine serve_bench JSON line stamps the attribution
    next to the TTFT it explains (run the smallest real round rather
    than trusting the helper was wired in)."""
    import tools.serve_bench as sb

    monkeypatch.setattr(sys, "argv",
                        ["serve_bench", "--config", "tiny",
                         "--requests", "4", "--max-new", "4",
                         "--slots", "2", "--rate", "50"])
    assert sb.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["metric"] == "serve_tokens_per_sec"
    assert result["ttft_attr_total_ms"] >= result["ttft_attr_queue_ms"]
    parts = sum(v for k, v in result.items()
                if k.startswith("ttft_attr_")
                and k != "ttft_attr_total_ms")
    assert parts == pytest.approx(result["ttft_attr_total_ms"], abs=0.01)


if __name__ == "__main__":
    sys.exit(0)
