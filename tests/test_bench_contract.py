"""The bench measurement contract (VERDICT r3 weak #2): the driver keeps
only a ~2 KB tail of stdout and parses the final line from it, so that
line must be ONE compact JSON object. BENCH_r03 arrived as a 4 KB line
(embedded stack dumps) and parsed as null."""

import importlib.util
import json
import os
import sys

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_compact_is_single_bounded_line():
    s = bench._compact("a\nb\r\n  c  \n" + "x" * 500, 40)
    assert "\n" not in s and len(s) <= 40
    assert bench._compact("short", 100) == "short"


def test_emit_line_is_bounded_and_parseable(capsys):
    result = {
        "metric": bench.METRIC, "value": 0.0, "unit": "%MFU",
        "vs_baseline": 0.0,
        "tpu_error": "e" * 2000,
        "cpu_error": "c" * 2000,
        "last_good_tpu_measurement": {"value": 68.08, "pad": "p" * 2000},
        "am_startup_latency": {"runs": 3, "pad": "q" * 2000},
        "error": "z" * 2000,
    }
    bench._emit(result)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(line) <= 1500, len(line)
    parsed = json.loads(line)
    # the headline fields survive every truncation
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in parsed, key
    # dropped fields are recorded
    assert "truncated" in parsed


def test_emit_small_result_untouched(capsys):
    result = {"metric": bench.METRIC, "value": 68.08, "unit": "%MFU",
              "vs_baseline": 1.702}
    bench._emit(result)
    line = capsys.readouterr().out.strip()
    assert json.loads(line) == result


def test_compact_last_good_keeps_headline_only():
    last = {"metric": "m", "value": 68.08, "unit": "%MFU",
            "commit": "abc", "measured_at": "t", "step_time_s": 1.0,
            "tokens_per_sec_per_chip": 15897.0,
            "llama3_8b_layer_step_ms": 63.08, "generate_batch": 8}
    out = bench._compact_last_good(last)
    assert out["value"] == 68.08 and out["commit"] == "abc"
    assert "llama3_8b_layer_step_ms" not in out
    assert len(json.dumps(out)) < 300


if __name__ == "__main__":
    sys.exit(0)
