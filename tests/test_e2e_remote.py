"""Multi-host E2E: the full client → AM → executor chain over the
RemoteClusterBackend with two simulated hosts.

The VERDICT-r1 acceptance bar: gang-schedule 2 "hosts" (separate node
root dirs via ExecTransport) and pass the barrier / heartbeat / AM-retry
suite unchanged. Executors run in NODE-side workdirs — not the client's
app dir — and localize the frozen conf + resources through the staging
store, which is what proves the shared-filesystem assumption is gone
(conf is fetched by URI into the container's own cwd)."""

from __future__ import annotations

import os
import stat

import pytest

from tony_tpu import constants as C

from test_e2e import _dump_logs, run_job, script


def remote_overrides(tmp_path, nodes="nodeA:3,nodeB:3", transport="exec"):
    return {
        "tony.cluster.backend": "remote",
        "tony.cluster.nodes": nodes,
        "tony.cluster.node-transport": transport,
        "tony.cluster.node-root": str(tmp_path / "nodes"),
        "tony.staging.location": str(tmp_path / "shared-store"),
    }


# ---------------------------------------------------------------------------
# ssh shim (VERDICT-r2 item 7): a PATH-shimmed `ssh` that parses the real
# argv shape (`ssh -o k=v ... host cmd`) and runs the remote command in a
# local `bash -c` with stdin passed through — so SSHTransport.launch/kill
# themselves (script-over-stdin, pidfile pgid kill, rc-255 branch) are the
# code under test, mirroring the fake-gsutil pattern in test_storage.py.
# ---------------------------------------------------------------------------

_SSH_SHIM = """#!/usr/bin/env bash
args=()
while [ $# -gt 0 ]; do
  case "$1" in
    -o) shift 2 ;;          # -o consumes its value, like real ssh
    -*) shift ;;
    *) args+=("$1"); shift ;;
  esac
done
host="${args[0]}"
cmd="${args[1]}"
if [ -n "${TONY_SSH_SHIM_LOG:-}" ]; then
  printf '%s :: %s\\n' "$host" "$cmd" >> "$TONY_SSH_SHIM_LOG"
fi
if [ "$host" = "brokenhost" ]; then
  exit 255                   # ssh's transport-failure rc
fi
exec bash -c "$cmd"
"""


@pytest.fixture()
def ssh_shim(tmp_path, monkeypatch):
    """Install the shim first on PATH (inherited by the AM subprocess)
    and return the path of its call log."""
    shim_dir = tmp_path / "sshshim"
    shim_dir.mkdir()
    shim = shim_dir / "ssh"
    shim.write_text(_SSH_SHIM)
    shim.chmod(shim.stat().st_mode | stat.S_IXUSR | stat.S_IXGRP)
    monkeypatch.setenv("PATH", f"{shim_dir}{os.pathsep}"
                               f"{os.environ.get('PATH', '')}")
    log = tmp_path / "ssh_calls.log"
    monkeypatch.setenv("TONY_SSH_SHIM_LOG", str(log))
    return log


def _node_workdirs(tmp_path):
    root = tmp_path / "nodes"
    return sorted(os.listdir(root)) if root.is_dir() else []


def test_gang_barrier_across_two_nodes(tmp_path):
    """2 workers spread over 2 nodes rendezvous through the AM barrier."""
    client = run_job(
        tmp_path,
        ["--executes", script("check_jax_env.py"),
         "--conf", "tony.worker.instances=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    workdirs = _node_workdirs(tmp_path)
    assert len(workdirs) == 2, workdirs
    # each executor fetched the frozen conf through the store into its own
    # node-side workdir — the client's app dir was never read from there
    for wd in workdirs:
        fetched = tmp_path / "nodes" / wd / C.TONY_FINAL_CONF
        assert fetched.exists(), f"conf not localized into {wd}"


def test_node_side_cwd_is_not_app_dir(tmp_path):
    marker = str(tmp_path / "cwds")
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=2",
         "--conf", "tony.worker.command=bash -c 'mkdir -p %s && pwd > %s/$TASK_INDEX'" % (marker, marker),
         ],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    cwds = {open(os.path.join(marker, f)).read().strip()
            for f in os.listdir(marker)}
    assert len(cwds) == 2
    for cwd in cwds:
        assert cwd.startswith(str(tmp_path / "nodes")), cwd
        assert not cwd.startswith(client.app_dir), cwd


def test_missed_heartbeats_fail_on_remote_backend(tmp_path, monkeypatch):
    monkeypatch.setenv(C.TEST_TASK_EXECUTOR_NUM_HB_MISS, "100")
    client = run_job(
        tmp_path,
        ["--executes", script("sleep_30.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-missed-heartbeats=5"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:2"))
    assert client.final_status == "FAILED"
    assert "missed" in (client.final_message or "")


def test_am_retry_recovers_on_remote_backend(tmp_path):
    """Session retry relaunches on the node pool (stale-session containers
    from attempt 0 are killed through the transport)."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0_if_retry.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.am.retry-count=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_worker_failure_fails_app_on_remote_backend(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("exit_1.py"),
         "--conf", "tony.worker.instances=1"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:1"))
    assert client.final_status == "FAILED"


def test_gang_barrier_over_ssh_transport(tmp_path, ssh_shim):
    """The full chain with transport=ssh through the shim: launch scripts
    travel over stdin into `bash -s`, conf localizes through the store,
    2 workers gang-rendezvous."""
    client = run_job(
        tmp_path,
        ["--executes", script("check_jax_env.py"),
         "--conf", "tony.worker.instances=2"],
        conf_overrides=remote_overrides(tmp_path, transport="ssh"))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    calls = ssh_shim.read_text() if ssh_shim.exists() else ""
    launches = [ln for ln in calls.splitlines() if ":: bash -s" in ln]
    assert len(launches) == 2, calls
    assert {ln.split(" :: ")[0] for ln in launches} == {"nodeA", "nodeB"}
    for wd in _node_workdirs(tmp_path):
        assert (tmp_path / "nodes" / wd / C.TONY_FINAL_CONF).exists()


def test_am_retry_kills_stale_executors_over_ssh(tmp_path, ssh_shim):
    """Session retry on transport=ssh: attempt 0's containers are killed
    through SSHTransport.kill — the pidfile pgid TERM/KILL one-liner runs
    over the shim channel — and attempt 1 succeeds."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0_if_retry.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.am.retry-count=2"],
        conf_overrides=remote_overrides(tmp_path, transport="ssh"))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    calls = ssh_shim.read_text() if ssh_shim.exists() else ""
    kills = [ln for ln in calls.splitlines() if "kill -TERM" in ln]
    assert kills, f"no transport kills recorded:\n{calls}"
    assert all("container.pid" in ln for ln in kills)


def test_ssh_transport_failure_rc255_fails_task(tmp_path, ssh_shim):
    """A node whose ssh channel dies with rc 255 (transport failure) must
    surface as a failed container -> FAILED app, exercising the rc-255
    branch in RemoteClusterBackend._wait_container."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.registration-timeout-sec=5"],
        conf_overrides=remote_overrides(tmp_path, nodes="brokenhost:1",
                                        transport="ssh"))
    assert client.final_status == "FAILED", _dump_logs(client)


def test_crash_resume_on_store_no_shared_ckpt_dir(tmp_path, fake_gcs):
    """VERDICT r2 item 5 acceptance: AM-retry crash-resume where the
    checkpoints live on the (fake-gsutil) gs:// store — per-shard uploads
    + COMMIT marker, restore by URI; no shared local checkpoint dir
    between the attempts' node-side workdirs."""
    import json as _json

    from test_e2e import run_job as _run_job

    report_dir = str(tmp_path / "report")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    client = _run_job(
        tmp_path,
        ["--executes", script("train_crash_resume.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.am.retry-count=2",
         "--conf", "tony.execution.env=CKPT_DIR=gs://bkt/run-ckpts",
         "--conf", f"tony.execution.env=REPORT_DIR={report_dir}",
         "--conf", f"tony.execution.env=TONY_REPO_ROOT={repo}"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    report = _json.load(open(os.path.join(report_dir,
                                          "resume_report.json")))
    assert report["attempt"] == 1
    assert report["resumed_from"] == 3     # picked up attempt 0's last save
    assert report["finished_at"] == 6
    # the checkpoints really live in the store, committed
    assert (fake_gcs / "bkt" / "run-ckpts" / "step_3" / "COMMIT").exists()


def test_am_publishes_history_through_store(tmp_path):
    """The AM uploads finalized jhist + config to the staging store so an
    off-host portal can serve the job (reference: jhist on HDFS,
    events/EventHandler.java:97-113)."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    store_root = tmp_path / "shared-store" / client.app_id / "history"
    assert store_root.is_dir(), "history not published to the store"
    names = os.listdir(store_root)
    assert any(n.endswith(".jhist") and "-SUCCEEDED." in n
               for n in names), names
    assert C.PORTAL_CONFIG_FILE in names
    # aggregated container logs ride along (VERDICT r4 item 3): an
    # off-host portal can serve /logs/... from its fetched mirror
    logs_root = store_root / C.HISTORY_LOGS_DIR_NAME
    assert logs_root.is_dir(), "aggregated logs not published"
    worker_dirs = [d for d in os.listdir(logs_root)
                   if d.startswith("worker_0")]
    assert worker_dirs and (logs_root / worker_dirs[0] /
                            "stdout").exists()


def test_src_dir_ships_through_store_to_nodes(tmp_path):
    """User code travels client → store → node workdir (the HDFS
    upload/localize loop, TonyClient.java:519-590)."""
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text("print('trained-on-node')\n")
    client = run_job(
        tmp_path,
        ["--executes", "train.py",
         "--src_dir", str(src),
         "--conf", "tony.worker.instances=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    stdouts = []
    containers_dir = os.path.join(client.app_dir, "containers")
    for d in os.listdir(containers_dir):
        p = os.path.join(containers_dir, d, "stdout")
        if os.path.exists(p):
            stdouts.append(open(p).read())
    assert sum("trained-on-node" in s for s in stdouts) == 2


def test_node_label_pins_jobtype_to_matching_node(tmp_path):
    """VERDICT r4 item 2: a labeled jobtype lands ONLY on the node
    carrying that label (TonyClient.java:260 setNodeLabelExpression
    semantics on the static pool)."""
    marker = str(tmp_path / "hosts")
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=2",
         "--conf", "tony.worker.node-label=tpu",
         "--conf",
         "tony.worker.command=bash -c 'mkdir -p %s && pwd > %s/$TASK_INDEX'"
         % (marker, marker)],
        conf_overrides=remote_overrides(
            tmp_path, nodes="plainA:4,tpuB:4;label=tpu"))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    cwds = [open(os.path.join(marker, f)).read().strip()
            for f in os.listdir(marker)]
    assert len(cwds) == 2
    # ExecTransport keys node workdirs by container id under the shared
    # node root; assert via the backend's own placement record in the AM
    # log instead: every launch line names tpuB
    am_stderr = open(os.path.join(client.app_dir, "am.stderr")).read()
    launches = [ln for ln in am_stderr.splitlines()
                if "launched container_" in ln]
    assert len(launches) == 2, am_stderr
    assert all("on node tpuB" in ln for ln in launches), launches


def test_unsatisfiable_placement_fails_fast(tmp_path):
    """An impossible ask (label no node carries) fails the app in well
    under the 15-min registration timeout, naming the jobtype and the
    node inventory."""
    import time as _time

    t0 = _time.monotonic()
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.worker.node-label=gpu"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:2"))
    elapsed = _time.monotonic() - t0
    assert client.final_status == "FAILED"
    msg = client.final_message or ""
    assert "worker" in msg and "label='gpu'" in msg, msg
    assert "nodeA:2" in msg, msg
    assert elapsed < 30, f"fail-fast took {elapsed:.1f}s"


def test_joint_gang_infeasibility_fails_fast(tmp_path):
    """ps=2 + worker=3 on a 4-slot pool: each jobtype fits alone, the
    gang can never co-reside -> FAILED fast with the joint message."""
    import time as _time

    t0 = _time.monotonic()
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0.py"),
         "--conf", "tony.ps.instances=2",
         "--conf", "tony.worker.instances=3"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:4"))
    assert client.final_status == "FAILED"
    msg = client.final_message or ""
    assert "jointly need" in msg and "slots" in msg, msg
    assert _time.monotonic() - t0 < 30


def test_rendezvous_at_width_48(tmp_path):
    """VERDICT r4 weak #5: a production-width 48-task gang registers
    through the barrier and succeeds. This exact storm exposed (and now
    guards) the launch-time liveliness bug: 48 concurrently booting
    executors take longer than the heartbeat-expiry window to send their
    first ping, so liveliness must start at registerWorkerSpec
    (ApplicationMaster.java:851), not container launch."""
    import time as _time

    t0 = _time.monotonic()
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=48",
         "--conf", "tony.worker.command=bash -c 'sleep 0.5'",
         "--conf", "tony.task.heartbeat-interval-ms=500"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeW:48"))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    # every member of the gang really went through TASK_STARTED
    started = [e for e in _history_event_list(client)
               if e["type"] == "TASK_STARTED"
               and e["payload"]["task_type"] == "worker"]
    assert len(started) == 48, len(started)
    assert _time.monotonic() - t0 < 120


def _history_event_list(client):
    import os as _os

    from tony_tpu import constants as _C
    from tony_tpu.events.handler import parse_events

    hist_base = _os.path.join(client.app_dir, _C.HISTORY_DIR_NAME)
    finals = [_os.path.join(d, f) for d, _, fs in _os.walk(hist_base)
              for f in fs if f.endswith(".jhist")]
    assert finals, "no history file"
    return [e.to_dict() for e in parse_events(finals[0])]
