"""Multi-host E2E: the full client → AM → executor chain over the
RemoteClusterBackend with two simulated hosts.

The VERDICT-r1 acceptance bar: gang-schedule 2 "hosts" (separate node
root dirs via ExecTransport) and pass the barrier / heartbeat / AM-retry
suite unchanged. Executors run in NODE-side workdirs — not the client's
app dir — and localize the frozen conf + resources through the staging
store, which is what proves the shared-filesystem assumption is gone
(conf is fetched by URI into the container's own cwd)."""

from __future__ import annotations

import os

import pytest

from tony_tpu import constants as C

from test_e2e import _dump_logs, run_job, script


def remote_overrides(tmp_path, nodes="nodeA:3,nodeB:3"):
    return {
        "tony.cluster.backend": "remote",
        "tony.cluster.nodes": nodes,
        "tony.cluster.node-transport": "exec",
        "tony.cluster.node-root": str(tmp_path / "nodes"),
        "tony.staging.location": str(tmp_path / "shared-store"),
    }


def _node_workdirs(tmp_path):
    root = tmp_path / "nodes"
    return sorted(os.listdir(root)) if root.is_dir() else []


def test_gang_barrier_across_two_nodes(tmp_path):
    """2 workers spread over 2 nodes rendezvous through the AM barrier."""
    client = run_job(
        tmp_path,
        ["--executes", script("check_jax_env.py"),
         "--conf", "tony.worker.instances=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    workdirs = _node_workdirs(tmp_path)
    assert len(workdirs) == 2, workdirs
    # each executor fetched the frozen conf through the store into its own
    # node-side workdir — the client's app dir was never read from there
    for wd in workdirs:
        fetched = tmp_path / "nodes" / wd / C.TONY_FINAL_CONF
        assert fetched.exists(), f"conf not localized into {wd}"


def test_node_side_cwd_is_not_app_dir(tmp_path):
    marker = str(tmp_path / "cwds")
    client = run_job(
        tmp_path,
        ["--conf", "tony.worker.instances=2",
         "--conf", "tony.worker.command=bash -c 'mkdir -p %s && pwd > %s/$TASK_INDEX'" % (marker, marker),
         ],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    cwds = {open(os.path.join(marker, f)).read().strip()
            for f in os.listdir(marker)}
    assert len(cwds) == 2
    for cwd in cwds:
        assert cwd.startswith(str(tmp_path / "nodes")), cwd
        assert not cwd.startswith(client.app_dir), cwd


def test_missed_heartbeats_fail_on_remote_backend(tmp_path, monkeypatch):
    monkeypatch.setenv(C.TEST_TASK_EXECUTOR_NUM_HB_MISS, "100")
    client = run_job(
        tmp_path,
        ["--executes", script("sleep_30.py"),
         "--conf", "tony.worker.instances=1",
         "--conf", "tony.task.max-missed-heartbeats=5"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:2"))
    assert client.final_status == "FAILED"
    assert "missed" in (client.final_message or "")


def test_am_retry_recovers_on_remote_backend(tmp_path):
    """Session retry relaunches on the node pool (stale-session containers
    from attempt 0 are killed through the transport)."""
    client = run_job(
        tmp_path,
        ["--executes", script("exit_0_if_retry.py"),
         "--conf", "tony.worker.instances=2",
         "--conf", "tony.am.retry-count=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)


def test_worker_failure_fails_app_on_remote_backend(tmp_path):
    client = run_job(
        tmp_path,
        ["--executes", script("exit_1.py"),
         "--conf", "tony.worker.instances=1"],
        conf_overrides=remote_overrides(tmp_path, nodes="nodeA:1"))
    assert client.final_status == "FAILED"


def test_src_dir_ships_through_store_to_nodes(tmp_path):
    """User code travels client → store → node workdir (the HDFS
    upload/localize loop, TonyClient.java:519-590)."""
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text("print('trained-on-node')\n")
    client = run_job(
        tmp_path,
        ["--executes", "train.py",
         "--src_dir", str(src),
         "--conf", "tony.worker.instances=2"],
        conf_overrides=remote_overrides(tmp_path))
    assert client.final_status == "SUCCEEDED", _dump_logs(client)
    stdouts = []
    containers_dir = os.path.join(client.app_dir, "containers")
    for d in os.listdir(containers_dir):
        p = os.path.join(containers_dir, d, "stdout")
        if os.path.exists(p):
            stdouts.append(open(p).read())
    assert sum("trained-on-node" in s for s in stdouts) == 2
