"""tools/onchip_e2e.py mechanics, driven on the CPU backend.

The tool's purpose is the real-chip lifecycle proof (client -> AM ->
executor -> worker claiming the TPU tunnel), which can't run under the
test suite's forced-CPU env — but every moving part EXCEPT the chip can:
the probe gate, the submission, the log scrape, and the honest ok=False
verdict when the backend isn't a TPU. Pinning those here means a healthy
tunnel window can't be wasted on a broken tool."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "onchip_e2e.py")


def test_onchip_e2e_cpu_mechanics(tmp_path):
    result_path = tmp_path / "onchip_result.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    env.update(JAX_PLATFORMS="cpu", TONY_ONCHIP_STEPS="2",
               TONY_ONCHIP_CONFIG="tiny", TONY_ONCHIP_SEQ="128",
               # never the real tools/ slot: a rehearsal must not clobber
               # genuine on-chip evidence from a healthy-tunnel window
               TONY_ONCHIP_RESULT=str(result_path))
    proc = subprocess.run([sys.executable, TOOL], env=env,
                          capture_output=True, text=True, timeout=360)
    # honest verdict: the chain ran, but a CPU backend is NOT on-chip
    # evidence, so the tool must exit nonzero with ok=False
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"] is False
    assert rec["final_status"] == "SUCCEEDED"
    assert rec["device"]["backend"] == "cpu"
    assert rec["final_loss"] > 0
    assert rec["commit"]
    assert json.loads(result_path.read_text())["ok"] is False
