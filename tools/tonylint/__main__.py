"""CLI: python -m tools.tonylint [paths...] [options]

Exit codes: 0 clean (new findings == 0 and baseline not stale),
1 findings / stale baseline, 2 usage error. The nonzero-on-new-findings
contract makes it gate-able exactly like tools/bench_compare.py.

Pre-commit fast path:
    python -m tools.tonylint --changed
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tools.tonylint import default_rules, lint_repo, repo_root, save_baseline
from tools.tonylint.engine import BASELINE_FILE, GitError


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tonylint",
        description="TonY-TPU control-plane static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    parser.add_argument("paths", nargs="*", default=[],
                        help="package dirs/files to scan (default: tony_tpu)")
    parser.add_argument("--changed", action="store_true",
                        help="per-file rules only visit files touched per "
                             "git (project-wide rules always run)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable report on stdout")
    parser.add_argument("--rules", default="",
                        help="comma list of rule ids to run (default: all)")
    parser.add_argument("--list", action="store_true", dest="list_rules",
                        help="list rule ids and exit")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {BASELINE_FILE})")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                             "(add one-line justifications by hand; it may "
                             "only shrink afterwards)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetected)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.id:24s} {rule.description}")
        return 0

    root = os.path.abspath(args.root) if args.root else repo_root()
    packages = [p.rstrip("/") for p in args.paths] or ["tony_tpu"]
    wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
    rule_filter = (lambda r: r.id in wanted) if wanted else None

    if args.update_baseline and (args.changed or wanted or args.paths):
        # a subset scan would overwrite the baseline with only the
        # subset's buckets, silently deleting every other file's /
        # rule's accepted debt (a positional path is the same subset
        # trap as --changed/--rules)
        print("tonylint: --update-baseline rewrites the WHOLE baseline "
              "and needs a full default scan — drop --changed/--rules/"
              "paths", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    try:
        if args.update_baseline:
            # run WITHOUT a baseline so every finding lands in the new one
            report = lint_repo(root, packages=packages, changed=False,
                               baseline_path=os.devnull,
                               rule_filter=rule_filter)
            path = args.baseline or os.path.join(root, BASELINE_FILE)
            save_baseline(path, report.findings)
            print(f"baseline written: {path} "
                  f"({len(report.findings)} entr(y/ies))")
            return 0

        report = lint_repo(root, packages=packages, changed=args.changed,
                           baseline_path=args.baseline,
                           rule_filter=rule_filter)
    except GitError as exc:
        # never report "clean" because git failed — the pre-commit gate
        # must fail loudly, not check zero files
        print(f"tonylint: {exc}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0
    if args.as_json:
        payload = report.to_dict()
        payload["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=2))
    else:
        print(report.render())
        print(f"({elapsed:.2f}s)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
