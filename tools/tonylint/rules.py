"""The shipped rule set. Order is display order in `--list`."""

from __future__ import annotations

from tools.tonylint.engine import Rule
from tools.tonylint.rules_conf import ConfigKeyRegistryRule
from tools.tonylint.rules_legacy import (AlertHotLoopRule,
                                         AlertRuleRegistryRule,
                                         GaugeRegistryRule, PrintBanRule,
                                         RendererCoverageRule)
from tools.tonylint.rules_locks import GuardedByRule, NoBlockingUnderLockRule
from tools.tonylint.rules_profiler import (ProcessEntryProfilerRule,
                                           WatchdogBeaconRule)
from tools.tonylint.rules_rpc import (AttemptFencingRule, RedactOnEgressRule,
                                      TracePropagationRule)
from tools.tonylint.rules_threads import ThreadHygieneRule


def default_rules() -> list[Rule]:
    return [
        GuardedByRule(),
        NoBlockingUnderLockRule(),
        AttemptFencingRule(),
        RedactOnEgressRule(),
        TracePropagationRule(),
        ConfigKeyRegistryRule(),
        ThreadHygieneRule(),
        PrintBanRule(),
        GaugeRegistryRule(),
        RendererCoverageRule(),
        AlertRuleRegistryRule(),
        AlertHotLoopRule(),
        WatchdogBeaconRule(),
        ProcessEntryProfilerRule(),
    ]
