"""Rules migrated from the three scattered regex checks that predate
tonylint (tests/test_logs.py, tests/test_fleet.py, tests/test_alerts.py).
The tests still exist as one-line wrappers over these rules, so tier-1
coverage is unchanged — the implementation just moved where scopes and
suppressions exist.

- print-ban: control-plane processes log through observability/logs.py
  so records carry the {app_id, task, attempt, trace_id} stamp; a bare
  print() bypasses it. Deliberate raw-stdout markers keep their legacy
  `log-ok:` escape (line or two lines above).
- gauge-registry: every tony_job_* gauge the AM exports must be a key
  of fleet.JOB_GAUGES (else fleet /metrics silently drops it), and
  gauge names must be literals, never f-strings.
- renderer-coverage: every events.schema.EventType has a renderer that
  produces text even on an empty payload.
- alert-rule-registry: every quoted built-in rule-id literal resolves in
  alerts.BUILTIN_RULES (no silently-dead rules).
- alert-hot-loop: the alert engine may only run on the AM monitor /
  portal fleet-scan cadences — hot-loop modules must not import it, and
  the two sanctioned call sites must exist.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from tools.tonylint.engine import Finding, Project, PyFile, Rule

PRINT_BAN_DIRS = ("tony_tpu/am/", "tony_tpu/executor/", "tony_tpu/rpc/",
                  "tony_tpu/portal/", "tony_tpu/serve/")

AM_FILE = "tony_tpu/am/application_master.py"
FLEET_FILE = "tony_tpu/observability/fleet.py"
RENDER_FILE = "tony_tpu/events/render.py"
ALERTS_FILE = "tony_tpu/observability/alerts.py"
GAUGE_RE = re.compile(r"^tony_job_[a-z0-9_]+$")
RULE_ID_RE = re.compile(r"^(?:train|serve|fleet)\.[a-z0-9_]+$")
ALERT_RULE_SOURCES = (AM_FILE, "tony_tpu/portal/server.py",
                      "tony_tpu/portal/__main__.py",
                      "tony_tpu/cli/__main__.py", ALERTS_FILE, FLEET_FILE)
ALERT_HOT_PATHS = ("tony_tpu/train/", "tony_tpu/executor/",
                   "tony_tpu/serve/engine.py", "tony_tpu/serve/frontend.py",
                   "tony_tpu/serve/__main__.py")


class PrintBanRule(Rule):
    id = "print-ban"
    description = ("no bare print() in control-plane modules — use the "
                   "structured logger, or tag a deliberate stdout marker "
                   "with `log-ok:`")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(PRINT_BAN_DIRS):
                continue
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    if "log-ok" in pf.comment_near(node.lineno, back=2):
                        continue
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        "bare print() in a control-plane module — log "
                        "through observability/logs.py (or tag a "
                        "deliberate marker with a `log-ok:` comment)")


class GaugeRegistryRule(Rule):
    id = "gauge-registry"
    description = ("AM tony_job_* gauge literals must be keys of "
                   "fleet.JOB_GAUGES, and never f-string-assembled")
    project_wide = True

    def __init__(self, job_gauges: Optional[set] = None,
                 step_time_gauges: Optional[dict] = None):
        # injectable for fixture tests; defaults import the live tables
        self._job_gauges = job_gauges
        self._step_time_gauges = step_time_gauges

    def _tables(self) -> tuple[set, dict]:
        if self._job_gauges is not None:
            return set(self._job_gauges), dict(self._step_time_gauges or {})
        from tony_tpu.observability import fleet
        return set(fleet.JOB_GAUGES), dict(fleet.STEP_TIME_GAUGES)

    def run(self, project: Project) -> Iterable[Finding]:
        pf = project.file(AM_FILE)
        if pf is None:
            return
        job_gauges, step_time = self._tables()
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if GAUGE_RE.match(node.value) \
                        and node.value not in job_gauges:
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        f'"{node.value}" is exported by the AM but not '
                        f"aggregated by fleet.JOB_GAUGES — the fleet "
                        f"/metrics would silently drop it")
            elif isinstance(node, ast.JoinedStr):
                if any(isinstance(p, ast.Constant)
                       and "tony_job_" in str(p.value)
                       for p in node.values):
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        "f-string-assembled tony_job_* gauge name — "
                        "register a literal in fleet.JOB_GAUGES instead "
                        "(fleet.STEP_TIME_GAUGES exists for this)")
        extra = set(step_time.values()) - job_gauges
        if extra:
            yield Finding(
                self.id, FLEET_FILE, 1,
                f"fleet.STEP_TIME_GAUGES values missing from "
                f"fleet.JOB_GAUGES: {sorted(extra)}")


class RendererCoverageRule(Rule):
    id = "renderer-coverage"
    description = ("every events.schema.EventType has a renderer that "
                   "produces text on an empty payload")
    project_wide = True

    def run(self, project: Project) -> Iterable[Finding]:
        if project.file(RENDER_FILE) is None:
            return
        from tony_tpu.events.render import RENDERERS, render_event
        from tony_tpu.events.schema import EventType
        for etype in EventType:
            if etype not in RENDERERS:
                yield Finding(
                    self.id, RENDER_FILE, 1,
                    f"event type {etype.value} has no renderer — the "
                    f"portal/CLI timeline would show raw payload dicts")
                continue
            try:
                ok = bool(render_event(etype.value, {}))
            except Exception as exc:  # noqa: BLE001 — the finding IS the report
                yield Finding(
                    self.id, RENDER_FILE, 1,
                    f"renderer for {etype.value} raised on an empty "
                    f"payload: {exc!r}")
                continue
            if not ok:
                yield Finding(
                    self.id, RENDER_FILE, 1,
                    f"renderer for {etype.value} returns empty text on an "
                    f"empty payload")


class AlertRuleRegistryRule(Rule):
    id = "alert-rule-registry"
    description = ("every quoted built-in alert rule-id literal must be a "
                   "key of alerts.BUILTIN_RULES (no silently-dead rules)")
    project_wide = True

    def __init__(self, builtin_rules: Optional[set] = None):
        self._builtin = builtin_rules

    def run(self, project: Project) -> Iterable[Finding]:
        if self._builtin is not None:
            builtin = set(self._builtin)
        else:
            if project.file(ALERTS_FILE) is None:
                return
            from tony_tpu.observability.alerts import BUILTIN_RULES
            builtin = set(BUILTIN_RULES)
        for rel in ALERT_RULE_SOURCES:
            pf = project.file(rel)
            if pf is None:
                continue
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and RULE_ID_RE.match(node.value) \
                        and node.value not in builtin:
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        f'rule-id literal "{node.value}" is not registered '
                        f"in alerts.BUILTIN_RULES — no engine would ever "
                        f"evaluate it (silently dead)")


class AlertHotLoopRule(Rule):
    id = "alert-hot-loop"
    description = ("the alert engine runs only on the AM monitor / portal "
                   "fleet-scan cadence — hot-loop modules must not reach it")
    project_wide = True

    def run(self, project: Project) -> Iterable[Finding]:
        am = project.file(AM_FILE)
        fleet = project.file(FLEET_FILE)
        if am is None or fleet is None:
            return
        for pf in project.files:
            if not (pf.relpath.startswith(ALERT_HOT_PATHS[:2])
                    or pf.relpath in ALERT_HOT_PATHS[2:]):
                continue
            for marker in ("observability.alerts", "AlertEngine",
                           "import alerts"):
                if marker in pf.source:
                    yield Finding(
                        self.id, pf.relpath, 1,
                        f"hot-loop module references {marker!r} — alert "
                        f"evaluation must stay on the monitor/fleet-scan "
                        f"cadence")
                    break
        # positive controls: the two sanctioned evaluate() call sites
        if "_check_alerts" not in am.source:
            yield Finding(self.id, AM_FILE, 1,
                          "sanctioned AM call site _check_alerts is gone — "
                          "alert evaluation lost its monitor-cadence home")
        if "alert_engine.evaluate" not in fleet.source:
            yield Finding(self.id, FLEET_FILE, 1,
                          "sanctioned fleet call site alert_engine.evaluate "
                          "is gone — fleet-scope rules are never evaluated")
