"""profiler coverage: daemon loops beat a beacon; entries install the pair.

The stall watchdog (observability/profiler.py) can only autopsy a wedge
it can SEE: a daemon worker loop that never registers a progress beacon
is invisible to it, and a long-running ``__main__`` that skips
``install_process_profiler`` has no profiler, no watchdog, and no
SIGUSR2 stack dump at all. Two checks:

- ``watchdog-beacon``: every thread-entry function (a ``target=`` of a
  ``threading.Thread`` construction, or the ``run()`` of a Thread
  subclass) that contains a ``while`` loop must carry beacon evidence —
  a ``register_beacon(...)`` call, or ``.beat(``/``.idle(`` on each
  iteration. Loops with a legitimate reason to stay dark (the profiler's
  own threads — the observer cannot watch itself) carry a justified
  suppression.
- ``process-entry-profiler``: every long-running process entry (AM,
  executor, portal, serve replica, and the CLI that hosts the router
  verb) must call ``install_process_profiler(`` — the one-call wiring
  for faulthandler + sampling profiler + stall watchdog.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.tonylint.engine import (Finding, Project, PyFile, Rule,
                                   dotted_name)
from tools.tonylint.rules_threads import THREAD_DIRS

# the metrics push worker (train/metrics.py) is a control-plane daemon
# loop living outside THREAD_DIRS
BEACON_DIRS = THREAD_DIRS + ("tony_tpu/train/",)

# every long-running __main__ the tentpole wires; the CLI is on the
# list because its `router` verb IS the fleet router daemon
ENTRY_FILES = (
    "tony_tpu/am/__main__.py",
    "tony_tpu/executor/__main__.py",
    "tony_tpu/portal/__main__.py",
    "tony_tpu/serve/__main__.py",
    "tony_tpu/cli/__main__.py",
)


def _thread_target_names(pf: PyFile) -> set[str]:
    """Trailing names of every ``target=`` passed to a Thread
    construction in this module (``self._run`` -> ``_run``)."""
    names: set[str] = set()
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("threading.Thread",
                                               "Thread")):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            tgt = kw.value
            if isinstance(tgt, ast.Attribute):
                names.add(tgt.attr)
            elif isinstance(tgt, ast.Name):
                names.add(tgt.id)
    return names


def _has_beacon_evidence(fn: ast.AST) -> bool:
    """``register_beacon(...)`` or a ``.beat(``/``.idle(`` call anywhere
    in the function — AST shape, so a comment or string mentioning the
    beacon protocol does not satisfy the check."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        tail = name.rpartition(".")[2]
        if tail in ("register_beacon", "beat", "idle"):
            return True
    return False


def _contains_while(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.While) for n in ast.walk(fn))


class WatchdogBeaconRule(Rule):
    id = "watchdog-beacon"
    description = ("daemon worker loops must register a stall-watchdog "
                   "beacon and beat()/idle() it — a dark loop's wedge "
                   "is invisible to the autopsy")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(BEACON_DIRS):
                continue
            targets = _thread_target_names(pf)
            # a `run` method only counts when its class subclasses
            # Thread — TaskExecutor.run() is a main-thread lifecycle,
            # not a daemon loop, and must not be dragged in by name
            candidates: list[ast.FunctionDef] = []
            for cls in ast.walk(pf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                if any(dotted_name(b) in ("threading.Thread", "Thread")
                       for b in cls.bases):
                    for stmt in cls.body:
                        if isinstance(stmt, ast.FunctionDef) \
                                and stmt.name == "run":
                            candidates.append(stmt)
            if targets:
                seen = set(id(fn) for fn in candidates)
                for node in ast.walk(pf.tree):
                    if isinstance(node, ast.FunctionDef) \
                            and node.name in targets \
                            and node.name != "run" \
                            and id(node) not in seen:
                        candidates.append(node)
            for node in candidates:
                if not _contains_while(node):
                    continue
                if _has_beacon_evidence(node):
                    continue
                yield Finding(
                    self.id, pf.relpath, node.lineno,
                    f"thread loop {node.name}() never registers a "
                    f"watchdog beacon (observability/profiler."
                    f"register_beacon) nor beats one — a wedge here is "
                    f"invisible to the stall autopsy")


class ProcessEntryProfilerRule(Rule):
    id = "process-entry-profiler"
    description = ("every long-running __main__ must install the "
                   "profiler/faulthandler pair "
                   "(install_process_profiler)")
    project_wide = True

    def run(self, project: Project) -> Iterable[Finding]:
        for rel in ENTRY_FILES:
            pf = project.file(rel)
            if pf is None:
                yield Finding(
                    self.id, rel, 1,
                    "long-running process entry missing from the scan "
                    "set — was it moved without updating "
                    "rules_profiler.ENTRY_FILES?")
                continue
            if "install_process_profiler(" not in pf.source:
                yield Finding(
                    self.id, rel, 1,
                    "long-running process entry never calls "
                    "install_process_profiler(...) — no sampling "
                    "profiler, no stall watchdog, no SIGUSR2 "
                    "all-thread dump for this process")
