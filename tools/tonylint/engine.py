"""tonylint engine: rule registry, file model, suppressions, baseline.

TonY's control plane earns its reliability from conventions the compiler
never checks — attempt-fenced RPC mutations, lock-guarded shared state on
the AM/session/liveliness hot paths, `redact()` on every egress, a
`tony.*` config registry that must stay in sync with its docs. This
module is the machinery those conventions are enforced with; the rules
themselves live in the sibling ``rules_*`` modules.

Design points:

- Files are parsed ONCE (``ast`` + ``tokenize``) into :class:`PyFile`;
  every rule shares the parse. The whole-repo pass must stay inside the
  tier-1 test budget (<10 s — it is a test, tests/test_lint.py).
- Suppression is per line: ``# tony: disable=<rule-id>[,<rule-id>...]``
  on the offending line or the line directly above, optionally followed
  by ``-- <justification>``. Rule authors never special-case call sites;
  the justification lives next to the code it excuses.
- The baseline (tools/lint_baseline.json) may only shrink: a finding
  count above its entry fails the run (new debt), and an entry above the
  actual count ALSO fails the run (stale — shrink the file). An empty
  baseline is the steady state.
- ``--changed`` restricts per-file rules to files touched per git;
  project-wide rules (registry/coverage checks) always run — they are
  cross-file by nature and cheap.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import subprocess
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

# comment grammars (shared by the engine and several rules)
DISABLE_RE = re.compile(r"tony:\s*disable=([a-z0-9_,\-*]+)")
GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
HOLDS_RE = re.compile(r"holds:\s*([A-Za-z_][A-Za-z0-9_]*)")

BASELINE_FILE = os.path.join("tools", "lint_baseline.json")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    @property
    def key(self) -> str:
        """Baseline bucket: line numbers drift under unrelated edits, so
        baselined debt is counted per (file, rule), not per line."""
        return f"{self.path}::{self.rule}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class PyFile:
    """One parsed source file: AST + per-line comments + suppressions."""

    def __init__(self, root: str, relpath: str, source: str):
        self.root = root
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        # line -> comment text (sans '#'), via tokenize so strings that
        # merely contain '#' are never misread as comments
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass
        # line -> set of disabled rule ids ('*' disables everything)
        self.suppressions: dict[int, set[str]] = {}
        for line, text in self.comments.items():
            m = DISABLE_RE.search(text)
            if m:
                ids = {part.strip() for part in m.group(1).split(",")
                       if part.strip()}
                self.suppressions[line] = ids

    def comment_near(self, line: int, back: int = 1) -> str:
        """The comment on `line` plus up to `back` lines above, joined —
        the print-ban's legacy `log-ok` escape looks 2 lines back."""
        parts = [self.comments.get(n, "")
                 for n in range(max(1, line - back), line + 1)]
        return " ".join(p for p in parts if p)

    def is_comment_line(self, line: int) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        return self.lines[line - 1].lstrip().startswith("#")

    def annotation_at(self, line: int) -> str:
        """Comment attached to the statement starting at `line`: its own
        trailing comment, or a comment-ONLY line directly above. A
        trailing comment of the PREVIOUS statement never leaks down."""
        parts = [self.comments.get(line, "")]
        if self.is_comment_line(line - 1):
            parts.insert(0, self.comments.get(line - 1, ""))
        return " ".join(p for p in parts if p)

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        candidates = [line]
        if self.is_comment_line(line - 1):
            candidates.append(line - 1)
        for n in candidates:
            ids = self.suppressions.get(n)
            if ids and (rule_id in ids or "*" in ids):
                return True
        return False


class Project:
    """The unit a lint run sees: every parsed file under the scanned
    package root(s), plus read access to sibling files (docs, conf)."""

    def __init__(self, root: str, rel_files: Iterable[str],
                 sources: Optional[dict[str, str]] = None):
        self.root = root
        self.files: list[PyFile] = []
        self.errors: list[Finding] = []
        # per-file rules in --changed mode only visit this subset;
        # project-wide rules always see .files in full
        self.changed_only: Optional[set[str]] = None
        for rel in sorted(set(rel_files)):
            try:
                if sources is not None and rel in sources:
                    src = sources[rel]
                else:
                    with open(os.path.join(root, rel), "r",
                              encoding="utf-8") as f:
                        src = f.read()
                self.files.append(PyFile(root, rel, src))
            except (OSError, SyntaxError, ValueError) as exc:
                self.errors.append(Finding(
                    "parse-error", rel.replace(os.sep, "/"), 1,
                    f"could not parse: {exc}"))

    def scan_files(self) -> list[PyFile]:
        """Files a PER-FILE rule should visit (honors --changed)."""
        if self.changed_only is None:
            return self.files
        return [pf for pf in self.files if pf.relpath in self.changed_only]

    def file(self, relpath: str) -> Optional[PyFile]:
        rel = relpath.replace(os.sep, "/")
        for pf in self.files:
            if pf.relpath == rel:
                return pf
        return None

    def read_text(self, relpath: str) -> Optional[str]:
        try:
            with open(os.path.join(self.root, relpath), "r",
                      encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


class Rule:
    """Base rule. Subclasses set `id`/`description` and implement
    `run(project)`. `project_wide` rules ignore --changed restriction
    (cross-file registry/coverage checks — they are cheap and a change
    anywhere can break them)."""

    id: str = ""
    description: str = ""
    project_wide: bool = False

    def run(self, project: Project) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    # convenience for per-file AST rules
    def files(self, project: Project) -> list[PyFile]:
        return project.files if self.project_wide else project.scan_files()


# ---------------------------------------------------------------------------
# helpers shared by rules
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """'time.sleep' for Attribute chains, 'sleep' for bare Names, ''
    otherwise. Subscripts are transparent (self._locks[i] -> self._locks)."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return ".".join(reversed(parts)) if parts else ""


def iter_class_defs(tree: ast.Module) -> Iterable[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


def iter_functions(node: ast.AST) -> Iterable[ast.FunctionDef]:
    for child in ast.walk(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield child


def is_trivial_body(fn: ast.FunctionDef) -> bool:
    """Docstring-only / pass / Ellipsis — an abstract declaration, not an
    implementation (rpc/service.py's handler interfaces)."""
    for stmt in fn.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        if isinstance(stmt, ast.Raise):  # raise NotImplementedError
            continue
        return False
    return True


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> dict[str, dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return dict(data.get("entries", {}))


def save_baseline(path: str, findings: list[Finding],
                  why: str = "baselined at introduction") -> None:
    """Rewrite the baseline to the current findings. A surviving
    bucket keeps its hand-written `why` — the documented workflow adds
    justifications by hand after generation, and a later legitimate
    rewrite (debt shrank elsewhere) must not erase them."""
    existing = load_baseline(path)
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    entries = {key: {"count": n,
                     "why": existing.get(key, {}).get("why", why)}
               for key, n in sorted(counts.items())}
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def apply_baseline(findings: list[Finding], baseline: dict[str, dict],
                   judgeable: Optional[Callable[[str], bool]] = None
                   ) -> tuple[list[Finding], list[str]]:
    """Split findings into (new, stale-baseline-entries).

    Per (file, rule) bucket: up to `count` findings are accepted debt;
    any excess is NEW. A bucket whose actual count fell BELOW its entry
    is STALE — the baseline must shrink with the debt, or deleted debt
    could silently regrow inside the old budget. `judgeable` limits the
    stale check to keys the run could actually observe: a --changed or
    --rules subset run never visited the other buckets, so a zero count
    there means "not scanned", not "fixed"."""
    by_key: dict[str, list[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new: list[Finding] = []
    stale: list[str] = []
    for key, fs in sorted(by_key.items()):
        budget = int(baseline.get(key, {}).get("count", 0))
        if len(fs) > budget:
            new.extend(fs[budget:])
    for key, entry in sorted(baseline.items()):
        if judgeable is not None and not judgeable(key):
            continue
        actual = len(by_key.get(key, []))
        if actual < int(entry.get("count", 0)):
            stale.append(
                f"{key}: baseline allows {entry.get('count')} but only "
                f"{actual} remain — shrink tools/lint_baseline.json")
    return new, stale


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def discover_files(root: str, packages: Iterable[str]) -> list[str]:
    rels: list[str] = []
    for pkg in packages:
        base = os.path.join(root, pkg)
        if os.path.isfile(base) and base.endswith(".py"):
            rels.append(os.path.relpath(base, root))
            continue
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(files):
                if name.endswith(".py"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, name), root))
    return rels


class GitError(RuntimeError):
    """--changed could not determine the touched set. Raised (never
    swallowed): a pre-commit gate that silently checks zero files
    because git failed would pass exactly when it must not."""


def changed_files(root: str) -> set[str]:
    """Root-relative paths touched vs HEAD (staged + unstaged +
    untracked) — the `--changed` pre-commit fast path. `--relative`
    makes diff paths relative to `root` (not the git toplevel), so a
    project nested below the toplevel still matches its relpaths —
    otherwise the gate would silently check zero files and pass."""
    out: set[str] = set()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--relative", "HEAD", "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError) as exc:
        raise GitError(f"git unavailable for --changed: {exc}") from exc
    for proc in (diff, untracked):
        if proc.returncode != 0:
            err = (proc.stderr.strip() or "no output").splitlines()[0]
            raise GitError(
                f"git failed for --changed (rc={proc.returncode}): {err}")
        out |= {line.strip().replace(os.sep, "/")
                for line in proc.stdout.splitlines() if line.strip()}
    return out


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)   # new (unbaselined)
    baselined: int = 0
    stale_baseline: list[str] = field(default_factory=list)
    suppressed: int = 0
    checked_files: int = 0
    rules: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "findings": [f.to_dict() for f in self.findings],
                "baselined": self.baselined,
                "stale_baseline": self.stale_baseline,
                "suppressed": self.suppressed,
                "checked_files": self.checked_files,
                "rules": self.rules}

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines += [f"stale baseline: {s}" for s in self.stale_baseline]
        status = "clean" if self.ok else f"{len(self.findings)} finding(s)"
        if self.stale_baseline:
            status += f", {len(self.stale_baseline)} stale baseline entr(y/ies)"
        lines.append(
            f"tonylint: {status} over {self.checked_files} file(s) "
            f"({self.suppressed} suppressed, {self.baselined} baselined)")
        return "\n".join(lines)


def run_rules(project: Project, rules: list[Rule],
              baseline: Optional[dict[str, dict]] = None) -> Report:
    report = Report(rules=[r.id for r in rules],
                    checked_files=len(project.files))
    raw: list[Finding] = list(project.errors)
    for rule in rules:
        try:
            found = list(rule.run(project))
        except Exception as exc:  # noqa: BLE001 — a crashed rule (e.g. a
            # registry rule importing a syntax-broken live module) must
            # surface as a finding in the report, never as a traceback
            # that eats the report for --json consumers / pre-commit
            raw.append(Finding(
                rule.id, f"<rule:{rule.id}>", 1,
                f"rule crashed: {exc!r} — fix the rule or the tree it "
                f"inspects"))
            continue
        for finding in found:
            pf = project.file(finding.path)
            if pf is not None and pf.is_suppressed(finding.rule, finding.line):
                report.suppressed += 1
                continue
            raw.append(finding)
    raw.sort(key=lambda f: (f.path, f.line, f.rule))
    if baseline:
        rule_by_id = {r.id: r for r in rules}

        def judgeable(key: str) -> bool:
            path, _, rule_id = key.rpartition("::")
            rule = rule_by_id.get(rule_id)
            if rule is None:     # rule not in this run (--rules subset)
                return False
            if (project.changed_only is not None and not rule.project_wide
                    and path not in project.changed_only):
                return False     # per-file rule never visited this file
            return True

        new, stale = apply_baseline(raw, baseline, judgeable)
        report.baselined = len(raw) - len(new)
        report.findings = new
        report.stale_baseline = stale
    else:
        report.findings = raw
    return report


def lint_repo(root: str, rules: Optional[list[Rule]] = None,
              packages: Iterable[str] = ("tony_tpu",),
              changed: bool = False,
              baseline_path: Optional[str] = None,
              rule_filter: Optional[Callable[[Rule], bool]] = None) -> Report:
    """The one entry point the CLI, the tier-1 test, and the migrated
    legacy-check wrappers all share."""
    from tools.tonylint.rules import default_rules
    rules = list(rules if rules is not None else default_rules())
    if rule_filter is not None:
        rules = [r for r in rules if rule_filter(r)]
    project = Project(root, discover_files(root, packages))
    if changed:
        project.changed_only = changed_files(root)
    baseline = load_baseline(
        baseline_path if baseline_path is not None
        else os.path.join(root, BASELINE_FILE))
    return run_rules(project, rules, baseline)
