"""tonylint — project-specific static analysis for TonY-TPU's
control-plane invariants (lock discipline, attempt fencing, config-key
registry, redaction on egress, thread hygiene, + the migrated legacy
checks). See docs/STATIC_ANALYSIS.md for the rule catalog.

Run:  python -m tools.tonylint [tony_tpu/] [--changed] [--json]
Test: tests/test_lint.py runs the same engine in-process (tier-1).
"""

from tools.tonylint.engine import (Finding, Project, Report, Rule,
                                   apply_baseline, lint_repo, load_baseline,
                                   run_rules, save_baseline)
from tools.tonylint.rules import default_rules

__all__ = ["Finding", "Project", "Report", "Rule", "apply_baseline",
           "default_rules", "findings_for", "lint_repo", "load_baseline",
           "repo_root", "run_rules", "save_baseline"]

import functools as _functools
import os as _os


def repo_root() -> str:
    return _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))


@_functools.lru_cache(maxsize=1)
def _repo_report() -> Report:
    """One shared full-rule pass over the repo at HEAD. The four
    migrated wrapper tests each ask for one rule id; without the cache
    each call would re-parse all ~110 files (~0.6 s apiece of identical
    tier-1 work). Runs WITHOUT the baseline: the wrappers are the
    tier-1 hard assertions the pre-migration regex checks were — a
    baseline entry must not be able to satisfy them."""
    return lint_repo(repo_root(), baseline_path=_os.devnull)


def findings_for(*rule_ids: str) -> list[str]:
    """Rendered findings of the named rule(s) over the repo at HEAD —
    the one-line wrapper surface the migrated legacy tests call
    (tests/test_logs.py, tests/test_fleet.py, tests/test_alerts.py)."""
    wanted = set(rule_ids)
    return [f.render() for f in _repo_report().findings
            if f.rule in wanted]
