"""Control-plane RPC rules: attempt-fencing and redact-on-egress.

attempt-fencing: a task relaunch bumps the slot's attempt; every RPC
mutation path that a superseded (zombie) executor can still reach must
compare the caller's attempt against the slot's before mutating —
otherwise a zombie re-fills the rendezvous barrier it was evicted from,
keeps the replacement's liveliness entry fresh, or completes the
replacement with its own stale result (PR 2/11's fencing story). The
rule requires an ``attempt`` comparison in the named handler bodies.

redact-on-egress: anything that leaves the process boundary toward an
operator surface — webhook POSTs, sink files, live log-tail chunks —
must flow through ``logs.redact`` (PR 6/9). The rule finds egress
functions (urlopen/Request with a payload, ``*Sink`` delivery methods,
the log-tail readers, trace-export surfaces: ``*Collector`` export/
drain methods and the serving-traces sidecar writer) and requires a
redact call in their bodies.

trace-propagation: any outbound HTTP request in ``tony_tpu/serve/``
that targets another replica's data plane (``/v1/generate`` or
``/v1/migrate`` in the URL) must forward the request-trace header —
a hop that drops ``X-Tony-Trace`` silently severs the distributed
trace at that boundary, and the stitched waterfall then blames the
wrong process for the missing time.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.tonylint.engine import (Finding, Project, PyFile, Rule,
                                   dotted_name, is_trivial_body,
                                   iter_class_defs)

# RPC mutation paths a superseded attempt can reach. These names are the
# contract: a new fenced handler gets added here when it grows a
# per-task mutation (see docs/STATIC_ANALYSIS.md).
FENCED_HANDLERS = (
    "register_worker_spec",
    "register_worker_spec_with_generation",
    "register_execution_result",
    "task_executor_heartbeat",
    # elastic resize: an ask computed against a stale registry entry
    # must not fire on a superseded session attempt's fresh gang
    "request_resize",
)
# handler IMPLEMENTATIONS only: rpc/client.py's same-named methods are
# serialization stubs (they SEND the attempt; the server compares it)
FENCED_DIRS = ("tony_tpu/am/", "tony_tpu/session/", "tony_tpu/rpc/service.py")

EGRESS_DIRS = ("tony_tpu/",)
# log-tail payload producers (observability/logs.py): every chunk these
# return crosses the RPC boundary into CLI/portal output
LOG_TAIL_READERS = {("LogTail", "read"), ("LogTail", "tail_lines")}


def _mentions_attempt(node: ast.AST) -> bool:
    for child in ast.walk(node):
        if isinstance(child, ast.Name) and "attempt" in child.id:
            return True
        if isinstance(child, ast.Attribute) and "attempt" in child.attr:
            return True
    return False


class AttemptFencingRule(Rule):
    id = "attempt-fencing"
    description = ("RPC handlers that mutate per-task state "
                   f"({', '.join(FENCED_HANDLERS)}) must compare an "
                   "`attempt` before mutating")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(FENCED_DIRS):
                continue
            for node in ast.walk(pf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name not in FENCED_HANDLERS:
                    continue
                if is_trivial_body(node):
                    continue  # abstract interface declaration
                fenced = any(
                    isinstance(child, ast.Compare)
                    and _mentions_attempt(child)
                    for child in ast.walk(node))
                if not fenced:
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        f"{node.name}() mutates per-task state but never "
                        f"compares an attempt — a superseded (zombie) "
                        f"executor could mutate the replacement's slot")


def _calls_redact(fn: ast.AST) -> bool:
    for child in ast.walk(fn):
        if isinstance(child, ast.Call):
            name = dotted_name(child.func)
            if "redact" in name:
                return True
    return False


def _is_egress_fn(fn: ast.FunctionDef, cls_name: str) -> str:
    """Non-empty reason string when `fn` writes data across the process
    boundary toward an operator surface."""
    if cls_name.endswith("Sink") and fn.name in ("deliver", "write", "emit"):
        return f"{cls_name}.{fn.name} is a delivery sink"
    if (cls_name, fn.name) in LOG_TAIL_READERS:
        return f"{cls_name}.{fn.name} produces log-tail payloads"
    # trace-export surfaces: pull-endpoint snapshots and the history
    # sidecar both carry request traces (prompts ride in hop attrs if a
    # bug ever leaks them) to CLI/portal consumers
    if cls_name.endswith("Collector") and fn.name in ("export", "drain"):
        return f"{cls_name}.{fn.name} exports request-trace payloads"
    if fn.name == "write_serving_traces_file":
        return "writes the serving-traces history sidecar"
    for child in ast.walk(fn):
        if not isinstance(child, ast.Call):
            continue
        name = dotted_name(child.func)
        tail = name.rsplit(".", 1)[-1]
        has_data = any(kw.arg == "data" for kw in child.keywords)
        if tail == "urlopen" and (has_data or len(child.args) > 1):
            return "posts a payload via urlopen"
        if tail == "Request" and name.startswith("urllib") and has_data:
            return "builds an HTTP request with a payload"
    return ""


class RedactOnEgressRule(Rule):
    id = "redact-on-egress"
    description = ("webhook/file-sink payloads and log-tail chunks must "
                   "flow through logs.redact / redact_payload")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(EGRESS_DIRS):
                continue
            for cls in iter_class_defs(pf.tree):
                for fn in cls.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        yield from self._check(pf, fn, cls.name)
            # module-level functions
            for fn in pf.tree.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check(pf, fn, "")

    def _check(self, pf: PyFile, fn: ast.FunctionDef,
               cls_name: str) -> Iterable[Finding]:
        reason = _is_egress_fn(fn, cls_name)
        if reason and not _calls_redact(fn):
            yield Finding(
                self.id, pf.relpath, fn.lineno,
                f"{fn.name}() {reason} but never calls redact() / "
                f"redact_payload() — secrets could cross the egress "
                f"boundary unredacted")


# replica-to-replica data-plane paths: a request forwarded here is part
# of ONE client request's distributed trace
TRACED_PATHS = ("/v1/generate", "/v1/migrate")
TRACE_DIRS = ("tony_tpu/serve/",)


def _builds_traced_request(call: ast.Call) -> str:
    """The traced path literal when `call` constructs an HTTP request to
    another replica's data plane, else ''."""
    name = dotted_name(call.func)
    if name.rsplit(".", 1)[-1] != "Request":
        return ""
    if not call.args:
        return ""
    for child in ast.walk(call.args[0]):
        if isinstance(child, ast.Constant) and isinstance(child.value, str):
            for path in TRACED_PATHS:
                if path in child.value:
                    return path
    return ""


def _forwards_trace_header(fn: ast.AST) -> bool:
    for child in ast.walk(fn):
        if isinstance(child, ast.Constant) and child.value == "X-Tony-Trace":
            return True
        if isinstance(child, ast.Attribute) and child.attr == "HEADER":
            return True
    return False


class TracePropagationRule(Rule):
    id = "trace-propagation"
    description = ("outbound /v1/generate and /v1/migrate requests in "
                   "tony_tpu/serve/ must forward the X-Tony-Trace "
                   "header so the distributed trace survives the hop")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(TRACE_DIRS):
                continue
            for node in ast.walk(pf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for child in ast.walk(node):
                    if not isinstance(child, ast.Call):
                        continue
                    path = _builds_traced_request(child)
                    if path and not _forwards_trace_header(node):
                        yield Finding(
                            self.id, pf.relpath, child.lineno,
                            f"{node.name}() POSTs {path} to another "
                            f"replica without forwarding the "
                            f"X-Tony-Trace header — the distributed "
                            f"trace is severed at this hop")
                        break  # one finding per function is enough
