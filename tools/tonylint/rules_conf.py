"""config-key-registry: the 149-key `tony.*` registry must stay closed.

conf/keys.py is the single source of truth for configuration key names
(the reference's TonyConfigurationKeys.java); docs/configuration.md is
generated from it (tools/gen_config_docs.py) and tests/test_conf.py
pins the generated file. What nothing checked until now: stray literals.
A `conf.get_str("tony.task.comand")` typo — or a key invented inline and
never registered — read as "unset" forever and no test noticed.

The rule closes the loop, all statically (keys.py is PARSED, never
imported, so the lint can run against a broken tree):

- every `tony.*` string literal in tony_tpu/ must be a registered static
  key, or match a dynamic builder shape (`tony.<jobtype>.<attr>` for the
  attrs keys.py's jobtype_key helpers define, `tony.queues.<q>.<attr>`
  for the queue-hierarchy helpers) with the jobtype segment outside
  RESERVED_SEGMENTS;
- reserved segments are respected: `tony.<reserved>.<x>` literals must
  be exact registered keys, never dynamic matches;
- every registered key is documented in docs/configuration.md;
- every registered key constant is referenced somewhere outside keys.py
  (a key nothing reads is dead weight or a rename's orphan).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from tools.tonylint.engine import Finding, Project, PyFile, Rule

KEYS_FILE = "tony_tpu/conf/keys.py"
DOCS_FILE = "docs/configuration.md"
KEY_LITERAL_RE = re.compile(r"^tony\.[a-z][a-z0-9_.\-]*$")


class KeyRegistry:
    """Parsed view of conf/keys.py: static keys, reserved segments, and
    the dynamic per-jobtype / per-queue attribute shapes derived from
    the helper functions themselves (the registry stays self-describing
    — a new helper is picked up without touching the lint)."""

    def __init__(self, tree: ast.Module):
        self.static: dict[str, str] = {}       # literal -> CONSTANT_NAME
        self.const_lines: dict[str, int] = {}  # CONSTANT_NAME -> lineno
        self.reserved: set[str] = set()
        self.jobtype_attrs: set[str] = set()
        self.queue_attrs: set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str) \
                        and node.value.value.startswith("tony."):
                    self.static[node.value.value] = name
                    self.const_lines[name] = node.lineno
                elif name == "RESERVED_SEGMENTS":
                    for child in ast.walk(node.value):
                        if isinstance(child, ast.Constant) \
                                and isinstance(child.value, str):
                            self.reserved.add(child.value)
            elif isinstance(node, ast.FunctionDef):
                self._harvest_helper(node)

    def _harvest_helper(self, fn: ast.FunctionDef) -> None:
        for child in ast.walk(fn):
            if not isinstance(child, ast.Return) or child.value is None:
                continue
            val = child.value
            # return jobtype_key(jobtype, "attr")
            if isinstance(val, ast.Call) and isinstance(val.func, ast.Name) \
                    and val.func.id == "jobtype_key" and len(val.args) == 2 \
                    and isinstance(val.args[1], ast.Constant):
                self.jobtype_attrs.add(str(val.args[1].value))
            # return f"tony.queues.{queue}.<attr>"
            elif isinstance(val, ast.JoinedStr):
                parts = [p.value for p in val.values
                         if isinstance(p, ast.Constant)]
                text = "".join(str(p) for p in parts)
                if text.startswith("tony.queues.") and text.count(".") >= 3:
                    self.queue_attrs.add(text.rsplit(".", 1)[-1])

    def classify(self, literal: str) -> Optional[str]:
        """None when the literal is a legitimate key; else the problem."""
        if literal in self.static:
            return None
        parts = literal.split(".")
        if len(parts) < 2 or not parts[-1]:
            return "malformed tony.* key"
        segment = parts[1]
        if segment == "queues":
            if len(parts) >= 4 and ".".join(parts[3:]) in self.queue_attrs:
                return None
            return (f"unknown queue-hierarchy key (expected "
                    f"tony.queues.<q>.<{'|'.join(sorted(self.queue_attrs))}>)")
        if segment in self.reserved:
            return (f"not in conf/keys.py and '{segment}' is a reserved "
                    f"segment (typo, or register the key)")
        if len(parts) >= 3 and ".".join(parts[2:]) in self.jobtype_attrs:
            return None  # dynamic tony.<jobtype>.<attr>
        return ("not a registered key and not a dynamic "
                "tony.<jobtype>.<attr> shape — register it in conf/keys.py")


def _string_literals(pf: PyFile) -> Iterable[tuple[int, str]]:
    """(line, value) for plain string constants, skipping docstrings —
    prose ABOUT keys must not count as key usage (or misusage)."""
    doc_lines: set[int] = set()
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                doc_lines.add(body[0].value.lineno)
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.lineno not in doc_lines:
            yield node.lineno, node.value


class ConfigKeyRegistryRule(Rule):
    id = "config-key-registry"
    description = ("every tony.* literal resolves against conf/keys.py "
                   "(+ dynamic shapes); every registered key is referenced "
                   "and documented in docs/configuration.md")
    project_wide = True

    def run(self, project: Project) -> Iterable[Finding]:
        keys_pf = project.file(KEYS_FILE)
        if keys_pf is None:
            return
        registry = KeyRegistry(keys_pf.tree)
        docs = project.read_text(DOCS_FILE) or ""
        # 1) stray / drifted literals anywhere in the package
        for pf in project.files:
            if pf.relpath == KEYS_FILE:
                continue
            for line, value in _string_literals(pf):
                if not KEY_LITERAL_RE.match(value):
                    continue
                problem = registry.classify(value)
                if problem:
                    yield Finding(self.id, pf.relpath, line,
                                  f'"{value}": {problem}')
        # 2) registered keys must be documented + referenced
        corpus = "\n".join(pf.source for pf in project.files
                           if pf.relpath != KEYS_FILE)
        for literal, const in sorted(registry.static.items()):
            lineno = registry.const_lines.get(const, 1)
            if docs and literal not in docs:
                yield Finding(
                    self.id, KEYS_FILE, lineno,
                    f"{const} = \"{literal}\" is not documented in "
                    f"{DOCS_FILE} — regenerate it "
                    f"(python tools/gen_config_docs.py)")
            if not re.search(rf"\b{re.escape(const)}\b", corpus) \
                    and literal not in corpus:
                yield Finding(
                    self.id, KEYS_FILE, lineno,
                    f"{const} = \"{literal}\" is defined but never "
                    f"referenced anywhere in tony_tpu/ — dead key or "
                    f"rename orphan")
