"""thread-hygiene: control-plane threads must be reapable and loud.

A non-daemon thread nobody joins wedges AM/executor shutdown (the
tier-1 suite's leak detector exists because exactly this bit PR 1);
a bare ``except:`` or a silently-swallowed exception in a control-plane
thread turns a real fault into an unexplained hang. Three checks:

- every ``threading.Thread(...)`` construction passes ``daemon=...``,
  sets ``<target>.daemon = True`` / ``setDaemon(True)`` after
  construction, or its target is ``.join()``-ed somewhere in the same
  module; a class subclassing ``threading.Thread`` must set ``daemon``
  in its body;
- no bare ``except:`` (it catches SystemExit/KeyboardInterrupt and hides
  shutdown);
- an ``except`` whose body is ONLY ``pass``/``continue`` must log
  instead (or carry a justification suppression) — a handler that sets
  a flag or returns a fallback is deliberate and is left alone.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.tonylint.engine import (Finding, Project, PyFile, Rule,
                                   dotted_name)

THREAD_DIRS = ("tony_tpu/am/", "tony_tpu/executor/", "tony_tpu/rpc/",
               "tony_tpu/session/", "tony_tpu/observability/",
               "tony_tpu/cluster/", "tony_tpu/portal/", "tony_tpu/serve/",
               "tony_tpu/events/")


def _is_thread_join_shape(node: ast.Call) -> bool:
    """Distinguish Thread.join from str.join by call shape: str.join
    REQUIRES exactly one iterable positional arg, Thread.join takes
    nothing or a numeric timeout (positional or keyword). So
    `sep.join(parts)` is never evidence, while `t.join()`,
    `t.join(2.0)` and `t.join(timeout=x)` are."""
    if not node.args:
        return True
    if len(node.args) == 1 and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, (int, float)):
        return True
    return False


def _module_has_thread_join(pf: PyFile) -> bool:
    """True when the module contains a `.join()` call whose receiver can
    be a thread. A textual `".join(" in source` check is defeated by any
    `", ".join(...)` — string joins (constant receivers, or any
    variable receiver called with an iterable arg: see
    `_is_thread_join_shape`) and path joins (os.path/posixpath/ntpath)
    are excluded by AST shape instead."""
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Constant):          # ", ".join(...)
            continue
        if isinstance(recv, ast.JoinedStr):          # f"{sep}".join(...)
            continue
        name = dotted_name(node.func)
        if name.startswith(("os.path.", "posixpath.", "ntpath.",
                            "shlex.", "str.")):
            continue
        if not _is_thread_join_shape(node):          # sep.join(parts)
            continue
        return True
    return False


def _class_sets_daemon(node: ast.ClassDef) -> bool:
    """True when the class body assigns `daemon`/`self.daemon` or passes
    a `daemon=` keyword (e.g. to super().__init__) — AST shape, so a
    comment merely mentioning 'daemon' does not satisfy the check."""
    for child in ast.walk(node):
        if isinstance(child, (ast.Assign, ast.AnnAssign)):
            targets = (child.targets if isinstance(child, ast.Assign)
                       else [child.target])
            for tgt in targets:
                if (isinstance(tgt, ast.Name) and tgt.id == "daemon") \
                        or (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "daemon"):
                    return True
        elif isinstance(child, ast.Call):
            if any(kw.arg == "daemon" for kw in child.keywords):
                return True
    return False


def _assign_target_names(assign: ast.Assign) -> set[str]:
    names: set[str] = set()
    for tgt in assign.targets:
        if isinstance(tgt, ast.Attribute):
            names.add(tgt.attr)
        elif isinstance(tgt, ast.Name):
            names.add(tgt.id)
    return names


def _thread_target_daemonized(pf: PyFile, assign: ast.Assign) -> bool:
    """True when the Thread assigned here is made a daemon after
    construction — `t = Thread(...)` + `t.daemon = True` (or the legacy
    `t.setDaemon(True)`), the stdlib's own documented idiom. Only a
    literal True counts: `t.daemon = False` is an explicit non-daemon."""
    names = _assign_target_names(assign)
    if not names:
        return False
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                        and isinstance(tgt.value, (ast.Attribute, ast.Name))):
                    recv = tgt.value
                    tail = (recv.attr if isinstance(recv, ast.Attribute)
                            else recv.id)
                    if tail in names:
                        return True
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "setDaemon"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value is True):
            recv = node.func.value
            tail = (recv.attr if isinstance(recv, ast.Attribute)
                    else recv.id if isinstance(recv, ast.Name) else None)
            if tail in names:
                return True
    return False


def _thread_target_joined(pf: PyFile, assign: ast.Assign) -> bool:
    """True when the Thread assigned here is `.join()`-ed in the same
    module — `self._thread = Thread(...)` + `self._thread.join()`. The
    evidence is a Call node whose receiver's trailing name matches the
    assignment target (AST shape: a comment or log string mentioning
    `.join(` does not count)."""
    names = _assign_target_names(assign)
    if not names:
        return False
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Subscript):   # self._threads[i].join()
            recv = recv.value
        tail = (recv.attr if isinstance(recv, ast.Attribute)
                else recv.id if isinstance(recv, ast.Name) else None)
        if tail in names:
            return True
    return False


class ThreadHygieneRule(Rule):
    id = "thread-hygiene"
    description = ("threads must be daemon or provably joined; no bare "
                   "except; swallowed exceptions in control-plane code "
                   "must log")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(THREAD_DIRS):
                continue
            parent_of: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(pf.tree):
                for child in ast.iter_child_nodes(node):
                    parent_of[child] = node
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in ("threading.Thread",
                                                       "Thread"):
                    yield from self._check_thread(pf, node, parent_of)
                elif isinstance(node, ast.ClassDef):
                    yield from self._check_thread_subclass(pf, node)
                elif isinstance(node, ast.ExceptHandler):
                    yield from self._check_handler(pf, node)

    def _check_thread(self, pf: PyFile, node: ast.Call,
                      parent_of: dict) -> Iterable[Finding]:
        if any(kw.arg == "daemon" for kw in node.keywords):
            return
        parent = parent_of.get(node)
        # X = Thread(...) (possibly behind an Attribute target):
        # joined, or daemonized after construction?
        if isinstance(parent, ast.Assign) and (
                _thread_target_joined(pf, parent)
                or _thread_target_daemonized(pf, parent)):
            return
        yield Finding(
            self.id, pf.relpath, node.lineno,
            "threading.Thread(...) is neither daemon=... nor provably "
            "joined in this module — a leaked non-daemon thread wedges "
            "shutdown")

    def _check_thread_subclass(self, pf: PyFile,
                               node: ast.ClassDef) -> Iterable[Finding]:
        subclasses = any(
            dotted_name(base) in ("threading.Thread", "Thread")
            for base in node.bases)
        if not subclasses:
            return
        if not _class_sets_daemon(node) and not _module_has_thread_join(pf):
            yield Finding(
                self.id, pf.relpath, node.lineno,
                f"class {node.name}(threading.Thread) never sets daemon "
                f"and instances are never joined in this module")

    def _check_handler(self, pf: PyFile,
                       node: ast.ExceptHandler) -> Iterable[Finding]:
        if node.type is None:
            yield Finding(
                self.id, pf.relpath, node.lineno,
                "bare `except:` — catches SystemExit/KeyboardInterrupt; "
                "catch Exception (and log) instead")
            return
        # only BROAD catches must log: `except OSError: pass` on a
        # best-effort cleanup path is deliberate; `except Exception: pass`
        # hides faults the control plane should at least whisper about
        broad = any(
            isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
            for n in ast.walk(node.type))
        swallowed = all(isinstance(stmt, (ast.Pass, ast.Continue))
                        for stmt in node.body)
        if broad and swallowed:
            yield Finding(
                self.id, pf.relpath, node.lineno,
                "broad exception swallowed without logging in "
                "control-plane code — log at debug level or add a "
                "justified suppression")
