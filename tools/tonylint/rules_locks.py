"""Lock-discipline rules: guarded-by and no-blocking-under-lock.

The control plane's shared state (session task table, AM bookkeeping
dicts, liveliness shards, metrics stores) is protected by per-object
``threading.Lock``/``RLock`` fields by convention — PR 11's
``note_full_serve`` fix was exactly a missed-lock increment caught late
in review. These rules turn the convention into a checked annotation:

``# guarded-by: _lock`` on the attribute's assignment line declares
that, within the class, every other read/write of ``self.<attr>`` must
sit lexically inside ``with self._lock`` (subscripted lock tables like
``with self._locks[idx]`` match their ``_locks`` attribute). A method
whose ``def`` line carries ``# holds: _lock`` is treated as entered
with the lock already held (documented caller contract, e.g. the AM's
``_close_relaunch_downtime``).

``no-blocking-under-lock`` flags calls that sleep or do I/O while a
``with <...lock...>`` body is open — the liveliness sweep, heartbeat
handlers, and monitor loop all contend on these locks, so one
``time.sleep`` under them stalls W tasks.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.tonylint.engine import (Finding, GUARDED_BY_RE, HOLDS_RE, Project,
                                   PyFile, Rule, dotted_name, iter_class_defs)

# dirs whose shared state carries guarded-by annotations (ISSUE scope:
# the AM/session/liveliness hot paths + the observability stores the
# monitor loop and RPC handlers share; executor has its own small locks)
GUARDED_DIRS = ("tony_tpu/session/", "tony_tpu/am/", "tony_tpu/observability/",
                "tony_tpu/executor/")

# fully-qualified calls that block: sleeping, subprocess, sockets, HTTP
BLOCKING_DOTTED = {
    "time.sleep", "sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.create_connection",
    "urllib.request.urlopen", "urlopen",
}
# method names that are RPC / process-control round-trips regardless of
# receiver (backend container ops fork/TERM processes; the cluster/metrics
# client methods are network RPCs with retries)
BLOCKING_METHODS = {
    "stop_container", "start_container",
    "task_executor_heartbeat", "register_execution_result",
    "register_worker_spec", "update_metrics", "read_task_logs", "read_log",
    "request_preemption",
}


def _lock_attr_of(expr: ast.AST) -> Optional[str]:
    """The lock-ish attribute a with-item guards on, or None.

    Matches `self.X` / `self.X[i]` / bare `X` / `X[i]` where the name
    contains "lock" (``_lock``, ``_locks``, ``_respec_lock``...) —
    and `threading.Lock()` style inline constructions are ignored.

    A lock reached through ANOTHER object (`self.peer._lock`,
    `registry._lock`) returns its full dotted path: it still counts as
    "a lock is held" for no-blocking-under-lock, but a dotted path can
    never equal a `guarded-by: <attr>` identifier — holding the wrong
    object's same-named lock must not silence guarded-by."""
    node = expr
    if isinstance(node, ast.withitem):
        node = node.context_expr
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return dotted_name(node) or node.attr
    if isinstance(node, ast.Name) and "lock" in node.id.lower():
        return node.id
    return None


class _LockTrackingVisitor(ast.NodeVisitor):
    """Shared traversal: maintains the set of lock attribute names whose
    `with` body lexically encloses the current node. Nested function
    definitions reset the held set — a closure runs after the lock is
    long released."""

    def __init__(self, held: Optional[set[str]] = None):
        self.held: set[str] = set(held or ())

    def visit_With(self, node: ast.With) -> None:
        locks = {name for item in node.items
                 for name in [_lock_attr_of(item)] if name}
        added = locks - self.held
        self.held |= added
        for item in node.items:
            self.visit(item)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    visit_AsyncWith = visit_With

    def _visit_nested_def(self, node: ast.AST) -> None:
        saved, self.held = self.held, set()
        self.generic_visit(node)
        self.held = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested_def(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested_def(node)


class _GuardedAccessVisitor(_LockTrackingVisitor):
    def __init__(self, rule_id: str, pf: PyFile, guarded: dict[str, str],
                 held: set[str], out: list[Finding]):
        super().__init__(held)
        self.rule_id = rule_id
        self.pf = pf
        self.guarded = guarded
        self.out = out

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr in self.guarded):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                self.out.append(Finding(
                    self.rule_id, self.pf.relpath, node.lineno,
                    f"self.{node.attr} is `# guarded-by: {lock}` but is "
                    f"accessed outside `with self.{lock}`"))
        self.generic_visit(node)


class GuardedByRule(Rule):
    id = "guarded-by"
    description = ("attributes annotated `# guarded-by: <lock>` may only be "
                   "read/written inside `with self.<lock>` (method-level "
                   "`# holds: <lock>` documents a caller-holds contract)")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            if not pf.relpath.startswith(GUARDED_DIRS):
                continue
            yield from self._check_file(pf)

    def _check_file(self, pf: PyFile) -> Iterable[Finding]:
        for cls in iter_class_defs(pf.tree):
            guarded: dict[str, str] = {}     # attr -> lock attr
            # collect annotations: `self.X = ... # guarded-by: _lock`
            # (attribute assignment inside a method, typically __init__)
            # or a class-level `X = ... / X: T = ...` with the comment
            for node in ast.walk(cls):
                if not hasattr(node, "lineno"):
                    continue
                # the annotation sits on the assignment line or on its own
                # comment line directly above (long constructions wrap)
                m = GUARDED_BY_RE.search(pf.annotation_at(node.lineno))
                if not m:
                    continue
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            guarded[tgt.attr] = m.group(1)
                        elif isinstance(tgt, ast.Name):
                            guarded[tgt.id] = m.group(1)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                # only __init__ is exempt (construction precedes sharing);
                # a method that RE-declares an annotated attribute is
                # checked like any other — resetting guarded state
                # without the lock is exactly the bug class this catches
                if fn.name == "__init__":
                    continue
                held: set[str] = set()
                hm = HOLDS_RE.search(pf.annotation_at(fn.lineno))
                if hm:
                    held.add(hm.group(1))
                out: list[Finding] = []
                visitor = _GuardedAccessVisitor(self.id, pf, guarded, held,
                                                out)
                for stmt in fn.body:
                    visitor.visit(stmt)
                yield from out


class _BlockingCallVisitor(_LockTrackingVisitor):
    def __init__(self, rule_id: str, pf: PyFile, out: list[Finding]):
        super().__init__()
        self.rule_id = rule_id
        self.pf = pf
        self.out = out

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            name = dotted_name(node.func)
            tail = name.rsplit(".", 1)[-1]
            parts = name.split(".")
            # `self.foo()` is a local method, not an RPC — but
            # `self.backend.stop_container()` / `client.heartbeat()` are
            remote_method = (tail in BLOCKING_METHODS and len(parts) >= 2
                             and not (len(parts) == 2 and parts[0] == "self"))
            blocking = (name in BLOCKING_DOTTED
                        or (name.startswith(("time.", "subprocess.",
                                             "socket."))
                            and tail in {d.rsplit(".", 1)[-1]
                                         for d in BLOCKING_DOTTED})
                        or remote_method)
            if blocking:
                locks = ", ".join(sorted(self.held))
                self.out.append(Finding(
                    self.rule_id, self.pf.relpath, node.lineno,
                    f"blocking call {name}() inside `with {locks}` — "
                    f"sleeps/subprocess/RPC must not run under a "
                    f"control-plane lock"))
        self.generic_visit(node)


class NoBlockingUnderLockRule(Rule):
    id = "no-blocking-under-lock"
    description = ("time.sleep / subprocess / socket / RPC round-trips must "
                   "not execute lexically inside a `with <lock>` body")

    def run(self, project: Project) -> Iterable[Finding]:
        for pf in self.files(project):
            out: list[Finding] = []
            _BlockingCallVisitor(self.id, pf, out).visit(pf.tree)
            yield from out
