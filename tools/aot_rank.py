"""Offline screening of tuner variants with the real XLA:TPU compiler.

The axon tunnel is flaky (single claim, hours-long wedges), but JAX's
AOT path runs the REAL XLA:TPU compiler against a detached
TopologyDescription — no chip needed. So while the tunnel is down, every
tools/tune_mfu.py variant can be compiled for an actual v5e target and
screened by its compiled HBM plan (argument + temp bytes vs the 16 GiB
chip) and a roofline bound (model-accounted FLOPs vs MXU peak, XLA
'bytes accessed' vs HBM bandwidth).

This is SCREENING, not measurement: XLA's cost_analysis can't price the
Mosaic custom-call kernels (its optimal_seconds comes back as a negative
sentinel on these programs, and its flops/bytes skip kernel internals),
so the bound is a floor on step time, not an estimate. The value is
(a) variants that will OOM or blow compile are eliminated offline, and
(b) the HBM plan per variant is exact — so a short healthy-tunnel
window is spent measuring only configs that can actually run.

Usage (CPU host, no TPU):
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu python tools/aot_rank.py [variant ...]

One JSON line per variant, then a ranked summary on stderr; full results
in tools/aot_rank_result.json.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from bench import peak_flops  # noqa: E402
from tune_mfu import VARIANTS, build_config, variant_globals  # noqa: E402
from tony_tpu.models.llama import llama_init, llama_loss  # noqa: E402
from tony_tpu.train.step import make_train_step  # noqa: E402

V5E_HBM = 16 * 1024 ** 3
RESULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "aot_rank_result.json")


def _single_v5e_mesh():
    from jax.experimental import topologies

    # v5e:1x1 violates the default chips-per-host bound; take one device
    # of the smallest valid slice — the compiled program is single-chip
    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    return jax.sharding.Mesh([topo.devices[0]], ("chip",)), topo.devices[0]


def rank_one(name: str, spec: dict, mesh, dev) -> dict:
    from jax.sharding import NamedSharding, PartitionSpec as P

    config = build_config(spec)
    b, s = spec["batch"], spec["seq"]

    def sds(tree):
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                l.shape, l.dtype, sharding=NamedSharding(mesh, P())),
            tree)

    with variant_globals(spec):
        params_shape = jax.eval_shape(partial(llama_init, config),
                                      jax.random.PRNGKey(0))
        optimizer = optax.adamw(3e-4)
        opt_shape = jax.eval_shape(optimizer.init, params_shape)
        step = make_train_step(partial(llama_loss, config=config),
                               optimizer)
        tok = jax.ShapeDtypeStruct((b, s), jnp.int32,
                                   sharding=NamedSharding(mesh, P()))
        t0 = time.monotonic()
        exe = jax.jit(step).lower(
            sds(params_shape), sds(opt_shape),
            {"inputs": tok, "targets": tok}).compile()
    ca = exe.cost_analysis()
    ma = exe.memory_analysis()
    live = (ma.argument_size_in_bytes + ma.temp_size_in_bytes)
    out = {
        "variant": name,
        "hbm_gib": round(live / 1024 ** 3, 2),
        "hbm_temp_gib": round(ma.temp_size_in_bytes / 1024 ** 3, 2),
        "fits_v5e": bool(live <= V5E_HBM),
        "compile_s": round(time.monotonic() - t0, 1),
    }
    # roofline FLOOR on step time: model-accounted train FLOPs at MXU
    # peak vs XLA-visible HBM traffic at ~819 GB/s (v5e). A real step is
    # slower than both; the bound mainly exposes bandwidth-heavy configs.
    model_flops = b * s * config.flops_per_token(s)
    t_compute = model_flops / peak_flops(dev)
    t_bw = float(ca.get("bytes accessed", 0.0)) / 819e9
    floor_s = max(t_compute, t_bw)
    out["floor_ms"] = round(floor_s * 1e3, 2)
    out["bound"] = "bandwidth" if t_bw > t_compute else "compute"
    out["mfu_ceiling_pct"] = round(100.0 * t_compute / floor_s, 2)
    return out


def rank_decode(mesh) -> list[dict]:
    """AOT A/B of the decode step: bf16 vs int8 weight-only vs int8
    weights + int8 KV cache, against the real v5e target. The verdict
    that matters is memory_analysis: temp==0 proves the dequant FUSES
    (a single materialized bf16 LM head alone would be ~131 MB of temp),
    and argument bytes are the per-step weight/cache stream. Measured
    2026-07-31: bf16 2376.3 MB args / int8 1305.9 MB, both temp 0."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.models.generate import decode_step
    from tony_tpu.models.llama import get_config, llama_init
    from tony_tpu.models.quant import quantize_params

    config = get_config("llama3_1b_proxy")
    b, cache_len = 8, 192
    nl, nkv, hd = config.n_layers, config.n_kv_heads, config.head_dim

    def sds_tree(tree):
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                l.shape, l.dtype, sharding=NamedSharding(mesh, P())),
            tree)

    params_s = jax.eval_shape(partial(llama_init, config),
                              jax.random.PRNGKey(0))
    qparams_s = jax.eval_shape(quantize_params, params_s)
    tok = jax.ShapeDtypeStruct((b,), jnp.int32,
                               sharding=NamedSharding(mesh, P()))
    pos = jax.ShapeDtypeStruct((), jnp.int32,
                               sharding=NamedSharding(mesh, P()))

    def cache_sds(qc):
        kv = jnp.int8 if qc else jnp.bfloat16
        c = {"k": jax.ShapeDtypeStruct((nl, b, nkv, cache_len, hd), kv),
             "v": jax.ShapeDtypeStruct((nl, b, nkv, cache_len, hd), kv)}
        if qc:
            c["k_scale"] = jax.ShapeDtypeStruct(
                (nl, b, nkv, cache_len, 1), jnp.float32)
            c["v_scale"] = jax.ShapeDtypeStruct(
                (nl, b, nkv, cache_len, 1), jnp.float32)
        return sds_tree(c)

    results = []
    for tag, ps, qc in (("decode_bf16", params_s, False),
                        ("decode_int8", qparams_s, False),
                        ("decode_int8_qcache", qparams_s, True)):
        t0 = time.monotonic()
        exe = jax.jit(partial(decode_step, config=config)).lower(
            sds_tree(ps), cache=cache_sds(qc), token=tok,
            pos=pos).compile()
        ma = exe.memory_analysis()
        rec = {"variant": tag,
               "args_mb": round(ma.argument_size_in_bytes / 1e6, 1),
               "temp_mb": round(ma.temp_size_in_bytes / 1e6, 1),
               "dequant_fused": bool(ma.temp_size_in_bytes < 16e6),
               "compile_s": round(time.monotonic() - t0, 1)}
        results.append(rec)
        print(json.dumps(rec), flush=True)
    return results


def rank_decode_8b(mesh) -> list[dict]:
    """The capability-unlock check: Llama-3-8B single-chip v5e serving.
    bf16 CANNOT fit (16.07 GB params alone vs 15.75 GB HBM — the real
    compiler OOMs at 15.96G used), int8 weights + int8 KV cache FITS
    (9.12 GB args, dequant fused, temp 0) at batch 4 x 2k context.
    Measured 2026-07-31 via this mode (--decode-8b)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.models.generate import decode_step
    from tony_tpu.models.llama import get_config, llama_init
    from tony_tpu.models.quant import quantize_params

    # TONY_AOT_8B_CTX extends the check to long contexts (verified
    # 2026-07-31: 32k-ctx b1 int8+qcache fits at 10.78 GB, temp 0.5 MB)
    cache_len = int(os.environ.get("TONY_AOT_8B_CTX", "2048"))
    b = 4 if cache_len <= 4096 else 1
    config = get_config("llama3_8b", max_seq=max(8192, cache_len))
    nl, nkv, hd = config.n_layers, config.n_kv_heads, config.head_dim

    def sds_tree(tree):
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                l.shape, l.dtype, sharding=NamedSharding(mesh, P())),
            tree)

    params_s = jax.eval_shape(partial(llama_init, config),
                              jax.random.PRNGKey(0))
    tok = jax.ShapeDtypeStruct((b,), jnp.int32,
                               sharding=NamedSharding(mesh, P()))
    pos = jax.ShapeDtypeStruct((), jnp.int32,
                               sharding=NamedSharding(mesh, P()))

    def cache_sds(qc):
        kv = jnp.int8 if qc else jnp.bfloat16
        c = {"k": jax.ShapeDtypeStruct((nl, b, nkv, cache_len, hd), kv),
             "v": jax.ShapeDtypeStruct((nl, b, nkv, cache_len, hd), kv)}
        if qc:
            c["k_scale"] = jax.ShapeDtypeStruct(
                (nl, b, nkv, cache_len, 1), jnp.float32)
            c["v_scale"] = jax.ShapeDtypeStruct(
                (nl, b, nkv, cache_len, 1), jnp.float32)
        return sds_tree(c)

    results = []
    for tag, ps, qc in (
            ("8b_decode_bf16", params_s, False),
            ("8b_decode_int8_qcache",
             jax.eval_shape(quantize_params, params_s), True)):
        t0 = time.monotonic()
        try:
            exe = jax.jit(partial(decode_step, config=config)).lower(
                sds_tree(ps), cache=cache_sds(qc), token=tok,
                pos=pos).compile()
            ma = exe.memory_analysis()
            rec = {"variant": tag, "fits_v5e": True,
                   "args_gb": round(
                       ma.argument_size_in_bytes / 1e9, 2),
                   "temp_mb": round(ma.temp_size_in_bytes / 1e6, 1),
                   "compile_s": round(time.monotonic() - t0, 1)}
        except Exception as e:
            rec = {"variant": tag, "fits_v5e": False,
                   "error": f"{type(e).__name__}: {str(e)[:140]}"}
        results.append(rec)
        print(json.dumps(rec), flush=True)
    return results


def main() -> int:
    for flag, fn in (("--decode", rank_decode),
                     ("--decode-8b", rank_decode_8b)):
        if flag in sys.argv[1:]:
            mesh, _ = _single_v5e_mesh()
            results = fn(mesh)
            with open(RESULT_PATH.replace(
                    ".json", f"_{flag.strip('-').replace('-', '_')}.json"),
                    "w", encoding="utf-8") as f:
                json.dump({"measured_at": time.strftime(
                    "%Y-%m-%dT%H:%MZ", time.gmtime()),
                    "results": results}, f, indent=2)
            return 0
    names = sys.argv[1:] or list(VARIANTS)
    mesh, dev = _single_v5e_mesh()
    results = []
    for name in names:
        try:
            rec = rank_one(name, VARIANTS[name], mesh, dev)
        except Exception as e:  # rank what compiles; report the rest
            rec = {"variant": name,
                   "error": f"{type(e).__name__}: {str(e)[:160]}"}
        results.append(rec)
        print(json.dumps(rec), flush=True)
    ranked = sorted((r for r in results if "mfu_ceiling_pct" in r),
                    key=lambda r: (-r["fits_v5e"], -r["mfu_ceiling_pct"]))
    for i, r in enumerate(ranked):
        print(f"[rank {i + 1}] {r['variant']}: ceiling "
              f"{r['mfu_ceiling_pct']}% ({r['bound']}-bound, hbm "
              f"{r['hbm_gib']} GiB, fits={r['fits_v5e']})",
              file=sys.stderr)
    for r in results:
        if "error" in r:
            print(f"[fail] {r['variant']}: {r['error']}", file=sys.stderr)
    with open(RESULT_PATH, "w", encoding="utf-8") as f:
        json.dump({"measured_at": time.strftime(
            "%Y-%m-%dT%H:%MZ", time.gmtime()), "results": results},
            f, indent=2)
    return 0


if __name__ == "__main__":
    main()
